"""Elastic multi-host training: preemption-tolerant data-parallel workers
with bitwise-equal recovery (doc/fault_tolerance.md "Multi-host recovery").

The reference's scale-out story was a distributed parameter server
(mshadow-ps ``Push``/``Pull``, ``src/nnet/nnet_ps_server.cpp``); this
module lands that story on preemptible fleets, where the interesting
property is not peak bandwidth but *survivability*: a killed host must
mean restore-last-good and rejoin — never a dead run — and the recovered
run must end **bitwise equal** to a fault-free one.

Design (one deliberate invariant per layer):

* **Input sharding** — every host reads the same global sample stream
  but materializes only instances ``i % hosts == rank`` through the
  ``nworker`` pool, whose per-instance RNG keys on the GLOBAL
  epoch-absolute index (``io/iter_augment.py``).  The PR 5 invariant,
  promoted from threads to hosts: interleaving the per-host streams
  reconstructs the 1-host stream bitwise at any host count.
* **Step math** — each optimizer step's global batch is split into
  ``shards`` fixed micro-shards (``dist.shards``, a multiple of the
  host count).  A host computes gradient contributions for the shards
  it owns (shard ``s`` → host ``s % hosts``), pushes them to the
  coordinator, pulls the full set back, and every host folds the SAME
  transported bytes in ascending shard order before one local optimizer
  apply.  Because the fold never mentions the host count, params stay
  bitwise-replicated with no broadcast — and a 4-host run equals a
  1-host run equals a recovered run, byte for byte.  (This is the
  parameter-server push/pull shape, not an XLA collective: on a TPU
  fleet the same exchange rides ``jax.distributed`` + DCN allreduce;
  over the chaos-drill harness it rides the coordinator socket so that
  a killed process is an ordinary, drillable event.)
* **Coordination point** — ``TrainSupervisor`` + ``AsyncCheckpointer``
  (PR 1/3) already own restore-last-good; :class:`ElasticSupervisor`
  subclasses the supervisor so that every gate-accepted save is a
  cross-host barrier (rank 0 writes, everyone fences), recovery
  rendezvouses the next membership *generation* before restoring, and a
  post-restore CRC barrier proves all hosts resumed from identical
  bytes.
* **Membership** — workers heartbeat an :class:`ElasticCoordinator`
  (a thread in the launcher process, so no worker death can take it
  down).  A missed heartbeat, a dead socket, or a reported fault bumps
  the generation and aborts in-flight collectives: blocked peers get a
  rollback notice and raise ``faults.HostLossError`` — a RECOVERABLE
  fault — while the launcher respawns the lost rank, which rejoins the
  rendezvous at the restored step.

The whole story is drillable: ``train.fault_plan=host_loss=N[:rank]``
kills a worker mid-step, ``partition=N:secs`` takes one off the network
(``runtime/faults.py``), and ``tests/test_elastic.py`` proves the
bitwise-equal-recovery headline at 1, 2 and 4 hosts.
"""

from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import faults
from ..runtime.supervisor import SupervisorConfig, TrainSupervisor

# --- wire protocol ---------------------------------------------------------
#
# One frame = MAGIC + u32 header length + JSON header + raw buffers
# (lengths in the header's "blens").  Tensors travel as raw bytes —
# floats never round-trip through text, which is what lets every host
# fold the identical gradient bytes.

_MAGIC = b'CXEL'


def send_frame(sock: socket.socket, hdr: dict,
               bufs: Tuple[bytes, ...] = ()) -> None:
    hdr = dict(hdr)
    hdr['blens'] = [len(b) for b in bufs]
    payload = json.dumps(hdr).encode()
    # header in one send, then each buffer as-is: the per-step gradient
    # payload is never copied into a second staging buffer
    sock.sendall(_MAGIC + struct.pack('<I', len(payload)) + payload)
    for b in bufs:
        sock.sendall(b)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            # transport speaks OSError-family; the client/coordinator
            # map it onto the typed taxonomy at the boundary
            # lint: allow(fault-taxonomy): transport-layer OSError contract
            raise ConnectionError('elastic peer closed the connection')
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Tuple[dict, List[bytes]]:
    magic = _recv_exact(sock, 4)
    if magic != _MAGIC:
        # a garbled frame IS a broken connection (same contract as above)
        # lint: allow(fault-taxonomy): transport-layer OSError contract
        raise ConnectionError(f'elastic protocol: bad magic {magic!r}')
    (hlen,) = struct.unpack('<I', _recv_exact(sock, 4))
    hdr = json.loads(_recv_exact(sock, hlen).decode())
    bufs = [_recv_exact(sock, n) for n in hdr.get('blens', [])]
    return hdr, bufs


def params_crc(params) -> int:
    """crc32 over every param leaf's bytes, in pytree order — the cheap
    cross-host "did we all restore the same model" probe (the elastic
    analog of ``trainer.check_weight_consistency``)."""
    import jax
    crc = 0
    for leaf in jax.tree.leaves(params):
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(leaf)).tobytes(),
                         crc)
    return crc


# --- coordinator -----------------------------------------------------------


class _Member:
    """One registered worker, from the coordinator's side."""

    def __init__(self, rank: int, conn: socket.socket):
        self.rank = rank
        self.conn = conn
        self.last_hb = time.monotonic()
        self.gen = -1            # generation this member last rendezvoused


class ElasticCoordinator:
    """Membership + collectives service for one elastic training job.

    Runs in the LAUNCHER process (threads named ``cxxnet-elastic-*``) so
    no worker preemption can take it down.  All state transitions happen
    under ``_cond``; blocked request handlers wait on it and re-check
    the generation — a membership change releases every waiter with a
    rollback notice instead of leaving it parked on a dead collective.
    """

    def __init__(self, nhosts: int, heartbeat_timeout: float = 6.0,
                 on_host_lost: Optional[Callable[[int], None]] = None,
                 failure_log: Optional[faults.FailureLog] = None):
        if nhosts < 1:
            raise ValueError(f'nhosts must be >= 1, got {nhosts}')
        self.nhosts = int(nhosts)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.on_host_lost = on_host_lost
        # `is None`, not truthiness: an EMPTY FailureLog is falsy
        self.failure_log = (faults.global_failure_log()
                            if failure_log is None else failure_log)
        self._cond = threading.Condition()
        self._gen = 0                 # guarded-by: _cond
        self._stop = False            # guarded-by: _cond
        self._hello: Dict[int, _Member] = {}     # guarded-by: _cond
        self._members: Dict[int, _Member] = {}   # guarded-by: _cond
        self._welcomed_gen = -1       # guarded-by: _cond
        self._contrib: Dict[int, Tuple[dict, List[bytes]]] = {} \
            # guarded-by: _cond
        self._result = None           # guarded-by: _cond
        self._result_step = -1        # guarded-by: _cond
        self._result_left = 0        # guarded-by: _cond
        self._barriers: Dict[str, Dict[int, object]] = {} \
            # guarded-by: _cond
        self._released: Dict[str, Tuple[int, int, Dict[int, object]]] = {} \
            # guarded-by: _cond
        self._events: List[str] = []  # guarded-by: _cond
        self._threads: List[threading.Thread] = []  # guarded-by: _cond
        self._conns: List[socket.socket] = []       # guarded-by: _cond
        self._srv: Optional[socket.socket] = None
        self.address = ''

    # -- lifecycle --
    def start(self) -> str:
        """Bind, start the accept + monitor threads, return host:port."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(('127.0.0.1', 0))
        srv.listen(self.nhosts * 4)
        # closing a socket does NOT reliably wake a thread blocked in
        # accept(); poll with a timeout so stop() is prompt
        srv.settimeout(0.5)
        self._srv = srv
        host, port = srv.getsockname()
        self.address = f'{host}:{port}'
        for name, fn in (('cxxnet-elastic-accept', self._accept_loop),
                         ('cxxnet-elastic-mon', self._monitor_loop)):
            t = threading.Thread(target=fn, name=name, daemon=True)
            t.start()
            with self._cond:
                self._threads.append(t)
        return self.address

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            conns = list(self._conns)
            threads = list(self._threads)
            self._cond.notify_all()
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in threads:
            t.join(timeout=5.0)

    def events(self) -> List[str]:
        with self._cond:
            return list(self._events)

    def generation(self) -> int:
        with self._cond:
            return self._gen

    # -- internals --
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._srv.accept()
            except socket.timeout:
                with self._cond:
                    if self._stop:
                        return
                continue
            except OSError:
                return                       # stop() closed the socket
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name='cxxnet-elastic-conn', daemon=True)
            with self._cond:
                if self._stop:
                    conn.close()
                    return
                self._conns.append(conn)
                self._threads.append(t)
            t.start()

    def _monitor_loop(self) -> None:
        while True:
            with self._cond:
                if self._stop:
                    return
                now = time.monotonic()
                stale = [m for m in self._members.values()
                         if m.gen == self._gen
                         and now - m.last_hb > self.heartbeat_timeout]
                for m in stale:
                    self._lost_locked(m.rank, 'missed heartbeats')
                self._cond.wait(timeout=self.heartbeat_timeout / 4)

    def _lost_locked(self, rank: int, why: str) -> None:  # requires-lock: _cond
        """Membership event: drop ``rank``, bump the generation, release
        every blocked collective/barrier with a rollback."""
        m = self._members.pop(rank, None)
        if m is None or m.gen != self._gen:
            return                       # already stale — counted once
        self._gen += 1
        self._events.append(f'gen={self._gen} lost rank {rank}: {why}')
        self.failure_log.record(
            'host_lost', f'rank {rank} left generation {self._gen - 1} '
            f'({why}); generation now {self._gen}')
        self._contrib.clear()
        self._barriers.clear()
        self._released.clear()
        self._result = None
        self._cond.notify_all()
        cb = self.on_host_lost
        if cb is not None:
            threading.Thread(target=cb, args=(rank,),
                             name='cxxnet-elastic-lost-cb',
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        rank = None
        is_hb = False
        try:
            while True:
                hdr, bufs = recv_frame(conn)
                op = hdr['op']
                if op == 'hb_attach':
                    rank = int(hdr['rank'])
                    is_hb = True
                    continue
                if op == 'hb':
                    with self._cond:
                        m = self._members.get(rank)
                        if m is None:
                            m = self._hello.get(rank)
                        if m is not None:
                            m.last_hb = time.monotonic()
                    continue
                rank = int(hdr.get('rank', -1))
                if op == 'hello':
                    self._op_hello(conn, rank)
                elif op == 'push':
                    self._op_push(conn, rank, hdr, bufs)
                elif op == 'barrier':
                    self._op_barrier(conn, rank, hdr)
                elif op == 'fault':
                    self._op_fault(conn, rank, hdr)
                elif op == 'bye':
                    with self._cond:
                        m = self._members.get(rank)
                        if m is not None and m.conn is conn:
                            # graceful leave after the done barrier: not
                            # a membership fault
                            self._members.pop(rank, None)
                    send_frame(conn, {'op': 'ok'})
                    return
                else:
                    send_frame(conn, {'op': 'error',
                                      'error': f'unknown op {op!r}'})
        except (ConnectionError, OSError, ValueError, KeyError) as e:
            with self._cond:
                if self._stop:
                    return
                if rank is not None and rank in self._hello \
                        and self._hello[rank].conn is conn:
                    # died while waiting in a rendezvous: un-register so
                    # a respawn's hello can take the slot
                    self._hello.pop(rank, None)
                if rank is not None and not is_hb \
                        and rank in self._members \
                        and self._members[rank].conn is conn:
                    self._lost_locked(rank, f'connection dropped ({e!r})')
                elif rank is not None and is_hb:
                    # a dying process drops its heartbeat socket first —
                    # use it as an early loss signal
                    if rank in self._members \
                            and self._members[rank].gen == self._gen:
                        self._lost_locked(
                            rank, f'heartbeat connection dropped ({e!r})')
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # long-lived coordinators see endless reconnect churn: drop
            # this handler's bookkeeping so the lists stay bounded by
            # LIVE connections, not historical ones
            me = threading.current_thread()
            with self._cond:
                if conn in self._conns:
                    self._conns.remove(conn)
                if me in self._threads:
                    self._threads.remove(me)

    def _op_hello(self, conn: socket.socket, rank: int) -> None:
        """Rendezvous: one hello per rank; when all ``nhosts`` ranks are
        waiting, the generation is sealed and everyone gets a welcome."""
        with self._cond:
            if not 0 <= rank < self.nhosts:
                send_frame(conn, {'op': 'error',
                                  'error': f'rank {rank} out of range '
                                           f'0..{self.nhosts - 1}'})
                return
            # a re-hello replaces any stale registration for the rank —
            # and releases the superseded hello's parked handler (gen=-2
            # sentinel), or its thread would poll until stop()
            self._members.pop(rank, None)
            old = self._hello.get(rank)
            if old is not None:
                old.gen = -2
                self._cond.notify_all()
            me = _Member(rank, conn)
            self._hello[rank] = me
            if len(self._hello) == self.nhosts:
                # seal: the waiting hellos become the new generation's
                # membership (a bump mid-rendezvous just means they seal
                # into the newer generation)
                gen = self._gen
                for r, m in self._hello.items():
                    m.gen = gen
                    m.last_hb = time.monotonic()
                    self._members[r] = m
                self._hello.clear()
                self._welcomed_gen = gen
                self._events.append(
                    f'gen={gen} rendezvous complete ({self.nhosts} '
                    'hosts)')
                self._cond.notify_all()
            else:
                while not self._stop and me.gen == -1:
                    self._cond.wait(timeout=1.0)
            if me.gen == -2:
                # superseded by a newer hello from the same rank (the
                # client gave up and reconnected): this reply pairs
                # with a request nobody is waiting on — end the conn
                send_frame(conn, {'op': 'rollback', 'gen': self._gen,
                                  'why': 'superseded by a newer hello'})
                return
            gen = me.gen if me.gen >= 0 else self._gen
        send_frame(conn, {'op': 'welcome', 'gen': gen,
                          'nhosts': self.nhosts})

    def _op_push(self, conn: socket.socket, rank: int, hdr: dict,
                 bufs: List[bytes]) -> None:
        """Gradient-shard gather-broadcast: stash this host's shard
        payloads; when every member has pushed, hand the full assembled
        set back to each of them (the ps-lite Push+Pull pair in one
        round trip)."""
        with self._cond:
            m = self._members.get(rank)
            if m is None or m.gen != self._gen:
                send_frame(conn, {'op': 'rollback', 'gen': self._gen,
                                  'why': 'stale generation'})
                return
            if any(self._barriers.values()):
                # a peer is already waiting at a barrier while this host
                # still pushes steps: the hosts disagree about where the
                # run is — a config skew, not a transient
                send_frame(conn, {'op': 'error',
                                  'error': 'peers disagree: a host is at '
                                           'a barrier while this one '
                                           'still trains (step/config '
                                           'skew)'})
                return
            my_gen = self._gen
            step = int(hdr['step'])
            self._contrib[rank] = (hdr, bufs)
            if len(self._contrib) == self.nhosts:
                shards: Dict[int, Tuple[bytes, bytes]] = {}
                steps = set()
                for h, bs in self._contrib.values():
                    steps.add(int(h['step']))
                    for i, sid in enumerate(h['shards']):
                        shards[int(sid)] = (bs[2 * i], bs[2 * i + 1])
                if len(steps) != 1:
                    self._result = ('error',
                                    f'hosts pushed different steps '
                                    f'{sorted(steps)}')
                else:
                    order = sorted(shards)
                    flat = []
                    for sid in order:
                        flat += [shards[sid][0], shards[sid][1]]
                    self._result = ('pull', {'step': step,
                                             'shards': order}, flat)
                # version the result by step: a fast host may push step
                # t+1 before every peer consumed step t's result, and
                # must wait for ITS step, not adopt the stale one
                self._result_step = step
                self._result_left = self.nhosts
                self._contrib.clear()
                self._cond.notify_all()
            else:
                while (not self._stop and self._gen == my_gen
                       and not (self._result is not None
                                and self._result_step == step)):
                    self._cond.wait(timeout=1.0)
            if self._gen != my_gen or self._result is None \
                    or self._result_step != step:
                send_frame(conn, {'op': 'rollback', 'gen': self._gen,
                                  'why': 'membership changed mid-step'})
                return
            result = self._result
            self._result_left -= 1
            if self._result_left == 0:
                self._result = None
        if result[0] == 'error':
            send_frame(conn, {'op': 'error', 'error': result[1]})
        else:
            send_frame(conn, dict(result[1], op='pull'),
                       tuple(result[2]))

    def _op_barrier(self, conn: socket.socket, rank: int,
                    hdr: dict) -> None:
        """All-hosts fence, with a value exchange: release carries every
        member's value keyed by rank (the save gate, the restore-step
        broadcast, and the CRC verify all ride this one op)."""
        tag = str(hdr['tag'])
        with self._cond:
            m = self._members.get(rank)
            if m is None or m.gen != self._gen:
                send_frame(conn, {'op': 'rollback', 'gen': self._gen,
                                  'why': 'stale generation'})
                return
            my_gen = self._gen
            waiting = self._barriers.setdefault(tag, {})
            waiting[rank] = hdr.get('value')
            if len(waiting) == self.nhosts:
                self._released[tag] = (my_gen, self.nhosts, dict(waiting))
                del self._barriers[tag]
                self._cond.notify_all()
            else:
                while (not self._stop and self._gen == my_gen
                       and not (tag in self._released
                                and self._released[tag][0] == my_gen)):
                    self._cond.wait(timeout=1.0)
            rel = self._released.get(tag)
            if self._gen != my_gen or rel is None or rel[0] != my_gen:
                send_frame(conn, {'op': 'rollback', 'gen': self._gen,
                                  'why': 'membership changed at barrier'})
                return
            values = rel[2]
            left = rel[1] - 1
            if left == 0:
                del self._released[tag]
            else:
                self._released[tag] = (rel[0], left, values)
        send_frame(conn, {'op': 'release', 'tag': tag,
                          'values': {str(r): v for r, v in values.items()}})

    def _op_fault(self, conn: socket.socket, rank: int,
                  hdr: dict) -> None:
        """A worker reports a recoverable fault: bump the generation so
        every peer rolls back with it (deterministic faults — NaN at
        step S — arrive from all hosts; the bump happens once)."""
        with self._cond:
            m = self._members.get(rank)
            if m is not None and m.gen == self._gen:
                self._gen += 1
                self._events.append(
                    f'gen={self._gen} rank {rank} reported fault: '
                    f'{hdr.get("kind", "?")} at step {hdr.get("step")}')
                self._members.pop(rank, None)
                self._contrib.clear()
                self._barriers.clear()
                self._released.clear()
                self._result = None
                self._cond.notify_all()
            else:
                # stale or already-dropped member: the generation already
                # moved past this fault
                self._members.pop(rank, None)
        send_frame(conn, {'op': 'ok', 'gen': self.generation()})


# --- client ----------------------------------------------------------------


class ElasticClient:
    """One worker's connection to the coordinator: a synchronous op
    socket (the step loop's push/barrier round trips) plus a one-way
    heartbeat socket driven by a ``cxxnet-elastic-hb`` thread.

    Failure mapping: a reply of ``rollback`` → ``faults.HostLossError``
    (recoverable — restore and rendezvous); a dead/unresponsive socket →
    ``faults.CoordinatorUnreachableError`` (recoverable — from here a
    coordinator outage and a partition look the same); an ``error``
    reply → ``faults.ElasticSyncError`` (NOT recoverable: the hosts
    disagree about the run itself)."""

    def __init__(self, address: str, rank: int, nhosts: int,
                 heartbeat: float = 2.0, sync_timeout: float = 60.0,
                 rendezvous_timeout: float = 120.0):
        host, _, port = address.rpartition(':')
        self.host, self.port = host or '127.0.0.1', int(port)
        self.rank = int(rank)
        self.nhosts = int(nhosts)
        self.heartbeat = float(heartbeat)
        self.sync_timeout = float(sync_timeout)
        self.rendezvous_timeout = float(rendezvous_timeout)
        self.generation = -1          # guarded-by: _lock
        # per-generation barrier sequence numbers: barriers are lockstep
        # within a generation, so scoping the wire tag by (gen, seq)
        # keeps a fast host's NEXT use of a tag distinct from a slow
        # peer's not-yet-consumed release of the previous one
        self._bar_seq: Dict[str, int] = {}   # guarded-by: _lock
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None   # guarded-by: _lock
        self._hb_sock: Optional[socket.socket] = None
        self._silent_until = 0.0      # guarded-by: _lock
        self._closed = False          # guarded-by: _lock
        self._hb_thread: Optional[threading.Thread] = None

    # -- plumbing --
    def _dial(self) -> socket.socket:
        s = socket.create_connection((self.host, self.port), timeout=10.0)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def connect(self) -> None:
        sock, hb = self._dial(), self._dial()
        send_frame(hb, {'op': 'hb_attach', 'rank': self.rank})
        with self._lock:
            old = (self._sock, self._hb_sock)
            self._sock, self._hb_sock = sock, hb
        for s in old:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        if self._hb_thread is None:
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name=f'cxxnet-elastic-hb-{self.rank}',
                daemon=True)
            self._hb_thread.start()

    def _hb_loop(self) -> None:
        while True:
            with self._lock:
                if self._closed:
                    return
                sock = self._hb_sock
                silent = time.monotonic() < self._silent_until
            if sock is not None and not silent:
                try:
                    send_frame(sock, {'op': 'hb'})
                except OSError:
                    pass              # reconnects ride the next resync
            time.sleep(self.heartbeat)

    def _call(self, hdr: dict, bufs: Tuple[bytes, ...] = (),
              timeout: Optional[float] = None) -> Tuple[dict, List[bytes]]:
        """One synchronous round trip; maps transport failures onto the
        typed taxonomy (see class docstring)."""
        op = hdr['op']
        timeout = self.sync_timeout if timeout is None else timeout
        with self._lock:
            sock = self._sock
        if sock is None:
            raise faults.CoordinatorUnreachableError(op, 0.0)
        try:
            sock.settimeout(timeout)
            send_frame(sock, dict(hdr, rank=self.rank), bufs)
            reply, rbufs = recv_frame(sock)
        except (socket.timeout, TimeoutError, ConnectionError, OSError) \
                as e:
            # the socket is now DIRTY: a late reply to this op would
            # pair with the next request and desync every reply after
            # it.  Drop it — resync()/connect() dials fresh.
            with self._lock:
                if self._sock is sock:
                    self._sock = None
            try:
                sock.close()
            except OSError:
                pass
            raise faults.CoordinatorUnreachableError(op, timeout) from e
        if reply['op'] == 'rollback':
            with self._lock:
                self.generation = int(reply['gen'])
            raise faults.HostLossError(reply.get('why', 'rollback'),
                                       generation=int(reply['gen']))
        if reply['op'] == 'error':
            raise faults.ElasticSyncError(
                f'elastic {op} failed: {reply.get("error")}')
        return reply, rbufs

    # -- surface --
    def rendezvous(self) -> int:
        """Join the current membership generation (blocks until all
        ``nhosts`` ranks are present).  Returns the sealed generation."""
        from ..obs import span
        with span('elastic.rendezvous', 'elastic',
                  rank=self.rank) as sp:
            reply, _ = self._call({'op': 'hello'},
                                  timeout=self.rendezvous_timeout)
            if reply['op'] != 'welcome':
                raise faults.ElasticSyncError(
                    f'expected welcome, got {reply["op"]!r}')
            with self._lock:
                self.generation = int(reply['gen'])
                self._bar_seq.clear()
                sp.attrs['gen'] = self.generation
                return self.generation

    def all_shards(self, step: int, shard_ids: List[int],
                   flats: List[np.ndarray], losses: List[np.ndarray],
                   ) -> Tuple[Dict[int, np.ndarray], Dict[int, np.float32]]:
        """Push this host's shard gradients, pull the full set (every
        shard's bytes exactly as some host pushed them)."""
        from ..obs import span
        bufs: List[bytes] = []
        for f, l in zip(flats, losses):
            bufs.append(np.ascontiguousarray(f, np.float32).tobytes())
            bufs.append(np.ascontiguousarray(l, np.float32).tobytes())
        with span('elastic.push_pull', 'elastic', step=int(step),
                  rank=self.rank, shards=len(shard_ids)):
            reply, rbufs = self._call(
                {'op': 'push', 'step': int(step),
                 'shards': [int(s) for s in shard_ids]}, tuple(bufs))
        out_f: Dict[int, np.ndarray] = {}
        out_l: Dict[int, np.float32] = {}
        for i, sid in enumerate(reply['shards']):
            out_f[int(sid)] = np.frombuffer(rbufs[2 * i], np.float32)
            out_l[int(sid)] = np.frombuffer(rbufs[2 * i + 1],
                                            np.float32)[0]
        return out_f, out_l

    def barrier(self, tag: str, value=None,
                timeout: Optional[float] = None) -> Dict[int, object]:
        """Fence with all hosts; returns every member's value by rank.
        Wire tags are scoped by (generation, per-tag sequence) — all
        hosts execute the same barrier sequence within a generation, so
        the scoped tags line up by construction."""
        from ..obs import span
        with self._lock:
            seq = self._bar_seq.get(tag, 0)
            self._bar_seq[tag] = seq + 1
            wire = f'{self.generation}/{tag}#{seq}'
        with span('elastic.barrier', 'elastic', tag=tag,
                  rank=self.rank, wire=wire):
            reply, _ = self._call({'op': 'barrier', 'tag': wire,
                                   'value': value}, timeout=timeout)
        return {int(r): v for r, v in reply['values'].items()}

    def report_fault(self, kind: str, step: int) -> None:
        """Tell the coordinator this host is rolling back (peers must
        too).  Best-effort: if the coordinator already noticed — or is
        unreachable — the rendezvous will sort it out."""
        try:
            self._call({'op': 'fault', 'kind': kind, 'step': int(step)},
                       timeout=min(10.0, self.sync_timeout))
        except (faults.HostLossError, faults.CoordinatorUnreachableError,
                faults.ElasticSyncError):
            pass

    def resync(self, kind: str, step: int) -> int:
        """Recovery path: report the fault, reconnect if the transport
        died, and rendezvous into the next generation."""
        self.report_fault(kind, step)
        try:
            return self.rendezvous()
        except faults.CoordinatorUnreachableError:
            self.connect()            # partition healed / socket died
            return self.rendezvous()

    def partition(self, secs: float) -> None:
        """Deterministic network partition: stop heartbeating and go
        silent for ``secs`` (the ``partition=N:secs`` fault event)."""
        with self._lock:
            self._silent_until = time.monotonic() + secs
        time.sleep(secs)

    def abort(self) -> None:
        """Drop both sockets with NO goodbye — the abrupt-death
        simulation (the coordinator sees exactly what a preempted
        process leaves behind: dead connections)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            socks = (self._sock, self._hb_sock)
            self._sock = self._hb_sock = None
        for s in socks:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        t = self._hb_thread
        if t is not None:
            t.join(timeout=self.heartbeat + 2.0)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            sock, hb = self._sock, self._hb_sock
            self._sock = self._hb_sock = None
        for s in (sock, hb):
            if s is None:
                continue
            try:
                if s is sock:
                    s.settimeout(2.0)
                    send_frame(s, {'op': 'bye', 'rank': self.rank})
                    recv_frame(s)
            except OSError:          # ConnectionError included
                pass
            try:
                s.close()
            except OSError:
                pass
        t = self._hb_thread
        if t is not None:
            t.join(timeout=self.heartbeat + 2.0)


# --- the elastic step ------------------------------------------------------


@dataclass
class ElasticConfig:
    """Shape of one elastic job (config keys in doc/tasks.md)."""

    hosts: int = 1                 # dist.hosts
    rank: int = 0                  # dist.rank
    shards: int = 0                # dist.shards (0 = hosts)
    coordinator: str = ''          # dist.coordinator host:port
    heartbeat: float = 2.0         # dist.heartbeat seconds
    rejoin: int = 2                # dist.rejoin respawn budget (launcher)
    sync_timeout: float = 60.0     # dist.sync_timeout seconds
    incarnation: int = 0           # CXXNET_ELASTIC_INCARNATION
    batch_size: int = 0            # GLOBAL batch size (conf batch_size)

    def resolve(self) -> 'ElasticConfig':
        self.shards = self.shards or self.hosts
        if self.hosts < 1:
            raise ValueError(f'dist.hosts must be >= 1, got {self.hosts}')
        if not 0 <= self.rank < self.hosts:
            raise faults.DistInitError(
                f'dist.rank {self.rank} out of range for dist.hosts='
                f'{self.hosts}')
        if self.shards % self.hosts:
            raise ValueError(
                f'dist.shards={self.shards} must be a multiple of '
                f'dist.hosts={self.hosts} (each shard lives on exactly '
                'one host)')
        if self.batch_size % self.shards:
            raise ValueError(
                f'batch_size={self.batch_size} must divide into '
                f'dist.shards={self.shards} equal micro-shards')
        return self

    @property
    def owned_shards(self) -> List[int]:
        return [s for s in range(self.shards) if s % self.hosts == self.rank]


class ElasticStepper:
    """The elastic step loop body (the supervisor's ``make_stepper``
    protocol: ``feed``/``finish``/``discard``).

    One host batch (``batch_size/hosts`` rows, the host's stride of the
    global batch) = one optimizer step: per owned micro-shard, a
    grad-only dispatch (``trainer.compile_grad_step``); one push/pull
    with the coordinator; a fixed-ascending-order fold of ALL shard
    bytes (including this host's own, as transported — every host folds
    identical f32 buffers); one jitted optimizer apply.  The fold's
    shape depends only on ``dist.shards`` — never on the host count —
    which is the whole bitwise-at-any-host-count invariant."""

    def __init__(self, trainer, client: ElasticClient, cfg: ElasticConfig,
                 grad_fn=None, apply_fn=None):
        import jax
        self.tr = trainer
        self.client = client
        self.cfg = cfg
        self.grad_fn = grad_fn if grad_fn is not None \
            else trainer.compile_grad_step()
        self.apply_fn = apply_fn if apply_fn is not None \
            else trainer.compile_apply_grad()
        self.updates = 0
        # gradient wire format, fixed at construction from grad_acc
        # (same structure/shardings as params)
        leaves, self._treedef = jax.tree.flatten(trainer.grad_acc)
        self._leaf_shapes = [l.shape for l in leaves]
        self._leaf_sizes = [int(np.prod(s)) for s in self._leaf_shapes]
        self._leaf_shardings = [l.sharding for l in leaves]
        for l in leaves:
            if l.dtype != np.float32:
                raise ValueError(
                    'elastic training requires float32 params/grads '
                    f'(got {l.dtype}) — the wire fold is defined over '
                    'f32 bytes')

    def _flatten(self, grads) -> np.ndarray:
        import jax
        return np.concatenate(
            [np.asarray(l, np.float32).ravel()
             for l in jax.tree.leaves(grads)])

    def _unflatten_to_device(self, flat: np.ndarray):
        import jax
        leaves = []
        off = 0
        for shape, size, sh in zip(self._leaf_shapes, self._leaf_sizes,
                                   self._leaf_shardings):
            leaves.append(jax.device_put(
                flat[off:off + size].reshape(shape), sh))
            off += size
        return jax.tree.unflatten(self._treedef, leaves)

    def feed(self, batch) -> int:
        import jax
        tr = self.tr
        cfg = self.cfg
        step = tr.sample_counter
        # chaos hooks: host_loss kills this process here; partition goes
        # silent for N seconds before the step's collective
        secs = faults.elastic_step(step, cfg.rank, cfg.hosts,
                                   allow_kill=cfg.incarnation == 0)
        if secs:
            self.client.partition(secs)
        if batch.extra_data:
            raise ValueError('elastic training does not support '
                             'extra_data (attachtxt) chains')
        q = cfg.shards // cfg.hosts
        data = np.asarray(batch.data)
        label = np.asarray(batch.label)
        bs = batch.batch_size
        mask = np.ones(bs, np.float32)
        if batch.num_batch_padd and getattr(batch, 'pad_synthetic', False):
            mask[bs - batch.num_batch_padd:] = 0.0
        norm = tr._norm_args(batch)
        step_rng = jax.random.fold_in(
            tr._rng, 1 + step * 131 + tr.round)
        owned = cfg.owned_shards
        flats: List[np.ndarray] = []
        losses: List[np.ndarray] = []
        for s in owned:
            j0 = (s - cfg.rank) // cfg.hosts
            rows = slice(j0, None, q)
            d = tr._shard_batch(np.ascontiguousarray(data[rows]),
                                cast=not norm)
            l = tr._shard_batch(np.ascontiguousarray(label[rows]),
                                cast=False)
            m = tr._shard_batch(np.ascontiguousarray(mask[rows]),
                                cast=False)
            loss, grads = self.grad_fn(
                tr.params, d, l, (), m, jax.random.fold_in(step_rng, s),
                tr.round, norm=norm)
            flats.append(self._flatten(grads))
            losses.append(np.asarray(loss, np.float32).reshape(1))
        full, full_loss = self.client.all_shards(step, owned, flats,
                                                 losses)
        if sorted(full) != list(range(cfg.shards)):
            raise faults.ElasticSyncError(
                f'step {step}: pulled shards {sorted(full)}, expected '
                f'0..{cfg.shards - 1}')
        # the fixed-order fold: ascending shard id, then one 1/S scale —
        # identical bytes in, identical bytes out, on every host
        inv = np.float32(1.0 / cfg.shards)
        acc = full[0].copy()
        loss_acc = np.float32(full_loss[0])
        for s in range(1, cfg.shards):
            acc += full[s]
            loss_acc = np.float32(loss_acc + full_loss[s])
        acc *= inv
        loss_acc = np.float32(loss_acc * inv)
        gtree = self._unflatten_to_device(acc)
        tr.params, tr.opt_state = self.apply_fn(
            tr.params, tr.opt_state, gtree, tr.epoch_counter)
        tr._observe_loss(loss_acc)
        tr.epoch_counter += 1
        tr.sample_counter += 1
        self.updates += 1
        return 1

    def finish(self) -> int:
        return 0

    def discard(self) -> None:
        pass


# --- supervisor ------------------------------------------------------------


class ElasticSupervisor(TrainSupervisor):
    """``TrainSupervisor`` with the cross-host choreography layered on:

    * every gate-accepted save is an all-hosts barrier; rank 0 writes
      (shared checkpoint storage; params are bitwise-replicated, so one
      writer IS the fleet's checkpoint) — with ``save_async`` the
      barrier fences the snapshot and the ``AsyncCheckpointer`` commits
      behind the step loop exactly as on one host,
    * recovery rendezvouses the next membership generation (waiting out
      a respawned replacement), restores rank 0 first (quarantine
      authority is singular), broadcasts the restored step, then proves
      the resume with a params-CRC barrier,
    * ``HostLossError``/``CoordinatorUnreachableError`` join the
      RECOVERABLE set: a lost peer is a restore-and-rejoin, never a
      dead run.
    """

    RECOVERABLE = TrainSupervisor.RECOVERABLE + (
        faults.HostLossError, faults.CoordinatorUnreachableError)

    def __init__(self, trainer, ckpt_dir: str, config: SupervisorConfig,
                 client: ElasticClient, elastic: ElasticConfig,
                 failure_log: Optional[faults.FailureLog] = None):
        super().__init__(trainer, ckpt_dir, config, failure_log)
        self.client = client
        self.elastic = elastic

    def _have_step(self, step: int) -> bool:
        from ..nnet import sharded_ckpt
        return os.path.isdir(sharded_ckpt.step_dir(self.ckpt_dir, step))

    def save(self) -> str:
        """Cross-host gate-accepted save: fence all hosts at the step,
        rank 0 writes.  A step already on disk is skipped WITHOUT a
        barrier — that is the rejoining replacement's entry anchor,
        whose peers (mid-recovery survivors) are not at an anchor point
        and must not be waited on."""
        from ..nnet import sharded_ckpt
        step = self.trainer.sample_counter
        if self._have_step(step):
            self.failure_log.record(
                'save_skipped', f'step {step} already checkpointed '
                '(rejoin anchor)', step=step)
            return sharded_ckpt.step_dir(self.ckpt_dir, step)
        vals = self.client.barrier('save', value=step)
        if len(set(vals.values())) != 1:
            raise faults.ElasticSyncError(
                f'hosts arrived at the save barrier with different '
                f'steps: {vals}')
        if self.elastic.rank == 0:
            return super().save()
        self.failure_log.record(
            'save_delegated', f'step {step} saved by rank 0', step=step)
        if self.config.on_save is not None:
            self.config.on_save(step)
        return sharded_ckpt.step_dir(self.ckpt_dir, step)

    def restore(self) -> int:
        """Recovery: resync membership (new generation), then the
        coordinated restore."""
        self.client.resync('restore', self.trainer.sample_counter)
        return self.restore_synced()

    def restore_synced(self) -> int:
        """The coordinated restore itself — also the entry path for a
        rejoining worker that already rendezvoused: rank 0 restores
        resiliently (it alone may quarantine corrupt steps), broadcasts
        the landed step, peers restore that exact step, and a CRC
        barrier proves every host resumed from identical params."""
        from ..obs import span
        tr = self.trainer
        with span('elastic.restore', 'elastic', rank=self.elastic.rank):
            return self._restore_synced_inner(tr)

    def _restore_synced_inner(self, tr) -> int:
        if self.elastic.rank == 0:
            step = super().restore()
            self.client.barrier('restore', value=step)
        else:
            vals = self.client.barrier('restore', value=None)
            step = vals.get(0)
            if step is None:
                raise faults.ElasticSyncError(
                    'restore barrier released without rank 0\'s step')
            tr.reset_transient_state()
            tr.load_training_state(self.ckpt_dir, step=int(step),
                                   restore_params=True,
                                   retry=self.config.retry)
            self.failure_log.record('restored',
                                    f'resumed from step {step} (rank 0 '
                                    'authority)', step=int(step))
        crc = params_crc(tr.params)
        vals = self.client.barrier('verify', value=f'{step}:{crc}')
        if len(set(vals.values())) != 1:
            raise faults.ElasticSyncError(
                f'post-restore state diverged across hosts: {vals}')
        return int(step)


# --- worker driver ---------------------------------------------------------


def _find_augment(it):
    from ..io.iter_augment import AugmentIterator
    node = it
    while node is not None:
        if isinstance(node, AugmentIterator):
            return node
        node = getattr(node, 'base', None)
    return None


def elastic_train(task) -> None:
    """One elastic worker's whole training run, driven from the CLI
    (``task`` is ``main.LearnTask`` after ``init()``).  Single
    supervised ``run()`` over ``num_round`` epoch passes of the
    host-sharded stream; recovery — local faults, peer loss, this
    host's own rejoin after a respawn — all lands inside it.

    The in-process convenience path (``dist.hosts=1`` with no
    coordinator) spins a local :class:`ElasticCoordinator` thread, so a
    single-host elastic run needs no launcher — that run IS the
    bitwise twin the multi-host drills compare against."""
    import sys

    from ..io.data import ThreadBufferIterator
    from ..nnet import sharded_ckpt

    tr = task.net_trainer
    ecfg = ElasticConfig(
        hosts=task.dist_hosts, rank=max(0, task.dist_rank),
        shards=task.dist_shards, coordinator=task.dist_coordinator,
        heartbeat=task.dist_heartbeat, rejoin=task.dist_rejoin,
        sync_timeout=task.dist_sync_timeout,
        incarnation=int(os.environ.get('CXXNET_ELASTIC_INCARNATION',
                                       '0') or 0),
        batch_size=tr.batch_size).resolve()
    if tr.update_period != 1:
        raise ValueError(
            'elastic training owns the accumulate/apply split '
            '(dist.shards micro-shards per step); update_period must '
            'stay 1')
    top = task.itr_train
    if top is None:
        raise ValueError('elastic training needs a data= section')
    it = top.base if isinstance(top, ThreadBufferIterator) else top
    if not it.is_replay_stable():
        raise ValueError(
            'elastic recovery re-winds the stream bitwise: the train '
            'iterator must be replay-stable (imgbin/imgbin_stream with '
            'shuffle=0)')
    aug = _find_augment(it)
    if aug is None:
        raise ValueError(
            'elastic host sharding rides the augment stage\'s pooled '
            'thunk stream — use an imgbin-family iterator '
            '(iter=imgbin/imgbinx/imgbin_stream)')
    if aug.nworker == 0:
        top.set_param('nworker', '1')
    top.set_param('elastic_hosts', str(ecfg.hosts))
    top.set_param('elastic_rank', str(ecfg.rank))
    top.set_param('batch_size', str(ecfg.batch_size // ecfg.hosts))

    coord = None
    addr = ecfg.coordinator
    if not addr or addr == 'local':
        if ecfg.hosts != 1:
            raise ValueError(
                'dist.coordinator=host:port is required when '
                'dist.hosts > 1 (the launcher passes it to every '
                'worker)')
        coord = ElasticCoordinator(1,
                                   heartbeat_timeout=ecfg.heartbeat * 5)
        addr = coord.start()
    client = ElasticClient(addr, ecfg.rank, ecfg.hosts,
                           heartbeat=ecfg.heartbeat,
                           sync_timeout=ecfg.sync_timeout)
    ckpt_dir = os.path.join(task.name_model_dir, 'elastic_state')
    sup_cfg = SupervisorConfig(
        batch_deadline=task.watchdog_deadline or None,
        max_restarts=task.max_restarts,
        nan_breaker=task.nan_breaker,
        save_every=task.save_every,
        keep_last=task.keep_last,
        # one writer: peers fence at the save barrier but never touch
        # the shared checkpoint storage
        save_async=task.save_async if ecfg.rank == 0 else 0,
        save_workers=task.save_workers,
        pipeline_stats=it.pipeline_stats())
    sup = ElasticSupervisor(tr, ckpt_dir, sup_cfg, client, ecfg)
    # every worker registers into the process-wide telemetry hub: the
    # elastic gauges ride /metrics and the generation + membership view
    # rides /statusz (each worker process has its own hub + endpoints)
    from ..obs import get_hub
    from ..utils.metric import StatSet
    estats = StatSet()

    def _refresh_elastic():
        estats.gauge('rank', ecfg.rank)
        estats.gauge('hosts', ecfg.hosts)
        estats.gauge('generation', client.generation)
        estats.gauge('incarnation', ecfg.incarnation)
        estats.gauge('steps', tr.sample_counter)
        estats.gauge('restarts', sup.restarts_total)

    get_hub().register_stats('elastic', estats, refresh=_refresh_elastic)
    get_hub().register_status(
        'elastic', lambda: {'rank': ecfg.rank, 'hosts': ecfg.hosts,
                            'generation': client.generation,
                            'incarnation': ecfg.incarnation,
                            'shards': list(ecfg.owned_shards),
                            'steps': int(tr.sample_counter),
                            'restarts': sup.restarts_total})
    try:
        client.connect()
        gen = client.rendezvous()
        if not task.silent:
            print(f'elastic worker rank {ecfg.rank}/{ecfg.hosts}: joined '
                  f'generation {gen} (shards {ecfg.owned_shards}, '
                  f'incarnation {ecfg.incarnation})', flush=True)
        if gen > 0 or sharded_ckpt.all_steps(ckpt_dir):
            # rejoin (or a cold full-fleet resume): adopt the committed
            # step every peer restores, before the first batch
            sup.restore_synced()
        entry = tr.sample_counter
        num_round = task.num_round
        tr.round = 0           # one supervised run; RNG keys on step only

        def factory(k):
            def passes():
                for _ in range(num_round):
                    for b in iter(it):
                        yield b
            return itertools.islice(passes(), k + entry, None)

        n = sup.run(factory,
                    make_stepper=lambda: ElasticStepper(tr, client, ecfg))
        final = tr.sample_counter
        crc = params_crc(tr.params)
        vals = client.barrier('done', value=f'{final}:{crc}')
        if len(set(vals.values())) != 1:
            raise faults.ElasticSyncError(
                f'final state diverged across hosts: {vals}')
        if ecfg.rank == 0:
            if task.itr_evals:
                sys.stderr.write('[dist]')
                for ev, name in zip(task.itr_evals, task.eval_names):
                    sys.stderr.write(tr.evaluate(ev, name))
                sys.stderr.write('\n')
                sys.stderr.flush()
            task.start_counter = max(task.start_counter, task.num_round)
            task._save_model()
        # the headline receipt every drill greps: step + params crc —
        # twins across host counts / fault plans must print the same crc
        print(f'[elastic] rank {ecfg.rank} done: steps={final} '
              f'updates={n} params_crc={crc} '
              f'generation={client.generation} '
              f'restarts={sup.restarts_total}', flush=True)
    finally:
        sup.close()
        client.close()
        if coord is not None:
            coord.stop()


# --- launcher --------------------------------------------------------------


class ElasticLauncher:
    """Spawn, monitor, and respawn the per-host worker processes (the
    single-machine stand-in for the fleet's cluster manager, like
    ``tools/launch_dist.py`` for the jax.distributed path).  Owns the
    coordinator, so losing any worker — rank 0 included — never kills
    the membership service.  A worker that dies (preemption drill,
    crash, kill -9) is respawned with an incremented
    ``CXXNET_ELASTIC_INCARNATION`` while the ``dist.rejoin`` budget
    lasts; it rejoins the rendezvous and the run continues.

    Fleet observability (doc/observability.md "Fleet view"): with
    ``fleet_port >= 0``, fleet-scoped ``slo_specs``, or a
    ``trace_merge`` path, every worker gets an ephemeral ObsServer
    (``obs.port=0``) announcing its port into a per-rank file; the
    launcher scrapes each rank's ``/metrics`` into ONE rank-labeled
    exposition (``obs.fleet_port=``), evaluates ``fleet.*`` SLOs across
    ranks from its own supervision loop, and at run end merges the
    per-rank Chrome traces into one Perfetto file with a lane per host.
    The scrape survives any rank's mid-run death — a dead rank's rows
    drop and ``cxxnet_fleet_ranks_alive`` dips until the respawn."""

    def __init__(self, argv: List[str], hosts: int, rejoin: int = 2,
                 heartbeat: float = 2.0, worker_cmd: Optional[List[str]]
                 = None, env: Optional[Dict[str, str]] = None,
                 cwd: Optional[str] = None, silent: bool = False,
                 poll: float = 0.2, fleet_port: int = -1,
                 sample_every: float = 0.5,
                 slo_specs: Optional[List[Tuple[str, str]]] = None,
                 trace_merge: str = ''):
        self.argv = list(argv)
        self.hosts = int(hosts)
        self.rejoin = int(rejoin)
        self.heartbeat = float(heartbeat)
        self.worker_cmd = worker_cmd
        self.env = env
        self.cwd = cwd
        self.silent = silent
        self.poll = float(poll)
        self.coordinator: Optional[ElasticCoordinator] = None
        self.respawns: List[Tuple[int, int]] = []   # (rank, incarnation)
        # fleet observability (None until the first worker announces)
        self.fleet_port = int(fleet_port)
        # <= 0 = "auto" (mirrors main._obs_start): the fleet default
        # cadence, never a negative clamped into a 100 Hz scrape loop
        self.sample_every = (float(sample_every)
                             if float(sample_every) > 0 else 0.5)
        self.slo_specs = list(slo_specs or [])
        self.trace_merge = str(trace_merge or '')
        self.fleet_server = None
        self.fleet_scraper = None
        self.fleet_slo = None
        self.fleet_verdicts: Dict[str, dict] = {}
        self.fleet_metrics = ''
        self._sampler = None
        self._obs_dir: Optional[str] = None
        self._ports: Dict[int, int] = {}     # rank -> announced port

    def _fleet_enabled(self) -> bool:
        return (self.fleet_port >= 0 or bool(self.trace_merge)
                or bool(self.slo_specs))

    def _port_file(self, rank: int) -> str:
        return os.path.join(self._obs_dir, f'rank{rank}.port')

    def _trace_file(self, rank: int) -> str:
        return os.path.join(self._obs_dir, f'trace_rank{rank}.json')

    def _spawn(self, rank: int, incarnation: int, addr: str):
        import subprocess
        import sys
        env = dict(os.environ if self.env is None else self.env)
        env['CXXNET_ELASTIC_INCARNATION'] = str(incarnation)
        # dev/CI harness semantics (like tools/launch_dist.py): every
        # worker is one "host" on this machine, pinned to CPU; a real
        # fleet runs one worker per host under its own scheduler
        env.setdefault('JAX_PLATFORMS', 'cpu')
        cmd = list(self.worker_cmd
                   or [sys.executable, '-m', 'cxxnet_tpu.main'])
        cmd += self.argv
        if self._obs_dir is not None:
            # ephemeral per-rank endpoint + port announce file; the
            # respawned incarnation re-announces into the same path, so
            # the scraper follows it to the new port
            env['CXXNET_OBS_PORT_FILE'] = self._port_file(rank)
            cmd += ['obs.port=0']
            if self.trace_merge:
                cmd += [f'obs.trace_export={self._trace_file(rank)}']
        cmd += [f'dist.hosts={self.hosts}', f'dist.rank={rank}',
                f'dist.coordinator={addr}']
        return subprocess.Popen(cmd, env=env, cwd=self.cwd)

    def _fleet_poll(self) -> None:
        """One supervision-loop beat of the fleet leg: adopt newly
        announced rank ports, stand the merged endpoint + SLO engine up
        once the first rank answers, and pace the fleet sampler."""
        if self._obs_dir is None:
            return
        from ..obs.fleet import FleetScraper, FleetServer
        for rank in range(self.hosts):
            try:
                with open(self._port_file(rank), encoding='utf-8') as f:
                    port = int(f.read().strip() or 0)
            except (OSError, ValueError):
                continue
            if port and self._ports.get(rank) != port:
                self._ports[rank] = port
                if self.fleet_scraper is None:
                    self.fleet_scraper = FleetScraper()
                self.fleet_scraper.add_target(
                    rank, f'http://127.0.0.1:{port}')
        if self.fleet_scraper is None:
            return
        if self._sampler is None:
            from ..obs.history import GaugeSampler
            self._sampler = GaugeSampler(self.fleet_scraper.source,
                                         period=self.sample_every)
            if self.slo_specs:
                from ..obs.slo import SLOEngine, SLOSpec
                self.fleet_slo = SLOEngine(self._sampler.history)
                for name, text in self.slo_specs:
                    self.fleet_slo.add(SLOSpec.parse(name, text))
                self._sampler.add_listener(self.fleet_slo.on_tick)
        if self.fleet_server is None and self.fleet_port >= 0:
            self.fleet_server = FleetServer(self.fleet_scraper,
                                            engine=self.fleet_slo,
                                            port=self.fleet_port)
            if not self.silent:
                print(f'obs: fleet telemetry on {self.fleet_server.url} '
                      '(/metrics /statusz /healthz /slos, rank labels)',
                      flush=True)
        # ONE scrape per beat serves both consumers: the sampler's
        # source() pass feeds the SLO history AND refreshes the
        # scraper's per-rank snapshots behind last_merged() — a second
        # scrape here would double every rank's GET (and double the
        # stall window a hung rank can inflict on this loop)
        self._sampler.maybe_tick()

    def _fleet_close(self) -> None:
        if self.fleet_scraper is not None:
            self.fleet_metrics = self.fleet_scraper.last_merged()
        if self.fleet_slo is not None:
            self.fleet_verdicts = self.fleet_slo.status_view()
            if not self.silent:
                from ..obs.slo import summary_lines
                for line in summary_lines(self.fleet_verdicts):
                    print(f'[fleet] {line}', flush=True)
        if self.fleet_server is not None:
            self.fleet_server.close(timeout=5.0)
        if self._sampler is not None:
            self._sampler.close(timeout=5.0)
        if self.trace_merge and self._obs_dir is not None:
            from ..obs.fleet import merge_chrome_traces
            out = merge_chrome_traces(
                {r: self._trace_file(r) for r in range(self.hosts)},
                self.trace_merge)
            if out and not self.silent:
                print(f'obs: merged fleet Chrome trace -> {out} '
                      '(one lane per host; load in Perfetto)', flush=True)
        if self._obs_dir is not None:
            import shutil
            shutil.rmtree(self._obs_dir, ignore_errors=True)
            self._obs_dir = None

    def run(self) -> int:
        coord = ElasticCoordinator(self.hosts,
                                   heartbeat_timeout=self.heartbeat * 5)
        self.coordinator = coord
        addr = coord.start()
        if self._fleet_enabled():
            import tempfile
            self._obs_dir = tempfile.mkdtemp(prefix='cxxnet-fleet-')
        incarn = {r: 0 for r in range(self.hosts)}
        procs = {r: self._spawn(r, 0, addr) for r in range(self.hosts)}
        done: Dict[int, int] = {}
        budget = self.rejoin
        rc_final = 0
        try:
            while len(done) < self.hosts:
                time.sleep(self.poll)
                for rank, p in list(procs.items()):
                    if rank in done or p.poll() is None:
                        continue
                    rc = p.returncode
                    if rc == 0:
                        done[rank] = 0
                        continue
                    if budget > 0:
                        budget -= 1
                        incarn[rank] += 1
                        self.respawns.append((rank, incarn[rank]))
                        if not self.silent:
                            print(f'elastic launcher: rank {rank} exited '
                                  f'rc={rc} — respawning (incarnation '
                                  f'{incarn[rank]}, {budget} rejoin(s) '
                                  'left)', flush=True)
                        procs[rank] = self._spawn(rank, incarn[rank],
                                                  addr)
                    else:
                        rc_final = rc
                        # lint: allow(fault-taxonomy): launcher-internal control flow, caught below
                        raise _LaunchAborted(rank, rc)
                if not done:
                    # sample only while NO rank has finished cleanly: a
                    # crashed/killed rank never enters `done` (it gets
                    # respawned), so every MID-run death still dips
                    # ranks_alive and the SLOs see it — but once the
                    # first rank completes, the fleet is winding down
                    # and a staggered-exit beat would overwrite the
                    # last full view with a partial one (and book a
                    # bogus teardown breach)
                    self._fleet_poll()
        except _LaunchAborted as e:
            if not self.silent:
                print(f'elastic launcher: rank {e.rank} failed rc='
                      f'{e.rc} with no rejoin budget left — aborting',
                      flush=True)
            for p in procs.values():
                if p.poll() is None:
                    p.kill()
            for p in procs.values():
                p.wait()
        finally:
            # NO parting scrape: the workers are (mostly) gone by now,
            # and sampling the empty fleet would overwrite the last
            # live snapshot with an all-dead window and book a bogus
            # teardown breach — fleet_metrics/fleet_verdicts keep the
            # newest state observed while ranks were answering
            self._fleet_close()
            coord.stop()
        return rc_final


class _LaunchAborted(Exception):
    def __init__(self, rank: int, rc: int):
        self.rank, self.rc = rank, rc
        super().__init__(f'rank {rank} rc={rc}')
