"""Device mesh + sharding rules.

This replaces the reference's parallelism machinery wholesale
(``NeuralNetThread`` per GPU + mshadow-ps Push/PullReq,
``src/nnet/neural_net-inl.hpp:303-628``, ``updater/async_updater-inl.hpp``):
instead of explicit per-layer gradient push/pull with priorities, we lay out
a ``jax.sharding.Mesh`` with a ``data`` axis (data parallelism — the
reference's only mode) and an optional ``model`` axis (tensor parallelism,
beyond the reference), annotate leaf shardings, and let XLA's SPMD
partitioner insert ICI collectives (all-reduce for replicated-param grads,
all-gather/reduce-scatter around sharded matmuls) with latency hiding —
the compiler-native form of the reference's WFBP overlap.

Sharding rules for the 2-D mesh ``(data, model)``:
* batch:   P('data') on the leading axis,
* fullc wmat ``(nin, nh)``: P(None, 'model') when nh divides the axis —
  column-parallel dense layers (the 4096-wide AlexNet FCs are the case
  where this pays),
* fullc bias ``(nh,)``: P('model'),
* conv wmat HWIO: P(None, None, None, 'model') sharding output channels
  (disabled for grouped conv where channel locality matters),
* everything else replicated.

Scope note: this CNN tensor parallelism is **weight-sharding only** —
activations stay replicated, so every sharded layer boundary implies an
all-gather that XLA inserts.  That is deliberate: for the CNN zoo (AlexNet
era, model fits one chip many times over) TP is a capability demo exercised
by the multichip dryrun, not a perf path — data parallelism is the
production axis.  The fully sharded-activation design (row/column parallel
pairs with psum, sequence/expert axes) lives in ``models/transformer.py``,
where model scale actually demands it.

Optimizer state and gradient accumulators inherit the param sharding, so
the optimizer update runs fully sharded — the TPU equivalent of the
reference's ``update_on_server`` without a server.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..layers import base as lbase


def build_mesh(devices: Optional[List] = None, tp: int = 1) -> Mesh:
    """Build a (data, model) mesh over the given jax devices."""
    devs = list(devices) if devices else jax.devices()
    n = len(devs)
    if n % tp:
        raise ValueError(f'tensor_parallel={tp} must divide {n} devices')
    arr = np.asarray(devs).reshape(n // tp, tp)
    return Mesh(arr, ('data', 'model'))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P('data'))


def _leaf_spec(type_id: int, field: str, shape, num_group: int,
               tp: int) -> P:
    if tp <= 1:
        return P()
    if type_id == lbase.kFullConnect and field == 'wmat':
        if shape[1] % tp == 0:
            return P(None, 'model')
    elif type_id == lbase.kFullConnect and field == 'bias':
        if shape[0] % tp == 0:
            return P('model')
    elif type_id == lbase.kConv and field == 'wmat' and num_group == 1:
        if shape[3] % tp == 0:
            return P(None, None, None, 'model')
    elif type_id == lbase.kConv and field == 'bias' and num_group == 1:
        if shape[0] % tp == 0:
            return P('model')
    return P()


def param_shardings(net, params, mesh: Mesh) -> Dict:
    """Per-leaf NamedSharding pytree matching the params structure."""
    tp = mesh.shape.get('model', 1)
    out = {}
    for key, fields in params.items():
        i = int(key)
        info = net.cfg.layers[i]
        layer = net.layers[i]
        out[key] = {
            f: NamedSharding(mesh, _leaf_spec(info.type, f, v.shape,
                                              layer.param.num_group, tp))
            for f, v in fields.items()}
    return out
