"""Device mesh + sharding rules.

This replaces the reference's parallelism machinery wholesale
(``NeuralNetThread`` per GPU + mshadow-ps Push/PullReq,
``src/nnet/neural_net-inl.hpp:303-628``, ``updater/async_updater-inl.hpp``):
instead of explicit per-layer gradient push/pull with priorities, we lay out
a ``jax.sharding.Mesh`` with a ``data`` axis (data parallelism — the
reference's only mode) and an optional ``model`` axis (tensor parallelism,
beyond the reference), annotate leaf shardings, and let XLA's SPMD
partitioner insert ICI collectives (all-reduce for replicated-param grads,
all-gather/reduce-scatter around sharded matmuls) with latency hiding —
the compiler-native form of the reference's WFBP overlap.

Sharding rules for the 2-D mesh ``(data, model)``:
* batch:   P('data') on the leading axis,
* TP-eligible layers (fullc; ungrouped conv) pair Megatron-style
  column/row parallelism along each DATAFLOW chain: a column-parallel
  layer shards its OUTPUT features — fullc wmat ``(nin, nh)`` →
  P(None, 'model'), conv HWIO → P(None, None, None, 'model'), bias
  P('model') — leaving its activation sharded on ``model``; an eligible
  layer whose INPUT activation is model-sharded goes row-parallel —
  fullc P('model', None), conv P(None, None, 'model', None), bias
  replicated — consuming the shards in place so a single psum restores
  the replicated activation.  Paired boundaries therefore cost one
  all-reduce instead of the all-gather-per-layer of naive
  output-sharding-everywhere (the AlexNet fc6→fc7→fc8 chain and each
  Inception tower's 1x1→3x3 pair are the cases where this pays).
  Shardedness is tracked per graph node (``param_shardings``), flowing
  through elementwise/pooling layers and stopping at flatten/LRN/concat,
  so branched nets pair within a branch rather than across unrelated
  chains.  XLA's SPMD partitioner propagates the activation shardings
  and inserts the collectives; a layer whose feature axis does not
  divide ``tp`` falls back to the other orientation, then to
  replication.
* everything else replicated.

Scope note: for the CNN zoo (AlexNet era, model fits one chip many times
over) TP remains a capability demo exercised by the multichip dryrun and
the tp>1 oracle tests — data parallelism is the production axis.  The
hand-laid-out sharded-activation design (row/column pairs with explicit
psum, sequence/expert axes) lives in ``models/transformer.py``, where
model scale actually demands it.

Optimizer state and gradient accumulators inherit the param sharding, so
the optimizer update runs fully sharded — the TPU equivalent of the
reference's ``update_on_server`` without a server.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..layers import base as lbase


def build_mesh(devices: Optional[List] = None, tp: int = 1) -> Mesh:
    """Build a (data, model) mesh over the given jax devices."""
    devs = list(devices) if devices else jax.devices()
    n = len(devs)
    if n % tp:
        raise ValueError(f'tensor_parallel={tp} must divide {n} devices')
    arr = np.asarray(devs).reshape(n // tp, tp)
    return Mesh(arr, ('data', 'model'))


def parse_shard(spec: str) -> int:
    """``serve.shard`` grammar (doc/serving.md "Sharded serving"):
    ``''`` / ``'tp:1'`` = single device, ``'tp:N'`` = tensor-parallel
    decode over the first N devices.  Returns the model-axis width."""
    text = str(spec or '').strip().lower()
    if text in ('', 'tp:1'):
        return 1
    if text.startswith('tp:'):
        try:
            n = int(text[3:])
        except ValueError:
            n = 0
        if n >= 1:
            return n
    raise ValueError(f"serve.shard must be '' or 'tp:N', got {spec!r}")


def decode_mesh(tp: int, devices: Optional[List] = None) -> Mesh:
    """The 1xN ``('data', 'model')`` serving mesh over the first ``tp``
    devices — what ``serve.shard=tp:N`` builds (the decode engine's
    data axis is its slot batch, never device-sharded)."""
    devs = list(devices) if devices is not None else jax.devices()
    if tp > len(devs):
        raise ValueError(f'serve.shard=tp:{tp} needs {tp} devices, '
                         f'host has {len(devs)}')
    return build_mesh(devs[:tp], tp=tp)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P('data'))


def _layer_tp_mode(type_id: int, fields, num_group: int, tp: int,
                   prefer: str) -> Optional[str]:
    """Pick 'col' / 'row' / None for one layer: ``prefer`` first, the
    other orientation if the preferred feature axis doesn't divide
    ``tp``, None (replicate) if neither does."""
    w = fields.get('wmat')
    if w is None:
        return None
    if type_id == lbase.kFullConnect:
        ok = {'col': w.shape[1] % tp == 0, 'row': w.shape[0] % tp == 0}
    elif type_id == lbase.kConv and num_group == 1:
        ok = {'col': w.shape[3] % tp == 0, 'row': w.shape[2] % tp == 0}
    else:
        return None
    for mode in (prefer, 'row' if prefer == 'col' else 'col'):
        if ok[mode]:
            return mode
    return None


_TP_SPECS = {
    # (type, mode) -> field -> PartitionSpec
    (lbase.kFullConnect, 'col'): {'wmat': P(None, 'model'),
                                  'bias': P('model')},
    (lbase.kFullConnect, 'row'): {'wmat': P('model', None), 'bias': P()},
    (lbase.kConv, 'col'): {'wmat': P(None, None, None, 'model'),
                           'bias': P('model')},
    (lbase.kConv, 'row'): {'wmat': P(None, None, 'model', None),
                           'bias': P()},
}


# Single-in/single-out layers whose output keeps the input's channel/
# feature sharding: elementwise activations and spatial poolings.  NOT
# flatten (interleaves channels into features), NOT LRN (cross-channel
# window needs a halo), NOT concat/split (multi-node) — after those the
# activation is treated as replicated and the next eligible layer starts
# a fresh col/row pair.
_SHARDING_TRANSPARENT = frozenset((
    lbase.kRectifiedLinear, lbase.kSigmoid, lbase.kTanh, lbase.kSoftplus,
    lbase.kDropout, lbase.kMaxPooling, lbase.kSumPooling, lbase.kAvgPooling,
    lbase.kXelu, lbase.kReluMaxPooling, lbase.kInsanity,
    lbase.kInsanityPooling, lbase.kPRelu, lbase.kBatchNorm, lbase.kBias,
))


def param_shardings(net, params, mesh: Mesh) -> Dict:
    """Per-leaf NamedSharding pytree matching the params structure.

    With ``tp > 1``, eligible layers pair column/row parallelism along
    each DATAFLOW chain (see module docstring): a layer whose input
    activation is model-sharded — because its producer was column-parallel
    and everything in between preserves channel sharding — goes
    row-parallel (consuming the shards in place, one psum restores
    replication); otherwise it starts a new pair as column-parallel.
    Tracking shardedness per node instead of alternating a global parity
    keeps the one-psum-per-pair premise true on branched nets
    (Inception towers pair within each tower), where a sorted-index walk
    would mark a trunk-fed layer 'row' and force GSPMD to reshard."""
    tp = mesh.shape.get('model', 1)
    out = {}
    sharded_nodes = set()   # node ids whose activation is model-sharded
    for i, info in enumerate(net.cfg.layers):
        key = str(i)
        fields = params.get(key)
        if fields is not None:
            mode = None
            if tp > 1:
                prefer = ('row' if any(n in sharded_nodes
                                       for n in info.nindex_in) else 'col')
                mode = _layer_tp_mode(info.type, fields,
                                      net.layers[i].param.num_group, tp,
                                      prefer)
            if mode is None:
                specs = {f: P() for f in fields}
            else:
                table = _TP_SPECS[(info.type, mode)]
                # bias divisibility rides the wmat check for 'col' (same axis)
                specs = {f: table.get(f, P()) for f in fields}
            out[key] = {f: NamedSharding(mesh, specs[f]) for f in fields}
            if mode == 'col':
                sharded_nodes.update(info.nindex_out)
            elif (mode is None and info.type in _SHARDING_TRANSPARENT
                  and any(n in sharded_nodes for n in info.nindex_in)):
                # parameterized but channel-wise layers (batch_norm, bias,
                # prelu) pass a sharded activation through unchanged —
                # their per-channel params stay replicated; without this
                # the conv->bn->relu->conv chains of Inception-BN could
                # never form a col/row pair
                sharded_nodes.update(info.nindex_out)
            else:                # row/other: psum-restored or replicated out
                sharded_nodes.difference_update(info.nindex_out)
        elif (info.type in _SHARDING_TRANSPARENT
              and any(n in sharded_nodes for n in info.nindex_in)):
            sharded_nodes.update(info.nindex_out)
        else:
            sharded_nodes.difference_update(info.nindex_out)
    return out
