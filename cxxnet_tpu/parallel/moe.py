"""Expert parallelism: top-1 (switch) mixture-of-experts with all_to_all
dispatch.

No counterpart in the reference (SURVEY.md §2.5 lists expert parallelism
as absent); built TPU-first: experts are sharded over a mesh axis, tokens
are routed with two ``lax.all_to_all`` collectives (dispatch + combine)
that ride ICI, and every shape is static (capacity-bounded routing with
token dropping, the standard Switch-Transformer discipline) so the whole
thing jits.

Layout convention inside shard_map over ``axis_name`` (n devices):
* tokens: local ``(T, D)`` (batch/sequence sharded outside),
* expert weights: local ``(E/n, D, F)`` / ``(E/n, F, D)`` — each device
  owns ``E/n`` experts,
* gate: ``(D, E)`` replicated.

Dispatch: every device builds a per-expert capacity buffer ``(E, C, D)``
from its own tokens, all_to_all ships expert-group ``e`` to the device
owning it → ``(E/n, n*C, D)``; the expert FFN runs batched over its
``n*C`` slots; the reverse all_to_all brings results home and the combine
einsum scatters them back to token order scaled by the gate probability.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def switch_gate(x, gate_w, capacity: int):
    """Top-1 gating with capacity.  x:(T,D), gate_w:(D,E) ->
    dispatch:(T,E,C) 0/1, combine:(T,E,C) = dispatch * gate_prob,
    aux: {'balance_loss', 'drop_frac'}.

    ``balance_loss`` is the Switch auxiliary load-balancing loss
    ``E * sum_e f_e * P_e`` (f_e = routed token fraction, P_e = mean router
    probability; minimum 1.0 at uniform routing) — differentiable through
    P_e, so training pressure spreads the experts.  ``drop_frac`` is the
    fraction of tokens lost to the capacity bound (metric only,
    stop-gradient)."""
    logits = x @ gate_w.astype(x.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # (T,)
    sel = jax.nn.one_hot(expert, gate_w.shape[1], dtype=jnp.float32)
    pos = jnp.cumsum(sel, axis=0) * sel                      # 1-based slot
    keep = (pos > 0) & (pos <= capacity)
    slot = jnp.where(keep, pos - 1, 0).astype(jnp.int32)
    dispatch = (jax.nn.one_hot(slot.max(axis=-1), capacity,
                               dtype=jnp.float32)
                [:, None, :] * (sel * keep)[:, :, None])     # (T,E,C)
    gate_prob = (probs * sel).sum(-1, keepdims=True)         # (T,1)
    combine = dispatch * gate_prob[:, :, None]
    num_experts = gate_w.shape[1]
    f = sel.mean(axis=0)                                     # (E,)
    p = probs.mean(axis=0)                                   # (E,)
    aux = {
        'balance_loss': num_experts * jnp.sum(f * p),
        'drop_frac': lax.stop_gradient(
            1.0 - dispatch.sum() / jnp.float32(x.shape[0])),
    }
    return dispatch, combine, aux


def moe_ffn_local(x, gate_w, w1, w2, *, axis_name=None,
                  capacity_factor: float = 2.0):
    """Switch FFN.  Call INSIDE shard_map when ``axis_name`` is given
    (w1/w2 then hold the local expert shard); standalone single-device
    otherwise (w1/w2 hold all experts).

    x: (T, D) local tokens; w1: (E_local, D, F); w2: (E_local, F, D);
    gate_w: (D, E_global).  Returns (out (T, D), aux dict); aux values
    are means over the ``axis_name`` group when given.
    """
    n = 1 if axis_name is None else lax.psum(1, axis_name)
    e_local = w1.shape[0]
    e_global = e_local * n
    t = x.shape[0]
    capacity = max(1, int(capacity_factor * t / e_global))
    dispatch, combine, aux = switch_gate(x, gate_w, capacity)
    if axis_name is not None:
        aux = {k: lax.pmean(v, axis_name) for k, v in aux.items()}
    xf = x.astype(jnp.float32)
    buf = jnp.einsum('td,tec->ecd', xf, dispatch)            # (E, C, D)
    if axis_name is not None:
        # ship expert-group e to its owner; receive our experts' tokens
        # from every peer: (E, C, D) -> (E_local, n*C, D)
        buf = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=1,
                             tiled=True)
    h = jax.nn.relu(jnp.einsum('ecd,edf->ecf', buf,
                               w1.astype(jnp.float32)))
    y = jnp.einsum('ecf,efd->ecd', h, w2.astype(jnp.float32))
    if axis_name is not None:
        # (E_local, n*C, D) -> (E, C, D): results back to the sender
        y = lax.all_to_all(y, axis_name, split_axis=1, concat_axis=0,
                           tiled=True)
    out = jnp.einsum('ecd,tec->td', y, combine)
    return out.astype(x.dtype), aux


def moe_ffn_reference(x, gate_w, w1, w2, capacity_factor: float = 2.0):
    """Single-device oracle: same routing/capacity semantics, dense loop
    over all experts.  w1: (E, D, F), w2: (E, F, D).
    Returns (out, aux) like moe_ffn_local."""
    return moe_ffn_local(x, gate_w, w1, w2, axis_name=None,
                         capacity_factor=capacity_factor)
