"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.5: model always
fits one device), but this framework treats multi-dimensional sharding as
first-class.  The TPU-idiomatic formulation: stage parameters are stacked
on a leading axis sharded over the ``pipe`` mesh axis, the schedule is a
``lax.scan`` over ticks, and stage-to-stage activation transfer is a
``lax.ppermute`` — XLA overlaps the permute with the next tick's compute.

The schedule is plain GPipe: with S stages and M microbatches the loop
runs ``M + S - 1`` ticks; stage 0 injects microbatch ``t`` at tick ``t``,
stage ``S-1`` emits microbatch ``t-(S-1)``.  Bubble fraction
``(S-1)/(M+S-1)`` — pick ``M >= 4*S`` in real runs.  Backward is ordinary
``jax.grad`` through the scan (ppermute transposes to the reverse
permute), which yields the mirrored backward pipeline for free.

Used inside a ``shard_map`` whose mesh includes ``axis_name``; composes
with sequence/tensor/expert collectives on other axes because everything
lives in one shard_map body (see models/transformer.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_stage_loop(stage_fn: Callable, stage_params, xs,
                        *, axis_name: str, num_stages: int,
                        has_aux: bool = False):
    """Run microbatches through the pipeline.  Call INSIDE shard_map.

    stage_fn(params, x_mb) -> y_mb with ``y_mb.shape == x_mb.shape``
    (homogeneous stages — the transformer-block case); with
    ``has_aux=True`` it returns ``(y_mb, aux)`` where aux is a pytree of
    scalars (e.g. MoE balance loss / drop stats).
    stage_params: local shard of the stacked params — leaves have leading
    dim 1 (the stage owned by this device); passed to stage_fn squeezed.
    xs: (M, mb, ...) microbatches, replicated over ``axis_name``.
    Returns (M, mb, ...) outputs replicated over ``axis_name`` (the last
    stage's result is broadcast with a masked psum); with ``has_aux=True``
    returns ``(outs, aux)`` where each aux leaf is summed over stages and
    averaged over microbatches — bubble ticks (a stage running on garbage
    before/after its live window) are masked out of the average.
    """
    S = num_stages
    idx = lax.axis_index(axis_name)
    p_local = jax.tree.map(lambda a: a[0], stage_params)
    M = xs.shape[0]
    T = M + S - 1
    # stage i receives from i-1; no wraparound (stage 0 injects fresh data)
    perm = [(i, i + 1) for i in range(S - 1)]

    def tick(carry, t):
        state, outs, aux_acc = carry
        inj = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), 0,
                                       keepdims=False)
        x_in = jnp.where(idx == 0, inj, state)
        if has_aux:
            y, aux = stage_fn(p_local, x_in)
            # stage idx processes live microbatch m = t - idx
            valid = jnp.logical_and(t >= idx, t < idx + M).astype(jnp.float32)
            aux_acc = jax.tree.map(lambda a, v: a + valid * v, aux_acc, aux)
        else:
            y = stage_fn(p_local, x_in)
        widx = jnp.clip(t - (S - 1), 0, M - 1)
        old = lax.dynamic_index_in_dim(outs, widx, 0, keepdims=False)
        write = jnp.logical_and(idx == S - 1, t >= S - 1)
        outs = lax.dynamic_update_index_in_dim(
            outs, jnp.where(write, y, old), widx, 0)
        state = lax.ppermute(y, axis_name, perm) if perm else y
        return (state, outs, aux_acc), None

    state0 = jnp.zeros_like(xs[0])
    outs0 = jnp.zeros_like(xs)
    if has_aux:
        _, aux_shape = jax.eval_shape(stage_fn, p_local, xs[0])
        aux0 = jax.tree.map(lambda s: jnp.zeros(s.shape, jnp.float32),
                            aux_shape)
    else:
        aux0 = ()
    (_, outs, aux_acc), _ = lax.scan(tick, (state0, outs0, aux0),
                                     jnp.arange(T))
    # broadcast the last stage's outputs to every pipe rank
    outs = lax.psum(jnp.where(idx == S - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    if not has_aux:
        return outs
    # per-stage mean over its M live microbatches, summed across stages
    aux_out = jax.tree.map(lambda a: lax.psum(a / M, axis_name), aux_acc)
    return outs, aux_out


def split_microbatches(x, num_microbatches: int):
    """(B, ...) -> (M, B/M, ...)."""
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f'batch {b} not divisible by microbatches {num_microbatches}')
    return x.reshape(num_microbatches, b // num_microbatches, *x.shape[1:])
