"""Sequence / context parallelism: ring attention and all-to-all (Ulysses).

The reference is a fixed-shape CNN trainer with no sequence axis
(SURVEY.md §5: long-context is absent there), but this framework treats
long-context scale as first-class: attention over sequences longer than one
chip's memory runs sequence-sharded across the mesh.

Two interchangeable strategies, both pure ``shard_map`` programs whose
collectives ride ICI:

* ``ring_attention`` — K/V blocks rotate around the ring
  (``lax.ppermute``) while each device holds its Q shard; softmax is
  accumulated online flash-style (running max + denominator), so the full
  ``(seq, seq)`` score matrix never materializes.  Communication overlaps
  with the per-block matmuls under XLA's async collectives.
* ``ulysses_attention`` — ``lax.all_to_all`` re-shards from
  sequence-parallel to head-parallel, runs dense local attention per head
  group, and re-shards back.  Cheaper for moderate sequence lengths when
  heads >= devices.

Both compute exact attention: outputs match single-device attention to
numerical tolerance (tests/test_sequence_parallel.py).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:                                    # jax >= 0.5 spelling
    from jax import shard_map
except ImportError:                     # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map


def _local_attention(q, k, v, scale, mask=None):
    """Dense attention on local blocks.  q:(b,sq,h,d) k,v:(b,sk,h,d)."""
    scores = jnp.einsum('bqhd,bkhd->bhqk', q, k) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum('bhqk,bkhd->bqhd', p, v)


def attention_reference(q, k, v, causal: bool = False):
    """Single-device reference attention (the correctness oracle)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    mask = None
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), bool))[None, None]
    return _local_attention(q, k, v, scale, mask)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool):
    """Per-shard body: accumulate attention over all K/V blocks as they
    rotate around the ring."""
    n = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape
    sk = k.shape[1]

    def body(step, carry):
        k_blk, v_blk, acc, m, l = carry
        # global block index the K/V currently held came from
        src = (my_idx + step) % n
        scores = jnp.einsum('bqhd,bkhd->bhqk', q, k_blk) * scale
        if causal:
            q_pos = my_idx * sq + jnp.arange(sq)
            k_pos = src * sk + jnp.arange(sk)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)
        m_blk = jnp.max(scores, axis=-1)                     # (b,h,q)
        m_new = jnp.maximum(m, m_blk)
        # guard fully-masked rows (exp(-inf - -inf)) with a finite max
        m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
        p = jnp.exp(scores - m_safe[..., None])
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        corr = jnp.exp(jnp.where(jnp.isneginf(m), m_safe, m) - m_safe)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = (acc * corr[..., None]
                   + jnp.einsum('bhqk,bkhd->bhqd', p, v_blk))
        # rotate K/V to the next device in the ring
        perm = [(i, (i - 1) % n) for i in range(n)]
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (k_next, v_next, acc_new, m_new, l_new)

    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    m0 = jnp.full((b, h, sq), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    # constants start "unvarying" under shard_map's varying-manual-axes
    # tracking; mark them varying over the ring axis for the scan carry
    try:
        acc0, m0, l0 = (lax.pcast(x, (axis_name,), to='varying')
                        for x in (acc0, m0, l0))
    except (AttributeError, TypeError):   # older jax without vma tracking
        pass
    _, _, acc, m, l = lax.fori_loop(0, n, body, (k, v, acc0, m0, l0))
    l = jnp.where(l == 0.0, 1.0, l)
    out = acc / l[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)   # (b,sq,h,d)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = 'data',
                   causal: bool = False):
    """Exact attention over sequence-sharded q/k/v.

    Arrays are global ``(batch, seq, heads, head_dim)``; the sequence axis
    is sharded over ``axis_name`` of ``mesh``.
    """
    spec = P(None, axis_name, None, None)
    fn = shard_map(
        functools.partial(_ring_attention_local, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool,
                   use_flash: bool):
    """seq-sharded -> all_to_all -> head-sharded dense attention -> back."""
    n = lax.psum(1, axis_name)
    # (b, s/n, h, d) -> (b, s, h/n, d): gather sequence, scatter heads
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    from ..ops import pallas_kernels as pk
    if use_flash and pk.pltpu is not None:
        # fused online-softmax kernel: O(seq) memory for the local dense
        # attention after the head scatter (dense fallback when the TPU
        # pallas memory spaces aren't importable)
        out = pk.flash_attention(q, k, v, causal=causal)
    else:
        scale = 1.0 / math.sqrt(q.shape[-1])
        mask = None
        if causal:
            s = q.shape[1]
            mask = jnp.tril(jnp.ones((s, s), bool))[None, None]
        out = _local_attention(q, k, v, scale, mask)
    # (b, s, h/n, d) -> (b, s/n, h, d)
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                         tiled=True)
    return out


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = 'data',
                      causal: bool = False):
    """All-to-all (Ulysses) sequence parallelism; heads must divide the
    axis size."""
    if q.shape[2] % mesh.shape[axis_name]:
        raise ValueError('ulysses: heads must divide the mesh axis')
    from ..ops.pallas_kernels import attn_use_flash
    # post-gather local shape: full seq, heads split over the axis
    use_flash = attn_use_flash(
        q.shape[1], batch=q.shape[0],
        heads=max(1, q.shape[2] // mesh.shape[axis_name]))
    spec = P(None, axis_name, None, None)
    local = functools.partial(_ulysses_local, axis_name=axis_name,
                              causal=causal, use_flash=use_flash)
    wrap = functools.partial(shard_map, local, mesh=mesh,
                             in_specs=(spec, spec, spec), out_specs=spec)
    if not use_flash:
        fn = wrap()
    else:
        # pallas_call doesn't propagate varying-manual-axes through its
        # interpreter yet; jax's own error message prescribes disabling the
        # replication check (check_rep on older jax spellings)
        try:
            fn = wrap(check_vma=False)
        except TypeError:
            fn = wrap(check_rep=False)
    return fn(q, k, v)
