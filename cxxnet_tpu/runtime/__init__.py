"""Native runtime bindings (C++ data loader, ctypes)."""

from .native import NativePageReader, decode_jpeg, native_available
