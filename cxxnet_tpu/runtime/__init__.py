"""Runtime services: native bindings and the fault-tolerant training
runtime (retry/backoff, fault injection, train supervision)."""

from .native import NativePageReader, decode_jpeg, native_available
from . import faults  # noqa: F401
from .faults import (CheckpointCorruptError, DivergenceError,  # noqa: F401
                     FailureLog, FaultInjected, FaultPlan,
                     PipelineStallError, RetryError, RetryPolicy,
                     TrainingFault, active_plan, clear_plan,
                     global_failure_log, install_plan)
from .async_ckpt import (AsyncCheckpointer, host_tree,  # noqa: F401
                         snapshot_tree)
from .supervisor import SupervisorConfig, TrainSupervisor  # noqa: F401
