"""Asynchronous checkpointing: take the save path off the step loop.

The synchronous save path (``nnet/checkpoint.py``, ``nnet/sharded_ckpt.py``)
serializes the full param tree, fsyncs, and renames before the next batch
can run — at aggressive ``save_every`` settings (exactly what a
preemptible fleet wants) the step loop pays the full storage latency at
every boundary.  This module hides that latency the same way the training
step hides gradient communication (arXiv:1711.00705's overlap discipline,
applied to checkpoint I/O):

1. **Snapshot** — at the save boundary the param/opt trees are copied
   *on device* (:func:`snapshot_tree`): a cheap, non-blocking dispatch
   that creates fresh buffers, so the trainer's next donated step
   (``train_step`` donates params/opt_state/grad_acc) cannot invalidate
   what the writer is about to read.  The device→host transfer happens in
   the background, off the step loop.
2. **Background write** — :class:`AsyncCheckpointer` hands the snapshot to
   a committer thread which materializes the host copy and writes the
   tree via ``sharded_ckpt.save_tree_native``: per-shard files written in
   parallel on a small pool (plain write+fsync — the DIRECTORY rename is
   the atomic unit, so per-file atomicity dances would only add fsyncs),
   one rename commits the step, and the crc32 ``ckpt_digest.json``
   sidecar (same format ``verify_step_dir`` checks, accumulated from the
   in-memory bytes, landed via ``atomic_write``) follows — so
   verification, quarantine, and ``restore_resilient`` treat async and
   sync checkpoints identically.
3. **Double buffer** — at most one save is in flight.  A second boundary
   arriving before the previous write commits blocks only until that
   commit lands (never mid-step), so a slow disk degrades save cadence,
   not step integrity.

Failure semantics match the sync path, one boundary late: the background
write runs under the same ``RetryPolicy`` and the same
``faults.checkpoint_write_attempt`` injection hook; an exhausted retry is
recorded in the ``FailureLog`` (``async_save_failed``) and re-raised at
the next barrier (``submit``/``wait``).  The restore path barriers with
:meth:`AsyncCheckpointer.drain` instead — a failed *save* must never
block *recovery*; restore simply falls back to the previous good step.

Validity gates (e.g. the supervisor's "never save a poisoned checkpoint"
NaN-streak rule) must be resolved at SNAPSHOT time, by the caller, before
``submit`` — once a snapshot is queued it will be committed.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from . import faults


def snapshot_tree(tree):
    """Device-side copy of a pytree, safe against donation.

    Every ``jax.Array`` leaf is copied into a fresh device buffer (an
    async dispatch — the step loop does not wait for it); host leaves
    (numpy counters) are copied eagerly, since the trainer mutates its
    counters in place between boundaries.  The result is a snapshot the
    caller may hand to a background writer while training continues
    through donating steps."""
    import jax
    import jax.numpy as jnp

    def snap(x):
        if isinstance(x, jax.Array):
            y = jnp.copy(x)
            try:
                # start the device->host transfer now so the background
                # writer's np.asarray finds it already (or nearly) done
                y.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            return y
        return np.copy(np.asarray(x))

    return jax.tree.map(snap, tree)


def host_tree(tree):
    """Materialize a (snapshot) pytree on host — the blocking half of the
    device→host copy, meant to run on the background writer thread."""
    import jax
    return jax.tree.map(np.asarray, tree)


class AsyncCheckpointer:
    """Background checkpoint writer: double-buffered, retry-wrapped,
    failure-logged (module docstring has the full contract).

    One instance serializes all its saves (a single committer thread);
    ``workers`` bounds the per-shard write parallelism *within* one save.
    """

    def __init__(self, workers: int = 2,
                 failure_log: Optional[faults.FailureLog] = None):
        self.workers = max(1, int(workers))
        # `is None`, not truthiness: an EMPTY FailureLog is falsy
        self.failure_log = (faults.global_failure_log()
                            if failure_log is None else failure_log)
        self._committer = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix='ckpt_commit')
        # leaf-write pool, separate from the committer so a 1-worker
        # configuration cannot deadlock the orchestration on its own pool
        self._io = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix='ckpt_io')
        self._lock = threading.Lock()
        self._future: Optional[Future] = None
        self._in_flight = 0   # guarded-by: _lock (tests/metrics probe)
        self.commits = 0      # guarded-by: _lock
        self.submits = 0
        self._closed = False

    # -- core protocol -----------------------------------------------------
    def submit(self, fn: Callable[[], object], step: Optional[int] = None,
               label: str = 'ckpt') -> Future:
        """Queue ``fn()`` — the complete write (serialize-from-snapshot,
        atomic commit, digest) — on the background writer.

        Blocks until the PREVIOUS save commits (double buffer) and
        re-raises its deferred failure, so errors surface at the same
        boundary cadence the sync path has, one save late."""
        if self._closed:
            raise RuntimeError('AsyncCheckpointer is closed')
        self.wait()

        def task():
            from ..obs import span
            with self._lock:
                self._in_flight += 1
            try:
                with span('ckpt.commit', 'ckpt', step=step, label=label):
                    out = fn()
                with self._lock:
                    self.commits += 1
                return out
            except BaseException as e:
                self.failure_log.record(
                    'async_save_failed', f'{label}: {e!r}', step=step)
                raise
            finally:
                with self._lock:
                    self._in_flight -= 1

        self.submits += 1
        self._future = self._committer.submit(task)
        return self._future

    def wait(self) -> None:
        """Barrier: block until the in-flight save (if any) commits, and
        re-raise its failure.  The final save of a run must always pass
        through here — a process exiting with an uncommitted snapshot
        would silently lose its newest checkpoint."""
        f, self._future = self._future, None
        if f is not None:
            f.result()

    def drain(self) -> None:
        """Barrier for the RESTORE path: wait for the in-flight save but
        swallow its failure (already recorded in the failure log) — a
        failed save must not block recovery; restore falls back to the
        previous good checkpoint."""
        f, self._future = self._future, None
        if f is not None:
            try:
                f.result()
            except BaseException:   # noqa: BLE001 — recorded by task()
                pass

    def pending(self) -> bool:
        f = self._future
        return f is not None and not f.done()

    @property
    def io_pool(self) -> ThreadPoolExecutor:
        """The per-save shard-write pool (``workers`` wide) — submitted
        jobs that write trees themselves (e.g. the CLI's exact-sidecar
        job) pass this to ``save_tree_native`` so ``save_workers``
        governs every async write path, not just ``save_sharded_async``."""
        return self._io

    def in_flight(self) -> int:
        with self._lock:
            return self._in_flight

    def close(self, wait: bool = True) -> None:
        """Drain and shut the pools down.  Idempotent."""
        if self._closed:
            return
        if wait:
            self.drain()
        self._closed = True
        self._committer.shutdown(wait=wait)
        self._io.shutdown(wait=wait)

    # -- convenience writers ----------------------------------------------
    def save_sharded_async(self, ckpt_dir: str, step: int, snapshot,
                           retry: Optional[faults.RetryPolicy] = None,
                           on_commit: Optional[Callable[[str], None]] = None
                           ) -> Future:
        """Queue a native sharded-tree save of ``snapshot`` (a
        :func:`snapshot_tree` result) at ``step``.  Device→host
        materialization, the per-leaf atomic writes (parallel over this
        checkpointer's io pool), the directory commit, and the digest all
        run on the background writer; ``on_commit(path)`` (e.g. pruning)
        runs there too, after the digest lands."""
        from ..nnet import sharded_ckpt

        def job():
            path = sharded_ckpt.save_tree_native(
                ckpt_dir, step, host_tree(snapshot), retry=retry,
                pool=self._io)
            if on_commit is not None:
                on_commit(path)
            return path

        return self.submit(job, step=step, label=f'save_sharded:step_{step}')
