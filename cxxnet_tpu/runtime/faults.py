"""Fault-tolerance primitives: failure taxonomy, retry policy, failure log,
and a deterministic fault-injection harness.

The reference lineage assumes one uninterrupted process — the first
transient storage error or stalled producer kills the run.  On preemptible
TPU fleets the interesting operational regime is the opposite: faults are
routine and recovery must be *provable*.  This module supplies the three
shared building blocks:

* a typed failure taxonomy (:class:`TrainingFault` and friends) so the
  supervisor can tell "restore and resume" failures apart from fatal ones,
* :class:`RetryPolicy` — bounded exponential backoff with deterministic,
  seeded jitter, wrapped around every checkpoint storage read/write
  (``nnet/checkpoint.py``, ``nnet/sharded_ckpt.py``),
* :class:`FaultPlan` — a seeded, one-shot-per-event injection plan
  (raise-on-Nth-write, stall-batch-K, corrupt-checkpoint-shard,
  NaN-loss-at-step-S) that tests and the CLI (``train.fault_plan=`` config
  key, grammar in ``doc/fault_tolerance.md``) drive through the same hooks
  production code runs, so a recovery the suite proves is the recovery the
  fleet gets.

Injection hooks are ambient (:func:`install_plan` / :func:`active_plan`):
call sites in checkpoint/pipeline code are no-ops unless a plan is
installed, so the harness costs nothing when idle.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

# --- failure taxonomy -----------------------------------------------------


class TrainingFault(RuntimeError):
    """A failure the supervisor knows how to recover from (restore the
    last good checkpoint and resume), as opposed to a programming error."""


class DivergenceError(TrainingFault):
    """Training diverged: non-finite loss under ``nan_action=halt`` or the
    consecutive-NaN circuit breaker tripped."""

    def __init__(self, step: int, loss: float, streak: int = 1):
        self.step = int(step)
        self.loss = float(loss)
        self.streak = int(streak)
        super().__init__(
            f'divergence at step {step}: loss={loss!r} '
            f'({streak} consecutive non-finite)')


class PipelineStallError(TrainingFault):
    """The data pipeline missed its per-batch deadline."""

    def __init__(self, batch_index: int, deadline: float):
        self.batch_index = int(batch_index)
        self.deadline = float(deadline)
        super().__init__(
            f'data pipeline stalled: batch {batch_index} not produced '
            f'within {deadline:g}s')


class CheckpointCorruptError(TrainingFault):
    """A checkpoint failed integrity verification on restore."""


class HostLossError(TrainingFault):
    """A peer host left the elastic training world mid-run (preemption,
    crash, or a network partition that outlived the heartbeat timeout) —
    or the coordinator rolled this host's generation back because a peer
    faulted.  Recoverable by design (doc/fault_tolerance.md "Multi-host
    recovery"): every survivor restores the last good checkpoint,
    rendezvouses into the next membership generation, and resumes."""

    def __init__(self, reason: str, rank: Optional[int] = None,
                 generation: int = 0):
        self.rank = rank
        self.generation = int(generation)
        who = f'rank {rank}' if rank is not None else 'a peer'
        super().__init__(
            f'elastic membership change (generation {generation}): '
            f'{who} — {reason}')


class CoordinatorUnreachableError(TrainingFault):
    """The elastic coordinator did not answer within the sync timeout.
    From one host's view this is indistinguishable from being the minor
    side of a partition: recoverable — drop out, rendezvous afresh,
    restore-last-good."""

    def __init__(self, op: str, waited: float):
        self.op = op
        self.waited = float(waited)
        super().__init__(
            f'elastic coordinator unreachable: {op} got no reply '
            f'within {waited:g}s')


class ElasticSyncError(RuntimeError):
    """Cross-host state verification failed: after a coordinated restore
    the hosts' parameter digests disagree, or hosts arrived at the same
    barrier with different steps.  Deliberately NOT a
    :class:`TrainingFault`: the bitwise-replication invariant is broken,
    so restoring and retrying would diverge again — fail the run loudly
    (doc/fault_tolerance.md)."""


class DistInitError(RuntimeError):
    """``jax.distributed`` world initialization was misconfigured (rank
    out of range, bad worker count) or exhausted its retry budget.  A
    launch-time outcome, not a :class:`TrainingFault` — there is no
    checkpoint to restore before a world exists."""


class ScanStrictError(RuntimeError):
    """``scan_strict=1`` asserted the scanned K-dispatch path and an
    ExecutionPlan demotion would have silently fallen back to per-step.
    A configuration outcome, not a :class:`TrainingFault`: the supervisor
    must NOT restore-and-retry a run whose config contradicts itself —
    the operator asked to fail loudly instead of losing the dispatch win.
    ``reason`` is the demotion key from
    ``nnet.execution.DEMOTION_REASONS``."""

    def __init__(self, reason: str, detail: str):
        self.reason = str(reason)
        super().__init__(
            f'scan_strict=1: steps_per_dispatch would demote to per-step '
            f'[{reason}]: {detail}')


class RecompileStormError(RuntimeError):
    """A ledger-registered program (``obs/programs.py``) compiled more
    times than its declared shape-key bound: some caller is feeding the
    jitted function novel shapes — the classic recompile storm that
    silently turns a served fleet into a compile farm.  Raised only
    under ``obs.recompile=raise``; the default ``warn`` mode records
    this typed kind into the failure log and bumps the
    ``recompiles_total`` gauge instead.  Deliberately NOT a
    :class:`TrainingFault` (a restore replays the same shapes) and not
    a :class:`ServeError` (the trainer's programs are bounded too)."""

    def __init__(self, name: str, shape_key, bound: int, compiles: int):
        self.name = str(name)
        self.shape_key = shape_key
        self.bound = int(bound)
        self.compiles = int(compiles)
        super().__init__(
            f'program {name!r} compiled {compiles} times (shape-key '
            f'{shape_key!r}) but declared a bound of {bound}: recompile '
            'storm — fix the caller\'s shape bucketing, raise the '
            'declared bound, or set obs.recompile=warn to observe only')


class ServeError(RuntimeError):
    """Base of the online-serving failure taxonomy (doc/serving.md).
    Deliberately NOT a :class:`TrainingFault`: serving errors are
    per-request outcomes a client handles (shed load, retry elsewhere),
    not process-level faults a supervisor restores a checkpoint for."""


class ServeOverloadError(ServeError):
    """Admission control rejected a request: the batcher's bounded queue
    is full.  Typed so a fronting server can map it to HTTP 429 /
    RESOURCE_EXHAUSTED instead of letting clients pile onto a queue that
    can only grow tail latency."""

    def __init__(self, queue_depth: int, max_queue: int):
        self.queue_depth = int(queue_depth)
        self.max_queue = int(max_queue)
        super().__init__(
            f'serve queue full: {queue_depth}/{max_queue} requests pending')


class DeadlineExceededError(ServeError):
    """A request's deadline elapsed before its batch executed (or before
    the result reached the caller).  The row count lets the metrics layer
    account shed work."""

    def __init__(self, deadline: float, waited: float, rows: int = 0):
        self.deadline = float(deadline)
        self.waited = float(waited)
        self.rows = int(rows)
        super().__init__(
            f'request deadline {deadline:g}s exceeded after {waited:.3f}s')


class TokenDeadlineExceededError(DeadlineExceededError):
    """A decode request's deadline elapsed MID-STREAM: some tokens were
    already emitted when the slot was reclaimed.  Subclasses
    :class:`DeadlineExceededError` so predict-path handling applies;
    ``tokens_emitted`` lets the client keep the partial stream and the
    metrics layer account shed work at token granularity."""

    def __init__(self, deadline: float, waited: float,
                 tokens_emitted: int = 0):
        super().__init__(deadline, waited, rows=1)
        self.tokens_emitted = int(tokens_emitted)
        self.args = (
            f'decode deadline {deadline:g}s exceeded after {waited:.3f}s '
            f'({tokens_emitted} tokens emitted)',)


class DecodeSlotsExhaustedError(ServeError):
    """A decode request can NEVER be admitted by this engine: its prompt
    bucket or horizon exceeds the slot cache, or it needs more KV pages
    than the pool holds even when empty.  A sizing/config outcome, not a
    transient — shed immediately rather than queue forever."""

    def __init__(self, reason: str):
        super().__init__(f'decode request inadmissible: {reason}')


class DecodePagesExhaustedError(ServeError):
    """The paged KV pool ran dry mid-stream and this request was the
    preemption victim: its pages were reclaimed so older streams could
    finish.  ``tokens_emitted`` is the partial progress at shed time."""

    def __init__(self, tokens_emitted: int, pages: int):
        self.tokens_emitted = int(tokens_emitted)
        self.pages = int(pages)
        super().__init__(
            f'KV page pool exhausted ({pages} pages): request preempted '
            f'after {tokens_emitted} tokens')


class PrefixIndexFullError(ServeError):
    """A prompt's shareable prefix pages could not be published to the
    content-addressed prefix index: the configured page cap
    (``serve.prefix_share``) is smaller than the publish batch itself,
    so even evicting every reusable entry cannot make room.  An
    *observability* outcome, not a request error: the admission path
    records it and serves the request unshared — sharing degrades, the
    stream does not."""

    def __init__(self, needed: int, cap: int):
        self.needed = int(needed)
        self.cap = int(cap)
        super().__init__(
            f'prefix index cannot hold {needed} pages '
            f'(serve.prefix_share cap is {cap}): request served unshared')


class KVTierError(ServeError):
    """Base of the tiered-KV-cache failure family (``serve/kvcache.py``,
    doc/serving.md "Tiered KV cache").  Every member is an
    *availability* outcome, never a correctness one: a tier that cannot
    deliver its rows reports a miss and the request re-prefills — the
    bitwise stream-twin contract holds through every tier failure."""


class KVCorruptRecordError(KVTierError):
    """A tier-2 spill record failed digest verification, or its decoded
    header does not carry the exact key it was fetched for.  The store
    quarantines the record (renamed aside, never re-read) and reports a
    miss, so a poisoned record can never reach a stream — the same
    digest discipline the model registry applies to checkpoints."""

    def __init__(self, path: str, reason: str):
        self.path = str(path)
        self.reason = str(reason)
        super().__init__(
            f'corrupt KV spill record {path}: {reason} — quarantined, '
            'serving falls back to re-prefill')


class KVSpillError(KVTierError):
    """A tier-2 spill write failed terminally (out of disk, permission
    loss).  The entry is dropped — a cache never owes durability — and
    the failure is recorded so operators see the disk going bad before
    the hit rate quietly does."""

    def __init__(self, path: str, error: BaseException):
        self.path = str(path)
        super().__init__(f'KV spill to {path} failed: {error!r} — '
                         'entry dropped')


class SLOBreachError(RuntimeError):
    """A declarative SLO (``slo.<name>=`` config grammar, evaluated by
    the ``obs.slo`` engine; doc/observability.md "SLOs and burn rates")
    transitioned to BREACHED: the watched gauge violated its threshold
    over BOTH the long and the short burn-rate window.  An
    *observability* outcome, never control flow inside the serving or
    training path: the engine counts breaches, records this typed kind
    into the failure log — which arms the flight-recorder postmortem —
    and strict callers raise it at run boundaries via
    ``SLOEngine.check_strict``.  Deliberately NOT a
    :class:`TrainingFault`: a breached objective is a degraded state to
    alarm on, not a fault a checkpoint restore could repair."""

    def __init__(self, msg: str, name: str = '', measure=None,
                 threshold=None, window: float = 0.0, ratio=None,
                 breaches: int = 1):
        self.name = str(name)
        self.measure = measure
        self.threshold = threshold
        self.window = float(window)
        self.ratio = ratio
        self.breaches = int(breaches)
        super().__init__(msg)


def slo_breach_kinds() -> set:
    """The ``record()`` kind strings denoting a typed
    :class:`SLOBreachError` — the second family (after
    :func:`training_fault_kinds`) that arms a flight-recorder dump."""
    out = set()
    stack = [SLOBreachError]
    while stack:
        cls = stack.pop()
        out.add(cls.__name__)
        stack.extend(cls.__subclasses__())
    return out


class FreshnessSLOError(SLOBreachError, ServeError):
    """The train-while-serve freshness SLO was breached: a hot-swapped
    model version took longer than ``online.freshness_slo`` seconds to
    travel from its optimizer step to the first request served on it
    (doc/online.md).  The first consumer of the generic SLO engine (a
    per-swap ``window=0`` spec) — and still a :class:`ServeError` for
    embedders that route serving-side outcomes by that base.  The
    pipeline counts breaches per swap and only raises (strict mode) at
    run boundaries — a stale-but-correct model must keep serving; its
    breach records keep the historical ``freshness_slo_breach`` kind,
    which deliberately does NOT arm a postmortem dump."""

    def __init__(self, step: int, freshness_s: float, slo_s: float,
                 breaches: int = 1):
        self.step = int(step)
        self.freshness_s = float(freshness_s)
        self.slo_s = float(slo_s)
        SLOBreachError.__init__(
            self,
            f'freshness SLO breached: checkpoint step {step} first served '
            f'{freshness_s:.3f}s after its optimizer step '
            f'(slo={slo_s:g}s, {breaches} breach(es) total)',
            name='freshness', measure=freshness_s, threshold=slo_s,
            breaches=breaches)


class MemoryBudgetExceededError(ServeError):
    """Loading a model would exceed the serve fleet's device-memory
    budget and no cold model could be evicted to make room."""

    def __init__(self, model_id: str, needed: int, budget: int,
                 resident: int):
        self.model_id = str(model_id)
        self.needed = int(needed)
        self.budget = int(budget)
        self.resident = int(resident)
        super().__init__(
            f'model {model_id!r} needs {needed} bytes but the serve '
            f'budget is {budget} with {resident} resident and nothing '
            'evictable (every loaded model is serving)')


class RequestAbandonedError(ServeError):
    """The client stopped waiting (slow-client abandonment) before the
    engine ran the request, so the worker dropped it without executing.
    Typed so the scenario ledger reconciles abandoned work exactly: the
    drop is counted once, by the worker/engine that discards the
    request, never by the abandoning client (doc/serving.md)."""

    def __init__(self, waited: float = 0.0):
        self.waited = float(waited)
        super().__init__(
            f'request abandoned by client after {waited:.3f}s')


class AutoscaleError(ServeError):
    """Base of the autoscaler taxonomy (doc/serving.md "Scenarios and
    autoscaling").  A :class:`ServeError`: autoscaling outcomes are
    serving-side conditions an operator alarms on, not process faults a
    checkpoint restore could repair."""


class AutoscaleDegradedError(AutoscaleError):
    """The autoscaler reached its declared ceiling with the objective
    still AT_RISK/BREACHED and degraded *explicitly*: admission was
    clamped to the declared floor so further overload sheds are typed
    (:class:`ServeOverloadError`), never silent.  The autoscaler records
    this kind into the failure log when it enters the degraded rung;
    strict callers may raise it at run boundaries."""

    def __init__(self, objective: str, verdict: str, actions: int):
        self.objective = str(objective)
        self.verdict = str(verdict)
        self.actions = int(actions)
        super().__init__(
            f'autoscaler exhausted its declared bounds: objective '
            f'{objective!r} still {verdict} after {actions} action(s) — '
            'degrading explicitly (admission clamped, sheds typed)')


class TuneError(RuntimeError):
    """Base of the autotuner taxonomy (doc/autotune.md).  Deliberately
    NOT a :class:`TrainingFault` or :class:`ServeError`: tuning runs
    offline (``task=autotune``) or as a bounded online controller — its
    failures are search/plan conditions an operator reads from the
    receipt, never process faults a checkpoint restore could repair."""


class TuneSpecError(TuneError):
    """A malformed ``autotune=`` spec: unknown knob, bounds outside the
    knob's declared safety range, lo > hi, or an option value that does
    not parse.  Raised at config parse, like a bad ``slo.*`` spec."""


class TuneProbeError(TuneError):
    """A stage-2 measured probe failed (the candidate's engine or step
    loop raised).  The search records the candidate as failed and moves
    on — a broken candidate must cost one probe, not the search."""

    def __init__(self, candidate: str, cause: BaseException):
        self.candidate = str(candidate)
        super().__init__(
            f'measured probe failed for candidate {candidate!r}: '
            f'{type(cause).__name__}: {cause}')


class TuneRecompileVetoError(TuneError):
    """The online re-plan guard rejected a candidate BEFORE it compiled:
    applying it would push a ledger program family past its declared
    compile budget (``obs.recompile`` sentinel bound).  Recorded into
    the failure log by :class:`~cxxnet_tpu.tune.TuneController` so a
    veto is observable; the sentinel itself never fires."""

    def __init__(self, knob: str, program: str, headroom: int):
        self.knob = knob
        self.program = program
        self.headroom = int(headroom)
        super().__init__(
            f're-plan of {knob!r} vetoed: program {program!r} has '
            f'{headroom} compile(s) of budget left — applying would '
            'risk a recompile storm')


class FaultInjected(OSError):
    """Deterministic injected fault.  Subclasses ``OSError`` so the
    storage retry policies treat it exactly like a real transient I/O
    error — the injection exercises the production retry path, not a
    special-cased test path."""


class RetryError(OSError):
    """Raised when a :class:`RetryPolicy` exhausts its attempts; carries
    the last underlying error as ``__cause__``."""

    def __init__(self, op_name: str, attempts: int, last: BaseException):
        self.op_name = op_name
        self.attempts = attempts
        super().__init__(
            f'{op_name}: failed after {attempts} attempts: {last!r}')


# --- failure log ----------------------------------------------------------


@dataclass
class FailureRecord:
    kind: str                      # e.g. 'stall', 'divergence', 'io_retry'
    detail: str
    step: Optional[int] = None
    monotonic: float = 0.0


#: class-level failure listeners: called as ``fn(record, log)`` after a
#: record lands in ANY FailureLog (the telemetry hub's flight recorder
#: hooks here so a fault-triggered postmortem covers supervisor-owned
#: logs and the global one alike, doc/observability.md)
_FAILURE_LISTENERS: List[Callable] = []


def add_failure_listener(fn: Callable) -> Callable:
    """Register ``fn(record, log)`` on every FailureLog record; returns
    ``fn`` so callers can :func:`remove_failure_listener` it later."""
    if fn not in _FAILURE_LISTENERS:
        _FAILURE_LISTENERS.append(fn)
    return fn


def remove_failure_listener(fn: Callable) -> None:
    try:
        _FAILURE_LISTENERS.remove(fn)
    except ValueError:
        pass


def training_fault_kinds() -> set:
    """The ``record()`` kind strings that denote a typed
    :class:`TrainingFault` (the supervisor records faults under
    ``type(e).__name__``) — what arms a flight-recorder dump."""
    out = set()
    stack = [TrainingFault]
    while stack:
        cls = stack.pop()
        out.add(cls.__name__)
        stack.extend(cls.__subclasses__())
    return out


class FailureLog:
    """Append-only, thread-safe record of faults seen and actions taken.
    The supervisor owns one; subsystems without a supervisor reference
    (e.g. ``trainer.train_step_flops``) report to the process-wide default
    via :func:`global_failure_log`."""

    def __init__(self):
        self._lock = threading.Lock()
        self._records: List[FailureRecord] = []

    def record(self, kind: str, detail: str,
               step: Optional[int] = None) -> FailureRecord:
        rec = FailureRecord(kind, detail, step, time.monotonic())
        with self._lock:
            self._records.append(rec)
        for fn in list(_FAILURE_LISTENERS):     # outside the lock
            try:
                fn(rec, self)
            # lint: allow(fault-taxonomy): a broken telemetry listener must never turn an observed fault into a new one
            except Exception:
                pass
        return rec

    def records(self, kind: Optional[str] = None) -> List[FailureRecord]:
        with self._lock:
            out = list(self._records)
        if kind is not None:
            out = [r for r in out if r.kind == kind]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def summary(self) -> str:
        counts: Dict[str, int] = {}
        for r in self.records():
            counts[r.kind] = counts.get(r.kind, 0) + 1
        return ', '.join(f'{k}={v}' for k, v in sorted(counts.items())) \
            or 'no failures'


_GLOBAL_LOG = FailureLog()


def global_failure_log() -> FailureLog:
    return _GLOBAL_LOG


# --- retry policy ---------------------------------------------------------


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff for storage operations.

    ``delay(k) = min(max_delay, base_delay * multiplier**k) * (1 + j)``
    where ``j`` is uniform in ``[-jitter, +jitter]`` drawn from a seeded
    stream — the schedule is a pure function of (seed, op_name), so runs
    are reproducible.  ``sleep`` is injectable so tests assert the
    schedule without waiting it out."""

    max_attempts: int = 4
    base_delay: float = 0.05
    multiplier: float = 2.0
    max_delay: float = 2.0
    jitter: float = 0.1
    seed: int = 0
    retry_on: Tuple[type, ...] = (OSError, TimeoutError)
    sleep: Callable[[float], None] = time.sleep

    def delays(self, op_name: str = '') -> List[float]:
        """The full deterministic backoff schedule (one entry per retry)."""
        rng = random.Random((self.seed << 16)
                            ^ zlib.crc32(op_name.encode()))
        out = []
        for k in range(max(0, self.max_attempts - 1)):
            d = min(self.max_delay, self.base_delay * self.multiplier ** k)
            out.append(d * (1.0 + rng.uniform(-self.jitter, self.jitter)))
        return out

    def call(self, fn: Callable, op_name: str = 'storage_op',
             log: Optional[FailureLog] = None):
        """Run ``fn()`` retrying on ``retry_on`` with the backoff
        schedule; raises :class:`RetryError` (chained to the last error)
        once attempts are exhausted."""
        schedule = self.delays(op_name)
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                return fn()
            except self.retry_on as e:  # noqa: PERF203 — the slow path
                last = e
                # `is None`, not truthiness: an EMPTY FailureLog is falsy
                (global_failure_log() if log is None else log).record(
                    'io_retry', f'{op_name} attempt {attempt + 1}/' +
                    f'{self.max_attempts} failed: {e!r}')
                if attempt < len(schedule):
                    self.sleep(schedule[attempt])
        raise RetryError(op_name, self.max_attempts, last) from last


#: Default policy for checkpoint storage; modules take a ``retry=`` param
#: defaulting to this, so one knob retunes the whole I/O layer.
DEFAULT_IO_RETRY = RetryPolicy()

#: Zero-delay variant for tests that only care about attempt counts.
NO_WAIT_RETRY = RetryPolicy(base_delay=0.0, max_delay=0.0, jitter=0.0,
                            sleep=lambda _t: None)


# --- deterministic fault injection ---------------------------------------


def _parse_event(val: str) -> Tuple[int, Optional[float]]:
    """``"7"`` -> (7, None); ``"5:0.25"`` -> (5, 0.25)."""
    head, _, tail = val.partition(':')
    return int(head), (float(tail) if tail else None)


class FaultPlan:
    """A seeded plan of fault events, driven by ambient hooks.

    Event kinds (grammar ``kind=arg[;kind=arg...]``, parsed from the
    ``train.fault_plan=`` config value by :meth:`parse`):

    * ``raise_on_write=N`` — the N-th checkpoint storage write attempt
      (1-based, counted across the process) raises :class:`FaultInjected`.
    * ``stall_write=N[:secs]`` — the N-th checkpoint storage write attempt
      sleeps ``secs`` (default 0.5) before proceeding: a deterministic
      slow-storage event, used to prove the async writer's double-buffer
      backpressure and the restore-barriers-on-pending-save contract
      without ever racing a real disk.
    * ``stall_batch=K[:secs]`` — the pipeline producer sleeps ``secs``
      (default 30) before handing over batch index K (0-based), tripping
      any consumer deadline shorter than that.
    * ``corrupt_shard=STEP`` — after the sharded checkpoint for ``STEP``
      commits, one of its payload files (seeded choice) is truncated,
      so integrity verification must catch it on restore.
    * ``nan_at_step=S`` — the loss observed at sample step S reads as NaN,
      exercising ``nan_action`` / the divergence circuit breaker without
      needing genuinely divergent math.
    * ``corrupt_model=N`` — after the N-th ``%04d.model`` file *commits*
      (model bytes + digest sidecar both on disk), the model file is
      truncated so a hot-reloading server's digest verification must
      reject it (the serving half of the chaos contract,
      doc/online.md).
    * ``host_loss=N[:rank]`` — at global step N the elastic worker whose
      rank matches (default: the highest rank) dies abruptly
      (``os._exit``), simulating a preempted host; survivors must
      restore-last-good and the launcher respawns the rank
      (doc/fault_tolerance.md "Multi-host recovery").  Fires only on a
      worker's FIRST incarnation — a respawned replacement replays the
      step it died at, and re-firing would be a death loop.
    * ``partition=N:secs`` — at global step N this elastic worker stops
      heartbeating and delays its collective traffic for ``secs``
      (default 30): a deterministic network partition.  Outliving the
      coordinator's heartbeat timeout makes the worker a declared host
      loss; a short blip just stalls the step.
    * ``corrupt_kv=N`` — after the N-th tiered-KV spill record *commits*
      (record bytes + crc32 sidecar both on disk, fired on the staged
      file BEFORE the rename makes it visible), the record is truncated
      so the store's digest verification must quarantine it and the
      request must fall back to a re-prefill — never a crash, never a
      non-twin stream (doc/serving.md "Tiered KV cache").
    * ``slow_step=N[:secs]`` — the N-th decode engine loop iteration
      (1-based, counted across the process) sleeps ``secs`` (default
      0.05) before stepping: deterministic serve-path latency injection.
      The sleep lands on the decode loop thread *between* token
      boundaries, so token streams stay bitwise identical to the
      fault-free twin — only timing (deadlines, queue depth, autoscaler
      pressure) shifts.  The serve half of a chaos drill composes this
      with a ``serve.scenario=`` traffic shape (doc/serving.md).

    Any event kind also accepts the RECURRING form ``kind@every=K``
    (e.g. ``raise_on_write@every=3``, ``stall_batch@every=50:0.2``):
    the event fires deterministically on every K-th occurrence of its
    hook (1-based count / step multiples of K) for the life of the
    plan — how a long-lived online run keeps faults arriving instead of
    spending its plan in the first minute.  One-shot events fire at
    most once; :meth:`fired` exposes everything that actually triggered
    (recurring firings are tagged ``kind@every=K#occurrence``) so tests
    can assert the plan executed.  All hooks are thread-safe (the stall
    hook runs on the producer thread)."""

    def __init__(self, seed: int = 0,
                 raise_on_write: Tuple[int, ...] = (),
                 stall_batch: Tuple[Tuple[int, Optional[float]], ...] = (),
                 corrupt_shard: Tuple[int, ...] = (),
                 nan_at_step: Tuple[int, ...] = (),
                 stall_write: Tuple[Tuple[int, Optional[float]], ...] = (),
                 corrupt_model: Tuple[int, ...] = (),
                 host_loss: Tuple[Tuple[int, Optional[float]], ...] = (),
                 partition: Tuple[Tuple[int, Optional[float]], ...] = (),
                 raise_on_write_every: Tuple[int, ...] = (),
                 stall_batch_every: Tuple[Tuple[int, Optional[float]],
                                          ...] = (),
                 corrupt_shard_every: Tuple[int, ...] = (),
                 nan_at_step_every: Tuple[int, ...] = (),
                 stall_write_every: Tuple[Tuple[int, Optional[float]],
                                          ...] = (),
                 corrupt_model_every: Tuple[int, ...] = (),
                 host_loss_every: Tuple[Tuple[int, Optional[float]],
                                        ...] = (),
                 partition_every: Tuple[Tuple[int, Optional[float]],
                                        ...] = (),
                 slow_step: Tuple[Tuple[int, Optional[float]], ...] = (),
                 slow_step_every: Tuple[Tuple[int, Optional[float]],
                                        ...] = (),
                 corrupt_kv: Tuple[int, ...] = (),
                 corrupt_kv_every: Tuple[int, ...] = ()):
        def _periods(vals):
            out = set()
            for k in vals:
                if int(k) <= 0:
                    raise ValueError(f'@every period must be > 0, got {k}')
                out.add(int(k))
            return out

        self.seed = int(seed)
        self._raise_on_write = set(raise_on_write)
        self._stall = {k: (30.0 if s is None else s) for k, s in stall_batch}
        self._stall_write = {n: (0.5 if s is None else s)
                             for n, s in stall_write}
        self._corrupt = set(corrupt_shard)
        self._nan = set(nan_at_step)
        self._corrupt_model = set(corrupt_model)
        # host_loss: step -> target rank (None = highest rank; the rank
        # rides the event's ':' argument slot); partition: step -> secs
        self._host_loss = {n: (None if r is None else int(r))
                           for n, r in host_loss}
        self._partition = {n: (30.0 if s is None else s)
                           for n, s in partition}
        # recurring (@every=K) variants: period -> fire on every K-th
        # occurrence; deterministic, never consumed
        self._raise_every = _periods(raise_on_write_every)
        self._stall_every = {int(k): (30.0 if s is None else s)
                             for k, s in stall_batch_every}
        self._stall_write_every = {int(k): (0.5 if s is None else s)
                                   for k, s in stall_write_every}
        self._corrupt_every = _periods(corrupt_shard_every)
        self._nan_every = _periods(nan_at_step_every)
        self._corrupt_model_every = _periods(corrupt_model_every)
        self._host_loss_every = {int(k): (None if r is None else int(r))
                                 for k, r in host_loss_every}
        self._partition_every = {int(k): (30.0 if s is None else s)
                                 for k, s in partition_every}
        self._slow_step = {n: (0.05 if s is None else s)
                           for n, s in slow_step}
        self._slow_step_every = {int(k): (0.05 if s is None else s)
                                 for k, s in slow_step_every}
        self._corrupt_kv = set(corrupt_kv)
        self._corrupt_kv_every = _periods(corrupt_kv_every)
        if 0 in self._host_loss_every or 0 in self._partition_every:
            raise ValueError('@every period must be > 0')
        if 0 in self._stall_every or 0 in self._stall_write_every:
            raise ValueError('@every period must be > 0')
        if 0 in self._slow_step_every:
            raise ValueError('@every period must be > 0')
        # step-keyed recurring events fire once per DISTINCT step: a
        # supervised restore replays step numbers, and re-firing on the
        # replay would turn every recovery into a death loop (the
        # count-based hooks are monotone and need no such guard)
        self._nan_fired_steps: set = set()
        self._corrupt_fired_steps: set = set()
        self._partition_fired_steps: set = set()
        self._write_count = 0
        self._model_count = 0
        self._decode_count = 0
        self._kv_count = 0
        self._fired: List[str] = []
        self._lock = threading.Lock()

    #: every grammar kind :meth:`parse` accepts (each also takes the
    #: recurring ``@every`` form) — the doc/fault_tolerance.md grammar
    #: table is drift-tested against :meth:`registered_kinds`
    KINDS = ('raise_on_write', 'stall_batch', 'stall_write',
             'corrupt_shard', 'nan_at_step', 'corrupt_model',
             'host_loss', 'partition', 'slow_step', 'corrupt_kv')

    @classmethod
    def registered_kinds(cls) -> Tuple[str, ...]:
        """Grammar keys the parser accepts, ``seed`` included — the
        code-side truth the doc-table drift test compares against."""
        return ('seed',) + cls.KINDS

    @classmethod
    def parse(cls, text: str) -> 'FaultPlan':
        from ..utils.config import parse_kv_list
        seed = 0
        kw: Dict[str, list] = {k: [] for k in cls.KINDS}
        kw.update({f'{k}_every': [] for k in cls.KINDS})
        timed = ('stall_batch', 'stall_write', 'host_loss', 'partition',
                 'slow_step', 'stall_batch_every', 'stall_write_every',
                 'host_loss_every', 'partition_every', 'slow_step_every')
        for key, val in parse_kv_list(text):
            if key == 'seed':
                seed = int(val)
                continue
            # recurring form: kind@every=K (keeps one-shot specs intact)
            kind, at, mod = key.partition('@')
            if at and mod != 'every':
                raise ValueError(f'unknown fault_plan event: {key!r}')
            name = f'{kind}_every' if at else kind
            if name not in kw:
                raise ValueError(f'unknown fault_plan event: {key!r}')
            kw[name].append(_parse_event(val) if name in timed
                            else int(val))
        return cls(seed=seed, **{k: tuple(v) for k, v in kw.items()})

    # -- introspection --
    def fired(self) -> List[str]:
        with self._lock:
            return list(self._fired)

    def _mark(self, tag: str) -> None:
        with self._lock:
            self._fired.append(tag)

    def describe(self) -> str:
        parts = [f'seed={self.seed}']
        parts += [f'raise_on_write={n}' for n in sorted(self._raise_on_write)]
        parts += [f'raise_on_write@every={k}'
                  for k in sorted(self._raise_every)]
        parts += [f'stall_batch={k}:{s:g}'
                  for k, s in sorted(self._stall.items())]
        parts += [f'stall_batch@every={k}:{s:g}'
                  for k, s in sorted(self._stall_every.items())]
        parts += [f'stall_write={n}:{s:g}'
                  for n, s in sorted(self._stall_write.items())]
        parts += [f'stall_write@every={n}:{s:g}'
                  for n, s in sorted(self._stall_write_every.items())]
        parts += [f'corrupt_shard={s}' for s in sorted(self._corrupt)]
        parts += [f'corrupt_shard@every={s}'
                  for s in sorted(self._corrupt_every)]
        parts += [f'corrupt_model={s}' for s in sorted(self._corrupt_model)]
        parts += [f'corrupt_model@every={s}'
                  for s in sorted(self._corrupt_model_every)]
        parts += [f'nan_at_step={s}' for s in sorted(self._nan)]
        parts += [f'nan_at_step@every={s}' for s in sorted(self._nan_every)]
        parts += [f'host_loss={n}' + ('' if r is None else f':{r}')
                  for n, r in sorted(self._host_loss.items())]
        parts += [f'host_loss@every={k}' + ('' if r is None else f':{r}')
                  for k, r in sorted(self._host_loss_every.items())]
        parts += [f'partition={n}:{s:g}'
                  for n, s in sorted(self._partition.items())]
        parts += [f'partition@every={k}:{s:g}'
                  for k, s in sorted(self._partition_every.items())]
        parts += [f'slow_step={n}:{s:g}'
                  for n, s in sorted(self._slow_step.items())]
        parts += [f'slow_step@every={k}:{s:g}'
                  for k, s in sorted(self._slow_step_every.items())]
        parts += [f'corrupt_kv={n}' for n in sorted(self._corrupt_kv)]
        parts += [f'corrupt_kv@every={k}'
                  for k in sorted(self._corrupt_kv_every)]
        return ';'.join(parts)

    @staticmethod
    def _periodic_hit(count: int, periods) -> Optional[int]:
        """The period that makes occurrence ``count`` (1-based) fire, or
        None.  Smallest matching period wins the tag; one fire per
        occurrence regardless of how many periods divide it."""
        for k in sorted(periods):
            if count > 0 and count % k == 0:
                return k
        return None

    # -- hooks (called from production code when a plan is installed) --
    def on_checkpoint_write(self, path: str) -> None:
        """Every checkpoint storage write *attempt* calls this first; the
        injected error is retryable by design (see :class:`FaultInjected`)."""
        with self._lock:
            self._write_count += 1
            n = self._write_count
            secs = self._stall_write.pop(n, None)
            if secs is not None:
                self._fired.append(f'stall_write={n}:{secs:g}')
            else:
                k = self._periodic_hit(n, self._stall_write_every)
                if k is not None:
                    secs = self._stall_write_every[k]
                    self._fired.append(f'stall_write@every={k}#{n}')
            hit = n in self._raise_on_write
            if hit:
                self._raise_on_write.discard(n)
                self._fired.append(f'raise_on_write={n}')
            else:
                k = self._periodic_hit(n, self._raise_every)
                if k is not None:
                    hit = True
                    self._fired.append(f'raise_on_write@every={k}#{n}')
        if secs is not None:
            time.sleep(secs)
        if hit:
            raise FaultInjected(
                f'injected fault: checkpoint write #{n} to {path}')

    def on_pipeline_item(self, scope: str, index: int) -> None:
        """Producer-side hook, per item; only batch-scoped buffers
        participate (inner page/instance buffers pass other scopes).
        Recurring stalls count batches 1-based (batch index K-1 is the
        K-th batch)."""
        if scope != 'batch':
            return
        with self._lock:
            secs = self._stall.pop(index, None)
            if secs is not None:
                self._fired.append(f'stall_batch={index}:{secs:g}')
            else:
                k = self._periodic_hit(index + 1, self._stall_every)
                if k is not None:
                    secs = self._stall_every[k]
                    self._fired.append(f'stall_batch@every={k}#{index}')
        if secs is not None:
            time.sleep(secs)

    def has_nan_events(self) -> bool:
        with self._lock:
            return bool(self._nan) or bool(self._nan_every)

    def on_loss(self, step: int, loss: float) -> float:
        with self._lock:
            if step in self._nan:
                self._nan.discard(step)
                self._fired.append(f'nan_at_step={step}')
                return float('nan')
            k = self._periodic_hit(step, self._nan_every)
            if k is not None and step not in self._nan_fired_steps:
                self._nan_fired_steps.add(step)
                self._fired.append(f'nan_at_step@every={k}#{step}')
                return float('nan')
        return loss

    #: exit status of a host_loss-killed elastic worker — the launcher
    #: treats it exactly like a preemption (respawn, never fail the run)
    HOST_LOSS_EXIT = 117

    def on_elastic_step(self, step: int, rank: int, nhosts: int,
                        allow_kill: bool = True) -> Optional[float]:
        """Per-global-step hook on every elastic worker (the plan is
        replicated per process, so firing decisions are deterministic
        and identical on all hosts).  ``host_loss`` whose target rank
        matches kills THIS process abruptly (``os._exit``) — only when
        ``allow_kill`` (the worker's first incarnation: a respawned
        replacement replays the fatal step and must not re-die).
        ``partition`` returns the seconds this worker should drop off
        the network; the elastic client implements the silence."""
        kill = False
        secs = None
        with self._lock:
            tgt = self._host_loss.get(step, '-')
            if tgt != '-':
                want = (nhosts - 1) if tgt is None else tgt
                if want == rank:
                    if allow_kill:
                        del self._host_loss[step]
                        self._fired.append(f'host_loss={step}:{rank}')
                        kill = True
                    else:
                        self._fired.append(
                            f'host_loss={step}:{rank}#disarmed')
            if not kill:
                k = self._periodic_hit(step, self._host_loss_every)
                if k is not None:
                    want = self._host_loss_every[k]
                    want = (nhosts - 1) if want is None else want
                    if want == rank:
                        if allow_kill:
                            self._fired.append(
                                f'host_loss@every={k}#{step}:{rank}')
                            kill = True
                        else:
                            # a respawned replacement keeps the plan
                            # disarmed for its whole lifetime (it cannot
                            # tell replayed steps from fresh ones) —
                            # recorded so drills can see the suppression
                            self._fired.append(
                                f'host_loss@every={k}#{step}:{rank}'
                                '#disarmed')
            if not kill:
                secs = self._partition.get(step)
                if secs is not None \
                        and step not in self._partition_fired_steps:
                    self._partition_fired_steps.add(step)
                    self._fired.append(f'partition={step}:{secs:g}')
                elif step in self._partition_fired_steps:
                    secs = None         # replayed step: fire once
                else:
                    k = self._periodic_hit(step, self._partition_every)
                    if k is not None:
                        self._partition_fired_steps.add(step)
                        secs = self._partition_every[k]
                        self._fired.append(
                            f'partition@every={k}#{step}:{secs:g}')
        if kill:
            import os
            import sys
            print(f'fault plan: host_loss fired — rank {rank} dies at '
                  f'step {step}', file=sys.stderr, flush=True)
            os._exit(self.HOST_LOSS_EXIT)
        return secs

    def on_decode_step(self) -> None:
        """Every decode engine loop iteration calls this first (via the
        ambient :func:`decode_step`).  The N-th iteration (1-based,
        counted per plan) sleeps its configured seconds on the loop
        thread, *between* token boundaries — streams stay bitwise
        identical to the fault-free twin; only latency shifts."""
        with self._lock:
            self._decode_count += 1
            n = self._decode_count
            secs = self._slow_step.pop(n, None)
            if secs is not None:
                self._fired.append(f'slow_step={n}:{secs:g}')
            else:
                k = self._periodic_hit(n, self._slow_step_every)
                if k is not None:
                    secs = self._slow_step_every[k]
                    self._fired.append(f'slow_step@every={k}#{n}')
        if secs is not None:
            time.sleep(secs)

    def on_model_committed(self, path: str) -> None:
        """After the N-th model-file commit (file + digest sidecar both
        durable), truncate the model file: the digest no longer matches,
        so a hot-reloading registry must reject the checkpoint and keep
        the previous version serving (doc/online.md chaos drill)."""
        with self._lock:
            self._model_count += 1
            n = self._model_count
            hit = n in self._corrupt_model
            if hit:
                self._corrupt_model.discard(n)
                self._fired.append(f'corrupt_model={n}')
            else:
                k = self._periodic_hit(n, self._corrupt_model_every)
                if k is not None:
                    hit = True
                    self._fired.append(f'corrupt_model@every={k}#{n}')
        if not hit:
            return
        import os
        size = os.path.getsize(path)
        if size > 1:
            with open(path, 'r+b') as f:
                f.truncate(size // 2)
        else:
            os.unlink(path)

    def on_kv_record_committed(self, path: str) -> None:
        """After the N-th tiered-KV spill record commit (record + crc32
        sidecar both durable; fired on the STAGED file, before the
        rename), truncate the record: digest verification must
        quarantine it on the next promote and the request must fall
        back to a re-prefill (doc/serving.md "Tiered KV cache")."""
        with self._lock:
            self._kv_count += 1
            n = self._kv_count
            hit = n in self._corrupt_kv
            if hit:
                self._corrupt_kv.discard(n)
                self._fired.append(f'corrupt_kv={n}')
            else:
                k = self._periodic_hit(n, self._corrupt_kv_every)
                if k is not None:
                    hit = True
                    self._fired.append(f'corrupt_kv@every={k}#{n}')
        if not hit:
            return
        import os
        size = os.path.getsize(path)
        if size > 1:
            with open(path, 'r+b') as f:
                f.truncate(size // 2)
        else:
            os.unlink(path)

    def on_shard_committed(self, step: int, path: str) -> None:
        """Truncate one payload file of a just-committed sharded
        checkpoint (seeded pick) so restore-time verification must
        reject it.  Recurring form fires on every step that is a
        multiple of K."""
        with self._lock:
            if step in self._corrupt:
                self._corrupt.discard(step)
                self._fired.append(f'corrupt_shard={step}')
            else:
                k = self._periodic_hit(step, self._corrupt_every)
                if k is None or step in self._corrupt_fired_steps:
                    return
                self._corrupt_fired_steps.add(step)
                self._fired.append(f'corrupt_shard@every={k}#{step}')
        import os
        victims = []
        for root, _dirs, files in os.walk(path):
            for f in sorted(files):
                # corrupt a payload shard, not the integrity sidecar —
                # the point is proving verification catches bad DATA
                if f == 'ckpt_digest.json':
                    continue
                victims.append(os.path.join(root, f))
        if not victims:
            return
        victim = victims[random.Random(self.seed ^ step).randrange(
            len(victims))]
        size = os.path.getsize(victim)
        if size > 1:
            with open(victim, 'r+b') as f:
                f.truncate(size // 2)
        else:
            os.unlink(victim)


# --- ambient plan ---------------------------------------------------------

_ACTIVE: Optional[FaultPlan] = None


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install ``plan`` as the process-wide active fault plan (None
    clears); returns the previous one so tests can restore it."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, plan
    return prev


def clear_plan() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _ACTIVE


def checkpoint_write_attempt(path: str) -> None:
    """Call at the top of every checkpoint storage write attempt."""
    plan = _ACTIVE
    if plan is not None:
        plan.on_checkpoint_write(path)


def pipeline_item(scope: Optional[str], index: int) -> None:
    plan = _ACTIVE
    if plan is not None and scope is not None:
        plan.on_pipeline_item(scope, index)


def shard_committed(step: int, path: str) -> None:
    plan = _ACTIVE
    if plan is not None:
        plan.on_shard_committed(step, path)


def elastic_step(step: int, rank: int, nhosts: int,
                 allow_kill: bool = True) -> Optional[float]:
    """Call at the top of every elastic worker's global step; returns
    partition seconds to enforce, or None (see
    :meth:`FaultPlan.on_elastic_step`)."""
    plan = _ACTIVE
    if plan is None:
        return None
    return plan.on_elastic_step(step, rank, nhosts, allow_kill=allow_kill)


def decode_step() -> None:
    """Call once at the top of every decode engine loop iteration (see
    :meth:`FaultPlan.on_decode_step`); a no-op when no plan is active."""
    plan = _ACTIVE
    if plan is not None:
        plan.on_decode_step()


def model_committed(path: str, staged: Optional[str] = None) -> None:
    """Call when a model file's bytes + digest are both durable.  The
    train CLI's save-then-digest path calls it after the commit
    (``nnet.checkpoint.write_model_digest`` — corruption lands on the
    live file); the online publish path calls it with ``staged=`` the
    pre-rename temp file (``publish_model_file`` — corruption lands
    BEFORE the file is visible, so digest verification catches it
    deterministically)."""
    plan = _ACTIVE
    if plan is not None:
        plan.on_model_committed(path if staged is None else staged)


def kv_record_committed(path: str, staged: Optional[str] = None) -> None:
    """Call when a tiered-KV spill record's bytes + digest sidecar are
    both durable; ``staged=`` is the pre-rename temp file, so injected
    corruption (``corrupt_kv=N``) lands BEFORE the record is visible
    and digest verification catches it deterministically on promote."""
    plan = _ACTIVE
    if plan is not None:
        plan.on_kv_record_committed(path if staged is None else staged)
