"""ctypes bindings to the native runtime (runtime/libcxxnet_runtime.so).

The native library provides a background-threaded BinaryPage stream reader
and libjpeg decoding — the C++ path the reference used for its data pipeline
(``iter_thread_imbin``/``thread_buffer``/``decoder``).  Build with
``make -C runtime``; everything degrades gracefully to the pure-Python
implementations when the .so is absent (``native_available()`` is False).
Set ``CXXNET_NO_NATIVE=1`` to force the Python path.
"""

from __future__ import annotations

import ctypes
import os
from typing import Iterator, Optional

import numpy as np

_LIB = None
_TRIED = False


def _lib_path() -> str:
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(here, 'runtime', 'libcxxnet_runtime.so')


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get('CXXNET_NO_NATIVE') == '1':
        return None
    path = _lib_path()
    if not os.path.exists(path):
        # try building it once, quietly
        makefile_dir = os.path.dirname(path)
        if os.path.exists(os.path.join(makefile_dir, 'Makefile')):
            os.system(f'make -s -C {makefile_dir} >/dev/null 2>&1')
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    lib.cxr_open.restype = ctypes.c_void_p
    lib.cxr_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    if hasattr(lib, 'cxr_open_order'):      # older prebuilt .so lacks it
        lib.cxr_open_order.restype = ctypes.c_void_p
        lib.cxr_open_order.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.c_int]
    lib.cxr_next_page.restype = ctypes.c_int
    lib.cxr_next_page.argtypes = [ctypes.c_void_p]
    lib.cxr_get_obj.restype = ctypes.c_void_p
    lib.cxr_get_obj.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                ctypes.POINTER(ctypes.c_size_t)]
    lib.cxr_close.argtypes = [ctypes.c_void_p]
    lib.cxr_jpeg_decode.restype = ctypes.c_int
    lib.cxr_jpeg_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    _LIB = lib
    return lib


def native_available() -> bool:
    return _load() is not None


def native_order_available() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, 'cxr_open_order')


class NativePageReader:
    """Iterates the blobs of a BinaryPage stream with C++-side prefetch.

    ``order`` (a sequence of page indices) switches the reader thread to
    seek-based random access — the imgbinx shuffled-epoch path — still
    prefetching ``prefetch_pages`` ahead."""

    def __init__(self, path: str, prefetch_pages: int = 2, order=None):
        lib = _load()
        if lib is None:
            raise RuntimeError('native runtime not available')
        self._lib = lib
        if order is not None:
            if not hasattr(lib, 'cxr_open_order'):
                raise RuntimeError('native runtime lacks cxr_open_order '
                                   '(rebuild runtime/)')
            arr = np.ascontiguousarray(order, dtype=np.int64)
            self._h = lib.cxr_open_order(
                path.encode(),
                arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(arr), prefetch_pages)
        else:
            self._h = lib.cxr_open(path.encode(), prefetch_pages)
        if not self._h:
            raise IOError(f'cannot open {path}')

    def iter_pages(self) -> Iterator[list]:
        """Yield each page's blobs as a list (page granularity is the unit
        of distributed sharding and shuffle)."""
        lib = self._lib
        while True:
            n = lib.cxr_next_page(self._h)
            if n == -2:
                raise RuntimeError('imgbin: truncated page (ordered read '
                                   'past end of .bin)')
            if n < 0:
                return
            page = []
            for r in range(n):
                size = ctypes.c_size_t()
                ptr = lib.cxr_get_obj(self._h, r, ctypes.byref(size))
                page.append(ctypes.string_at(ptr, size.value))
            yield page

    def __iter__(self) -> Iterator[bytes]:
        for page in self.iter_pages():
            yield from page

    def close(self) -> None:
        if self._h:
            self._lib.cxr_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # lint: allow(fault-taxonomy): interpreter-teardown finalizer; raising in __del__ aborts shutdown
            pass


def decode_jpeg(blob: bytes) -> Optional[np.ndarray]:
    """Decode a JPEG blob to (h, w, 3) uint8 RGB via libjpeg; None if the
    native lib is unavailable or the blob is not a decodable JPEG."""
    lib = _load()
    if lib is None:
        return None
    w = ctypes.c_int()
    h = ctypes.c_int()
    rc = lib.cxr_jpeg_decode(blob, len(blob), None, 0,
                             ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    out = np.empty((h.value, w.value, 3), np.uint8)
    rc = lib.cxr_jpeg_decode(blob, len(blob),
                             out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
                             ctypes.byref(w), ctypes.byref(h))
    if rc != 0:
        return None
    return out
