"""Supervised training runtime: watchdog, circuit breaker, restore/resume.

``TrainSupervisor`` runs the ``nnet.trainer`` step loop under supervision,
turning "crashes cleanly" into "degrades gracefully and provably
recovers".  The recovery state machine (doc/fault_tolerance.md):

::

    RUNNING --fault--> FAILED --(restarts left)--> RESTORING --> RUNNING
                          |                                        ^
                          +--(max_restarts exhausted)--> raise      |
                                   anchor/periodic checkpoints -----+

Fault detection (all surfaced as ``faults.TrainingFault`` subclasses):

* **pipeline stall** — batches are pulled through a
  ``utils.thread_buffer.ThreadBuffer`` with a per-batch ``deadline``;
  a producer that misses it raises ``PipelineStallError``,
* **divergence** — the trainer's ``nan_action=halt`` /
  consecutive-NaN ``nan_breaker`` gate raises ``DivergenceError``
  (the supervisor installs its ``nan_breaker`` on the trainer),
* **corrupt checkpoint** — restore verifies integrity digests and falls
  back to the newest intact step (``sharded_ckpt.restore_resilient``).

Recovery restores the trainer's EXACT-resume sidecar (params + optimizer
state + gradient accumulator + counters, ``trainer.save_training_state``)
and resumes the batch stream at the restored ``sample_counter`` — because
the trainer's per-step RNG is a pure function of the restored counters, a
supervised run that faulted and recovered ends bitwise-identical to an
uninterrupted run with the same seed (proved by
``tests/test_fault_tolerance.py``).

The batch source contract is a *restartable factory*: ``batch_factory(k)``
returns an iterator yielding batch k, k+1, ... of the epoch.  Anything
deterministic and replayable qualifies (a list slice, a seeded iterator
chain re-wound with ``itertools.islice``); the factory is re-invoked after
every restore, so one poisoned iterator never wedges the run.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Callable, Iterator, Optional

from ..obs import span
from ..utils.thread_buffer import ThreadBuffer
from . import faults


@dataclass
class SupervisorConfig:
    """Knobs for one supervised run (config-key mapping in main.py /
    doc/fault_tolerance.md)."""

    batch_deadline: Optional[float] = 60.0   # None disables the watchdog
    max_restarts: int = 3                    # per run() call
    nan_breaker: int = 3                     # 0 keeps the trainer's own
    save_every: int = 0                      # steps between periodic saves
    buffer_size: int = 2                     # watchdog prefetch depth
    keep_last: int = 4                       # ckpt steps kept (0 = all);
                                             # also the corrupt-fallback depth
    save_async: int = 0                      # 1 = background checkpoint
                                             # writer (runtime/async_ckpt)
    save_workers: int = 2                    # per-save shard-write threads
    # the train chain's utils.metric.StatSet when the pooled input
    # pipeline is on (nworker, doc/io.md): the watchdog buffer reports
    # its stalls there, and its presence marks the chain as POOLED —
    # the first batch then also pays the pool's window fill
    # (nworker*4 decoded+augmented instances), so the first-deadline
    # grace doubles rather than deterministically tripping the watchdog
    pipeline_stats: Optional[object] = None
    # called as on_save(step) after EVERY accepted checkpoint save
    # (anchor, periodic, final) — i.e. only at moments the NaN gate
    # allowed a save, so a listener mirroring params elsewhere (the
    # online pipeline's serving model files, doc/online.md) inherits the
    # never-publish-poisoned-params guarantee for free.  Runs on the
    # step-loop thread at a window boundary: keep it snapshot-cheap.
    on_save: Optional[Callable[[int], None]] = None
    retry: faults.RetryPolicy = field(
        default_factory=lambda: faults.DEFAULT_IO_RETRY)


class TrainSupervisor:
    """Run a trainer's step loop under watchdog + recovery supervision.

    One supervisor per trainer; ``run()`` may be called repeatedly (e.g.
    once per round) — checkpoints accumulate in ``ckpt_dir`` and the
    restart budget is per call.
    """

    #: fault classes that trigger restore-and-resume; anything else is a
    #: programming error and propagates
    RECOVERABLE = (faults.PipelineStallError, faults.DivergenceError,
                   faults.CheckpointCorruptError)

    def __init__(self, trainer, ckpt_dir: str,
                 config: Optional[SupervisorConfig] = None,
                 failure_log: Optional[faults.FailureLog] = None):
        self.trainer = trainer
        self.ckpt_dir = ckpt_dir
        self.config = config or SupervisorConfig()
        # `is None`, not truthiness: an EMPTY FailureLog is falsy
        self.failure_log = (faults.global_failure_log()
                            if failure_log is None else failure_log)
        self.state = 'IDLE'
        self.restarts_total = 0
        self._async = None
        if self.config.save_async:
            from .async_ckpt import AsyncCheckpointer
            self._async = AsyncCheckpointer(
                workers=self.config.save_workers,
                failure_log=self.failure_log)
        if self.config.nan_breaker and not trainer.nan_breaker:
            trainer.nan_breaker = self.config.nan_breaker

    # --- checkpoint side --------------------------------------------------
    def save(self) -> str:
        """Checkpoint the trainer's exact-resume state at the current
        sample step (atomic + retried inside the sharded path).  An
        existing dir for the step is REPLACED, never trusted: post-restore
        replay rewrites bitwise-identical state, but a same-step save from
        a later round (or a stale dir left by an earlier process) carries
        different counters — skipping it would make a later restore adopt
        the wrong ``round``/RNG stream.

        With ``save_async`` the step loop only pays for the snapshot (a
        non-blocking device-side copy — the trainer's donated buffers are
        never handed to the writer) plus any double-buffer backpressure
        from a still-uncommitted previous save; serialization, the atomic
        commit, the digest, and pruning all run on the background writer.
        The caller resolves the NaN-streak validity gate BEFORE calling
        save() — i.e. at snapshot time — so a deferred write can never
        commit params the gate would have rejected."""
        import shutil
        from ..nnet import sharded_ckpt
        tr = self.trainer
        step = tr.sample_counter
        if self._async is not None and not self._async_usable():
            self._async.close()
            self._async = None
        if self._async is not None:
            with span('train.save', 'train', step=step, mode='async'):
                self._async.save_sharded_async(
                    self.ckpt_dir, step, tr.snapshot_training_state(),
                    retry=self.config.retry,
                    on_commit=lambda _p: self._prune())
            if self.config.on_save is not None:
                self.config.on_save(step)
            return sharded_ckpt.step_dir(self.ckpt_dir, step)
        with span('train.save', 'train', step=step, mode='sync'):
            old = sharded_ckpt.step_dir(self.ckpt_dir, step)
            if os.path.isdir(old):
                shutil.rmtree(old, ignore_errors=True)
            path = tr.save_training_state(self.ckpt_dir, step,
                                          retry=self.config.retry)
        self._prune()
        if self.config.on_save is not None:
            self.config.on_save(step)
        return path

    def _async_usable(self) -> bool:
        """The native async writer gathers every leaf onto this host
        (``np.asarray``); state sharded across HOSTS is not fully
        addressable and would fail (or, addressable-but-huge, spike host
        memory) where the sync orbax path writes shards in place.  Checked
        once at the first save: multi-host state falls back to synchronous
        saves with a logged ``save_async_fallback`` record instead of
        failing every save at its barrier."""
        if getattr(self, '_async_checked', False):
            return True
        import jax
        tr = self.trainer
        ok = all(getattr(x, 'is_fully_addressable', True)
                 for x in jax.tree.leaves(
                     {'p': tr.params, 'o': tr.opt_state, 'g': tr.grad_acc}))
        if ok:
            self._async_checked = True
            return True
        self.failure_log.record(
            'save_async_fallback',
            'training state is not fully host-addressable (multi-host '
            'shards): async native saves would gather — falling back to '
            'synchronous sharded saves')
        return False

    def wait_for_saves(self) -> None:
        """Barrier on the async writer (no-op in sync mode): blocks until
        the in-flight save commits and re-raises its deferred failure —
        the sync path's error surface, one boundary late.  ``run()``
        passes the FINAL save through this always."""
        if self._async is not None:
            with span('train.save_barrier', 'train'):
                self._async.wait()

    def close(self) -> None:
        """Release the background writer's threads (drains first).  The
        supervisor stays usable for sync saves afterwards; long-lived
        embedders (the CLI, wrapper.py) should call this when done —
        each un-closed async supervisor otherwise parks 1 + save_workers
        idle threads until process exit."""
        if self._async is not None:
            self._async.close()
            self._async = None

    def _prune(self) -> None:
        """Bound disk growth: keep only the ``keep_last`` newest intact
        checkpoints (the exact-sidecar pruning idiom, main.py), which is
        also how deep the corrupt-fallback chain can reach.  Quarantined
        ``.corrupt`` dirs get the same bound — they are full-size
        checkpoints kept for post-mortem, and exactly the degraded-storage
        deployments that produce them can least afford unbounded growth."""
        keep = self.config.keep_last
        if not keep:
            return
        import shutil
        from ..nnet import sharded_ckpt
        for step in sharded_ckpt.all_steps(self.ckpt_dir)[keep:]:
            shutil.rmtree(sharded_ckpt.step_dir(self.ckpt_dir, step),
                          ignore_errors=True)
        for step in sharded_ckpt.quarantined_steps(self.ckpt_dir)[keep:]:
            shutil.rmtree(
                sharded_ckpt.step_dir(self.ckpt_dir, step) + '.corrupt',
                ignore_errors=True)

    def restore(self) -> int:
        """Restore the newest intact checkpoint (quarantining corrupt
        ones) into the trainer — params, optimizer state, counters — and
        clear in-flight per-step state the fault may have poisoned."""
        if self._async is not None:
            # barrier on any pending save BEFORE scanning the dir: the
            # newest checkpoint may still be mid-commit, and restoring
            # while its writer races the scan could roll back one step
            # further than necessary.  drain(), not wait(): a FAILED
            # pending save is already in the log, and recovery must fall
            # back to the previous good step, not die on the save error.
            self._async.drain()
        tr = self.trainer
        with span('train.restore', 'train'):
            tr.reset_transient_state()
            step = tr.load_training_state(self.ckpt_dir,
                                          restore_params=True,
                                          fallback=True,
                                          retry=self.config.retry)
        self.failure_log.record('restored', f'resumed from step {step}',
                                step=step)
        return step

    # --- the supervised loop ----------------------------------------------
    def run(self, batch_factory: Callable[[int], Iterator],
            n_steps: Optional[int] = None,
            before_step: Optional[Callable[[int], None]] = None,
            make_stepper: Optional[Callable[[], object]] = None) -> int:
        """Supervised step loop over one epoch of batches.

        ``batch_factory(k)`` must yield batch k, k+1, ... deterministically
        (see module docstring).  Runs until at least ``n_steps`` updates
        have been applied this call (a windowed stepper can overshoot by
        up to K-1 inside the dispatch that crosses the budget; staged
        batches beyond it are discarded, never dispatched), or until the
        factory's iterator is exhausted when ``n_steps`` is None.
        Returns the number of updates applied.
        ``before_step(i)`` (i = batches consumed so far this call) runs
        before each batch — progress printing / trace windows hook here.

        ``make_stepper`` composes the scanned K-dispatch hot loop with
        supervision (``nnet.execution.ExecutionPlan.round_stepper``): a
        fresh ``WindowedStepper`` is built per (re)start, batches feed it
        instead of ``trainer.update``, and recovery operates at
        dispatch-window granularity — the re-wind targets the restored
        ``sample_counter`` (epoch-absolute, counts only DISPATCHED
        steps), so batches staged into a window a fault destroyed are
        simply re-pulled.  The divergence gate still sees every per-step
        loss (the scan returns the full vector; ``trainer._gate_losses``).
        Default (None) is the classic per-step loop.  Periodic saves land
        at window boundaries: a save fires when a dispatch CROSSES a
        ``save_every`` multiple, which for the per-step default reduces to
        the historical every-``save_every``-steps cadence exactly.

        On a recoverable fault: log -> restore last good checkpoint ->
        re-create the batch stream at the restored position -> continue.
        After ``max_restarts`` recoveries the fault propagates (with the
        failure log telling the whole story).
        """
        from ..nnet.execution import WindowedStepper
        cfg = self.config
        tr = self.trainer
        base = tr.sample_counter
        # anchor: recovery can never roll back past this run's entry
        # state — unless that state is suspect: a NaN streak left open
        # by a previous round means the params may already be poisoned,
        # and anchoring them would make them the newest restore target
        # (the death loop every other save guard exists to prevent).
        # With no anchor, recovery may lawfully roll back PAST base to
        # the last clean checkpoint.
        anchored = tr.nan_streak == 0
        last_saved = None
        if anchored:
            self.save()
            last_saved = tr.sample_counter
        else:
            self.failure_log.record(
                'save_skipped',
                f'anchor skipped: {tr.nan_streak} non-finite loss(es) '
                f'open at run() entry', step=tr.sample_counter)
        restarts = 0
        self.state = 'RUNNING'
        while True:
            start = tr.sample_counter - base
            # the first batch after a (re)start lawfully includes epoch
            # setup (page permutation, cold decode caches) and the
            # re-wind — reproducing `start` batches takes up to `start`
            # production intervals.  Grant at least the same 5x grace
            # the io-level buffer gives epoch setup, more after a deep
            # recovery, instead of letting either deterministically
            # re-trip the watchdog and exhaust max_restarts
            first = None if cfg.batch_deadline is None \
                else cfg.batch_deadline * max(5, start + 1)
            if first is not None and cfg.pipeline_stats is not None:
                # pooled producers (nworker): the first batch also fills
                # the pool's in-flight window before anything is emitted
                first *= 2
            # fault_base keeps injected stall indices epoch-absolute
            # across restarts (the producer's enumerate restarts at 0)
            buf = ThreadBuffer(lambda s=start: batch_factory(s),
                               buffer_size=cfg.buffer_size,
                               deadline=cfg.batch_deadline,
                               first_deadline=first,
                               fault_scope='batch',
                               fault_base=start)
            buf.stats = cfg.pipeline_stats
            # a FRESH stepper per (re)start: a fault mid-window abandons
            # the staged-but-undispatched batches, and the re-wound
            # stream re-pulls them into a new window
            stepper = (make_stepper() if make_stepper is not None
                       else WindowedStepper(tr, k=1, lookahead=0))
            fed = start
            try:
                for batch in buf:
                    if before_step is not None:
                        before_step(fed)
                    fed += 1
                    delta = stepper.feed(batch)
                    done = tr.sample_counter - base
                    if delta and cfg.save_every \
                            and done % cfg.save_every < delta:
                        # a periodic save must never checkpoint
                        # NaN-poisoned params — it would become the
                        # "newest intact" restore target (a CRC digest
                        # cannot see NaNs) and wedge recovery in a
                        # death loop.  Settle the one-step-deferred
                        # divergence gate first, and skip the save
                        # while a non-finite streak is open (the
                        # breaker may still be counting)
                        tr.flush_divergence_check()
                        if tr.nan_streak == 0:
                            self.save()
                            last_saved = tr.sample_counter
                    if n_steps is not None and done >= n_steps:
                        # budget reached: staged-but-undispatched batches
                        # are DISCARDED, not finished — a windowed stepper
                        # may overshoot by at most K-1 within the dispatch
                        # that crossed the line, never by a whole tail
                        stepper.discard()
                        break
                # the epoch tail (a part-filled window, or the K=1
                # lookahead's last batch) dispatches per-step INSIDE the
                # try: a tail-step fault recovers like any other
                stepper.finish()
                # the divergence gate is deferred one step: the LAST
                # update's loss is still pending — settle it inside the
                # try so a final-step NaN recovers like any other
                tr.flush_divergence_check()
            except self.RECOVERABLE as e:
                self.state = 'FAILED'
                # quiesce the pipeline BEFORE restoring: a still-running
                # producer would keep pulling batches (and consuming
                # injected fault events) underneath the recovery
                buf.close(timeout=5.0)
                self.failure_log.record(
                    type(e).__name__, str(e), step=tr.sample_counter)
                restarts += 1
                self.restarts_total += 1
                if restarts > cfg.max_restarts:
                    self.failure_log.record(
                        'giving_up',
                        f'{restarts - 1} restarts exhausted '
                        f'({self.failure_log.summary()})',
                        step=tr.sample_counter)
                    if self._async is not None:
                        # don't abandon an in-flight save on the way out:
                        # it may be the newest recovery point a wrapping
                        # retry (or operator) restores from
                        self._async.drain()
                    raise
                self.state = 'RESTORING'
                self.restore()
                if anchored and tr.sample_counter < base:
                    raise faults.CheckpointCorruptError(
                        f'restored to step {tr.sample_counter}, before '
                        f'this run\'s anchor {base}')
                if tr.sample_counter < base:
                    # un-anchored entry: rolling back past base to the
                    # last clean checkpoint is the intended outcome
                    base = tr.sample_counter
                self.state = 'RUNNING'
            else:
                # same guard as the periodic save: never leave
                # mid-NaN-streak params as the newest restore target —
                # and skip the rewrite when a periodic save already
                # committed this exact step
                if tr.nan_streak != 0:
                    self.failure_log.record(
                        'save_skipped',
                        f'final save skipped: {tr.nan_streak} non-finite '
                        f'loss(es) open', step=tr.sample_counter)
                elif last_saved != tr.sample_counter:
                    self.save()
                # the FINAL save always barriers: returning with the
                # newest checkpoint still uncommitted would let a process
                # exit lose it (deferred write errors surface here too)
                self.wait_for_saves()
                self.state = 'IDLE'
                return tr.sample_counter - base
            finally:
                buf.close(timeout=5.0)
