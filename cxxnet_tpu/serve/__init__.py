"""Online inference serving (doc/serving.md).

The first subsystem on the serving half of the north star: turn the
offline ``task=pred`` loop into an always-on predict service that can
sit behind heavy live traffic.

* :class:`~cxxnet_tpu.serve.engine.PredictEngine` — inference-only model
  state, jitted predict over a small closed ladder of batch-size buckets
  (compile cache provably bounded), atomic hot parameter swap,
* :class:`~cxxnet_tpu.serve.batcher.DynamicBatcher` — bounded request
  queue with admission control, a max-wait/max-batch coalescing window,
  per-request deadlines, per-bucket latency/throughput stats,
* :class:`~cxxnet_tpu.serve.registry.ModelRegistry` — watch the training
  run's ``model_dir`` for new atomically-renamed checkpoints,
  digest-verify, warm, swap — without dropping in-flight requests.

Entry points: ``task=serve`` in the CLI (``main.py``), ``Net.serve_*``
in the Python wrapper, ``net_serve_*`` in the C ABI glue (``capi.py``).
"""

from ..runtime.faults import (DeadlineExceededError, ServeError,
                              ServeOverloadError)
from .batcher import DynamicBatcher, ServeRequest
from .engine import PredictEngine
from .registry import ModelRegistry, load_model_params

__all__ = ['PredictEngine', 'DynamicBatcher', 'ServeRequest',
           'ModelRegistry', 'load_model_params', 'ServeError',
           'ServeOverloadError', 'DeadlineExceededError']
