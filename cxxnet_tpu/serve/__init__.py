"""Online inference serving (doc/serving.md).

The first subsystem on the serving half of the north star: turn the
offline ``task=pred`` loop into an always-on predict service that can
sit behind heavy live traffic.

* :class:`~cxxnet_tpu.serve.engine.PredictEngine` — inference-only model
  state, jitted predict over a small closed ladder of batch-size buckets
  (compile cache provably bounded), atomic hot parameter swap,
* :class:`~cxxnet_tpu.serve.batcher.DynamicBatcher` — bounded request
  queue with admission control, a max-wait/max-batch coalescing window,
  per-request deadlines, per-bucket latency/throughput stats,
* :class:`~cxxnet_tpu.serve.registry.ModelRegistry` — watch the training
  run's ``model_dir`` for new atomically-renamed checkpoints,
  digest-verify, warm, swap — without dropping in-flight requests,
* :class:`~cxxnet_tpu.serve.decode.DecodeEngine` — continuous-batching
  autoregressive decode: one persistent compiled step over a paged KV
  cache, requests join/leave at token boundaries, token streams
  bitwise-twin offline ``transformer.generate``,
* :class:`~cxxnet_tpu.serve.registry.MultiModelRegistry` — N models on
  one chip under a :class:`~cxxnet_tpu.serve.registry.MemoryBudgeter`
  (evict-cold, never the serving model; per-model reload machinery),
* :class:`~cxxnet_tpu.serve.kvcache.TieredKVCache` — graftcache: the
  tiered KV prefix cache (HBM page pool → bounded host RAM →
  crc32-digested disk records) behind the prefix index; evictions
  demote instead of dropping, later hits promote back without a
  re-prefill, and ``serve.kv_share_dir`` lets N replicas adopt each
  other's tier-2 records,
* :mod:`~cxxnet_tpu.serve.scenario` — graftstorm: seeded, replayable
  adversarial traffic scenarios (``serve.scenario=``) with an exactly
  reconciling :class:`~cxxnet_tpu.serve.scenario.ScenarioLedger`,
* graftshard — mesh-sharded decode serving: ``serve.shard=tp:N``
  head-shards the decode params + paged KV pool across N devices with
  every stream a bitwise twin of single-device ``generate``;
  ``serve.prefill_workers=N`` disaggregates prompt prefill onto
  dedicated threads; :class:`~cxxnet_tpu.serve.engine.\
ReplicatedPredictEngine` puts N data-parallel predict replicas behind
  one batcher (``serve.replicas=N``),
* :class:`~cxxnet_tpu.serve.autoscale.Autoscaler` — SLO-verdict-driven
  scaling over declared-safe surfaces (``serve.autoscale=``), bounded,
  hysteresis-damped, reversible; explicit typed degradation at the
  ceiling.

Entry points: ``task=serve`` (+ ``serve.mode=decode``) in the CLI
(``main.py``), ``Net.serve_*`` in the Python wrapper, ``net_serve_*`` /
``lm_serve_*`` in the C ABI glue (``capi.py``).
"""

from ..runtime.faults import (AutoscaleDegradedError, AutoscaleError,
                              DeadlineExceededError,
                              DecodePagesExhaustedError,
                              DecodeSlotsExhaustedError,
                              KVCorruptRecordError, KVSpillError,
                              KVTierError, MemoryBudgetExceededError,
                              RequestAbandonedError, ServeError,
                              ServeOverloadError, TokenDeadlineExceededError)
from .autoscale import AutoscalePolicy, Autoscaler
from .batcher import DynamicBatcher, ServeRequest
from .decode import (DecodeEngine, DecodeService, lm_loader,
                     load_lm_params, save_lm_params)
from .engine import PredictEngine, ReplicatedPredictEngine
from .kvcache import TieredKVCache
from .kvstore import KVStore
from .registry import (MemoryBudgeter, ModelRegistry, MultiModelRegistry,
                       load_model_params)
from .scenario import (ScenarioLedger, ScenarioRequest, ScenarioSpec,
                       drive_scenario)

__all__ = ['PredictEngine', 'ReplicatedPredictEngine', 'DynamicBatcher',
           'ServeRequest',
           'ModelRegistry', 'MultiModelRegistry', 'MemoryBudgeter',
           'load_model_params', 'DecodeEngine', 'DecodeService',
           'save_lm_params', 'load_lm_params', 'lm_loader',
           'ScenarioSpec', 'ScenarioRequest', 'ScenarioLedger',
           'drive_scenario', 'AutoscalePolicy', 'Autoscaler', 'ServeError',
           'ServeOverloadError', 'DeadlineExceededError',
           'TokenDeadlineExceededError', 'DecodeSlotsExhaustedError',
           'DecodePagesExhaustedError', 'MemoryBudgetExceededError',
           'RequestAbandonedError', 'AutoscaleError',
           'AutoscaleDegradedError', 'TieredKVCache', 'KVStore',
           'KVTierError', 'KVCorruptRecordError', 'KVSpillError']
