"""SLO-driven autoscaling with graceful degradation
(doc/serving.md "Scenarios and autoscaling").

The observability stack opened a loop — gauges (PR 13), typed SLO
verdicts (PR 14), compiler/HBM truth (PR 15) — and this module closes
it, μ-cuDNN-style: *measure, then adapt within declared-safe bounds*.
The :class:`Autoscaler` reads ``hub.slos_view()`` verdicts and
``hub.gauge_snapshot()`` and acts ONLY through surfaces the serving
stack already proves safe:

* ``DecodeEngine.set_live_limits`` — grow/shrink decode slot and KV
  page ADMISSION caps (the physical pool is baked into the compiled
  step; clamping admission is token-boundary safe by construction and
  never frees a referenced page),
* ``DynamicBatcher.set_max_queue`` — admission queue capacity,
* ``MemoryBudgeter.set_budget`` / fleet eviction — device-memory
  pressure relief in multi-model serving,
* ``OnlinePipeline.set_qps`` / ``set_train_throttle`` — the
  train/serve split in ``task=online``.

Every action is bounded by the policy's declared min/max, rate-limited
per knob (``cooldown``), damped by consecutive-verdict hysteresis
(``hysteresis`` — an OK↔AT_RISK flap at a burn-rate boundary produces
ZERO actions), span-logged, and reversible (sustained OK drifts every
knob back to its bound baseline).  A verdict the autoscaler cannot
repair — still BREACHED with every knob at its ceiling — degrades
*explicitly*: admission clamps to the declared floor so sheds stay
typed (``ServeOverloadError``), and a typed
:class:`~cxxnet_tpu.runtime.faults.AutoscaleDegradedError` lands in the
failure log.  Silence is never an outcome.
"""

from __future__ import annotations

import collections
import math
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import format_report, get_hub, record_event
from ..runtime import faults
from ..utils.config import parse_kv_list
from ..utils.metric import StatSet

__all__ = ['AutoscalePolicy', 'Autoscaler', 'Knob', 'worst_verdict',
           'OK', 'AT_RISK', 'BREACHED']

OK, AT_RISK, BREACHED = 'OK', 'AT_RISK', 'BREACHED'
_SEVERITY = {OK: 0, AT_RISK: 1, BREACHED: 2}


def worst_verdict(view: dict) -> str:
    """Worst SLO state across a ``slos_view()`` body (no specs / no
    data = OK) — the one verdict-reading rule every controller that
    rides the scaling machinery shares (the autoscaler here, the online
    :class:`~cxxnet_tpu.tune.TuneController`)."""
    worst = OK
    for entry in (view or {}).values():
        state = entry.get('state', OK) if isinstance(entry, dict) \
            else str(entry)
        if _SEVERITY.get(state, 0) > _SEVERITY[worst]:
            worst = state
    return worst


@dataclass(frozen=True)
class AutoscalePolicy:
    """Declared bounds and damping for one autoscaler
    (``serve.autoscale=`` config grammar, ``k=v;k=v...``).

    ``min_*``/``max_*`` bound each knob (slots/pages clamp further to
    the engine's physical capacity at bind time); ``cooldown`` is the
    per-knob action rate limit in seconds; ``hysteresis`` is how many
    CONSECUTIVE same-direction evaluations must agree before anything
    moves; ``step`` is the multiplicative grow/shrink factor;
    ``interval`` > 0 starts a ``cxxnet-scale-*`` evaluation thread
    (0 = manual :meth:`Autoscaler.evaluate` ticks — tests and the
    scenario bench drive it deterministically)."""

    min_slots: int = 1
    max_slots: int = 0          # 0 = engine physical capacity
    min_pages: int = 1
    max_pages: int = 0          # 0 = engine physical capacity
    min_queue: int = 1
    max_queue: int = 0          # 0 = the batcher's bound at bind time
    cooldown: float = 0.25
    hysteresis: int = 2
    step: float = 1.5
    interval: float = 0.0

    #: grammar keys :meth:`parse` accepts — the doc/serving.md
    #: autoscale table is drift-tested against this tuple
    KEYS = ('min_slots', 'max_slots', 'min_pages', 'max_pages',
            'min_queue', 'max_queue', 'cooldown', 'hysteresis', 'step',
            'interval')

    @classmethod
    def registered_keys(cls) -> Tuple[str, ...]:
        return cls.KEYS

    @classmethod
    def parse(cls, text: str) -> 'AutoscalePolicy':
        ints = {'min_slots', 'max_slots', 'min_pages', 'max_pages',
                'min_queue', 'max_queue', 'hysteresis'}
        kw: Dict[str, object] = {}
        for key, val in parse_kv_list(text):
            if key not in cls.KEYS:
                raise ValueError(f'unknown autoscale option: {key!r}')
            kw[key] = int(val) if key in ints else float(val)
        pol = cls(**kw)
        if pol.hysteresis < 1:
            raise ValueError('hysteresis must be >= 1')
        if pol.step <= 1.0:
            raise ValueError('step must be > 1.0')
        for lo, hi in (('min_slots', 'max_slots'),
                       ('min_pages', 'max_pages'),
                       ('min_queue', 'max_queue')):
            lo_v, hi_v = getattr(pol, lo), getattr(pol, hi)
            if lo_v < 1 or (hi_v and hi_v < lo_v):
                raise ValueError(f'need 1 <= {lo} <= {hi} (0 = unbounded '
                                 f'ceiling), got {lo_v}..{hi_v}')
        return pol

    def describe(self) -> str:
        """Round-trips through :meth:`parse`."""
        return (f'min_slots={self.min_slots};max_slots={self.max_slots};'
                f'min_pages={self.min_pages};max_pages={self.max_pages};'
                f'min_queue={self.min_queue};max_queue={self.max_queue};'
                f'cooldown={self.cooldown:g};'
                f'hysteresis={self.hysteresis};step={self.step:g};'
                f'interval={self.interval:g}')


class Knob:
    """One bounded, reversible control surface: a current value moved
    multiplicatively between [lo, hi], restored toward its baseline on
    sustained OK.  The setter is the ONLY side effect.  Public since the
    autotuner's online leg (cxxnet_tpu/tune/controller.py) re-plans
    through the same bounded-knob machinery."""

    def __init__(self, name: str, lo: int, hi: int, value: int,
                 setter: Callable[[int], object]):
        if not lo <= value <= hi:
            value = max(lo, min(hi, value))
            setter(value)
        self.name = name
        self.lo = int(lo)
        self.hi = int(hi)
        self.baseline = int(value)
        self.value = int(value)
        self.setter = setter
        self.last_action = -math.inf    # monotonic secs

    def target(self, direction: int, step: float) -> int:
        if direction > 0:
            return min(self.hi, max(self.value + 1,
                                    int(math.ceil(self.value * step))))
        # downward drift is always TOWARD the baseline, never past it:
        # reversibility means returning to the declared resting point
        if self.value <= self.baseline:
            return self.value
        return max(self.baseline, int(self.value / step))


_Knob = Knob     # pre-PR-19 private spelling, kept importable


class Autoscaler:
    """Closes the verdict loop over bound serving components.

    ``verdicts``/``gauges`` are injectable zero-arg callables (default:
    the hub's ``slos_view``/``gauge_snapshot``) so tests and the bench
    drive scaling decisions deterministically.  :meth:`evaluate` is the
    whole control law — one call per tick, manual unless
    ``policy.interval`` > 0."""

    def __init__(self, policy: AutoscalePolicy, hub=None,
                 verdicts: Optional[Callable[[], dict]] = None,
                 gauges: Optional[Callable[[], dict]] = None,
                 failure_log=None, name: str = 'autoscale'):
        self.policy = policy
        self.name = name
        self._hub = hub
        self._verdicts = verdicts
        self._gauges = gauges
        self._log = failure_log
        self.stats = StatSet()
        self._lock = threading.Lock()
        self._knobs: Dict[str, Knob] = {}       # guarded-by: _lock
        self._engine = None                      # guarded-by: _lock
        self._fleet = None                       # guarded-by: _lock
        self._online = None                      # guarded-by: _lock
        self._streak = 0                         # guarded-by: _lock
        self._streak_dir = 0                     # guarded-by: _lock
        self._degraded = False                   # guarded-by: _lock
        self._last_verdict = OK                  # guarded-by: _lock
        self._history: collections.deque = (
            collections.deque(maxlen=256))       # guarded-by: _lock
        self._closed = False                     # guarded-by: _lock
        self._ticker: Optional[threading.Thread] = None
        if policy.interval > 0:
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True,
                name=f'cxxnet-scale-{name}')
            self._ticker.start()

    # -- binding safe surfaces ---------------------------------------------
    def bind_engine(self, engine) -> None:
        """Bind a ``DecodeEngine``: its live slot/page admission caps
        become the ``slots``/``pages`` knobs, bounded by policy ∩
        physical capacity.  The CURRENT caps are the baseline the
        autoscaler returns to on sustained OK."""
        pol = self.policy
        slot_cap, page_cap = engine.live_limits()
        phys_slots, phys_pages = engine.slots, engine.n_pages - 1
        with self._lock:
            self._engine = engine
            self._knobs['slots'] = Knob(
                'slots', max(1, pol.min_slots),
                min(phys_slots, pol.max_slots or phys_slots), slot_cap,
                lambda v: engine.set_live_limits(max_slots=v))
            self._knobs['pages'] = Knob(
                'pages', max(1, pol.min_pages),
                min(phys_pages, pol.max_pages or phys_pages), page_cap,
                lambda v: engine.set_live_limits(max_pages=v))

    def bind_batcher(self, batcher) -> None:
        """Bind a ``DynamicBatcher``: admission queue capacity becomes
        the ``queue`` knob — also the degradation rung's clamp."""
        pol = self.policy
        with self._lock:
            self._knobs['queue'] = Knob(
                'queue', max(1, pol.min_queue),
                max(pol.max_queue or batcher.max_queue,
                    batcher.max_queue),
                batcher.max_queue, batcher.set_max_queue)

    def bind_fleet(self, fleet) -> None:
        """Bind a multi-model fleet (``MultiModelRegistry`` or a bare
        ``MemoryBudgeter``): under sustained pressure the autoscaler
        relieves device memory by evicting through the registry's own
        never-busy/never-pinned eviction policy."""
        with self._lock:
            self._fleet = fleet

    def bind_online(self, pipeline) -> None:
        """Bind an ``OnlinePipeline``: the train/serve split becomes a
        control surface (throttle training under serving pressure,
        release it on sustained OK)."""
        with self._lock:
            self._online = pipeline

    # -- verdict + gauge sources -------------------------------------------
    def _read_verdict(self) -> str:
        """Worst state across every SLO spec (no specs / no data = OK)."""
        src = self._verdicts
        if src is None:
            hub = self._hub if self._hub is not None else get_hub()
            src = hub.slos_view
        return worst_verdict(src() or {})

    def gauge_view(self) -> dict:
        src = self._gauges
        if src is None:
            hub = self._hub if self._hub is not None else get_hub()
            src = hub.gauge_snapshot
        return src() or {}

    # -- the control law ---------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One tick: read the verdict, update the hysteresis streak,
        act at most once per knob (cooldown-bounded, min/max-bounded).
        Returns the actions taken (possibly empty) — each a dict
        ``{knob, from, to, verdict}`` also kept in :attr:`history`."""
        now = time.monotonic() if now is None else float(now)
        verdict = self._read_verdict()
        direction = 1 if verdict in (AT_RISK, BREACHED) else -1
        with self._lock:
            if self._closed:
                return []
            self._last_verdict = verdict
            if direction != self._streak_dir:
                # direction change resets the streak: a verdict flapping
                # at a burn-rate boundary never accumulates enough
                # agreement to act — zero oscillating actions
                self._streak_dir = direction
                self._streak = 1
            else:
                self._streak += 1
            self.stats.gauge('verdict', _SEVERITY[verdict])
            self.stats.gauge('streak', self._streak * direction)
            if self._streak < self.policy.hysteresis:
                return []
            actions = self._act(direction, verdict, now)
            if actions:
                self.stats.inc('actions', len(actions))
            return actions

    def _act(self, direction, verdict, now):  # requires-lock: _lock
        actions: List[dict] = []

        def move(knob, target, kind):  # requires-lock: _lock
            frm, knob.value = knob.value, int(target)
            knob.last_action = now
            knob.setter(knob.value)
            act = {'knob': knob.name, 'from': frm, 'to': knob.value,
                   'verdict': verdict, 'kind': kind}
            actions.append(act)
            self._history.append(act)
            record_event(f'autoscale.{kind}', 'autoscale',
                         knob=knob.name, frm=frm, to=knob.value,
                         verdict=verdict)

        headroom = False
        for knob in self._knobs.values():
            if self._degraded and knob.name == 'queue':
                # the degraded rung clamped admission explicitly; only
                # sustained recovery re-opens it — growing it back under
                # the same pressure that degraded us would oscillate
                continue
            tgt = knob.target(direction, self.policy.step)
            if direction > 0 and knob.value < knob.hi:
                headroom = True
            if tgt == knob.value:
                continue
            if now - knob.last_action < self.policy.cooldown:
                continue
            move(knob, tgt, 'grow' if direction > 0 else 'shrink')
        if direction > 0:
            self._act_pressure(verdict, headroom, bool(actions), now,
                               move)
        else:
            self._act_recovered(now, move)
        return actions

    def _act_pressure(self, verdict, headroom, acted, now, move):  # requires-lock: _lock
        """Degradation ladder under sustained AT_RISK/BREACHED:
        (1) the knob moves above already grew toward declared ceilings;
        (2) relieve shared pressure — throttle the train half, evict
        cold fleet models; (3) at the ceiling with the objective still
        BREACHED, degrade explicitly: clamp admission to the floor so
        sheds stay typed, and record the typed kind."""
        if self._online is not None:
            try:
                self._online.set_train_throttle(0.01 * _SEVERITY[verdict])
            # lint: allow(fault-taxonomy): a detached pipeline must not kill the control loop
            except Exception:
                pass
        if verdict != BREACHED or headroom or acted:
            return
        if self._fleet is not None:
            evict = getattr(self._fleet, 'evict_coldest', None)
            if evict is not None:
                try:
                    if evict():
                        self.stats.inc('fleet_evictions')
                # lint: allow(fault-taxonomy): eviction is best-effort relief; failure falls through to explicit degradation
                except Exception:
                    pass
        if not self._degraded:
            q = self._knobs.get('queue')
            if q is not None and q.value > q.lo:
                move(q, q.lo, 'degrade')
            self._degraded = True
            self.stats.gauge('degraded', 1)
            err = faults.AutoscaleDegradedError(
                self.name, verdict, len(self._history))
            log = self._log if self._log is not None \
                else faults.global_failure_log()
            log.record(type(err).__name__, str(err))

    def _act_recovered(self, now, move):  # requires-lock: _lock
        if self._online is not None:
            try:
                self._online.set_train_throttle(0.0)
            # lint: allow(fault-taxonomy): a detached pipeline must not kill the control loop
            except Exception:
                pass
        if self._degraded:
            # leave the degraded rung the same way we entered it:
            # explicitly, back to the queue baseline
            q = self._knobs.get('queue')
            if q is not None and q.value < q.baseline:
                move(q, q.baseline, 'recover')
            self._degraded = False
            self.stats.gauge('degraded', 0)

    # -- introspection / lifecycle -----------------------------------------
    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def knob_values(self) -> Dict[str, int]:
        with self._lock:
            return {k: kn.value for k, kn in self._knobs.items()}

    def status_view(self) -> dict:
        """The ``/statusz`` provider body: policy, verdict, streak,
        knob state, recent actions, bound-engine capacity truth."""
        with self._lock:
            out = {
                'policy': self.policy.describe(),
                'verdict': self._last_verdict,
                'streak': self._streak * self._streak_dir,
                'degraded': self._degraded,
                'knobs': {k: {'value': kn.value, 'lo': kn.lo,
                              'hi': kn.hi, 'baseline': kn.baseline}
                          for k, kn in self._knobs.items()},
                'actions': list(self._history)[-16:],
            }
            engine = self._engine
        if engine is not None:
            out['engine'] = engine.capacity_view()
        return out

    def register_into(self, hub, name: Optional[str] = None):
        """Register stats + the ``/statusz`` provider under ``name``."""
        name = name or self.name
        hub.register_stats(name, self.stats)
        hub.register_status(name, self.status_view)
        return self

    def report(self, name: Optional[str] = None) -> str:
        return format_report(name or self.name, self.stats)

    def _tick_loop(self) -> None:
        while True:
            time.sleep(self.policy.interval)
            with self._lock:
                if self._closed:
                    return
            self.evaluate()

    def close(self, timeout: Optional[float] = None) -> None:
        with self._lock:
            self._closed = True
        if self._ticker is not None:
            self._ticker.join(timeout if timeout is not None
                              else self.policy.interval + 1.0)
