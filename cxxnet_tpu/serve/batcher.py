"""Dynamic micro-batching: coalesce concurrent requests onto bucket shapes.

The batching core of the serving subsystem (doc/serving.md).  Individual
clients submit small, oddly-sized requests; executing each alone wastes
the accelerator (a 1-row forward costs nearly as much as a 32-row one)
and, worse, every novel size would be a fresh XLA compile.  The
``DynamicBatcher`` sits between clients and a ``PredictEngine``:

* a **bounded queue** with admission control — a full queue rejects
  immediately with a typed ``ServeOverloadError`` (fail fast beats
  queueing into certain deadline misses),
* a **batching window** — the worker takes the oldest request, then
  waits at most ``max_wait`` seconds (or until ``max_batch`` rows, the
  engine's largest bucket) for more requests to coalesce.  Arrival order
  is preserved; requests are never split across executed batches,
* **per-request deadlines** — a request whose deadline passes before its
  batch runs gets a typed ``DeadlineExceededError`` instead of a stale
  answer; the caller side of :meth:`wait` enforces the same bound, so a
  wedged worker cannot strand clients,
* **metrics** — per-bucket latency distributions, throughput, queue
  depth and shed counters accumulate in a ``utils.metric.StatSet`` and
  print in the familiar ``\\tname-metric:value`` eval-line format at
  shutdown.

Thread model: any number of client threads call :meth:`submit`; one
daemon worker drains the queue and drives the engine.  ``close()`` is
idempotent and re-entrant — it finishes queued work, then joins.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, List, Optional

import numpy as np

from ..obs import format_report, next_trace_id, record_event, span
from ..runtime.faults import (DeadlineExceededError, RequestAbandonedError,
                              ServeError, ServeOverloadError)
from ..utils.bucketing import bucket_for
from ..utils.metric import StatSet

__all__ = ['DynamicBatcher', 'ServeRequest']


class ServeRequest:
    """One in-flight request: payload rows in, scores (or a typed error)
    out, with a completion event the client blocks on.

    ``meta`` carries per-request options for engines that need more than
    rows — the decode engine (serve/decode.py) reads ``max_new`` /
    ``temperature`` / ``rng`` from it and streams emitted token ids into
    ``tokens`` (with per-token emit times in ``token_times``) before
    setting the completion event."""

    __slots__ = ('data', 'n', 't_submit', 'deadline', 'deadline_abs',
                 'event', 'result', 'error', 'abandoned', 'meta',
                 'tokens', 'token_times', 'trace_id')

    def __init__(self, data: np.ndarray, deadline: float, meta=None):
        self.data = data
        self.n = int(data.shape[0])
        # one trace id per request lifetime: every span of this
        # request's lifecycle (admit -> queue -> prefill -> decode ->
        # emit -> finish) carries it, across batcher and engine threads
        self.trace_id = next_trace_id()
        self.t_submit = time.monotonic()
        self.deadline = float(deadline)
        self.deadline_abs = self.t_submit + float(deadline)
        self.event = threading.Event()
        self.result: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.meta = meta or {}
        self.tokens: list = []          # incremental decode emissions
        self.token_times: list = []
        # set by wait() on caller timeout or by abandon() when a slow
        # client walks away: the worker drops the request at pop time
        # (best-effort — a request already mid-batch still executes)
        # instead of burning a forward nobody reads.  The DROP side owns
        # the count (single-owner counting: every submitted request
        # lands in exactly one terminal counter, always worker/engine
        # side, so `submitted` reconciles exactly — doc/serving.md)
        self.abandoned = False


class DynamicBatcher:
    """Coalesce concurrent predict requests into bucket-sized batches.

    ``engine`` is a ``serve.engine.PredictEngine`` (anything with
    ``predict_scores(np.ndarray) -> np.ndarray`` and a ``buckets``
    ladder works).  ``max_wait`` trades tail latency for batch
    efficiency; ``deadline`` is the default per-request bound.
    """

    def __init__(self, engine, max_queue: int = 64, max_wait: float = 0.002,
                 deadline: float = 1.0, stats: Optional[StatSet] = None,
                 cost_fn=None, max_cost: int = 0):
        if max_queue <= 0:
            raise ValueError('max_queue must be positive')
        self.engine = engine
        # guarded-by: _cond (live-retunable via set_max_queue)
        self.max_queue = int(max_queue)
        self.max_wait = float(max_wait)
        self.deadline = float(deadline)
        self.max_batch = int(engine.buckets[-1])
        # optional per-request admission pricing (decode engines with
        # prefix sharing expose ``prefill_cost``): the coalescing window
        # ALSO closes when accumulated cost would pass ``max_cost``, so
        # a window prices prefix-hit prompts at their tails instead of
        # treating every request as one equally-expensive row
        if max_cost > 0 and cost_fn is None:
            raise ValueError('max_cost needs a cost_fn')
        self.cost_fn = cost_fn
        self.max_cost = int(max_cost)
        # engines that own request completion (the decode engine admits
        # requests into slots and finishes them from its own loop) expose
        # execute_requests; the default predict path stays synchronous
        self._exec = getattr(engine, 'execute_requests', None)
        self.stats = stats if stats is not None else StatSet()
        self._cond = threading.Condition()
        self._q: Deque[ServeRequest] = collections.deque()  # guarded-by: _cond
        self._closed = False       # guarded-by: _cond
        self._t0 = time.monotonic()
        self._worker = threading.Thread(target=self._loop, daemon=True,
                                        name='serve-batcher')
        self._worker.start()

    # -- client side -------------------------------------------------------
    def submit_async(self, data: np.ndarray,
                     deadline: Optional[float] = None,
                     meta=None) -> ServeRequest:
        """Enqueue a request; returns immediately.  Raises
        ``ServeOverloadError`` when the queue is full and ``ServeError``
        after ``close()`` — admission control never blocks."""
        data = np.asarray(data)
        if data.ndim < 2:
            raise ValueError('request must be (n, ...) with a row axis')
        req = ServeRequest(data, self.deadline if deadline is None
                           else deadline, meta=meta)
        with self._cond:
            if self._closed:
                raise ServeError('batcher is closed')
            # every admission attempt is a submission — `submitted`
            # minus the terminal counters is exactly the in-flight
            # count, which is how the scenario ledger proves no request
            # is ever dropped or double-counted (serve/scenario.py)
            self.stats.inc('submitted')
            if len(self._q) >= self.max_queue:
                self.stats.inc('rejected')
                raise ServeOverloadError(len(self._q), self.max_queue)
            self._q.append(req)
            depth = len(self._q)
            self.stats.peak('queue_peak', depth)
            self._cond.notify()
        record_event('serve.admit', 'serve', req.trace_id, rows=req.n,
                     queue_depth=depth)
        return req

    def wait(self, req: ServeRequest) -> np.ndarray:
        """Block until ``req`` completes; returns its score rows or
        raises its typed error.  Bounded by the request deadline even if
        the worker never answers."""
        remaining = req.deadline_abs - time.monotonic()
        if not req.event.wait(timeout=max(0.0, remaining) + 0.05):
            # grace covers the set()-after-deadline race; a still-unset
            # event past it means the batch never ran for us.  Mark the
            # walk-away but do NOT count here: the worker counts the
            # drop when it pops the request (single-owner counting —
            # caller-side counting double-counts when the worker later
            # expires or completes the same request)
            req.abandoned = True
            raise DeadlineExceededError(
                req.deadline, time.monotonic() - req.t_submit, req.n)
        if req.error is not None:
            raise req.error
        return req.result

    def abandon(self, req: ServeRequest) -> bool:
        """Slow-client walk-away: mark ``req`` abandoned so the worker
        drops it at pop time with a typed
        :class:`~cxxnet_tpu.runtime.faults.RequestAbandonedError`
        (counted once, on the drop side).  Best-effort by design: a
        request already past admission completes normally.  Returns
        False when the request has already finished."""
        if req.event.is_set():
            return False
        req.abandoned = True
        return True

    def submit(self, data: np.ndarray,
               deadline: Optional[float] = None) -> np.ndarray:
        """Enqueue and block for the scores — the one-call client path."""
        return self.wait(self.submit_async(data, deadline))

    # -- worker side -------------------------------------------------------
    def _expire(self, req: ServeRequest, now: float) -> None:
        req.error = DeadlineExceededError(req.deadline, now - req.t_submit,
                                          req.n)
        self.stats.inc('expired')
        record_event('serve.finish', 'serve', req.trace_id, rows=req.n,
                     error='DeadlineExceededError')
        req.event.set()

    def _drop_abandoned(self, req: ServeRequest) -> None:
        """The single worker-side drop path for an abandoned request:
        past its deadline it is a deadline miss (the caller's wait()
        already raised that), otherwise a typed client walk-away.
        Either way the drop is counted exactly once, here."""
        now = time.monotonic()
        if now >= req.deadline_abs:
            self._expire(req, now)
            return
        req.error = RequestAbandonedError(now - req.t_submit)
        self.stats.inc('abandoned')
        record_event('serve.finish', 'serve', req.trace_id, rows=req.n,
                     error='RequestAbandonedError')
        req.event.set()

    def _gather(self, first: ServeRequest) -> List[ServeRequest]:
        """Coalesce from the queue behind ``first`` until the window
        closes, the next request would overflow ``max_batch``, or —
        with a ``cost_fn`` — accumulated admission cost would pass
        ``max_cost`` (the first request always rides regardless of its
        cost)."""
        batch = [first]
        rows = first.n
        cost = self.cost_fn(first) if self.cost_fn is not None else 0
        window_end = time.monotonic() + self.max_wait
        while rows < self.max_batch:
            with self._cond:
                if not self._q:
                    remaining = window_end - time.monotonic()
                    if remaining <= 0 or self._closed:
                        break
                    self._cond.wait(remaining)
                    if not self._q:
                        continue   # spurious wake or window check
                if self._q[0].n + rows > self.max_batch:
                    break          # preserve order: don't skip ahead
                if self.max_cost > 0:
                    nxt_cost = self.cost_fn(self._q[0])
                    if cost + nxt_cost > self.max_cost:
                        self.stats.inc('cost_closed')
                        break      # preserve order: don't skip ahead
                    cost += nxt_cost
                nxt = self._q.popleft()
            if nxt.abandoned:      # caller gave up: typed drop, counted here
                self._drop_abandoned(nxt)
                continue
            now = time.monotonic()
            if now >= nxt.deadline_abs:
                self._expire(nxt, now)
                continue
            batch.append(nxt)
            rows += nxt.n
        return batch

    def _execute(self, batch: List[ServeRequest]) -> None:
        # the coalescing window just closed: a request whose deadline
        # already passed while it waited must not ride the batch — a
        # stale answer wastes a forward (or a decode slot) nobody will
        # read.  Shed it here (typed, counted once), not forwarded.
        now = time.monotonic()
        live = []
        for r in batch:
            if r.abandoned:
                self._drop_abandoned(r)
            elif now >= r.deadline_abs:
                self._expire(r, now)
            else:
                live.append(r)
        if not live:
            return
        batch = live
        # queue-wait span per request: submit -> window close (the same
        # monotonic clock, expressed in ns for the flight recorder)
        now_ns = time.monotonic_ns()
        for r in batch:
            t0_ns = int(r.t_submit * 1e9)
            record_event('serve.queue', 'serve', r.trace_id,
                         t_start_ns=t0_ns, dur_ns=now_ns - t0_ns)
        if self._exec is not None:
            # engine-owned completion (decode): admission into slots may
            # block per-request; errors land per request inside the
            # engine, but a non-request fault must not kill the worker
            try:
                self._exec(batch)
                self.stats.observe('coalesced', len(batch))
            except BaseException as e:
                # per-REQUEST counting: the engine already finished (and
                # counted) some of the batch; only the strays land here,
                # one count each, so the ledger reconciles exactly
                for r in batch:
                    if not r.event.is_set():
                        self.stats.inc('engine_errors')
                        r.error = e
                        r.event.set()
            return
        rows = sum(r.n for r in batch)
        try:
            # the concat stays inside the try: a shape-mismatched request
            # must surface as that batch's per-request error, not kill
            # the worker thread and wedge the service
            data = (batch[0].data if len(batch) == 1 else
                    np.concatenate([r.data for r in batch], axis=0))
            with span('serve.forward', 'serve', rows=rows,
                      coalesced=len(batch)):
                scores = self.engine.predict_scores(data)
        except BaseException as e:  # surface engine faults per-request
            for r in batch:
                self.stats.inc('engine_errors')
                r.error = e
                r.event.set()
            return
        bucket = bucket_for(rows, self.engine.buckets) \
            or self.engine.buckets[-1]
        done = time.monotonic()
        off = 0
        for r in batch:
            r.result = scores[off:off + r.n]
            off += r.n
            self.stats.inc('requests')
            self.stats.observe(f'latency_ms[b{bucket}]',
                               (done - r.t_submit) * 1e3)
        self.stats.inc(f'batches[b{bucket}]')
        self.stats.inc(f'rows[b{bucket}]', rows)
        self.stats.observe('coalesced', len(batch))
        for r in batch:
            record_event('serve.finish', 'serve', r.trace_id, rows=r.n,
                         latency_ms=(done - r.t_submit) * 1e3)
            r.event.set()

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._closed:
                    self._cond.wait(0.05)
                if not self._q:   # closed and drained
                    return
                first = self._q.popleft()
            if first.abandoned:    # caller gave up: typed drop, counted here
                self._drop_abandoned(first)
                continue
            now = time.monotonic()
            if now >= first.deadline_abs:
                self._expire(first, now)
                continue
            self._execute(self._gather(first))

    # -- lifecycle ---------------------------------------------------------
    def close(self, timeout: Optional[float] = None) -> bool:
        """Graceful, idempotent shutdown: stop admitting, let the worker
        finish every queued request, join it.  Safe to call any number
        of times, from any thread; returns True once the worker exited."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if threading.current_thread() is self._worker:
            return False   # re-entrant close from a request callback
        self._worker.join(timeout)
        return not self._worker.is_alive()

    def set_max_queue(self, n: int) -> int:
        """Retune admission capacity live (the autoscaler's queue knob,
        serve/autoscale.py — always bounded by the caller's declared
        min/max).  Shrinking never drops queued requests: only future
        admissions see the new bound.  Returns the previous value."""
        n = int(n)
        if n <= 0:
            raise ValueError('max_queue must be positive')
        with self._cond:
            prev, self.max_queue = self.max_queue, n
        return prev

    def depth(self) -> int:
        """Requests pending admission right now — the pull-style gauge
        :meth:`register_into` folds in per ``/metrics`` render, so
        queue-pressure SLOs (``serve.queue_depth<=...``) see the live
        queue, not just the event-time peaks."""
        with self._cond:
            return len(self._q)

    def register_into(self, hub, name: str = 'serve') -> None:
        """THE ``serve`` stat registration (task=serve and the online
        pipeline share it, so the gauge spelling can't drift): the
        batcher's StatSet plus a refresh folding the live queue depth
        in per render."""
        hub.register_stats(
            name, self.stats,
            refresh=lambda: self.stats.gauge('queue_depth',
                                             self.depth()))

    def report(self, name: str = 'serve') -> str:
        """Eval-line-format stats snapshot (``utils.metric.StatSet``),
        with overall requests/sec appended — rendered by the hub's one
        ``format_report`` so key spelling cannot drift."""
        elapsed = max(time.monotonic() - self._t0, 1e-9)
        self.stats.gauge('reqs_per_sec',
                         self.stats.get('requests') / elapsed)
        return format_report(name, self.stats)
