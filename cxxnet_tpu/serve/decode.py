"""Continuous-batching decode engine over a paged KV cache.

PR 2's serving stack covers fixed-shape predict; this module opens the
autoregressive path (doc/serving.md "Continuous decode").  The design
goal is the one μ-cuDNN teaches for training applied at serving time:
the work granularity per step — here, WHICH requests ride each decode
step — sets utilization, so the decode loop is ONE persistent compiled
program that requests join and leave at token boundaries:

* **slots** — the compiled step advances a fixed number of request
  slots at once (inactive slots compute into a scratch page and are
  ignored).  A request admitted by the ``DynamicBatcher`` joins a free
  slot at the next token boundary, emits tokens incrementally, and
  leaves on EOS / horizon / deadline — the program never retraces as
  traffic changes,
* **paged KV cache** — K/V live in a fixed pool of fixed-size pages;
  each slot holds a page table mapping its logical cache positions to
  physical pages.  Pages are allocated on demand as a stream grows and
  freed the moment it ends, so memory scales with *live tokens*, not
  ``slots × horizon``.  When the pool runs dry the youngest stream is
  preempted with a typed ``DecodePagesExhaustedError`` carrying its
  token-level progress,
* **bitwise-twin discipline** — per-request sampling RNG is derived
  exactly as ``transformer.generate`` derives it
  (``jax.random.split(rng, max_new + 1)``; pick *n* uses key *n*), the
  prefill and per-token step run through the SAME module functions
  (``transformer.prefill_kv`` / ``transformer.decode_step``), and the
  paged pool gathers into the same dense cache layout before attending
  — so every request's token stream equals an offline
  ``transformer.generate`` call with the same seed, no matter when it
  joined the running loop or who shared its steps.

Two serving multipliers ride the same pool (ROADMAP item 2):

* **prefix sharing** (``serve.prefix_share``, doc/serving.md "Prefix
  sharing") — a content-addressed index maps (model version, pad width,
  logical page, exact token span) -> physical page for every FULL
  prompt page a prefill produced.  A new request whose prompt prefix
  hits the index splices the shared physical pages into its page table
  (refcounted — a page frees only when its last referencing page table
  AND the index let go) and prefills only the tail, attending over the
  shared rows; full shared pages are immutable by construction (decode
  writes only at positions past the prompt bucket), and the one
  partially-filled last page is privately rematerialized by the tail
  prefill — the copy-on-write rule.  N requests sharing a system
  prompt cost ONE prefill and one set of pages,
* **greedy speculative decoding** (``serve.draft``/``serve.spec_k``,
  doc/serving.md "Speculative decoding") — a small draft model
  proposes K-1 tokens per slot from its own dense per-slot cache; the
  target verifies the whole (slots, K) window in ONE multi-token step
  (``transformer.verify_step``) and accepts the longest agreeing prefix
  plus one corrected token.  Every accepted token is the target's own
  greedy argmax at its position, so the stream is TOKEN-EQUAL to the
  target decoding alone — the bitwise-twin discipline holds with a
  draft bolted on, on every ``serve.dtype`` tier.

The attention itself has two legs behind ``serve.flash_decode``
(doc/serving.md "Flash paged decode"): the gather path materializes each
slot's pages into a dense (T, heads, hd) view per step, while the Pallas
**paged flash-decode kernel** (``ops.pallas_kernels.paged_flash_decode``)
reads the pages in place via the page table — bitwise-equal outputs,
pinned by twin tests on the CPU ``interpret=True`` path.  ``dtype``
selects the quantized-inference tier (``serve.dtype``, doc/serving.md
"Quantized inference"): ``bf16`` casts params/pool/compute to bfloat16,
``int8`` additionally stores matmul weights as per-channel int8
(``nnet/quantize.py``) consumed through the W8A8 ``qdot`` leg — either
way the stream still has an EXACT offline twin (``transformer.generate``
over the engine's own stored tree + compute config).
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
import time
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..models import transformer as T
from ..nnet import quantize
from ..parallel import mesh as mesh_mod
from ..obs import format_report, record_event, span
from ..ops import pallas_kernels as PK
from ..runtime import faults as _faults
from ..runtime.faults import (DeadlineExceededError, DecodePagesExhaustedError,
                              DecodeSlotsExhaustedError,
                              PrefixIndexFullError, RequestAbandonedError,
                              ServeError, TokenDeadlineExceededError)
from ..utils.metric import StatSet

__all__ = ['DecodeEngine', 'DecodeService', 'save_lm_params',
           'load_lm_params', 'lm_loader', 'LM_PATTERN']


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _prompt_bucket(s0: int) -> int:
    """The engine's prompt size-class — ``generate()``'s bucketing rule
    in ONE place, so admission (``_admit``) and the batcher's pricing
    (``prefill_cost``) can never disagree about a prompt's bucket."""
    if os.environ.get('CXXNET_GEN_BUCKETS', '1') != '0':
        return T._size_class(s0, floor=8)
    return s0


class _Slot:
    """Host-side record of one occupied decode slot."""

    __slots__ = ('req', 's0b', 'w', 'pos', 'kidx', 'last_tok', 'temp',
                 'keys', 'max_new', 'join_seq', 'last_emit')

    def __init__(self, req, s0b, w, tok0, keys, temp, max_new, join_seq):
        self.req = req
        self.s0b = int(s0b)
        self.w = int(w)
        self.pos = int(s0b)       # next cache position to write
        self.kidx = 1             # next sampling key index (tok0 used 0)
        self.last_tok = int(tok0)
        self.temp = float(temp)
        self.keys = keys          # (max_new + 1, 2) uint32
        self.max_new = int(max_new)
        self.join_seq = int(join_seq)
        self.last_emit = time.monotonic()


class DecodeEngine:
    """Slot-based continuous decode over a paged KV pool.

    ``params``/``cfg`` are a ``models.transformer`` tree and config
    (single-device; ``cfg.causal`` required).  ``slots`` is the width of
    the persistent compiled step; ``pages``/``page_size`` size the
    physical KV pool (page 0 is a scratch page for idle slots, so
    ``pages - 1`` are allocatable); ``max_prompt``/``max_new_bound``
    bound one request's horizon and fix the slot cache length ``T``
    (page-aligned).  ``eos_id`` is engine-wide (it is baked into the
    compiled step, exactly as ``generate`` bakes it per program).

    ``dtype`` (``serve.dtype``) selects the quantized serving tier:
    ``bf16``/``int8`` replace the compute config's dtype with bfloat16
    (params, KV pool and block math follow), int8 additionally storing
    matmul weights per-channel quantized (``nnet/quantize.py``) —
    either way :attr:`params`/:attr:`cfg` remain the stream oracle:
    ``transformer.generate(engine.params, ..., engine.cfg)`` is
    bitwise-equal to the engine's streams on every tier.
    ``flash_decode`` (``serve.flash_decode``) picks the attention leg:
    ``1``/``0`` force the Pallas paged flash-decode kernel / the dense
    gather; ``'auto'``/None defer to ``pallas_mode()``.
    ``kv_host_mb``/``kv_disk_mb``/``kv_dir``/``kv_share_dir``
    (``serve.kv_*``) attach the graftcache tier hierarchy behind the
    prefix index: evicted index entries demote host → disk instead of
    dropping, later probes promote them back without a re-prefill, and
    a share directory lets N replicas adopt each other's tier-2
    records (doc/serving.md "Tiered KV cache"); requires
    ``prefix_share > 0``.

    Requests arrive through :meth:`execute_requests` (the
    ``DynamicBatcher`` hands over each coalesced batch — the engine owns
    completion) or :meth:`submit_direct`.  Per request ``meta``:
    ``max_new`` (default ``max_new_bound``), ``temperature`` (0 =
    greedy), ``rng`` (a jax PRNG key or int seed; required when
    sampling).  Emitted token ids stream into ``req.tokens`` as they are
    picked; ``req.result`` is the final int32 array.  A stream ends at
    its first EOS — the offline twin keeps emitting EOS after it, so
    equality is prefix + implied-EOS tail.
    """

    def __init__(self, params, cfg, *, slots: int = 4, pages: int = 64,
                 page_size: int = 16, max_prompt: int = 64,
                 max_new_bound: int = 64, eos_id: Optional[int] = None,
                 stats: Optional[StatSet] = None, name: str = 'lm',
                 dtype: str = 'f32', flash_decode=None,
                 prefix_share: int = 0, spec_k: int = 0, draft=None,
                 kv_host_mb: int = 0, kv_disk_mb: int = 0,
                 kv_dir: Optional[str] = None,
                 kv_share_dir: Optional[str] = None,
                 shard: str = '', prefill_workers: int = 0):
        if not cfg.causal:
            raise ValueError('DecodeEngine requires a causal config')
        if slots < 1 or pages < 2 or page_size < 1:
            raise ValueError('need slots >= 1, pages >= 2 (page 0 is '
                             'scratch), page_size >= 1')
        if prefix_share < 0:
            raise ValueError('prefix_share must be >= 0 (a page cap; '
                             '0 disables sharing)')
        if spec_k < 0 or (spec_k >= 2 and draft is None):
            raise ValueError('spec_k >= 2 needs a draft model '
                             '(draft=(params, cfg)); spec_k must be >= 0')
        if kv_host_mb < 0 or kv_disk_mb < 0:
            raise ValueError('kv_host_mb / kv_disk_mb must be >= 0')
        if (kv_host_mb or kv_disk_mb) and prefix_share <= 0:
            raise ValueError('the tiered KV cache sits behind the '
                             'prefix index: serve.kv_host_mb/kv_disk_mb '
                             'need serve.prefix_share > 0')
        if kv_disk_mb > 0 and not kv_dir:
            raise ValueError('serve.kv_disk_mb > 0 needs serve.kv_dir= '
                             '(the tier-2 record directory)')
        if kv_share_dir and kv_disk_mb <= 0:
            raise ValueError('serve.kv_share_dir shares tier-2 records: '
                             'it needs serve.kv_disk_mb > 0')
        # quantized tier (serve.dtype): bf16/int8 serve with a bfloat16
        # compute config — params, KV pool and block math all follow
        # cfg.dtype, so the offline twin is generate(engine.params,
        # engine.cfg) for EVERY tier
        self.serve_dtype = quantize.parse_serve_dtype(dtype)
        if self.serve_dtype != 'f32':
            cfg = dataclasses.replace(cfg, dtype=jnp.bfloat16)
        # serve.flash_decode tri-state over the global pallas_mode() gate
        self.use_flash = PK.decode_use_flash(flash_decode)
        # --- tensor-parallel decode (serve.shard, doc/serving.md
        # "Sharded serving"): a 1xN ('data', 'model') mesh; every matmul
        # weight column-shards its LAST axis over 'model' and the K/V
        # page pool shards its heads axis, with explicit all-gather
        # boundaries (transformer._rep) keeping the residual stream
        # replicated — column-sliced matmuls preserve each output
        # element's contraction order, so every stream stays a BITWISE
        # twin of single-device generate at any shard width.
        self._tp = mesh_mod.parse_shard(shard)
        self._mesh = None
        if self._tp > 1:
            if cfg.num_heads % self._tp:
                raise ValueError(
                    f'serve.shard=tp:{self._tp} must divide num_heads='
                    f'{cfg.num_heads} (the KV pool shards per head)')
            if cfg.num_experts:
                raise ValueError('serve.shard supports dense FFN only '
                                 '(num_experts > 0 is unsupported)')
            if slots < 2:
                # XLA lowers the degenerate single-row attention dot
                # (b*q == 1) through a different contraction blocking at
                # one head per device — measured 1-ulp drift at tp:4.
                # A sharded engine exists to widen batching anyway.
                raise ValueError('serve.shard=tp:N needs slots >= 2 '
                                 '(the bitwise-twin contract excludes '
                                 'single-row steps)')
            self._mesh = mesh_mod.decode_mesh(self._tp)
            # pallas kernels do not run SPMD over sharded operands
            # without shard_map — the gather leg is the sharded path
            self.use_flash = False
        self.cfg = cfg
        self.name = name
        self.slots = int(slots)
        self.page_size = int(page_size)
        self.n_pages = int(pages)
        self.max_prompt = int(max_prompt)
        self.max_new_bound = int(max_new_bound)
        self.eos_id = eos_id
        self.stats = stats if stats is not None else StatSet()
        horizon = T._size_class(self.max_prompt, floor=8) + max_new_bound
        self.pages_per_slot = _ceil_div(horizon, self.page_size)
        self.cache_len = self.pages_per_slot * self.page_size   # T
        hd = cfg.d_model // cfg.num_heads
        pool_shape = (cfg.num_stages, self.n_pages, self.page_size,
                      cfg.num_heads, hd)
        # sharded engines split the pool per head: each head's K/V pages
        # live on the head's device, so aggregate page capacity scales
        # with the mesh while the per-device slice stays one chip's share
        pool_sh = (None if self._mesh is None else NamedSharding(
            self._mesh, P(None, None, None, 'model', None)))
        self._kpool = jax.device_put(np.zeros(pool_shape, cfg.dtype),
                                     pool_sh)
        self._vpool = jax.device_put(np.zeros(pool_shape, cfg.dtype),
                                     pool_sh)
        self._cond = threading.Condition()
        # physical page 0 is scratch: idle slots write there, nobody reads
        self._free_pages: List[int] = list(
            range(self.n_pages - 1, 0, -1))       # guarded-by: _cond
        # per-physical-page reference counts: every referencing page
        # table holds one, the prefix index holds one more while an
        # entry points at the page — a page returns to the free list
        # only at zero, so preempting a stream can never free a page
        # another slot (or a future prefix hit) still reads
        self._page_refs = np.zeros(self.n_pages,
                                   np.int32)       # guarded-by: _cond
        self._free_min = self.n_pages - 1          # guarded-by: _cond
        # content-addressed FULL-prefix-page index (doc/serving.md
        # "Prefix sharing"): (version, w, logical page, exact padded
        # token span) -> {page, host K/V rows}.  OrderedDict = LRU;
        # bounded by ``prefix_share`` pages.  Host row mirrors let the
        # admitting thread run the tail prefill without touching the
        # loop-owned device pools.
        self._prefix_cap = int(prefix_share)
        self._prefix: collections.OrderedDict = (
            collections.OrderedDict())             # guarded-by: _cond
        # graftcache (serve/kvcache.py): host/disk tiers BEHIND the
        # index — eviction demotes host mirrors down-tier, a later probe
        # promotes them back into a freshly allocated physical page.
        # The cache owns its own `kv` StatSet (hub-registered by the
        # CLI) and its own internal lock; this engine only ever calls
        # it while holding _cond (demote/take) or with no lock at all
        # (prefetch) — lock order _cond -> kvcache._lock, never back.
        self._kv = None
        self.kv_stats: Optional[StatSet] = None
        if kv_host_mb > 0 or kv_disk_mb > 0:
            from .kvcache import KVStore, TieredKVCache
            self.kv_stats = StatSet()
            kv_store = None
            if kv_disk_mb > 0:
                kv_store = KVStore(kv_dir, kv_disk_mb * (1 << 20),
                                   share_dir=kv_share_dir,
                                   stats=self.kv_stats, name=name)
            self._kv = TieredKVCache(host_bytes=kv_host_mb * (1 << 20),
                                     store=kv_store, stats=self.kv_stats)
        # tier-promoted rows awaiting their device upload: (physical
        # page, host K rows, host V rows), each holding its own page
        # reference until the decode loop writes the rows at the next
        # token boundary — a promoting page is never an eviction victim
        self._pending_uploads: collections.deque = (
            collections.deque())                   # guarded-by: _cond
        self._table = np.zeros((self.slots, self.pages_per_slot),
                               np.int32)           # guarded-by: _cond
        self._slots: List[Optional[_Slot]] = (
            [None] * self.slots)                  # guarded-by: _cond
        self._joinq: collections.deque = (
            collections.deque())                  # guarded-by: _cond
        self._admitting = 0   # guarded-by: _cond (admit..join window)
        self._join_seq = 0    # guarded-by: _cond
        self._closed = False  # guarded-by: _cond
        # LOGICAL capacity caps — the autoscaler's grow/shrink surface
        # (serve/autoscale.py).  The PHYSICAL slots/pages are baked into
        # the compiled step (``decode.step`` declares bound=1, so a
        # resize would be a retrace the recompile sentinel rightly
        # flags); scaling therefore clamps ADMISSION only.  Shrinking
        # never touches a live stream: in-flight page growth stays
        # uncapped and a referenced page can never be freed (refcounts).
        self._live_slot_cap = self.slots           # guarded-by: _cond
        self._live_page_cap = self.n_pages - 1     # guarded-by: _cond
        # the ORIGINAL (pre-quantization) structure is the hot-swap
        # contract: .lm files always carry the f32 tree, place_params
        # validates against it and re-quantizes into the serving tier
        self._ref_treedef = jax.tree.structure(params)
        self._ref_shapes = [(tuple(l.shape), l.dtype)
                            for l in jax.tree.leaves(params)]
        self._params = self.place_params(params)  # guarded-by: _cond
        self._params_treedef = jax.tree.structure(self._params)
        self._pending_params = None   # guarded-by: _cond
        self._pending_version = None  # guarded-by: _cond
        self.version: object = 0
        self.swap_count = 0
        # --- greedy speculative decoding (serve.draft / serve.spec_k):
        # the draft keeps a DENSE per-slot cache (it is small — paging
        # and sharing buy nothing) advanced only inside spec windows
        self._spec_k = int(spec_k)
        self._draft_params = None          # guarded-by: _cond
        self._pending_draft = None         # guarded-by: _cond
        self._pending_draft_version = None  # guarded-by: _cond
        self.draft_version: object = -1
        self._draft_cfg = None
        if draft is not None:
            dparams, dcfg = draft
            if not dcfg.causal:
                raise ValueError('draft model must be causal')
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError(
                    f'draft vocab {dcfg.vocab_size} != target '
                    f'{cfg.vocab_size}: the verify window compares '
                    'token ids, the vocabularies must match')
            if self.serve_dtype != 'f32':
                dcfg = dataclasses.replace(dcfg, dtype=jnp.bfloat16)
            self._draft_cfg = dcfg
            self._draft_ref_treedef = jax.tree.structure(dparams)
            self._draft_ref_shapes = [tuple(l.shape) for l in
                                      jax.tree.leaves(dparams)]
            self._draft_params = self.place_draft_params(dparams)
            self._draft_placed_treedef = jax.tree.structure(
                self._draft_params)
            dhd = dcfg.d_model // dcfg.num_heads
            dshape = (dcfg.num_stages, self.slots, self.cache_len,
                      dcfg.num_heads, dhd)
            # the draft rides the mesh REPLICATED (it is small; its head
            # count need not divide tp) — duplicated compute, zero
            # collectives, bitwise-identical proposals on every device
            drep = (None if self._mesh is None
                    else NamedSharding(self._mesh, P()))
            self._kdc = jax.device_put(np.zeros(dshape, dcfg.dtype),
                                       drep)
            self._vdc = jax.device_put(np.zeros(dshape, dcfg.dtype),
                                       drep)
        # guarded-by: _pf_lock (prefill/tail program caches — touched by
        # prefill worker threads concurrently, never under _cond)
        self._pf_lock = threading.Lock()
        self._prefill_fns: collections.OrderedDict = collections.OrderedDict()
        self._tail_fns: collections.OrderedDict = collections.OrderedDict()
        self._spec_fns: dict = {}
        self._write_fns: dict = {}
        self._dwrite_fns: dict = {}
        # compiler-truth ledger rows (obs/programs.py): the decode step
        # is ONE program by construction (preallocated pools), declared
        # bound=1 so any shape drift trips the recompile sentinel;
        # prefill/tail/spec are LRU-bucketed ladders (unbounded
        # declaration — the gen-cache LRU is their own churn policy)
        from ..obs.programs import get_ledger
        _led = get_ledger()
        self._prog_step = _led.program('decode.step', bound=1)
        self._prog_prefill = _led.program('decode.prefill')
        self._prog_tail = _led.program('decode.tail_prefill')
        self._prog_spec = _led.program('decode.spec')
        self._step = self._build_step()
        # lint: allow(jit-ledger): one scalar-pick program ever (traced temperature); nothing a ledger row would say
        self._pick1 = jax.jit(self._pick_one)
        self._loop = threading.Thread(target=self._run, daemon=True,
                                      name=f'cxxnet-decode-{name}')
        self._loop.start()
        # -- disaggregated prefill: dedicated worker threads own the
        # prompt-prefill leg so a long cold prompt never serializes
        # behind another inside the batcher hand-off; finished KV
        # reaches the loop through the same _joinq token-boundary
        # integration as inline admission (streams stay bitwise twins
        # — only WHO ran the prefill program changes, never its math)
        # guarded-by: _cond (queue + worker wakeups)
        self._prefillq: collections.deque = collections.deque()
        self._prefill_threads: list = []
        for i in range(max(0, int(prefill_workers))):
            t = threading.Thread(target=self._prefill_worker, daemon=True,
                                 name=f'cxxnet-prefill-{name}-{i}')
            t.start()
            self._prefill_threads.append(t)

    # -- compiled programs -------------------------------------------------
    @staticmethod
    def _pick_one(logits, key, temp):
        """Traced-temperature pick for ONE request (prefill's first
        token): same categorical/argmax math as ``generate``'s static-
        temperature pick — identical operand values give identical
        draws, so one program covers every request temperature."""
        safe = jnp.where(temp > 0, temp, jnp.float32(1.0))
        sampled = jax.random.categorical(key, logits / safe, axis=-1)
        return jnp.where(temp > 0, sampled,
                         jnp.argmax(logits, axis=-1)).astype(jnp.int32)

    @staticmethod
    def _pick_slots(logits, r, temp):
        """Per-slot pick: per-slot keys, per-slot draws — bitwise the
        same stream the offline b=1 generate pulls from the same key
        schedule."""
        greedy = jnp.argmax(logits, axis=-1)
        safe = jnp.where(temp > 0, temp, jnp.float32(1.0))
        sampled = jax.vmap(
            lambda k_, lg, t_: jax.random.categorical(
                k_, lg / t_, axis=-1))(r, logits, safe)
        return jnp.where(temp > 0, sampled, greedy).astype(jnp.int32)

    def _build_step(self):
        cfg = self.cfg
        S, ps, pp = self.slots, self.page_size, self.pages_per_slot
        Tlen = self.cache_len
        hd = cfg.d_model // cfg.num_heads

        mesh = self._mesh

        if self.use_flash:
            def step(params, kpool, vpool, table, pos, w, tok, r, temp):
                # flash leg: K/V rows scatter into their physical pages
                # and the Pallas kernel reads them in place — no dense
                # cache is ever materialized (bitwise-equal to the
                # gather leg below; twin test pins it)
                logits, kpool, vpool = T.decode_step_paged(
                    params, cfg, tok, kpool, vpool, table, pos, w)
                nxt = self._pick_slots(logits, r, temp)
                return kpool, vpool, nxt

            return self._prog_step.jit(step, donate_argnums=(1, 2),
                                       key='flash', fixed=True)

        def step(params, kpool, vpool, table, pos, w, tok, r, temp):
            # gather each slot's pages into the dense cache layout the
            # shared decode_step math expects (gather is an exact copy:
            # the paged-vs-dense twin test pins this bitwise).  The
            # shard scope arms transformer._rep's all-gather boundaries
            # for the trace (identity when mesh is None).
            with T.shard_scope(mesh):
                st = kpool.shape[0]
                kc = kpool[:, table].reshape(st, S, Tlen,
                                             cfg.num_heads, hd)
                vc = vpool[:, table].reshape(st, S, Tlen,
                                             cfg.num_heads, hd)
                logits, _, _, knew, vnew = T.decode_step(
                    params, cfg, tok, kc, vc, pos, w)
                # scatter only the newly written rows back into the pool
                page = table[jnp.arange(S), pos // ps]
                off = pos % ps
                si = jnp.arange(st)[:, None]
                kpool = kpool.at[si, page[None, :],
                                 off[None, :]].set(knew)
                vpool = vpool.at[si, page[None, :],
                                 off[None, :]].set(vnew)
                nxt = self._pick_slots(logits, r, temp)
                return kpool, vpool, nxt

        return self._prog_step.jit(step, donate_argnums=(1, 2),
                                   key='gather', fixed=True)

    def _prefill_fn(self, s0b: int, draft: bool = False):
        key = ('draft', s0b) if draft else s0b
        with self._pf_lock:
            fn = self._prefill_fns.get(key)
            if fn is not None:
                self._prefill_fns.move_to_end(key)
                return fn
        self.stats.inc('prefill_programs')   # retrace visibility
        cfg = self._draft_cfg if draft else self.cfg
        mesh = None if draft else self._mesh

        def prefill(params, prompt, w):
            with T.shard_scope(mesh):
                return T.prefill_kv(params, prompt, w, cfg)

        fn = self._prog_prefill.jit(
            prefill, key=f'{"draft_" if draft else ""}s{s0b}',
            fixed=True)
        with self._pf_lock:
            self._prefill_fns[key] = fn
            # same LRU bound (and env knob) as generate's program cache
            while len(self._prefill_fns) > T._gen_cache_max():
                self._prefill_fns.popitem(last=False)
        return fn

    def _tail_fn(self, t0: int, tt: int):
        """Jitted prefix-shared tail prefill, keyed by (prefix, tail)
        lengths (``w`` stays a traced value, like the full prefill)."""
        with self._pf_lock:
            fn = self._tail_fns.get((t0, tt))
            if fn is not None:
                self._tail_fns.move_to_end((t0, tt))
                return fn
        self.stats.inc('prefill_programs')
        cfg = self.cfg
        mesh = self._mesh

        def tail_prefill(params, pk, pv, tail, w):
            with T.shard_scope(mesh):
                return T.prefill_tail_kv(params, pk, pv, tail, w, cfg)

        fn = self._prog_tail.jit(tail_prefill, key=f't{t0}+{tt}',
                                 fixed=True)
        with self._pf_lock:
            self._tail_fns[(t0, tt)] = fn
            while len(self._tail_fns) > T._gen_cache_max():
                self._tail_fns.popitem(last=False)
        return fn

    def _dwrite_fn(self, s0b: int):
        """Jitted draft-cache prompt write: the draft's prefill rows for
        one slot land in the dense per-slot cache (``sid`` is traced —
        one program per prompt bucket covers every slot)."""
        fn = self._dwrite_fns.get(s0b)
        if fn is None:
            def dwrite(kdc, vdc, dks, dvs, sid):
                kdc = jax.lax.dynamic_update_slice(
                    kdc, dks, (0, sid, 0, 0, 0))
                vdc = jax.lax.dynamic_update_slice(
                    vdc, dvs, (0, sid, 0, 0, 0))
                return kdc, vdc
            # lint: allow(jit-ledger): two dynamic-update-slices — cache keyed by the same prompt buckets the ledgered prefill already rows
            fn = self._dwrite_fns[s0b] = jax.jit(dwrite,
                                                 donate_argnums=(0, 1))
        return fn

    def _spec_fn(self, K: int):
        """Jitted speculative round at window width ``K``: K-1 greedy
        draft proposals (sequential ``decode_step``s over the dense
        draft cache) + ONE target ``verify_step`` over the (slots, K)
        window, its new K/V rows scattered into the page pool (the
        flash leg verifies in place).  Returns the consumed window and
        the target's per-position greedy picks; acceptance is host-side
        (variable per slot)."""
        fn = self._spec_fns.get(K)
        if fn is None:
            self.stats.inc('spec_programs')
            cfg, dcfg = self.cfg, self._draft_cfg
            S, ps, Tlen = self.slots, self.page_size, self.cache_len
            hd = cfg.d_model // cfg.num_heads
            use_flash = self.use_flash
            mesh = self._mesh

            def spec(params, dparams, kpool, vpool, kdc, vdc, table,
                     pos, w, tok):
                # draft proposals run OUTSIDE the shard scope: the
                # draft is replicated on the mesh, so its steps are
                # duplicated (bitwise-identical) compute per device
                window = [tok]
                dtok = tok
                for k in range(K - 1):
                    dlogits, kdc, vdc, _, _ = T.decode_step(
                        dparams, dcfg, dtok, kdc, vdc, pos + k, w)
                    dtok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
                    window.append(dtok)
                toks = jnp.stack(window, axis=1)            # (S, K)
                if use_flash:
                    logits, kpool, vpool = T.verify_step_paged(
                        params, cfg, toks, kpool, vpool, table, pos, w)
                else:
                    with T.shard_scope(mesh):
                        st = kpool.shape[0]
                        kc = kpool[:, table].reshape(st, S, Tlen,
                                                     cfg.num_heads, hd)
                        vc = vpool[:, table].reshape(st, S, Tlen,
                                                     cfg.num_heads, hd)
                        logits, _, _, knew, vnew = T.verify_step(
                            params, cfg, toks, kc, vc, pos, w)
                        tq = pos[:, None] + jnp.arange(K)[None, :]
                        page = table[jnp.arange(S)[:, None], tq // ps]
                        off = tq % ps
                        si = jnp.arange(st)[:, None, None]
                        kpool = kpool.at[si, page[None],
                                         off[None]].set(knew)
                        vpool = vpool.at[si, page[None],
                                         off[None]].set(vnew)
                tgt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return kpool, vpool, kdc, vdc, toks, tgt

            fn = self._spec_fns[K] = self._prog_spec.jit(
                spec, donate_argnums=(2, 3, 4, 5), key=f'k{K}',
                steps=K, fixed=True)
        return fn

    def _write_fn(self, n_pages: int, nrows: int):
        """Jitted prompt-K/V scatter: ``nrows`` prefilled rows into
        ``n_pages`` physical pages (the whole prompt, or just the tail
        past a prefix hit)."""
        key = (n_pages, nrows)
        fn = self._write_fns.get(key)
        if fn is None:
            ps = self.page_size

            def write(kpool, vpool, ks, vs, pages):
                st = kpool.shape[0]
                pad = n_pages * ps - nrows
                shaped = []
                for arr in (ks, vs):
                    a = arr[:, 0]                      # (stages, s0b, H, hd)
                    a = jnp.pad(a, ((0, 0), (0, pad), (0, 0), (0, 0)))
                    shaped.append(a.reshape(st, n_pages, ps,
                                            a.shape[-2], a.shape[-1]))
                kpool = kpool.at[:, pages].set(shaped[0])
                vpool = vpool.at[:, pages].set(shaped[1])
                return kpool, vpool

            # lint: allow(jit-ledger): pure pad+scatter of already-prefilled rows; the compute it stores was rowed by decode.prefill
            fn = self._write_fns[key] = jax.jit(write,
                                                donate_argnums=(0, 1))
        return fn

    # -- parameters (PredictEngine-compatible surface) ---------------------
    @property
    def params(self):
        with self._cond:
            return self._params

    def oracle_params(self):
        """The serving tree AS AN OFFLINE ORACLE should see it: for a
        sharded engine, a host copy — ``transformer.generate`` over
        mesh-committed leaves would itself compile SPMD and is NOT the
        single-device reference the twin contract pins against."""
        p = self.params
        if self._mesh is None:
            return p
        return jax.tree.map(np.asarray, p)

    def _check_tree(self, params) -> None:
        if jax.tree.structure(params) != self._ref_treedef:
            raise ValueError('swap_params: param tree structure differs '
                             'from the serving model')
        # dtype is part of the contract only on the f32 tier — the
        # quantized tiers normalize every incoming float dtype anyway
        strict = self.serve_dtype == 'f32'
        for leaf, (shape, dtype) in zip(jax.tree.leaves(params),
                                        self._ref_shapes):
            if tuple(leaf.shape) != shape or \
                    (strict and leaf.dtype != dtype):
                raise ValueError(
                    f'swap_params: leaf {tuple(leaf.shape)}/{leaf.dtype} '
                    f'!= serving {shape}/{dtype} — a shape change needs '
                    'a new engine, not a hot swap')

    def _quantize(self, host_tree):
        """Load/swap-time quantization into the serving tier — the hot
        path never re-quantizes weights (doc/serving.md)."""
        if self.serve_dtype == 'f32':
            return host_tree
        return quantize.quantize_tree(host_tree, self.serve_dtype,
                                      out_dtype=self.cfg.dtype,
                                      quant_key=quantize.lm_quant_key)

    def place_params(self, host_params):
        # this method's own output (the registry's warm->swap sequence
        # re-passes it) short-circuits the validate+quantize: an int8
        # tree is structurally distinct, a bf16 one re-casts to itself
        already = (getattr(self, '_params_treedef', None) is not None
                   and self._params_treedef != self._ref_treedef
                   and jax.tree.structure(host_params)
                   == self._params_treedef)
        if not already:
            if getattr(self, '_ref_treedef', None) is not None:
                self._check_tree(host_params)
            host_params = self._quantize(host_params)
        if self._mesh is not None:
            return self._shard_tree(host_params)
        return jax.tree.map(
            lambda h: h if isinstance(h, jax.Array)
            else jax.device_put(np.asarray(h)), host_params)

    def _shard_tree(self, tree):
        """Tensor-parallel placement of a (possibly quantized) param
        tree: matmul weights column-shard their LAST axis over 'model'
        (wq/wk/wv/wo/embed/head/w1/w2 — the layout transformer._rep's
        all-gather boundaries assume), QuantLeaf scales co-shard with
        their q (``quantize.shard_put``), and everything else — norms,
        biases, non-dividing leaves — replicates onto the mesh so no
        leaf stays committed to a lone device."""
        mesh, tpn = self._mesh, self._tp

        def one(name, leaf):
            nd = getattr(leaf, 'ndim', 0)
            if (name in quantize.LM_MATMUL_KEYS and 2 <= nd <= 3
                    and leaf.shape[-1] % tpn == 0):
                spec = (None,) * (nd - 1) + ('model',)
            else:
                spec = (None,) * nd
            return quantize.shard_put(leaf, mesh, P(*spec))

        return quantize._map_named(one, tree)

    def warm_params(self, params) -> None:
        placed = self.place_params(params)
        jax.block_until_ready(jax.tree.leaves(placed))

    def swap_params(self, params, version: object = None) -> None:
        """Hot-swap with DRAIN semantics: in-flight streams finish on
        the params they started with (one compiled step takes one tree —
        mixing versions inside a step is impossible by construction);
        new admissions wait, join under the new tree once the last
        pre-swap stream leaves.  Zero requests are dropped.  Blocks
        until the swap is applied."""
        placed = self.place_params(params)
        with self._cond:
            if self._closed:
                raise ServeError('decode engine is closed')
            while self._pending_params is not None:
                self._cond.wait(0.05)
            self._pending_params = placed
            self._pending_version = version
            self._cond.notify_all()
            while self._pending_params is not None and not self._closed:
                self._cond.wait(0.05)

    # -- speculative-decode draft model ------------------------------------
    def place_draft_params(self, host_params):
        """Validate + quantize a draft tree into the serving tier (the
        SAME tier as the target — verify consumes both through one
        ``qdot`` dispatch) and place it on device."""
        if self._draft_cfg is None:
            raise ValueError('engine was built without a draft model')
        td = jax.tree.structure(host_params)
        if td == self._draft_ref_treedef:
            # treedefs are shape-blind (target and draft trees share the
            # same nesting): a wrong-architecture tree must fail HERE,
            # typed, not at the next spec round's trace
            for leaf, shape in zip(jax.tree.leaves(host_params),
                                   self._draft_ref_shapes):
                if tuple(leaf.shape) != shape:
                    raise ValueError(
                        f'swap_draft_params: leaf {tuple(leaf.shape)} != '
                        f'draft {shape} — a shape change needs a new '
                        'engine, not a hot swap')
            host_params = quantize.quantize_lm_tree(
                host_params, self.serve_dtype,
                out_dtype=self._draft_cfg.dtype)
        elif td != getattr(self, '_draft_placed_treedef', None):
            raise ValueError('swap_draft_params: tree structure differs '
                             'from the draft model')
        if self._mesh is not None:
            # replicated on the mesh (see the draft-cache placement)
            rep = NamedSharding(self._mesh, P())
            return jax.tree.map(
                lambda h: jax.device_put(np.asarray(h) if not
                                         isinstance(h, jax.Array) else h,
                                         rep), host_params)
        return jax.tree.map(
            lambda h: h if isinstance(h, jax.Array)
            else jax.device_put(np.asarray(h)), host_params)

    def warm_draft_params(self, params) -> None:
        placed = self.place_draft_params(params)
        jax.block_until_ready(jax.tree.leaves(placed))

    def swap_draft_params(self, params, version: object = None) -> None:
        """Hot-swap the DRAFT tree with the same drain semantics as
        :meth:`swap_params`.  A draft change can never alter a stream
        (verify acceptance guards every token), so this only affects
        acceptance rate — but the drain keeps one spec round on one
        draft tree by construction."""
        placed = self.place_draft_params(params)
        with self._cond:
            if self._closed:
                raise ServeError('decode engine is closed')
            while self._pending_draft is not None:
                self._cond.wait(0.05)
            self._pending_draft = placed
            self._pending_draft_version = version
            self._cond.notify_all()
            while self._pending_draft is not None and not self._closed:
                self._cond.wait(0.05)

    # -- prefix index (requires-lock helpers) ------------------------------
    def _prefix_keys(self, padded, w, n):  # requires-lock: _cond
        """Content keys for the first ``n`` full pages of a padded
        prompt: (model version, pad width, logical page, EXACT token
        span through that page) — dict equality does the exact match,
        so there is no hash-collision correctness risk."""
        ps = self.page_size
        row = padded[0]
        return [(self.version, w, lp, row[:(lp + 1) * ps].tobytes())
                for lp in range(n)]

    def _prefix_probe(self, padded, w, s0b, touch=True):  # requires-lock: _cond
        """Longest consecutive full-page prefix hit: returns (n_hit,
        pages, host_k_rows, host_v_rows).  Hits must cover every bucket-
        pad slot (``n_hit * ps >= w``) so the tail prefill only ever
        sees real queries, and always leave >= 1 tail token to
        regenerate the last-position logits (>= 2 when sharded: XLA
        lowers a fully degenerate one-row-per-device dot differently,
        so the twin contract excludes single-query tails)."""
        ps = self.page_size
        max_hit = (s0b - 1 - (self._mesh is not None)) // ps
        pages, hks, hvs = [], [], []
        for key in self._prefix_keys(padded, w, max_hit):
            ent = self._prefix.get(key)
            if ent is None:
                break
            if touch:
                self._prefix.move_to_end(key)
            pages.append(ent['page'])
            hks.append(ent['hk'])
            hvs.append(ent['hv'])
        if len(pages) * ps < w:
            return 0, [], [], []
        return len(pages), pages, hks, hvs

    def _prefix_evict_one(self, demote: bool = True) -> bool:  # requires-lock: _cond
        """Drop the LRU index entry; frees its page when the index held
        the last reference.  With a tiered cache attached the entry's
        host mirrors DEMOTE down-tier instead of dropping (memory-moves
        only — spill I/O happens on the store's worker thread, never
        under this lock).  ``demote=False`` on param swaps: the rows
        are the old model's activations and their keys carry the old
        version — caching them would be pure waste."""
        if not self._prefix:
            return False
        key, ent = self._prefix.popitem(last=False)
        if demote and self._kv is not None and key[0] == self.version:
            self._kv.demote(key, ent['hk'], ent['hv'])
        self._release_pages([ent['page']])
        return True

    def _prefix_publish(self, padded, w, s0b, pages, hk_full, hv_full):  # requires-lock: _cond
        """Insert every not-yet-indexed FULL page of a just-prefilled
        prompt (immutable by construction: decode writes only at
        positions >= s0b).  ``pages``/``hk_full``/``hv_full``:
        the slot's physical pages and host K/V row mirrors for
        positions [0, s0b).  LRU-evicts at the ``prefix_share`` cap; a
        prompt whose shareable pages exceed the whole cap raises
        :class:`PrefixIndexFullError` internally — recorded, served
        unshared, never surfaced to the request."""
        ps = self.page_size
        n_pub = s0b // ps
        keys = self._prefix_keys(padded, w, n_pub)
        fresh = [i for i, k in enumerate(keys) if k not in self._prefix]
        if not fresh:
            return
        if len(fresh) > self._prefix_cap:
            self.stats.inc('prefix_index_full')
            from ..runtime import faults
            faults.global_failure_log().record(
                'prefix_index_full',
                repr(PrefixIndexFullError(n_pub, self._prefix_cap)))
            return
        for i in fresh:
            while len(self._prefix) >= self._prefix_cap:
                if not self._prefix_evict_one():
                    return               # cap raced to 0: give up quietly
            page = int(pages[i])
            self._page_refs[page] += 1   # the index's own reference
            self._prefix[keys[i]] = {
                'page': page,
                'hk': hk_full[:, i * ps:(i + 1) * ps],
                'hv': hv_full[:, i * ps:(i + 1) * ps]}
            self.stats.inc('prefix_published')

    def _reclaim_index_pages(self, n: int, exclude=()):  # requires-lock: _cond
        """Free up to ``n`` pages by dropping LRU index entries whose
        page the index alone still references — the pool-dry path
        prefers forgetting cold prefixes over preempting live streams.
        ``exclude``: physical pages that must survive even at refcount
        1 — the admission path passes the prefix pages it just probed,
        which its slot is about to splice (freeing one would alias the
        same physical page as both a shared prefix page and a fresh
        allocation, and tail writes would clobber the prefix rows)."""
        freed = 0
        for key in list(self._prefix):
            if freed >= n:
                break
            ent = self._prefix[key]
            if ent['page'] in exclude:
                continue
            if self._page_refs[ent['page']] == 1:
                if self._kv is not None and key[0] == self.version:
                    self._kv.demote(key, ent['hk'], ent['hv'])
                del self._prefix[key]
                self._release_pages([ent['page']])
                freed += 1
                self.stats.inc('prefix_reclaimed')
        return freed

    def _clear_prefix_index(self) -> None:  # requires-lock: _cond
        """Release every index reference (param swaps: cached rows are
        the OLD model's activations — stale keys would leak pages).
        Never demotes: the tiers must not inherit a dead version's rows
        (old-version entries already down-tier can never alias — the
        version is part of every key and every record header)."""
        while self._prefix:
            self._prefix_evict_one(demote=False)

    def _promote_splice(self, padded, w, s0b, n_hit,  # requires-lock: _cond
                        pages, hks, hvs) -> int:
        """Extend the index hit chain with tier-promoted pages: for
        each consecutive full page past ``n_hit`` whose rows tier 1
        holds (prefetched from disk OUTSIDE this lock), take the rows,
        re-publish the key against the freshly allocated physical page
        ``pages[lp]``, and queue the device upload for the decode loop
        (which scatters it at the next token boundary, BEFORE any join
        splices a table row at it).  Promoted pages join ``hks/hvs`` so
        the tail prefill attends over them exactly as over index hits —
        the promoted rows ARE the original prefill rows, so streams
        stay bitwise twins.  Returns the new ``n_hit``; memory-moves
        only, safe under the lock."""
        ps = self.page_size
        max_hit = (s0b - 1 - (self._mesh is not None)) // ps
        if n_hit >= max_hit:
            return n_hit
        keys = self._prefix_keys(padded, w, max_hit)
        taken = []
        for lp in range(n_hit, max_hit):
            ent = self._kv.take(keys[lp])
            if ent is None:
                break
            taken.append((keys[lp], ent))
        if not taken:
            return n_hit
        if (n_hit + len(taken)) * ps < w:
            # the probe's pad-coverage rule: hits must span every pad
            # slot or the tail prefill would see pad queries — put the
            # rows back rather than serve a chain we cannot splice
            for key, (hk, hv) in taken:
                self._kv.put_back(key, hk, hv)
            return n_hit
        for i, (key, (hk, hv)) in enumerate(taken):
            page = int(pages[n_hit + i])
            while len(self._prefix) >= self._prefix_cap:
                if not self._prefix_evict_one():
                    break
            if len(self._prefix) < self._prefix_cap:
                # the index's own reference, exactly as publish takes
                self._page_refs[page] += 1
                self._prefix[key] = {'page': page, 'hk': hk, 'hv': hv}
                self.stats.inc('prefix_published')
            # the pending upload's reference: until the rows land, the
            # page can be neither reclaimed nor reallocated
            self._page_refs[page] += 1
            self._pending_uploads.append((page, hk, hv))
            hks.append(hk)
            hvs.append(hv)
            self.stats.inc('kv_promoted_pages')
        return n_hit + len(taken)

    # -- page accounting (requires-lock helpers) ---------------------------
    def _alloc_pages(self, n: int) -> List[int]:  # requires-lock: _cond
        pages = [self._free_pages.pop() for _ in range(n)]
        for p in pages:
            self._page_refs[p] = 1
        if len(self._free_pages) < self._free_min:
            self._free_min = len(self._free_pages)
        return pages

    def _release_pages(self, pages) -> None:  # requires-lock: _cond
        """Drop one reference per page; a page returns to the free list
        only when nobody — page table or index — references it."""
        for p in pages:
            p = int(p)
            self._page_refs[p] -= 1
            if self._page_refs[p] <= 0:
                self._page_refs[p] = 0
                self._free_pages.append(p)
        self._cond.notify_all()

    def prefill_cost(self, req) -> int:
        """Admission-cost estimate for the batcher's coalescing budget
        (serve/batcher.py): the tokens THIS prompt's prefill would
        actually compute right now — a prefix-index hit costs only its
        tail.  Non-binding (the index can shift before admission); never
        touches the LRU clock."""
        prompt = np.asarray(req.data, np.int32)
        if prompt.ndim != 2 or prompt.shape[0] != 1:
            return max(1, int(prompt.size))
        s0 = prompt.shape[1]
        s0b = _prompt_bucket(s0)
        w = s0b - s0
        if self._prefix_cap <= 0:
            return s0b
        padded = np.pad(prompt, ((0, 0), (w, 0)))
        with self._cond:
            n_hit, _, _, _ = self._prefix_probe(padded, w, s0b,
                                                touch=False)
        return max(1, s0b - n_hit * self.page_size)

    def resident_bytes(self) -> int:
        """Device-memory ledger entry for the budgeter: params + pools
        (+ the draft tree and its dense cache when spec decoding).
        The paged KV pool is ONE allocation counted ONCE — prefix
        sharing multiplies page-table references, never this number
        (pinned by a regression test: two slots sharing a prefix report
        the same footprint as one).  The tiered cache's host/disk bytes
        are deliberately EXCLUDED: they are not device memory, and
        folding them in would double-count tiers against the
        ``hbm.headroom_frac`` / ``budget_drift()`` cross-check (their
        occupancy reports through the ``kv.*`` gauges instead; pinned
        by a kv_tier regression test)."""
        with self._cond:
            params = self._params
            draft = self._draft_params
            pool = self._kpool.nbytes + self._vpool.nbytes
            if self._draft_cfg is not None:
                pool += self._kdc.nbytes + self._vdc.nbytes
        total = pool + sum(l.nbytes for l in jax.tree.leaves(params))
        if draft is not None:
            total += sum(l.nbytes for l in jax.tree.leaves(draft))
        return int(total)

    def resident_bytes_per_device(self) -> list:
        """Per-device split of :meth:`resident_bytes` for sharded
        engines: one entry per mesh device, summed from each array's
        ``addressable_shards`` (replicated leaves — norms, biases, the
        draft — count their FULL bytes on EVERY device, matching what
        the allocator actually holds there).  Unsharded engines return
        the scalar as a one-entry vector so callers never branch.  The
        sum over devices therefore EXCEEDS ``resident_bytes()`` exactly
        by the replication overhead — the budgeter prices the max-
        loaded device, not the sum."""
        if self._mesh is None:
            return [self.resident_bytes()]
        with self._cond:
            arrs = list(jax.tree.leaves(self._params))
            arrs += [self._kpool, self._vpool]
            if self._draft_cfg is not None:
                arrs += [self._kdc, self._vdc]
            if self._draft_params is not None:
                arrs += list(jax.tree.leaves(self._draft_params))
        per = {d.id: 0 for d in self._mesh.devices.flat}
        for arr in arrs:
            for sh in arr.addressable_shards:
                if sh.device.id in per:
                    per[sh.device.id] += sh.data.nbytes
        return [per[d.id] for d in self._mesh.devices.flat]

    def kv_occupancy(self) -> Optional[Tuple[int, int]]:
        """``(host_bytes, disk_bytes)`` held by the tiered cache, or
        None when no tiers are attached — the fleet-report surface.
        Deliberately separate from :meth:`resident_bytes`: tier bytes
        are host/disk, never HBM, and must not feed the budgeter."""
        if self._kv is None:
            return None
        self._kv.refresh_gauges()
        store = self._kv.store
        return (self._kv.host_bytes(),
                0 if store is None else store.disk_bytes())

    def busy(self) -> bool:
        with self._cond:
            return (any(s is not None for s in self._slots)
                    or bool(self._joinq) or self._admitting > 0
                    or bool(self._prefillq))

    def set_live_limits(self, max_slots: Optional[int] = None,
                        max_pages: Optional[int] = None):
        """Clamp admission capacity live (the autoscaler's decode knob).

        Caps clamp to [1, physical]; a shrink takes effect at the next
        admission attempt — streams already past admission keep every
        page they grow into (the cap gates entry, not survival), so no
        autoscale action can ever corrupt or preempt a live stream.
        Returns the effective ``(slot_cap, page_cap)``."""
        with self._cond:
            if max_slots is not None:
                self._live_slot_cap = max(1, min(int(max_slots),
                                                 self.slots))
            if max_pages is not None:
                self._live_page_cap = max(1, min(int(max_pages),
                                                 self.n_pages - 1))
            self._cond.notify_all()
            return (self._live_slot_cap, self._live_page_cap)

    def live_limits(self):
        """Current logical ``(slot_cap, page_cap)`` admission clamps."""
        with self._cond:
            return (self._live_slot_cap, self._live_page_cap)

    def capacity_view(self) -> dict:
        """Physical vs live capacity in one snapshot — the autoscaler's
        ``/statusz`` provider surfaces this per bound engine."""
        with self._cond:
            return {'slots': self.slots,
                    'pages': self.n_pages - 1,
                    'live_slot_cap': self._live_slot_cap,
                    'live_page_cap': self._live_page_cap,
                    'free_pages': len(self._free_pages),
                    'occupied': sum(1 for s in self._slots
                                    if s is not None)}

    # -- admission ---------------------------------------------------------
    @property
    def buckets(self):
        """DynamicBatcher protocol: coalesce at most ``slots`` requests
        (one row each) per window."""
        return (self.slots,)

    def execute_requests(self, batch) -> None:
        """Batcher hand-off: admit each coalesced request into a slot
        (blocking for capacity up to its deadline).  The engine owns
        completion — per-request errors land on the request, never the
        worker.  With ``prefill_workers`` the hand-off is a queue push:
        dedicated prefill threads run admission concurrently, so one
        long cold prompt never heads-of-line-blocks the prompts behind
        it in the same coalescing window."""
        for req in batch:
            queued = False
            if self._prefill_threads:
                with self._cond:
                    if not self._closed:
                        self._prefillq.append(req)
                        self._cond.notify_all()
                        queued = True
            if not queued:
                self._admit_one(req)

    def _admit_one(self, req) -> None:
        """Admit ONE request, converting failures into typed
        per-request outcomes (never a raised exception — the caller
        may be a batcher worker or a prefill thread)."""
        try:
            self._admit(req)
        except BaseException as e:  # typed per-request outcome
            if isinstance(e, DeadlineExceededError):
                self.stats.inc('expired')
            elif isinstance(e, RequestAbandonedError):
                self.stats.inc('abandoned')
            elif isinstance(e, (DecodeSlotsExhaustedError,
                                DecodePagesExhaustedError)):
                self.stats.inc('shed_inadmissible')
            else:
                self.stats.inc('engine_errors')
            req.error = e
            req.event.set()

    def _prefill_worker(self) -> None:
        """Dedicated prefill thread: pop queued requests and run the
        full admission path (reserve -> prefill -> joinq).  After
        close(), the queue drains through ``_admit_one`` so every
        still-queued request fails typed (ServeError) instead of
        hanging its waiter."""
        while True:
            with self._cond:
                while not self._prefillq and not self._closed:
                    self._cond.wait(0.05)
                if not self._prefillq:
                    return          # closed and drained
                req = self._prefillq.popleft()
            self._admit_one(req)

    def submit_direct(self, prompt, max_new: int = None,
                      temperature: float = 0.0, rng=None,
                      deadline: float = 30.0):
        """Batcher-less admission (tests / embedding without a queue):
        returns the ``ServeRequest``; wait on ``req.event``."""
        from .batcher import ServeRequest
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        req = ServeRequest(prompt, deadline,
                           meta={'max_new': max_new,
                                 'temperature': temperature, 'rng': rng})
        self.execute_requests([req])
        return req

    def _admit(self, req) -> None:
        prompt = np.asarray(req.data, np.int32)
        if prompt.ndim != 2 or prompt.shape[0] != 1 or prompt.shape[1] < 1:
            raise ValueError('decode request payload must be one prompt '
                             'row: (1, s0) int tokens')
        s0 = prompt.shape[1]
        meta = req.meta or {}
        raw = meta.get('max_new')
        max_new = self.max_new_bound if raw is None else int(raw)
        temp = float(meta.get('temperature') or 0.0)
        rng = meta.get('rng')
        if max_new < 1:
            raise ValueError('max_new must be >= 1')
        if temp > 0 and rng is None:
            raise ValueError('temperature>0 sampling needs an rng key')
        s0b = _prompt_bucket(s0)
        w = s0b - s0
        if max_new > self.max_new_bound:
            raise DecodeSlotsExhaustedError(
                f'max_new={max_new} > engine bound {self.max_new_bound}')
        if s0b + max_new - 1 > self.cache_len:
            raise DecodeSlotsExhaustedError(
                f'prompt bucket {s0b} + max_new {max_new} exceeds the '
                f'slot cache ({self.cache_len} positions)')
        total_pages = (s0b + max_new - 2) // self.page_size + 1 \
            if max_new >= 2 else _ceil_div(s0b, self.page_size)
        if total_pages > min(self.pages_per_slot, self.n_pages - 1):
            raise DecodeSlotsExhaustedError(
                f'request needs {total_pages} KV pages; the pool can '
                f'offer at most {min(self.pages_per_slot, self.n_pages - 1)}')
        n_prompt = _ceil_div(s0b, self.page_size)
        # reserve the prompt pages plus the first decode position's page
        # now; later pages allocate on demand as the stream grows
        n0 = (s0b // self.page_size + 1) if max_new >= 2 else n_prompt
        ps = self.page_size
        padded = np.pad(prompt, ((0, 0), (w, 0)))
        if self._kv is not None and (s0b - 1) // ps > 0:
            # tier-2 promote prefetch: disk records rise into the host
            # tier HERE, on the admit thread with NO engine lock held —
            # the reserve loop's take() below is then memory-only.  The
            # reads are ThreadBuffer-double-buffered in the cache.
            with self._cond:
                want = [k for k in
                        self._prefix_keys(padded, w, (s0b - 1) // ps)
                        if k not in self._prefix]
            self._kv.prefetch(want)
        # --- reserve capacity (blocks; bounded by the request deadline)
        with self._cond:
            while True:
                if self._closed:
                    raise ServeError('decode engine is closed')
                if getattr(req, 'abandoned', False):
                    # the client walked away while we waited for
                    # capacity: a typed drop, never a burned slot
                    raise RequestAbandonedError(
                        time.monotonic() - req.t_submit)
                if total_pages > self._live_page_cap:
                    # autoscaler-clamped pool: shed fast and typed
                    # instead of waiting out a deadline the clamp
                    # guarantees we'd miss (the cap may grow back —
                    # the CLIENT retries, the queue does not)
                    raise DecodeSlotsExhaustedError(
                        f'request needs {total_pages} KV pages but the '
                        f'live page cap is {self._live_page_cap} '
                        f'(physical pool {self.n_pages - 1})')
                n_hit, hit_pages, hks, hvs = (
                    self._prefix_probe(padded, w, s0b)
                    if self._prefix_cap > 0 else (0, [], [], []))
                need = n0 - n_hit
                occupied = sum(1 for s in self._slots if s is not None)
                if (self._pending_params is None
                        and self._pending_draft is None
                        and occupied < self._live_slot_cap):
                    used = self.n_pages - 1 - len(self._free_pages)
                    # index-only pages count as used, so a shrunk live
                    # cap must reclaim them too — but never the hit
                    # pages this request is about to splice
                    short = max(need - len(self._free_pages),
                                used + need - self._live_page_cap)
                    if short > 0:
                        self._reclaim_index_pages(
                            short, exclude=set(hit_pages))
                        used = self.n_pages - 1 - len(self._free_pages)
                    if (len(self._free_pages) >= need
                            and used + need <= self._live_page_cap):
                        break
                remaining = req.deadline_abs - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceededError(
                        req.deadline, time.monotonic() - req.t_submit, 1)
                self._cond.wait(min(remaining, 0.05))
            sid = self._slots.index(None)
            self._slots[sid] = 'RESERVED'          # placeholder
            for p in hit_pages:                    # splice shared pages
                self._page_refs[p] += 1
            pages = list(hit_pages) + self._alloc_pages(need)
            if self._kv is not None:
                n_hit = self._promote_splice(padded, w, s0b, n_hit,
                                             pages, hks, hvs)
                self.kv_stats.inc('hits' if n_hit else 'misses')
            if n_hit:
                self.stats.inc('prefix_hits')
                self.stats.inc('prefix_hit_pages', n_hit)
                if n_hit == (s0b - 1) // ps and s0b % ps:
                    # the divergence page: everything shareable was
                    # shared, the partial last page is privately
                    # rematerialized by the tail prefill (the CoW rule)
                    self.stats.inc('cow_copies')
            elif self._prefix_cap > 0:
                self.stats.inc('prefix_misses')
            self._admitting += 1
            params = self._params
            draft_params = self._draft_params
            seq = self._join_seq
            self._join_seq += 1
        try:
            # --- RNG schedule: exactly generate()'s derivation
            if temp > 0:
                key = (jax.random.PRNGKey(rng) if isinstance(rng, int)
                       else rng)
                keys = np.asarray(jax.random.split(key, max_new + 1))
            else:
                keys = np.zeros((max_new + 1, 2), np.uint32)
            # --- prefill off the loop thread (joins stay token-aligned):
            # a prefix hit computes ONLY the tail, attending over the
            # shared rows' host mirrors (never the loop-owned pools)
            if n_hit:
                record_event('decode.prefix_hit', 'decode', req.trace_id,
                             hit_pages=n_hit)
                t0 = n_hit * ps
                with span('decode.tail_prefill', 'decode', req.trace_id,
                          prompt=s0b, tail=s0b - t0):
                    pk = np.concatenate(hks, axis=1)[:, None]
                    pv = np.concatenate(hvs, axis=1)[:, None]
                    ks, vs, logits0 = self._tail_fn(t0, s0b - t0)(
                        params, pk, pv, padded[:, t0:], np.int32(w))
                    hk_full = np.concatenate(
                        [pk[:, 0], np.asarray(ks)[:, 0]], axis=1)
                    hv_full = np.concatenate(
                        [pv[:, 0], np.asarray(vs)[:, 0]], axis=1)
            else:
                with span('decode.prefill', 'decode', req.trace_id,
                          prompt=s0b):
                    ks, vs, logits0 = self._prefill_fn(s0b)(
                        params, padded, np.int32(w))
                hk_full = hv_full = None   # mirrored lazily below
            dks = dvs = None
            if self._draft_cfg is not None and self._spec_k >= 2:
                # the draft full-prefills every prompt (it is small;
                # sharing its dense cache would buy nothing)
                dks, dvs, _ = self._prefill_fn(s0b, draft=True)(
                    draft_params, padded, np.int32(w))
            tok0 = int(self._pick1(logits0[0],
                                   jax.numpy.asarray(keys[0]),
                                   np.float32(temp)))
            if (self._prefix_cap > 0 and s0b // ps
                    and hk_full is None):
                # publish mirrors sync device->host HERE, outside the
                # engine lock — the decode loop takes _cond at every
                # token boundary and must not wait out a D2H copy
                hk_full = np.asarray(ks)[:, 0]
                hv_full = np.asarray(vs)[:, 0]
            now = time.monotonic()
            req.tokens.append(tok0)
            req.token_times.append(now)
            self.stats.inc('tokens')
            record_event('decode.emit', 'decode', req.trace_id,
                         token_index=0)
            done0 = self.eos_id is not None and tok0 == self.eos_id
            with self._cond:
                if done0 or max_new == 1:
                    self._slots[sid] = None
                    self._release_pages(pages)
                    self._finish(req)
                else:
                    # rows still to be written into the pool: the tail
                    # (hit) or the whole prompt (miss)
                    self._joinq.append(
                        {'sid': sid, 'pages': pages,
                         'wpages': pages[n_hit:n_prompt],
                         'wrows': s0b - n_hit * ps,
                         's0b': s0b, 'w': w, 'ks': ks, 'vs': vs,
                         'dks': dks, 'dvs': dvs,
                         'tok0': tok0, 'keys': keys, 'temp': temp,
                         'max_new': max_new, 'req': req, 'seq': seq})
                    self.stats.inc('joined')
                    if hk_full is not None:
                        self._prefix_publish(padded, w, s0b,
                                             pages[:s0b // ps],
                                             hk_full, hv_full)
                self._admitting -= 1
                self._cond.notify_all()
        except BaseException:
            with self._cond:
                self._slots[sid] = None
                self._release_pages(pages)
                self._admitting -= 1
                self._cond.notify_all()
            raise

    # -- the decode loop ---------------------------------------------------
    def _finish(self, req, error: Optional[BaseException] = None) -> None:
        """Complete a request (slot bookkeeping already done)."""
        if error is not None:
            req.error = error
        else:
            req.result = np.asarray(req.tokens, np.int32)
            self.stats.inc('completed')
            self.stats.observe('stream_len', len(req.tokens))
        record_event('decode.finish', 'decode',
                     getattr(req, 'trace_id', None),
                     tokens=len(req.tokens),
                     error=None if error is None else type(error).__name__)
        req.event.set()

    def _free_slot(self, sid: int) -> None:  # requires-lock: _cond
        """Release a slot's page references (caller holds the lock);
        refcounting decides which pages actually return to the pool —
        never one that another slot's table or the prefix index still
        holds."""
        row = self._table[sid]
        self._release_pages(int(p) for p in row[row != 0])
        row[:] = 0
        self._slots[sid] = None
        self._cond.notify_all()

    def _integrate_joins(self) -> None:  # requires-lock: _cond
        """Token boundary: splice every admitted request into its slot
        (caller holds the lock; pool writes release it per join).  A
        prefix-hit join splices the SHARED physical pages and writes
        only its freshly prefilled tail rows.  Tier-promoted pages
        upload FIRST: a promote enqueues its upload strictly before the
        promoted request's join is appended, so draining uploads ahead
        of joins guarantees every promoted page's rows are in the pool
        before any table row can reference it (the decode loop owns the
        device pools — this is the only thread that writes them)."""
        if self._pending_uploads:
            # one scatter for the whole backlog: a promote lands a whole
            # prefix of pages at once, and per-page uploads would pay a
            # dispatch each — batching matches the join path's
            # one-call-per-splice idiom
            batch = list(self._pending_uploads)
            self._pending_uploads.clear()
            ps = self.page_size
            pages = np.asarray([b[0] for b in batch], np.int32)
            hk = np.concatenate([b[1] for b in batch], axis=1)
            hv = np.concatenate([b[2] for b in batch], axis=1)
            wfn = self._write_fn(len(batch), len(batch) * ps)
            self._kpool, self._vpool = wfn(
                self._kpool, self._vpool, hk[:, None], hv[:, None],
                pages)
            # the uploads' own references (taken at promote) retire
            self._release_pages(pages.tolist())
            self.stats.inc('kv_uploads')
        while self._joinq:
            j = self._joinq.popleft()
            sid = j['sid']
            self._table[sid, :len(j['pages'])] = j['pages']
            if j['wpages']:
                wfn = self._write_fn(len(j['wpages']), j['wrows'])
                self._kpool, self._vpool = wfn(
                    self._kpool, self._vpool, j['ks'], j['vs'],
                    np.asarray(j['wpages'], np.int32))
            if j.get('dks') is not None:
                dwfn = self._dwrite_fn(j['s0b'])
                self._kdc, self._vdc = dwfn(
                    self._kdc, self._vdc, j['dks'], j['dvs'],
                    np.int32(sid))
            self._slots[sid] = _Slot(j['req'], j['s0b'], j['w'],
                                     j['tok0'], j['keys'], j['temp'],
                                     j['max_new'], j['seq'])

    def _expire_slots(self, now: float) -> None:  # requires-lock: _cond
        for sid, slot in enumerate(self._slots):
            if not isinstance(slot, _Slot):
                continue
            if now >= slot.req.deadline_abs:
                self.stats.inc('expired')
                self.stats.inc('tokens_shed',
                               slot.max_new - len(slot.req.tokens))
                err = TokenDeadlineExceededError(
                    slot.req.deadline, now - slot.req.t_submit,
                    len(slot.req.tokens))
                req = slot.req
                self._free_slot(sid)
                self._finish(req, err)

    def _alloc_step_pages(self, win: int = 1) -> None:  # requires-lock: _cond
        """On-demand page allocation for every slot about to write into
        an unmapped logical page — the whole ``win``-token window when
        spec decoding (verify writes rows at ``[pos, pos + win)``).
        Pool-dry first reclaims index-only prefix pages, then sheds the
        youngest stream (refcount-aware: a victim's shared pages stay
        alive for everyone else)."""
        order = sorted((s.join_seq, sid) for sid, s in
                       enumerate(self._slots) if isinstance(s, _Slot))
        for _seq, sid in order:
            slot = self._slots[sid]
            if not isinstance(slot, _Slot):
                continue            # shed as a victim earlier this pass
            last = min(slot.pos + win - 1, self.cache_len - 1)
            for lp in range(slot.pos // self.page_size,
                            last // self.page_size + 1):
                if self._table[sid, lp] != 0:
                    continue
                while not self._free_pages:
                    if self._reclaim_index_pages(1):
                        continue
                    victims = [(s.join_seq, vid) for vid, s in
                               enumerate(self._slots)
                               if isinstance(s, _Slot)]
                    vseq, vid = max(victims)
                    vslot = self._slots[vid]
                    self.stats.inc('shed_pages')
                    self.stats.inc('tokens_shed',
                                   vslot.max_new - len(vslot.req.tokens))
                    err = DecodePagesExhaustedError(
                        len(vslot.req.tokens), self.n_pages - 1)
                    vreq = vslot.req
                    self._free_slot(vid)
                    self._finish(vreq, err)
                    if vid == sid:
                        break
                if not isinstance(self._slots[sid], _Slot):
                    break           # shed as its own victim
                if self._free_pages:
                    self._table[sid, lp] = self._alloc_pages(1)[0]

    def _run(self) -> None:
        """Decode-loop thread body; a non-request fault (trace error,
        device loss) fails every in-flight stream with the error instead
        of stranding clients until their deadlines."""
        try:
            self._run_inner()
        except BaseException as e:  # noqa: BLE001 — loop must not vanish
            from ..runtime import faults
            faults.global_failure_log().record(
                'decode_loop_error', f'decode loop died: {e!r}')
            with self._cond:
                self._closed = True
                for sid, slot in enumerate(self._slots):
                    if isinstance(slot, _Slot):
                        req = slot.req
                        self._free_slot(sid)
                        self._finish(req, ServeError(
                            f'decode loop failed: {e!r}'))
                while self._joinq:
                    j = self._joinq.popleft()
                    self._finish(j['req'], ServeError(
                        f'decode loop failed: {e!r}'))
                self._cond.notify_all()

    def _run_inner(self) -> None:
        S = self.slots
        while True:
            # chaos surface: an installed FaultPlan's ``slow_step``
            # events sleep here, OFF the lock and between token
            # boundaries — latency shifts, streams never do
            _faults.decode_step()
            with self._cond:
                while True:
                    self._expire_slots(time.monotonic())
                    # joins first: anything admitted before a pending
                    # swap belongs to the old params' in-flight set
                    self._integrate_joins()
                    live = any(isinstance(s, _Slot) for s in self._slots)
                    if ((self._pending_params is not None
                            or self._pending_draft is not None)
                            and not live
                            and not self._joinq and self._admitting == 0):
                        if self._pending_params is not None:
                            self._params = self._pending_params
                            if self._pending_version is not None:
                                self.version = self._pending_version
                            self._pending_params = None
                            self.swap_count += 1
                            # the cached rows are the OLD model's
                            # activations: stale keys would leak pages
                            self._clear_prefix_index()
                        if self._pending_draft is not None:
                            self._draft_params = self._pending_draft
                            self._pending_draft = None
                            if self._pending_draft_version is not None:
                                self.draft_version = (
                                    self._pending_draft_version)
                                self._pending_draft_version = None
                        self._cond.notify_all()
                        continue
                    if live:
                        break
                    if (self._closed and not self._joinq
                            and self._admitting == 0):
                        return
                    self._cond.wait(0.05)
                # speculative window width: K proposals only when every
                # live stream is greedy (sampled streams keep their
                # per-key RNG schedule — spec pauses, never approximates)
                # and nobody is within K tokens of its horizon
                live_slots = [s for s in self._slots
                              if isinstance(s, _Slot)]
                K_step = 1
                if (self._spec_k >= 2 and self._draft_params is not None
                        and all(s.temp == 0 for s in live_slots)):
                    rem = min(s.max_new - len(s.req.tokens)
                              for s in live_slots)
                    K_step = max(1, min(self._spec_k, rem))
                self._alloc_step_pages(K_step)
                if not any(isinstance(s, _Slot) for s in self._slots):
                    continue        # every stream was shed this pass
                params = self._params
                dparams = self._draft_params
                table = np.array(self._table)
                pos = np.zeros(S, np.int32)
                w = np.zeros(S, np.int32)
                tok = np.zeros(S, np.int32)
                temp = np.zeros(S, np.float32)
                r = np.zeros((S, 2), np.uint32)
                stepped = []
                for sid, slot in enumerate(self._slots):
                    if isinstance(slot, _Slot):
                        pos[sid] = slot.pos
                        w[sid] = slot.w
                        tok[sid] = slot.last_tok
                        temp[sid] = slot.temp
                        r[sid] = slot.keys[slot.kidx]
                        stepped.append(sid)
            # the K/V pools (and the draft's dense caches) are
            # loop-thread-owned between token boundaries;
            # resident_bytes snapshots them under _cond
            if K_step >= 2:
                # hot path: record_event with explicit timestamps (not
                # a span ctx) — one fewer allocation per step, and gc
                # trigger frequency is the recorder's only real cost
                t0_ns = time.monotonic_ns()
                # lint: allow(lock-discipline): single-writer pool handoff (loop thread)
                (self._kpool, self._vpool, self._kdc, self._vdc,
                 window, tgt) = self._spec_fn(K_step)(
                    params, dparams, self._kpool, self._vpool,
                    self._kdc, self._vdc, table, pos, w, tok)
                window = np.asarray(window)
                tgt = np.asarray(tgt)
                # measured THROUGH the host sync above, like the plain
                # step leg — the dispatch alone is async and ~free
                record_event('decode.spec_verify', 'decode',
                             t_start_ns=t0_ns,
                             dur_ns=time.monotonic_ns() - t0_ns,
                             window=K_step, slots=len(stepped))
                now = time.monotonic()
                self.stats.inc('decode_steps')
                self.stats.inc('spec_steps')
                self.stats.observe('step_occupancy', len(stepped) / S)
                with self._cond:
                    for sid in stepped:
                        slot = self._slots[sid]
                        if not isinstance(slot, _Slot):
                            continue   # shed concurrently (defensive)
                        # accept the longest draft prefix the target
                        # agrees with, plus the target's own corrected
                        # token — every accepted token IS the target's
                        # greedy pick at its position
                        a = 0
                        while (a + 1 < K_step
                               and window[sid, a + 1] == tgt[sid, a]):
                            a += 1
                        self.stats.inc('spec_proposed', K_step - 1)
                        self.stats.inc('spec_accepted', a)
                        self.stats.observe('spec_window', a + 1)
                        for token in (int(t) for t in tgt[sid, :a + 1]):
                            slot.req.tokens.append(token)
                            slot.req.token_times.append(now)
                            self.stats.inc('tokens')
                            self.stats.observe(
                                'token_ms',
                                (now - slot.last_emit) * 1e3)
                            slot.last_emit = now
                            slot.last_tok = token
                            slot.pos += 1
                            slot.kidx += 1
                            hit_eos = (self.eos_id is not None
                                       and token == self.eos_id)
                            if (hit_eos or
                                    len(slot.req.tokens) >= slot.max_new):
                                req = slot.req
                                self._free_slot(sid)
                                self._finish(req)
                                break
                continue
            # hot path: explicit-timestamp record, not a span ctx (same
            # reasoning as the spec leg above)
            t0_ns = time.monotonic_ns()
            # lint: allow(lock-discipline): single-writer pool handoff (loop thread)
            self._kpool, self._vpool, nxt = self._step(
                params, self._kpool, self._vpool, table, pos, w, tok,
                r, temp)
            nxt = np.asarray(nxt)
            record_event('decode.step', 'decode', t_start_ns=t0_ns,
                         dur_ns=time.monotonic_ns() - t0_ns,
                         slots=len(stepped))
            now = time.monotonic()
            self.stats.inc('decode_steps')
            self.stats.observe('step_occupancy', len(stepped) / S)
            with self._cond:
                for sid in stepped:
                    slot = self._slots[sid]
                    if not isinstance(slot, _Slot):
                        continue    # shed concurrently (defensive)
                    token = int(nxt[sid])
                    slot.req.tokens.append(token)
                    slot.req.token_times.append(now)
                    self.stats.inc('tokens')
                    self.stats.observe('token_ms',
                                       (now - slot.last_emit) * 1e3)
                    slot.last_emit = now
                    slot.last_tok = token
                    slot.pos += 1
                    slot.kidx += 1
                    hit_eos = (self.eos_id is not None
                               and token == self.eos_id)
                    if hit_eos or len(slot.req.tokens) >= slot.max_new:
                        req = slot.req
                        self._free_slot(sid)
                        self._finish(req)

    # -- lifecycle / observability -----------------------------------------
    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting; finish in-flight streams (bounded by their
        horizons/deadlines); join the loop thread."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # dead programs must never be AOT-probed again: a later ledger
        # sweep re-lowering a stale (possibly SPMD) skeleton after this
        # engine's mesh is gone can crash the XLA client outright
        for prog in (self._prog_step, self._prog_prefill,
                     self._prog_tail, self._prog_spec):
            prog.retire()
        if threading.current_thread() is self._loop:
            return False
        ok = True
        for t in self._prefill_threads:
            t.join(timeout)
            ok = not t.is_alive() and ok
        self._loop.join(timeout)
        ok = not self._loop.is_alive() and ok
        if self._kv is not None:
            ok = self._kv.close(timeout) and ok
        return ok

    def report(self, name: Optional[str] = None) -> str:
        """Eval-line stats snapshot; folds in the ``generate`` program-
        cache hit/miss tallies (the serve surface for them) and the
        page-pool / prefix-share / spec-decode gauges (free-page
        low-water mark, shared-page count, index size, acceptance
        rate) so both multipliers are observable, not inferred."""
        gs = T.gen_cache_stats()
        self.stats.gauge('gen_cache.hit', gs['hit'])
        self.stats.gauge('gen_cache.miss', gs['miss'])
        with self._cond:
            free = len(self._free_pages)
            self.stats.gauge('free_pages', free)
            self.stats.gauge('free_pages_min', self._free_min)
            self.stats.gauge('pages_used', self.n_pages - 1 - free)
            self.stats.gauge('pages_shared',
                             int((self._page_refs[1:] > 1).sum()))
            self.stats.gauge('prefix_index_pages', len(self._prefix))
            self.stats.gauge('live_slot_cap', self._live_slot_cap)
            self.stats.gauge('live_page_cap', self._live_page_cap)
            if self._prefill_threads:
                self.stats.gauge('prefill_workers',
                                 len(self._prefill_threads))
                self.stats.gauge('prefill_queue', len(self._prefillq))
            if self._kv is not None:
                self.kv_stats.gauge('pending_uploads',
                                    len(self._pending_uploads))
        if self._kv is not None:
            # tier occupancy/hit gauges land on the separate `kv`
            # StatSet (its own /metrics family and SLO set name) —
            # NEVER on resident_bytes/budget_drift: host and disk
            # bytes are not HBM and must not read as such
            self._kv.refresh_gauges()
        proposed = self.stats.get('spec_proposed')
        if proposed:
            self.stats.gauge('spec_accept_rate',
                             self.stats.get('spec_accepted') / proposed)
        if self._tp > 1:
            self.stats.gauge('shard.tp', self._tp)
            for i, b in enumerate(self.resident_bytes_per_device()):
                self.stats.gauge(f'shard.resident_bytes[d{i}]', int(b))
        drift = self.budget_drift()
        if drift is not None:
            self.stats.gauge('budget_drift', round(drift, 4))
        return format_report(name or self.name, self.stats)

    def budget_drift(self) -> Optional[float]:
        """Signed relative drift of the closed-form
        :meth:`resident_bytes` ledger vs the compiled step's
        ``memory_analysis`` argument bytes (obs/programs.py) — the
        cross-check that keeps the MemoryBudgeter's arithmetic honest.
        The step's arguments are params + both pools + O(slots) scalars,
        so the comparison excludes the draft side (its programs are
        separate); None before the first step compiles or when the
        backend has no memory analysis."""
        truth = self._prog_step.argument_bytes()
        if truth <= 0:
            return None
        with self._cond:
            params = self._params
            pool = self._kpool.nbytes + self._vpool.nbytes
        closed = pool + sum(l.nbytes for l in jax.tree.leaves(params))
        return closed / truth - 1.0


# -- on-disk format for transformer param trees ----------------------------
# ``%04d.lm`` files: an .npz of the flattened tree written through the
# same atomic+retried+digested path as model files, so the registry's
# verify/blacklist machinery applies unchanged to decode models.

LM_PATTERN = r'^(\d+)\.lm$'


def _flatten_tree(tree, prefix=''):
    if isinstance(tree, dict):
        out = {}
        for k in sorted(tree):
            out.update(_flatten_tree(tree[k], f'{prefix}{k}/'))
        return out
    return {prefix[:-1]: np.asarray(tree)}


def save_lm_params(path: str, params, retry=None) -> str:
    """Atomically write a transformer param tree (+ crc32 sidecar)."""
    from ..nnet import checkpoint
    flat = _flatten_tree(params)
    checkpoint.save_model_file(
        path, lambda f: np.savez(f, **flat), retry=retry)
    checkpoint.write_model_digest(path)
    return path


def load_lm_params(path: str, retry=None):
    """Read a ``save_lm_params`` file back into a nested dict tree."""
    from ..nnet import checkpoint

    def read(f):
        z = np.load(f, allow_pickle=False)
        return {k: z[k] for k in z.files}

    flat = checkpoint.read_model_file(path, read, retry=retry)
    tree: dict = {}
    for key, leaf in flat.items():
        node = tree
        parts = key.split('/')
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = leaf
    return tree


def lm_loader(engine, path: str, retry=None):
    """Registry ``loader`` hook for decode models (the structural check
    happens in ``engine.place_params``)."""
    return load_lm_params(path, retry=retry)


class DecodeService:
    """The embeddable continuous-decode stack: admission-controlled
    ``DynamicBatcher`` fronting a ``DecodeEngine``, sharing one StatSet
    (the wrapper/C-ABI surface and the CLI drive both hold one of
    these)."""

    def __init__(self, params, cfg, *, slots: int = 4, pages: int = 64,
                 page_size: int = 16, max_prompt: int = 64,
                 max_new_bound: int = 64, eos_id: Optional[int] = None,
                 max_queue: int = 64, max_wait: float = 0.002,
                 deadline: float = 30.0, dtype: str = 'f32',
                 flash_decode=None, prefix_share: int = 0,
                 spec_k: int = 0, draft=None, kv_host_mb: int = 0,
                 kv_disk_mb: int = 0, kv_dir: Optional[str] = None,
                 kv_share_dir: Optional[str] = None, shard: str = '',
                 prefill_workers: int = 0):
        from .batcher import DynamicBatcher
        stats = StatSet()
        self.engine = DecodeEngine(
            params, cfg, slots=slots, pages=pages, page_size=page_size,
            max_prompt=max_prompt, max_new_bound=max_new_bound,
            eos_id=eos_id, stats=stats, dtype=dtype,
            flash_decode=flash_decode, prefix_share=prefix_share,
            spec_k=spec_k, draft=draft, kv_host_mb=kv_host_mb,
            kv_disk_mb=kv_disk_mb, kv_dir=kv_dir,
            kv_share_dir=kv_share_dir, shard=shard,
            prefill_workers=prefill_workers)
        # with prefix sharing on, admission prices each request at its
        # ACTUAL prefill cost (a hit is just its tail), so a coalescing
        # window full of hits admits everything while a burst of cold
        # prompts closes early instead of stacking full prefills in
        # front of the decode loop
        cost_kw = {}
        if prefix_share > 0:
            cost_kw = {'cost_fn': self.engine.prefill_cost,
                       'max_cost': 2 * self.engine.max_prompt}
        self.batcher = DynamicBatcher(self.engine, max_queue=max_queue,
                                      max_wait=max_wait, deadline=deadline,
                                      stats=stats, **cost_kw)

    def submit_async(self, prompt, max_new: int, temperature: float = 0.0,
                     rng=None, deadline: Optional[float] = None):
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim == 1:
            prompt = prompt[None]
        return self.batcher.submit_async(
            prompt, deadline=deadline,
            meta={'max_new': max_new, 'temperature': temperature,
                  'rng': rng})

    def generate(self, prompt, max_new: int, temperature: float = 0.0,
                 rng=None, deadline: Optional[float] = None) -> np.ndarray:
        """Submit one prompt and block for its full token stream."""
        req = self.submit_async(prompt, max_new, temperature, rng,
                                deadline)
        self.batcher.wait(req)
        return req.result

    def report(self, name: str = 'decode') -> str:
        return self.engine.report(name)

    def close(self, timeout: Optional[float] = None) -> None:
        self.batcher.close(timeout)
        self.engine.close(timeout)
