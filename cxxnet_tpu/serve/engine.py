"""Inference engine: bucketed-compile predict over an inference-only model.

``PredictEngine`` is the compute half of the serving subsystem
(doc/serving.md).  It differs from driving ``NetTrainer.predict`` directly
in three load-bearing ways:

* **inference-only state** — no optimizer moments, no gradient
  accumulator: the engine holds params only (roughly 1/3 the device
  memory of a trainer for SGD-momentum, 1/4 for Adam), loaded via a
  trainer constructed with ``inference_only = 1``,
* **provably bounded compile cache** — every request is padded up to one
  of a small configured ladder of batch-size buckets
  (``utils/bucketing.py``), so the jitted forward traces at most
  ``len(buckets)`` times, ever.  ``compile_count`` exposes the actual
  trace count (the counter increments inside the traced function, so it
  ticks exactly once per XLA compilation) — tests assert the bound
  instead of trusting it,
* **atomic parameter swap** — :meth:`swap_params` replaces the serving
  weights between batches without touching the compiled programs (the
  param tree's structure/shapes/dtypes are validated to match, so no
  retrace).  A batch in flight keeps the snapshot it started with;
  there is no window where a batch sees half-old, half-new weights.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np

import jax.numpy as jnp

from ..layers import ForwardContext
from ..nnet import quantize
from ..nnet.trainer import NetTrainer
from ..parallel.mesh import batch_sharding
from ..runtime.faults import (DeadlineExceededError, RequestAbandonedError,
                              ServeError)
from ..utils.bucketing import DEFAULT_BUCKETS, chunk_plan, pad_rows

__all__ = ['PredictEngine', 'ReplicatedPredictEngine']


def _as_4d(arr: np.ndarray) -> np.ndarray:
    """Request payloads arrive as (n, c, y, x) nodes or flat (n, d)
    matrices — same viewing rule as the C ABI (capi._as_4d)."""
    arr = np.asarray(arr)
    if arr.ndim == 4:
        return arr
    if arr.ndim == 2:
        return arr.reshape(arr.shape[0], 1, 1, arr.shape[1])
    raise ValueError(f'cannot view shape {arr.shape} as a request batch')


class PredictEngine:
    """Bucketed, hot-swappable jitted predict over a loaded model.

    ``trainer`` must be initialized (``init_model`` or ``load_model``);
    build it with ``inference_only = 1`` to skip optimizer-state
    allocation.  Requests are host float32 (or uint8) arrays shaped
    ``(n, c, y, x)`` or ``(n, d)``; inputs are expected pre-normalized
    (the serving wire contract — the ``device_normalize`` deferred-spec
    path is a training-iterator concern).
    """

    def __init__(self, trainer: NetTrainer,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 dtype: str = 'f32', device=None,
                 program_name: str = 'serve.predict',
                 fold_bn: int = 0, fold_batch=None):
        if trainer.net is None or trainer.params is None:
            raise ValueError('PredictEngine needs an initialized trainer '
                             '(init_model()/load_model() first)')
        # pinned-device replica mode (ReplicatedPredictEngine): params
        # and batches live whole on ONE device instead of sharding over
        # the trainer mesh — the forward math is the identical program,
        # only the placement differs
        self._device = device
        # quantized-inference storage tier (serve.dtype, doc/serving.md
        # "Quantized inference"): bf16 halves / int8 roughly quarters the
        # RESIDENT param bytes; the compiled forward expands weights to
        # f32 per call (weight-only — transient copies are freed between
        # requests, so the budgeter's ledger stays the quantized size)
        self.serve_dtype = quantize.parse_serve_dtype(dtype)
        self.trainer = trainer
        # graftfuse conv+BN folding (serve.fold_bn, nnet/fold.py): the
        # serving DAG retires each foldable BN to a pass-through and the
        # preceding conv absorbs its frozen calibration-batch affine —
        # one HLO op where three ran, and the ledger row (key suffix
        # '+fold') shows the fused program's compiler-truth cost.  f32
        # tier only: the pinned equality proof is an f32 statement, and
        # a quantized tree re-entering place_params cannot be told apart
        # from a fresh one (double-folding would corrupt the weights)
        self._fold_batch_arg = fold_batch
        self._fold_report = None
        self._last_placed = None   # identity of the newest fold+place
        self._fold_bn_layers = frozenset()
        if fold_bn and self.serve_dtype == 'f32':
            from ..nnet.fold import plan_conv_bn_pairs
            self._fold_bn_layers = frozenset(
                b for (_, b) in plan_conv_bn_pairs(trainer.net))
        self.buckets: Tuple[int, ...] = tuple(sorted(set(int(b)
                                                         for b in buckets)))
        if not self.buckets or self.buckets[0] <= 0:
            raise ValueError(f'bad bucket ladder {buckets!r}')
        ddim = 1 if device is not None else int(trainer._mesh.shape['data'])
        bad = [b for b in self.buckets if b % ddim]
        if bad:
            raise ValueError(
                f'buckets {bad} do not divide the mesh data axis ({ddim} '
                f'devices); pick multiples so padded batches shard evenly')
        # compiler-truth ledger row per bucket (obs/programs.py): the
        # declared bound IS the bucket-ladder contract, so a caller
        # bypassing the pad path trips the recompile sentinel.  Replicas
        # name their own row (serve.predict.rN) — each compiles its own
        # ladder, and folding them into one row would trip the bound
        from ..obs.programs import get_ledger
        self._program = get_ledger().program(program_name,
                                             bound=len(self.buckets))
        self.swap_count = 0
        self.version: object = 0
        # observability hook: called as on_serve(version) after every
        # completed forward with the version whose params ACTUALLY served
        # it (captured with the snapshot, so a swap mid-request reports
        # the old version) — the freshness tracker's serving-side probe
        # (online/freshness.py)
        self.on_serve = None
        self._inflight = 0      # forwards mid-execution (budgeter: busy())
        # the ORIGINAL f32 structure is the hot-swap contract (model
        # files carry f32 trees); _params holds the serving-tier storage
        self._ref_treedef = jax.tree.structure(trainer.params)
        self._ref_shapes = [(l.shape, l.dtype)
                            for l in jax.tree.leaves(trainer.params)]
        if device is None:
            def _put0(h):
                return h if isinstance(h, jax.Array) \
                    else jax.device_put(np.asarray(h))
        else:
            def _put0(h):
                return jax.device_put(np.asarray(h), device)
        if self.serve_dtype == 'f32':
            base = (self._fold(trainer.params) if self._fold_bn_layers
                    else trainer.params)
            self._params = (base if device is None
                            else jax.tree.map(_put0, base))
        else:
            self._params = jax.tree.map(
                _put0,
                quantize.quantize_tree(trainer.params, self.serve_dtype))
        self._params_treedef = jax.tree.structure(self._params)
        self._lock = threading.Lock()
        self._fwd = self._build_forward()

    # -- compiled forward --------------------------------------------------
    def _build_forward(self):
        tr = self.trainer
        net = tr.net
        top = net.cfg.layers[-1].nindex_out[-1]
        compute_dtype = tr.compute_dtype
        max_round = tr.max_round
        spmd = tr._mesh.devices.size
        quantized = self.serve_dtype != 'f32'
        fold_layers = self._fold_bn_layers

        def fwd(params, data):
            if quantized:
                # weight-only expansion: int8/bf16 storage -> f32 math;
                # XLA frees the expanded copies after the forward, so
                # only the quantized tree stays resident
                params = quantize.dequantize_tree(params, jnp.float32)
            ctx = ForwardContext(is_train=False, rng=None, round=0,
                                 max_round=max_round,
                                 compute_dtype=compute_dtype,
                                 spmd_devices=spmd)
            values, _ = net.forward(params, data, ctx,
                                    identity_layers=fold_layers)
            return values[top]

        # the ledger wrap compiles once per distinct signature — the
        # bucket key names the /programs row ('+fold' marks the folded
        # DAG, so /programs tells the fused program's flops/bytes apart
        # from an unfolded engine's); its compile count IS the provably-
        # bounded cache the tests assert (compile_count below)
        suffix = '+fold' if fold_layers else ''
        return self._program.jit(
            fwd, key_fn=lambda a, _k: f'b{a[1].shape[0]}{suffix}')

    # -- conv+BN folding (graftfuse) ---------------------------------------
    def _calib_batch(self) -> np.ndarray:
        """The calibration batch whose minibatch statistics the fold
        freezes: the caller's ``fold_batch`` (pass representative data —
        the folded net normalizes every future request with THESE
        statistics), else a seeded synthetic batch at the largest
        bucket, which keeps the fold deterministic and the equality
        proof meaningful, but encodes no data statistics."""
        if self._fold_batch_arg is not None:
            return _as_4d(np.asarray(self._fold_batch_arg, np.float32))
        c, y, x = self.trainer.net_cfg.input_shape
        rng = np.random.RandomState(0)
        return rng.randn(self.buckets[-1], c, y, x).astype(np.float32)

    def _fold(self, tree):
        """Fold every planned conv+BN pair of ``tree`` (f32 host/device)
        around the frozen calibration statistics; the pass itself proves
        the rewrite within pinned tolerances or raises ``FoldError`` —
        an engine never silently serves an unproven fold."""
        from ..nnet.fold import fold_params
        folded, report = fold_params(
            self.trainer.net, tree, self._calib_batch(),
            compute_dtype=self.trainer.compute_dtype)
        self._fold_report = report
        return folded

    def fold_view(self) -> Optional[dict]:
        """The newest fold's receipt (pairs, proof error, tolerances) —
        None when folding is off or nothing folded."""
        if self._fold_report is None:
            return None
        r = dict(self._fold_report)
        r['bn_layers'] = sorted(r['bn_layers'])
        return r

    @property
    def compile_count(self) -> int:
        """XLA compilations of the serving forward so far — re-based on
        the program ledger (one per distinct signature; the bucket
        ladder bounds it at ``len(buckets)``, and the ledger's
        recompile sentinel now enforces that bound as well)."""
        return self._program.compiles

    def ledger_bytes(self) -> Optional[int]:
        """The compiled forward's param bytes per ``memory_analysis``
        truth: newest entry's argument bytes minus its input batch —
        what ``budget_drift`` cross-checks :meth:`resident_bytes`
        against.  None before the first compile (or when the backend
        has no memory analysis)."""
        e = self._program.newest_entry()
        if e is None or e.argument_bytes <= 0:
            return None
        b = int(e.shape_key[1:]) if e.shape_key.startswith('b') else 0
        c, y, x = self.trainer.net_cfg.input_shape
        return int(e.argument_bytes) - b * c * y * x * 4

    # -- parameters --------------------------------------------------------
    @property
    def params(self):
        return self._params

    def _check_tree(self, params) -> None:
        if jax.tree.structure(params) != self._ref_treedef:
            raise ValueError('swap_params: param tree structure differs '
                             'from the serving model')
        # dtype is part of the contract only on the f32 tier — the
        # quantized tiers normalize every incoming float dtype anyway
        strict = self.serve_dtype == 'f32'
        for leaf, (shape, dtype) in zip(jax.tree.leaves(params),
                                        self._ref_shapes):
            if tuple(leaf.shape) != tuple(shape) or \
                    (strict and leaf.dtype != dtype):
                raise ValueError(
                    f'swap_params: leaf {tuple(leaf.shape)}/{leaf.dtype} '
                    f'!= serving {tuple(shape)}/{dtype} — a shape change '
                    'needs a new engine, not a hot swap')

    def place_params(self, host_params):
        """Quantize (serve.dtype tier) + device-put a host param tree
        with the serving params' shardings (structure/shape validated
        against the ORIGINAL f32 contract first).  This method's own
        output (the registry re-passes it through warm->swap)
        short-circuits the validate+quantize."""
        if self.serve_dtype != 'f32':
            if jax.tree.structure(host_params) != self._params_treedef \
                    or self._params_treedef == self._ref_treedef:
                self._check_tree(host_params)
                host_params = quantize.quantize_tree(host_params,
                                                     self.serve_dtype)
            dev = self._device
            return jax.tree.map(
                lambda h: h if isinstance(h, jax.Array) and dev is None
                else jax.device_put(np.asarray(h), dev), host_params)
        self._check_tree(host_params)
        if self._fold_bn_layers:
            # a hot-swapped tree is re-folded against the SAME frozen
            # calibration batch.  The sharding-based shortcut below
            # cannot tell this engine's own folded output from a FRESH
            # host tree that happens to share shardings (folding twice
            # would corrupt the weights; never folding a fresh tree
            # would serve unfolded BNs through a folded DAG) — object
            # identity with the last placement is the test
            if host_params is self._last_placed:
                return host_params
            host_params = self._fold(host_params)
        elif self._is_placed(host_params):
            return host_params   # already ours: skip the device round
        placed = jax.tree.map(
            lambda h, cur: jax.device_put(
                np.asarray(h, dtype=cur.dtype)
                if not isinstance(h, jax.Array) else h,
                cur.sharding),
            host_params, self._params)
        if self._fold_bn_layers:
            self._last_placed = placed
        return placed

    def _is_placed(self, params) -> bool:
        """True when every leaf is already a device array carrying the
        serving shardings — lets ``swap_params(place_params(x))`` (the
        registry's warm-then-swap sequence) skip a second placement."""
        return all(
            isinstance(leaf, jax.Array) and leaf.sharding == cur.sharding
            for leaf, cur in zip(jax.tree.leaves(params),
                                 jax.tree.leaves(self._params)))

    def warm_params(self, params) -> None:
        """Run one smallest-bucket forward with ``params`` and block:
        materializes the tree on device and pays any lazy transfer cost
        BEFORE the swap, so the first post-swap request sees no warmup
        stall.  No new compilation (shapes are bucket shapes)."""
        b = self.buckets[0]
        c, y, x = self.trainer.net_cfg.input_shape
        dummy = np.zeros((b, c, y, x), np.float32)
        jax.block_until_ready(self._fwd(params, self._put(dummy)))

    def swap_params(self, params, version: object = None) -> None:
        """Atomically make ``params`` (host or device tree) the serving
        weights.  In-flight batches keep the snapshot they captured;
        every batch dispatched after this call uses the new tree."""
        placed = self.place_params(params)
        with self._lock:
            self._params = placed
            self.swap_count += 1
            if version is not None:
                self.version = version

    def _snapshot(self):
        with self._lock:
            return self._params

    def _snapshot_versioned(self):
        """(params, version) captured atomically: the version a request
        reports is the version whose params it was computed with, even
        when a swap lands mid-request."""
        with self._lock:
            return self._params, self.version

    # -- fleet accounting (serve/registry.py MultiModelRegistry) -----------
    def resident_bytes(self) -> int:
        """Device bytes this engine keeps resident (its param tree) —
        the multi-model budgeter's ledger entry."""
        return int(sum(l.nbytes for l in jax.tree.leaves(self._params)))

    def busy(self) -> bool:
        """True while a forward is executing: the budgeter must never
        evict the model that is serving right now."""
        return self._inflight > 0

    def capacity_view(self) -> dict:
        """Declared capacity + live compile/residency truth in one
        snapshot — what the autoscaler's ``/statusz`` provider surfaces
        per bound engine (serve/autoscale.py)."""
        return {'buckets': list(self.buckets),
                'compile_count': int(self.compile_count),
                'resident_bytes': int(self.resident_bytes()),
                'busy': bool(self.busy())}

    # -- prediction --------------------------------------------------------
    def _put(self, data: np.ndarray):
        if data.dtype != np.float32:
            # jit programs are keyed by dtype as well as shape: normalize
            # the wire dtype or a uint8 client would double the cache
            data = data.astype(np.float32)
        return jax.device_put(np.ascontiguousarray(data),
                              self._device if self._device is not None
                              else batch_sharding(self.trainer._mesh))

    def warm(self) -> int:
        """Compile every bucket up front (cold-start cost paid at startup,
        not at first-request latency); returns ``compile_count``."""
        c, y, x = self.trainer.net_cfg.input_shape
        params = self._snapshot()
        for b in self.buckets:
            jax.block_until_ready(
                self._fwd(params, self._put(np.zeros((b, c, y, x),
                                                     np.float32))))
        return self.compile_count

    def predict_scores(self, data: np.ndarray) -> np.ndarray:
        """Final-node scores for ``n`` request rows: ``(n, k)`` float32.
        The input is padded to the smallest fitting bucket (oversize
        requests split into max-bucket chunks); pad rows never leave the
        engine.  The param snapshot is taken ONCE, so a multi-chunk
        request is never served by two model versions."""
        data = _as_4d(data)
        n = data.shape[0]
        params, version = self._snapshot_versioned()
        outs: List[np.ndarray] = []
        with self._lock:
            self._inflight += 1
        try:
            for off, take, bucket in chunk_plan(n, self.buckets):
                chunk = pad_rows(data[off:off + take], bucket)
                out = self._fwd(params, self._put(chunk))
                outs.append(np.asarray(out, np.float32)[:take])
        finally:
            with self._lock:
                self._inflight -= 1
        if self.on_serve is not None:
            self.on_serve(version)
        if not outs:
            return np.empty((0, 1), np.float32)
        scores = np.concatenate(outs, axis=0)
        return scores.reshape(n, -1)

    def predict(self, data: np.ndarray) -> np.ndarray:
        """Class id (argmax; raw value for single-score nets) per row —
        ``NetTrainer.predict`` semantics on the serving path."""
        return NetTrainer._pred_transform(self.predict_scores(data))


class _FleetPlaced(list):
    """Marker type: per-replica placed param trees (one per device) —
    distinguishes a fleet placement from an arbitrary host tree in the
    registry's place->warm->swap sequence."""


class ReplicatedPredictEngine:
    """Data-parallel ``PredictEngine`` replicas behind ONE batcher
    (``serve.replicas=N``, doc/serving.md "Sharded serving").

    Each replica pins the full param tree and its batches to one device
    (``PredictEngine(device=...)``); coalesced batches round-robin
    across replicas, so N windows execute concurrently instead of
    serializing through the batcher worker.  The forward is the SAME
    compiled program per replica — scores are independent of which
    replica answered.

    Hot swap is fleet-atomic: :meth:`swap_params` gates new dispatch,
    drains every replica's queue and in-flight batch, then swaps all
    replicas before traffic resumes — no window where two versions
    answer concurrently.

    Exposes the engine-owned-completion batcher protocol
    (``execute_requests`` + ``buckets``), the budgeter surface
    (``resident_bytes`` / ``busy``), and the per-device split
    (``resident_bytes_per_device``) the fleet budgeter prices.
    """

    def __init__(self, trainer: NetTrainer,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 dtype: str = 'f32', replicas: int = 2, devices=None,
                 stats=None, fold_bn: int = 0, fold_batch=None):
        n = int(replicas)
        if n < 1:
            raise ValueError('serve.replicas must be >= 1')
        devs = list(devices) if devices is not None else jax.devices()
        if n > len(devs):
            raise ValueError(f'serve.replicas={n} exceeds the '
                             f'{len(devs)} available devices')
        self.engines = [
            PredictEngine(trainer, buckets, dtype, device=devs[i],
                          program_name=f'serve.predict.r{i}',
                          fold_bn=fold_bn, fold_batch=fold_batch)
            for i in range(n)]
        self.buckets = self.engines[0].buckets
        self.stats = stats
        self._cond = threading.Condition()
        # guarded-by: _cond (per-replica batch queues + dispatch state)
        self._qs: List[collections.deque] = [collections.deque()
                                             for _ in range(n)]
        self._rr = 0
        self._inflight = [0] * n
        self._draining = False
        self._closed = False
        self._threads = []
        for i in range(n):
            t = threading.Thread(target=self._worker, args=(i,),
                                 daemon=True, name=f'cxxnet-replica-{i}')
            t.start()
            self._threads.append(t)

    # -- batcher protocol (engine-owned completion) ------------------------
    def execute_requests(self, batch) -> None:
        """One coalesced window -> the next replica's queue (strict
        round-robin; the batcher worker returns immediately).  A
        draining swap gates NEW windows here — already-queued windows
        keep flowing so the drain terminates under live traffic."""
        with self._cond:
            while self._draining and not self._closed:
                self._cond.wait(0.05)
            if self._closed:
                raise ServeError('replicated engine is closed')
            self._qs[self._rr].append(list(batch))
            self._rr = (self._rr + 1) % len(self.engines)
            self._cond.notify_all()

    def _worker(self, i: int) -> None:
        while True:
            with self._cond:
                while not self._qs[i] and not self._closed:
                    self._cond.wait(0.05)
                if self._closed:
                    # fail queued windows typed instead of stranding
                    # their waiters (mirrors the decode engine's close)
                    while self._qs[i]:
                        for r in self._qs[i].popleft():
                            r.error = ServeError(
                                'replicated engine is closed')
                            r.event.set()
                    return
                batch = self._qs[i].popleft()
                self._inflight[i] += 1
            try:
                self._run_batch(i, batch)
            finally:
                with self._cond:
                    self._inflight[i] -= 1
                    self._cond.notify_all()

    def _run_batch(self, i: int, batch) -> None:
        # same shed-then-forward discipline as the batcher's sync leg:
        # a request that expired (or walked away) while queued must not
        # ride the forward; single-owner counting lands HERE because
        # completion is engine-owned
        now = time.monotonic()
        live = []
        for r in batch:
            if getattr(r, 'abandoned', False):
                r.error = RequestAbandonedError(now - r.t_submit)
                if self.stats is not None:
                    self.stats.inc('abandoned')
                r.event.set()
            elif now >= r.deadline_abs:
                r.error = DeadlineExceededError(
                    r.deadline, now - r.t_submit, r.n)
                if self.stats is not None:
                    self.stats.inc('expired')
                r.event.set()
            else:
                live.append(r)
        if not live:
            return
        try:
            data = (live[0].data if len(live) == 1 else
                    np.concatenate([r.data for r in live], axis=0))
            scores = self.engines[i].predict_scores(data)
        except BaseException as e:   # surface faults per-request
            for r in live:
                if self.stats is not None:
                    self.stats.inc('engine_errors')
                r.error = e
                r.event.set()
            return
        done = time.monotonic()
        off = 0
        for r in live:
            r.result = scores[off:off + r.n]
            off += r.n
            if self.stats is not None:
                self.stats.inc('requests')
                self.stats.inc(f'replica_rows[r{i}]', r.n)
                self.stats.observe('latency_ms', (done - r.t_submit) * 1e3)
            r.event.set()

    # -- fleet-atomic hot swap ---------------------------------------------
    def place_params(self, host_params) -> '_FleetPlaced':
        """Registry protocol: one placed tree PER replica (each pins
        its own device) — the typed list keeps ``swap_params`` from
        mistaking a fleet placement for a host tree."""
        return _FleetPlaced(e.place_params(host_params)
                            for e in self.engines)

    def _as_fleet(self, params) -> '_FleetPlaced':
        if isinstance(params, _FleetPlaced):
            if len(params) != len(self.engines):
                raise ValueError('fleet placement arity != replicas')
            return params
        return self.place_params(params)

    def warm_params(self, params) -> None:
        """Warm every replica's forward with the candidate tree BEFORE
        the swap (registry warm->swap sequence, per device)."""
        for e, p in zip(self.engines, self._as_fleet(params)):
            e.warm_params(p)

    def swap_params(self, params, version: object = None) -> None:
        """Drain ALL replicas (queued + in-flight), swap every one,
        then reopen dispatch — requests never observe a mixed-version
        fleet."""
        placed = self._as_fleet(params)   # device copies BEFORE the gate
        with self._cond:
            self._draining = True
            while any(self._qs) or any(self._inflight):
                self._cond.wait(0.05)
        try:
            for eng, p in zip(self.engines, placed):
                eng.swap_params(p, version)
        finally:
            with self._cond:
                self._draining = False
                self._cond.notify_all()

    # -- engine surface -----------------------------------------------------
    @property
    def swap_count(self) -> int:
        return self.engines[0].swap_count

    @property
    def version(self):
        return self.engines[0].version

    @version.setter
    def version(self, v) -> None:
        for e in self.engines:
            e.version = v

    @property
    def compile_count(self) -> int:
        return sum(e.compile_count for e in self.engines)

    def warm(self) -> int:
        for e in self.engines:
            e.warm()
        return self.compile_count

    def resident_bytes(self) -> int:
        """Fleet total (every replica holds a full copy)."""
        return sum(e.resident_bytes() for e in self.engines)

    def resident_bytes_per_device(self) -> List[int]:
        """One entry per replica device — what the budgeter prices
        (max-loaded device), matching the sharded decode surface."""
        return [e.resident_bytes() for e in self.engines]

    def busy(self) -> bool:
        with self._cond:
            return any(self._inflight) or any(bool(q) for q in self._qs)

    def capacity_view(self) -> dict:
        with self._cond:
            queued = sum(len(q) for q in self._qs)
        return {'buckets': list(self.buckets),
                'replicas': len(self.engines),
                'compile_count': int(self.compile_count),
                'resident_bytes': int(self.resident_bytes()),
                'queued_windows': queued,
                'busy': bool(self.busy())}

    def predict_scores(self, data: np.ndarray) -> np.ndarray:
        """Batcher-less sync path: round-robin one replica (waits out a
        draining swap first, same no-mixed-version rule)."""
        with self._cond:
            while self._draining:
                self._cond.wait(0.05)
            i = self._rr
            self._rr = (self._rr + 1) % len(self.engines)
            self._inflight[i] += 1
        try:
            return self.engines[i].predict_scores(data)
        finally:
            with self._cond:
                self._inflight[i] -= 1
                self._cond.notify_all()

    def predict(self, data: np.ndarray) -> np.ndarray:
        return NetTrainer._pred_transform(self.predict_scores(data))

    def close(self, timeout: Optional[float] = None) -> bool:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        # retire the replicas' ledger rows: their device pins die with
        # this fleet, and a later sweep must not AOT-probe them
        for e in self.engines:
            e._program.retire()
        ok = True
        for t in self._threads:
            t.join(timeout)
            ok = not t.is_alive() and ok
        return ok
