"""graftcache — the tiered KV prefix cache (HBM → host → disk).

Sits BEHIND the PR 12 content-addressed prefix index
(``serve/decode.py``), extending its lifecycle without touching its
ownership rules:

* **Tier 0** is the live HBM page pool.  Unchanged: the decode loop
  thread owns the physical pages; the admit thread only ever touches
  host mirrors.
* **Tier 1** is this module's bounded host-RAM ``OrderedDict`` (LRU by
  insertion/touch): when the index evicts an entry whose page refcount
  hits zero, the page's host K/V row mirrors **demote** here instead
  of dropping.  The demote hook runs under the engine lock, so it is
  memory-moves only — tier-1 overflow hands the coldest entry to the
  tier-2 spill queue, whose disk writes happen on the store's own
  ``cxxnet-kv-store-*`` worker thread.
* **Tier 2** is :class:`~cxxnet_tpu.serve.kvstore.KVStore` — crc32-
  digested fixed-size records on disk, optionally shared cross-replica
  through ``serve.kv_share_dir``.

A later prefix **probe** that runs past the index promotes: the admit
thread calls :meth:`prefetch` OUTSIDE the engine lock (record reads
fan out over a small persistent reader pool, so a whole-prefix walk
never serialises page-sized I/O and the engine lock is never held
across it), then :meth:`take` under the lock hands the rows to the
engine, which
re-uploads them into a freshly allocated physical page on the decode
loop thread at the next token boundary.  The published rows ARE the
prefill rows, so bitwise stream twins hold through every demote /
promote / spill / adopt path — pinned by ``tests/test_kv_tiers.py``.

Telemetry: the cache owns a ``kv`` :class:`StatSet` registered on the
hub, so ``/metrics``, the gauge sampler and ``slo.kv_hit=
kv.hit_rate>=0.5@60``-style specs ride free.  Host/disk occupancy is
deliberately NOT part of ``DecodeEngine.resident_bytes()`` — the HBM
ledger / ``budget_drift()`` cross-check stays device-truth only.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

import numpy as np

from ..utils.metric import StatSet
from .kvstore import KVStore

__all__ = ['TieredKVCache', 'KVStore']


class TieredKVCache:
    """Host-tier LRU over spillable prefix-page entries.

    ``host_bytes`` bounds tier 1 (0 = no host tier: demotes go straight
    to the store, or drop when there is none); ``store`` is the
    optional tier-2 :class:`KVStore`.  Thread-safe: the engine calls
    :meth:`demote`/:meth:`take` under its own lock, the admit thread
    calls :meth:`prefetch` outside it — lock order is always
    ``engine._cond`` → ``TieredKVCache._lock``, and this module never
    calls back into the engine.
    """

    def __init__(self, *, host_bytes: int = 0,
                 store: Optional[KVStore] = None,
                 stats: Optional[StatSet] = None):
        self.stats = stats if stats is not None else StatSet()
        self._store = store
        self._host_cap = int(host_bytes)
        self._lock = threading.Lock()
        self._host: collections.OrderedDict = (
            collections.OrderedDict())   # guarded-by: _lock
        self._host_bytes = 0             # guarded-by: _lock
        self._readers: Optional[ThreadPoolExecutor] = None  # guarded-by: _lock

    # -- tier 1 ------------------------------------------------------------
    @staticmethod
    def _nbytes(hk, hv) -> int:
        return int(hk.nbytes) + int(hv.nbytes)

    def demote(self, key, hk: np.ndarray, hv: np.ndarray) -> None:
        """Index eviction hands an entry down-tier.  Copies the rows
        (the engine's mirrors are views into whole-prompt arrays, and a
        view would pin the full prompt's memory against a page-sized
        budget); memory-only — safe under the engine lock."""
        hk = np.ascontiguousarray(hk)
        hv = np.ascontiguousarray(hv)
        spill = []
        with self._lock:
            if key in self._host:
                self._host.move_to_end(key)
                return
            if self._host_cap > 0:
                self._host[key] = (hk, hv)
                self._host_bytes += self._nbytes(hk, hv)
                while self._host_bytes > self._host_cap and self._host:
                    k, (ck, cv) = self._host.popitem(last=False)
                    self._host_bytes -= self._nbytes(ck, cv)
                    spill.append((k, ck, cv))
            else:
                spill.append((key, hk, hv))
        self.stats.inc('demote_pages')
        for item in spill:
            if self._store is not None:
                self._store.spill(*item)  # async; drop-on-full inside
            else:
                self.stats.inc('host_evicted')

    def take(self, key):
        """Pop ``(hk, hv)`` for an exact key, or None — the promote
        read.  The entry leaves tier 1: it is about to live in the HBM
        index again, and will demote back here on its next eviction."""
        with self._lock:
            ent = self._host.pop(key, None)
            if ent is not None:
                self._host_bytes -= self._nbytes(*ent)
        if ent is not None:
            self.stats.inc('promote_pages')
        return ent

    def put_back(self, key, hk: np.ndarray, hv: np.ndarray) -> None:
        """Undo a :meth:`take` (the engine's pad-coverage rule rejected
        the promote chain); no counters move."""
        with self._lock:
            if key in self._host:
                return
            self._host[key] = (hk, hv)
            self._host_bytes += self._nbytes(hk, hv)

    # -- tier 2 promote path -----------------------------------------------
    def prefetch(self, keys) -> int:
        """Pull any of ``keys`` that tier 2 holds up into tier 1, in
        order, stopping at the first miss (prefix chains are
        consecutive: page ``lp`` is useless without ``lp-1``).  Runs on
        the admit thread OUTSIDE the engine lock; record reads fan out
        over a small persistent reader pool (a whole-prefix promote is
        dozens of page-sized records, and serial open/read/crc would
        put the disk walk on the admission critical path).  Records
        past the first miss may load and be discarded — bounded by the
        chain length, and the host dict only ever gains the consecutive
        run.  Returns the number promoted to tier 1."""
        store = self._store
        if store is None or not keys:
            return 0
        with self._lock:
            want = [k for k in keys if k not in self._host]
        if not want:
            return 0
        t0 = time.monotonic()
        got = 0
        if len(want) > 1:
            with self._lock:
                if self._readers is None:
                    self._readers = ThreadPoolExecutor(
                        4, thread_name_prefix='cxxnet-kv-read')
                ex = self._readers
            loaded = list(ex.map(store.load, want))
        else:
            loaded = [store.load(want[0])]
        for key, ent in zip(want, loaded):
            if ent is None:
                break
            hk, hv = ent
            with self._lock:
                if key not in self._host:
                    self._host[key] = (hk, hv)
                    self._host_bytes += self._nbytes(hk, hv)
            got += 1
        if got:
            self.stats.inc('disk_promote_pages', got)
        self.stats.observe('promote_ms',
                           (time.monotonic() - t0) * 1e3)
        return got

    # -- observability / lifecycle ------------------------------------------
    def refresh_gauges(self) -> None:
        """Tier occupancy + hit-rate gauges onto the ``kv`` StatSet —
        the hub refresh hook, also folded into the engine report."""
        with self._lock:
            self.stats.gauge('host_bytes', self._host_bytes)
            self.stats.gauge('host_entries', len(self._host))
        if self._store is not None:
            self.stats.gauge('disk_bytes', self._store.disk_bytes())
            self.stats.gauge('disk_entries', self._store.disk_entries())
        hits = self.stats.get('hits')
        total = hits + self.stats.get('misses')
        if total:
            self.stats.gauge('hit_rate', hits / total)

    def host_bytes(self) -> int:
        with self._lock:
            return self._host_bytes

    def host_entries(self) -> int:
        with self._lock:
            return len(self._host)

    @property
    def store(self) -> Optional[KVStore]:
        return self._store

    def flush(self, timeout: float = 5.0) -> bool:
        return self._store.flush(timeout) if self._store is not None \
            else True

    def close(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            readers, self._readers = self._readers, None
        if readers is not None:
            readers.shutdown(wait=True)
        if self._store is not None:
            self._store.flush(timeout if timeout is not None else 5.0)
            return self._store.close(timeout)
        return True
