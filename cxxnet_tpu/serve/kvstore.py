"""graftcache tier 2 — the on-disk KV spill record store.

One record per evicted prefix page: for a given engine shape every
record is the same byte size (the BinaryPage fixed-record idiom,
SURVEY.md §2.6), laid out as::

    b'CXKV1\\n' | u32 header_len | header JSON | K rows | V rows

with the exact PR 12 content key — ``(model version, pad width,
logical page, exact padded token span)`` — carried in the header and
re-checked on every read, so the sha256 *filename* digest is a lookup
convenience, never a correctness dependence.  Records commit through
the checkpoint publish discipline (``nnet/checkpoint.py``): staged
write + fsync, crc32 sidecar computed from the staged bytes and
committed BEFORE the rename, directory fsync — a reader can never
observe a record without its digest.  The ``corrupt_kv=N`` chaos hook
fires on the staged file between digest and rename, so injected
corruption is deterministically caught by :func:`verify_record`.

A record that fails digest verification (or whose header is not the
key it was fetched for) is **quarantined** — renamed aside with a
``.quarantine`` suffix, recorded as a typed
:class:`~cxxnet_tpu.runtime.faults.KVCorruptRecordError` — and
reported as a miss: the request re-prefills; a poisoned record can
never reach a stream.

Spill writes run on a dedicated ``cxxnet-kv-store-*`` worker thread
(the engine's demote hook runs under the decode lock and must never
touch a disk), bounded by a drop-on-full queue: a cache never owes
durability.  ``share_dir`` turns the store cross-replica: every
committed record is republished there under the same digest filename
+ sidecar discipline, and a local miss adopts a verified shared record
— one replica's prefill serves the fleet (doc/serving.md "Tiered KV
cache").
"""

from __future__ import annotations

import hashlib
import json
import os
import queue
import struct
import threading
from typing import Optional, Tuple

import numpy as np

from ..utils.metric import StatSet

_MAGIC = b'CXKV1\n'
_RECORD_SUFFIX = '.kv'


def key_digest(key) -> str:
    """Stable content digest of a PR 12 prefix key — the cross-replica
    record name.  ``repr`` of the model version is the canonical form
    (engine versions are ints / registry checkpoint numbers, identical
    across replicas serving the same model)."""
    version, w, lp, span = key
    h = hashlib.sha256()
    h.update(repr(version).encode())
    h.update(b'|%d|%d|' % (int(w), int(lp)))
    h.update(bytes(span))
    return h.hexdigest()


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes                 # bf16 serving tier
        return np.dtype(getattr(ml_dtypes, name))


def encode_record(key, hk: np.ndarray, hv: np.ndarray) -> bytes:
    """Serialize one page's host K/V row mirrors + their exact key."""
    version, w, lp, span = key
    hk = np.ascontiguousarray(hk)
    hv = np.ascontiguousarray(hv)
    if hk.shape != hv.shape or hk.dtype != hv.dtype:
        raise ValueError('K/V row mirrors must share shape and dtype')
    header = json.dumps(
        {'v': repr(version), 'w': int(w), 'lp': int(lp),
         'span': bytes(span).hex(), 'dtype': str(hk.dtype),
         'shape': list(hk.shape)}, sort_keys=True).encode()
    return b''.join([_MAGIC, struct.pack('<I', len(header)), header,
                     hk.tobytes(), hv.tobytes()])


def decode_record(blob: bytes, key) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a record back into ``(hk, hv)``; raises ``ValueError``
    unless the header carries EXACTLY ``key`` (digest collisions and
    stale-version aliasing both land here, never in a stream)."""
    if blob[:len(_MAGIC)] != _MAGIC:
        raise ValueError('bad record magic')
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from('<I', blob, off)
    off += 4
    header = json.loads(blob[off:off + hlen].decode())
    off += hlen
    version, w, lp, span = key
    want = {'v': repr(version), 'w': int(w), 'lp': int(lp),
            'span': bytes(span).hex()}
    got = {k: header.get(k) for k in want}
    if got != want:
        raise ValueError(f'record key mismatch: {got!r} != {want!r}')
    dtype = _np_dtype(header['dtype'])
    shape = tuple(int(s) for s in header['shape'])
    n = int(np.prod(shape)) * dtype.itemsize
    if len(blob) - off != 2 * n:
        raise ValueError(f'record payload is {len(blob) - off} bytes, '
                         f'expected {2 * n}')
    hk = np.frombuffer(blob, dtype=dtype, count=int(np.prod(shape)),
                       offset=off).reshape(shape)
    hv = np.frombuffer(blob, dtype=dtype, count=int(np.prod(shape)),
                       offset=off + n).reshape(shape)
    return hk, hv


class KVStore:
    """Tier-2 record store: bounded disk budget, LRU-by-mtime eviction,
    async spill worker, optional cross-replica ``share_dir``."""

    def __init__(self, root: str, budget_bytes: int,
                 share_dir: Optional[str] = None,
                 stats: Optional[StatSet] = None, name: str = 'kv',
                 max_queue: int = 256):
        self.root = os.fspath(root)
        self.share_dir = None if share_dir is None else os.fspath(share_dir)
        self.budget_bytes = int(budget_bytes)
        self.stats = stats if stats is not None else StatSet()
        os.makedirs(self.root, exist_ok=True)
        if self.share_dir is not None:
            os.makedirs(self.share_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._disk_bytes = 0             # guarded-by: _lock (record bytes)
        self._disk_entries = 0           # guarded-by: _lock
        # spills awaiting the worker: read-through so a promote landing
        # between enqueue and commit still finds the entry (a prefix
        # chain breaks on ANY mid-chain miss, so the queue window must
        # not read as one)
        self._inflight: dict = {}        # guarded-by: _lock
        self._scan_ledger()
        # spill queue: drop-on-full (a cache never owes durability; a
        # blocked producer here would be the decode admit thread)
        self._q: queue.Queue = queue.Queue(maxsize=int(max_queue))
        self._stop = threading.Event()
        self._worker = threading.Thread(
            target=self._run, daemon=True, name=f'cxxnet-kv-store-{name}')
        self._worker.start()

    # -- ledger ------------------------------------------------------------
    def _records(self):
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return [os.path.join(self.root, n) for n in sorted(names)
                if n.endswith(_RECORD_SUFFIX)]

    def _scan_ledger(self) -> None:
        total = entries = 0
        for path in self._records():
            try:
                total += os.path.getsize(path)
                entries += 1
            except OSError:
                pass
        with self._lock:
            self._disk_bytes, self._disk_entries = total, entries

    def disk_bytes(self) -> int:
        with self._lock:
            return self._disk_bytes

    def disk_entries(self) -> int:
        with self._lock:
            return self._disk_entries

    def record_path(self, key) -> str:
        return os.path.join(self.root, key_digest(key) + _RECORD_SUFFIX)

    # -- spill (async; worker thread) --------------------------------------
    def spill(self, key, hk: np.ndarray, hv: np.ndarray) -> bool:
        """Enqueue one demoted entry for the worker; False = queue full
        (entry dropped, counted — never blocks the caller).  An
        enqueued entry is immediately loadable through the in-flight
        read-through; a dropped one is gone.

        Spill-once: a key names an immutable span (version + pad + exact
        tokens), so an existing record can never be stale — a re-demote
        of an already-durable key just refreshes its LRU clock instead
        of burning the worker on an identical record + fsync storm."""
        with self._lock:
            queued = key in self._inflight
        path = self.record_path(key)
        if queued or os.path.exists(path):
            if not queued:
                try:
                    os.utime(path)
                except OSError:
                    pass
            self.stats.inc('spill_dedup')
            return True
        hk = np.ascontiguousarray(hk)
        hv = np.ascontiguousarray(hv)
        try:
            self._q.put_nowait((key, hk, hv))
        except queue.Full:
            self.stats.inc('spill_dropped')
            return False
        with self._lock:
            self._inflight[key] = (hk, hv)
        return True

    def _run(self) -> None:
        while True:
            try:
                item = self._q.get(timeout=0.05)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            try:
                if item is not None:
                    self._write_record(*item)
            except BaseException as e:  # noqa: BLE001 — worker survives
                from ..runtime import faults
                self.stats.inc('spill_errors')
                faults.global_failure_log().record(
                    'kv_spill_error',
                    repr(faults.KVSpillError(self.root, e)))
            finally:
                if item is not None:
                    # retire the read-through entry only if a re-spill
                    # hasn't replaced it (identity, not equality: the
                    # newer enqueue owns the key now)
                    with self._lock:
                        cur = self._inflight.get(item[0])
                        if cur is not None and cur[0] is item[1]:
                            del self._inflight[item[0]]
                self._q.task_done()
            if item is None:
                return

    def _publish(self, path: str, blob: bytes, chaos: bool) -> None:
        """Commit ``blob`` under ``path`` with the publish discipline:
        staged bytes + fsync, digest sidecar from the staged bytes
        committed BEFORE the rename, then rename + dir fsync.  The
        ``corrupt_kv`` chaos hook fires between digest and rename
        (``chaos`` gates it to the primary copy so one fault plan event
        is one poisoned record, not a record AND its shared twin)."""
        import zlib

        from ..nnet import checkpoint
        from ..runtime import faults
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = os.path.join(
            d, f'.{os.path.basename(path)}.pub.{os.getpid()}')
        try:
            with open(tmp, 'wb') as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            digest = {'size': len(blob),
                      'crc32': zlib.crc32(blob) & 0xFFFFFFFF}
            with checkpoint.atomic_write(
                    checkpoint.model_digest_path(path)) as f:
                f.write(json.dumps(digest).encode())
            if chaos:
                faults.kv_record_committed(path, staged=tmp)
            os.replace(tmp, path)
            try:
                dfd = os.open(d, os.O_RDONLY)
                try:
                    os.fsync(dfd)
                finally:
                    os.close(dfd)
            except OSError:
                pass
        finally:
            if os.path.exists(tmp):
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def _write_record(self, key, hk, hv) -> None:
        path = self.record_path(key)
        fresh = not os.path.exists(path)
        blob = encode_record(key, hk, hv)
        self._publish(path, blob, chaos=True)
        self.stats.inc('spills')
        if fresh:
            with self._lock:
                self._disk_bytes += len(blob)
                self._disk_entries += 1
        self._enforce_budget()
        if self.share_dir is not None:
            share = os.path.join(self.share_dir,
                                 os.path.basename(path))
            if not os.path.exists(share):
                self._publish(share, blob, chaos=False)
                self.stats.inc('published')

    def _enforce_budget(self) -> None:
        """Delete coldest (oldest-mtime) records until under budget —
        only the LOCAL root; the share dir is every replica's, pruned
        by whoever owns its retention."""
        if self.budget_bytes <= 0:
            return
        with self._lock:
            over = self._disk_bytes > self.budget_bytes
        if not over:
            return
        aged = []
        for path in self._records():
            try:
                aged.append((os.path.getmtime(path),
                             os.path.getsize(path), path))
            except OSError:
                pass
        aged.sort()
        freed_bytes = freed_entries = 0
        with self._lock:
            total = self._disk_bytes
        for _mt, size, path in aged:
            if total - freed_bytes <= self.budget_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            try:
                os.unlink(path + '.crc32')
            except OSError:
                pass
            freed_bytes += size
            freed_entries += 1
            self.stats.inc('disk_evicted')
        with self._lock:
            self._disk_bytes = max(0, self._disk_bytes - freed_bytes)
            self._disk_entries = max(0, self._disk_entries - freed_entries)

    # -- promote reads (caller thread; pipelined by the cache) -------------
    def _quarantine(self, path: str, reason: str) -> None:
        from ..runtime import faults
        err = faults.KVCorruptRecordError(path, reason)
        self.stats.inc('corrupt_quarantined')
        faults.global_failure_log().record('kv_corrupt_record', repr(err))
        size = 0
        try:
            size = os.path.getsize(path)
            os.replace(path, path + '.quarantine')
        except OSError:
            pass
        try:
            os.unlink(path + '.crc32')
        except OSError:
            pass
        if os.path.dirname(os.path.abspath(path)) == \
                os.path.abspath(self.root):
            with self._lock:
                self._disk_bytes = max(0, self._disk_bytes - size)
                self._disk_entries = max(0, self._disk_entries - 1)

    def _read_verified(self, path: str, key):
        """(hk, hv) from one record file, or None — digest mismatch and
        undecodable bytes both quarantine and read as a miss."""
        from ..nnet import checkpoint
        reason = checkpoint.verify_model_digest(path)
        if reason is not None:
            self._quarantine(path, reason)
            return None
        try:
            with open(path, 'rb') as f:
                blob = f.read()
            ent = decode_record(blob, key)
        except (OSError, ValueError) as e:
            self._quarantine(path, repr(e))
            return None
        try:
            os.utime(path)               # LRU clock for _enforce_budget
        except OSError:
            pass
        return ent

    def load(self, key):
        """(hk, hv) for ``key``: an in-flight spill first (enqueued but
        not yet committed — the rows in memory ARE the record), then the
        local root, else adopted from the share dir (the adopted copy is
        re-committed locally so the byte budget owns it), else None."""
        with self._lock:
            ent = self._inflight.get(key)
        if ent is not None:
            self.stats.inc('inflight_hits')
            return ent
        path = self.record_path(key)
        if os.path.exists(path):
            ent = self._read_verified(path, key)
            if ent is not None:
                return ent
        if self.share_dir is None:
            return None
        share = os.path.join(self.share_dir, os.path.basename(path))
        if not os.path.exists(share):
            return None
        ent = self._read_verified(share, key)
        if ent is None:
            return None
        self.stats.inc('adopts')
        blob = encode_record(key, *ent)
        fresh = not os.path.exists(path)
        self._publish(path, blob, chaos=False)
        if fresh:
            with self._lock:
                self._disk_bytes += len(blob)
                self._disk_entries += 1
        self._enforce_budget()
        return ent

    # -- lifecycle ---------------------------------------------------------
    def flush(self, timeout: float = 5.0) -> bool:
        """Block until every queued spill committed (tests and clean
        shutdown); False on timeout."""
        import time
        deadline = time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True

    def close(self, timeout: Optional[float] = None) -> bool:
        self._stop.set()
        try:
            self._q.put_nowait(None)     # wake the worker
        except queue.Full:
            pass
        self._worker.join(timeout if timeout is not None else 5.0)
        return not self._worker.is_alive()
