"""Model registry: checkpoint hot-reload without dropping requests.

The training side emits ``model_dir/%04d.model`` files via atomic
temp+fsync+rename (``nnet/checkpoint.py``) — a reader can never observe a
partial file.  The ``ModelRegistry`` closes the loop on the serving side:
it watches ``model_dir`` for a newer counter, verifies the file against
its ``.crc32`` digest sidecar (written by the train CLI at save time),
loads the params through the retrying model-file reader, warms them on
device, and atomically swaps them into the live ``PredictEngine``.
In-flight batches finish on the params they started with; every batch
dispatched after the swap serves the new ones — no request is ever
dropped or mixed across versions (engine snapshot semantics,
``serve/engine.py``).

Reload state machine (one cycle per detected counter, transitions
recorded in :attr:`transitions` for tests/observability)::

    IDLE -> DETECTED -> VERIFYING -> LOADING -> WARMING -> SWAPPED
                           |            |
                           +-> REJECTED-+   (recorded to the failure log;
                                             retried up to ``max_attempts``
                                             polls, then blacklisted)

A REJECTED checkpoint never reaches the engine: the previous version
keeps serving — corrupt or half-replicated storage degrades freshness,
not availability.
"""

from __future__ import annotations

import os
import re
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..nnet import checkpoint
from ..nnet.net_config import NetConfig
from ..obs import format_report, span
from ..runtime import faults

__all__ = ['ModelRegistry', 'MultiModelRegistry', 'MemoryBudgeter',
           'load_model_params', 'newest_model_file', 'load_into_trainer']

_MODEL_RE = re.compile(r'^(\d+)\.model$')


def newest_model_file(model_dir: str,
                      pattern=None) -> Optional[Tuple[int, str]]:
    """Highest-counter model file in ``model_dir`` as ``(counter, path)``
    (None when none match) — the one scan every fleet factory and the
    registry share."""
    rx = _MODEL_RE if pattern is None else re.compile(pattern)
    best: Optional[Tuple[int, str]] = None
    try:
        names = os.listdir(os.fspath(model_dir))
    except OSError:
        return None
    for name in names:
        m = rx.match(name)
        if m and (best is None or int(m.group(1)) > best[0]):
            best = (int(m.group(1)), os.path.join(model_dir, name))
    return best


def load_into_trainer(trainer, path: str, retry=None):
    """Load a model file into ``trainer`` through the retried reader
    (skipping the net_type prefix) — the fleet factories' load path."""

    def _read(f):
        f.read(4)
        trainer.load_model(f)

    checkpoint.read_model_file(path, _read, retry=retry)
    return trainer


def load_model_params(engine, path: str, retry=None):
    """Read a model file and return its HOST param tree, validated
    against ``engine``'s net structure (layer count and types must match
    — a hot swap cannot change architecture).  Raises
    ``CheckpointCorruptError`` on a truncated blob, ``ValueError`` on a
    structural mismatch; transient I/O errors retry under ``retry``."""

    def read(f):
        f.read(4)                      # net_type prefix
        cfg = NetConfig()
        cfg.load_net(f)
        f.read(8)                      # epoch_counter, irrelevant here
        (blob_len,) = struct.unpack('<Q', f.read(8))
        blob = f.read(blob_len)
        if len(blob) != blob_len:
            raise faults.CheckpointCorruptError(
                f'{path}: model blob truncated '
                f'({len(blob)}/{blob_len} bytes)')
        return cfg, blob

    cfg, blob = checkpoint.read_model_file(path, read, retry=retry)
    serving = engine.trainer.net_cfg.layers
    if len(cfg.layers) != len(serving) or any(
            a.type != b.type for a, b in zip(cfg.layers, serving)):
        raise ValueError(
            f'{path}: net structure differs from the serving model '
            f'({len(cfg.layers)} vs {len(serving)} layers) — '
            'hot reload cannot change architecture')
    return checkpoint.blob_to_params(engine.trainer.net, blob)


class ModelRegistry:
    """Watch ``model_dir`` and hot-swap newer checkpoints into ``engine``.

    ``current`` is the counter being served (pass the loaded model's
    counter so an already-served checkpoint is not re-loaded on the
    first poll; -1 means "adopt whatever appears first").  ``on_swap``
    (optional) is called as ``on_swap(counter, path)`` after each
    successful swap.
    """

    def __init__(self, engine, model_dir: str, poll_interval: float = 1.0,
                 current: int = -1, retry: Optional[faults.RetryPolicy] = None,
                 log: Optional[faults.FailureLog] = None,
                 on_swap: Optional[Callable[[int, str], None]] = None,
                 pattern: Optional[str] = None,
                 loader: Optional[Callable] = None,
                 attempts: Optional[dict] = None):
        self.engine = engine
        self.model_dir = os.fspath(model_dir)
        self.poll_interval = float(poll_interval)
        self.current = int(current)
        self.retry = faults.DEFAULT_IO_RETRY if retry is None else retry
        self.log = faults.global_failure_log() if log is None else log
        self.on_swap = on_swap
        # ``pattern``/``loader`` generalize the registry beyond NetTrainer
        # model files: decode models watch ``%04d.lm`` trees through the
        # same verify/blacklist machinery (serve/decode.py lm_loader).
        self._re = _MODEL_RE if pattern is None else re.compile(pattern)
        self._loader = load_model_params if loader is None else loader
        self.transitions: List[Tuple[str, str]] = []  # guarded-by: _lock
        # swap stamps: the step number of the last adopted checkpoint
        # (parsed from its %04d name — group 1 of ``pattern``) and when
        # it swapped in, the serving half of the freshness metric
        # (doc/online.md); surfaced via :meth:`report` / serve stats
        self.swaps = 0                       # guarded-by: _lock
        self.last_swap_step: int = -1        # guarded-by: _lock (-1: never)
        self.last_swap_time: Optional[float] = None   # guarded-by: _lock
        # counter -> failed poll cycles; a MultiModelRegistry passes a
        # shared dict so the blacklist survives evict/reload cycles
        self._attempts: dict = {} if attempts is None else attempts
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        engine.version = self.current

    # -- observability -----------------------------------------------------
    _MAX_TRANSITIONS = 512

    def _note(self, state: str, detail: str) -> None:
        with self._lock:
            self.transitions.append((state, detail))
            # a long-lived server must not grow this without bound
            if len(self.transitions) > self._MAX_TRANSITIONS:
                del self.transitions[:len(self.transitions)
                                     - self._MAX_TRANSITIONS]

    def states(self) -> List[str]:
        with self._lock:
            return [s for s, _ in self.transitions]

    # -- scanning ----------------------------------------------------------
    def candidates_on_disk(self) -> List[Tuple[int, str]]:
        """Model files newer than the serving counter, newest first."""
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.model_dir)
        except OSError:
            return out
        for name in names:
            m = self._re.match(name)
            # lint: allow(lock-discipline): current is a single monotone int advanced only by the poll thread; GIL-atomic reads, a stale value is one poll late
            if m and int(m.group(1)) > self.current:
                out.append((int(m.group(1)),
                            os.path.join(self.model_dir, name)))
        out.sort(reverse=True)
        return out

    def latest_on_disk(self) -> Optional[Tuple[int, str]]:
        """Newest (counter, path) model file in ``model_dir``, or None."""
        cand = self.candidates_on_disk()
        return cand[0] if cand else None

    # -- one reload cycle --------------------------------------------------
    def poll_once(self) -> bool:
        """Adopt the newest *loadable* checkpoint past the serving one:
        candidates are tried newest-first, so a blacklisted (persistently
        rejected) newest file falls back to the next-newest good one
        instead of pinning the server on a stale version.  Returns True
        when a swap happened.  Never raises for a bad checkpoint —
        rejection is recorded, counted toward that counter's blacklist,
        and the old version keeps serving."""
        for counter, path in self.candidates_on_disk():
            if self._attempts.get(counter, 0) >= self.retry.max_attempts:
                continue                  # blacklisted: persistent reject
            self._note('DETECTED', path)
            try:
                with span('registry.reload', 'serve', counter=counter):
                    self._note('VERIFYING', path)
                    reason = checkpoint.verify_model_digest(path)
                    if reason:
                        raise faults.CheckpointCorruptError(
                            f'{path}: {reason}')
                    self._note('LOADING', path)
                    params = self._loader(self.engine, path,
                                          retry=self.retry)
                    self._note('WARMING', path)
                    placed = self.engine.place_params(params)
                    self.engine.warm_params(placed)
            except Exception as e:
                # ANY failure (I/O, structure, device OOM during warm...)
                # must reject-and-count: an uncounted error would re-run
                # the full verify/load/warm cycle every poll forever
                self._attempts[counter] = self._attempts.get(counter, 0) + 1
                self._note('REJECTED', f'{path}: {e!r}')
                self.log.record('serve_reload_reject',
                                f'checkpoint {counter} rejected: {e!r}')
                continue
            with span('registry.swap', 'serve', counter=counter):
                self.engine.swap_params(placed, version=counter)
            self.current = counter
            with self._lock:
                self.swaps += 1
                self.last_swap_step = counter
                self.last_swap_time = time.monotonic()
            self._note('SWAPPED', path)
            if self.on_swap is not None:
                self.on_swap(counter, path)
            return True
        return False

    # -- freshness stamps ---------------------------------------------------
    def last_swap_age_s(self) -> float:
        """Seconds since the last successful swap (NaN before the first
        one) — how stale the serving version is, from the server's own
        clock."""
        with self._lock:
            t = self.last_swap_time
        return float('nan') if t is None else time.monotonic() - t

    def report(self, stats=None, name: str = 'registry') -> str:
        """Swap stamps + reject counters in eval-line format (optionally
        onto a shared ``StatSet``) — the serving half of the freshness
        metric (doc/online.md)."""
        from ..utils.metric import StatSet
        stats = StatSet() if stats is None else stats
        with self._lock:
            stats.gauge('swaps', self.swaps)
            stats.gauge('last_swap_step', self.last_swap_step)
            t = self.last_swap_time
        if t is not None:
            stats.gauge('last_swap_age_s', time.monotonic() - t)
        stats.gauge('blacklisted',
                    sum(1 for v in self._attempts.copy().values()
                        if v >= self.retry.max_attempts))
        return format_report(name, stats)

    def status_view(self) -> dict:
        """The /statusz JSON shape for one registry (state machine tail
        + swap stamps); the guarded stamps snapshot under the lock."""
        current = self.current     # single int, the poll loop's idiom
        with self._lock:
            return {'current': current,
                    'swaps': self.swaps,
                    'last_swap_step': self.last_swap_step,
                    'transitions': [s for s, _ in self.transitions[-12:]]}

    def register_into(self, hub, name: str = 'registry'):
        """Register this registry's gauges + state-machine view into a
        telemetry hub (ONE definition of the /metrics refresh and the
        /statusz shape, shared by task=serve and the online pipeline so
        the two can't drift).  Returns the hub-owned StatSet."""
        from ..utils.metric import StatSet
        stats = StatSet()
        hub.register_stats(name, stats,
                           refresh=lambda: self.report(stats=stats))
        hub.register_status(name, self.status_view)
        return stats

    # -- watcher lifecycle -------------------------------------------------
    def start(self) -> None:
        """Start the polling watcher thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name='serve-registry')
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception as e:       # watcher must outlive bad cycles
                self.log.record('serve_reload_error',
                                f'registry poll failed: {e!r}')

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop the watcher (idempotent, re-entrant safe)."""
        self._stop.set()
        t = self._thread
        if t is None or t is threading.current_thread():
            return True
        t.join(timeout)
        return not t.is_alive()


# --- multi-model fleet ----------------------------------------------------


class MemoryBudgeter:
    """Device-memory ledger for a fleet of serving models.

    Tracks per-model resident bytes against a budget (0 = unbounded).
    Models report either a scalar (single-device) or a per-device
    vector (sharded tp:N / replicated fleets); the budget is read as
    per-device HBM and ``over_budget()`` prices the MAX-loaded device.
    It does not free anything itself — :class:`MultiModelRegistry` asks
    it who is over budget and evicts; the split keeps the accounting
    unit-testable without engines."""

    def __init__(self, budget_bytes: int = 0):
        # guarded-by: _lock (live-retunable via set_budget)
        self.budget = int(budget_bytes)
        self._resident: Dict[str, int] = {}
        self._lock = threading.Lock()

    def set_budget(self, budget_bytes: int) -> int:
        """Retune the fleet budget live (the autoscaler's eviction
        lever, serve/autoscale.py); 0 = unbounded.  Accounting only —
        enforcement stays with :class:`MultiModelRegistry`, which evicts
        on its next pass.  Returns the previous budget."""
        with self._lock:
            prev, self.budget = self.budget, int(budget_bytes)
        return prev

    def account(self, model_id: str, nbytes) -> None:
        """Ledger one model: a scalar (single-device engine — its whole
        footprint sits on the default device) or a per-device vector
        (sharded/replicated engines, ``resident_bytes_per_device()``).
        Vectors index devices positionally; a scalar is device 0."""
        with self._lock:
            if isinstance(nbytes, (list, tuple)):
                self._resident[model_id] = tuple(int(b) for b in nbytes)
            else:
                self._resident[model_id] = int(nbytes)

    def release(self, model_id: str) -> int:
        with self._lock:
            ent = self._resident.pop(model_id, 0)
            return sum(ent) if isinstance(ent, tuple) else ent

    def usage(self) -> int:
        """Fleet-total resident bytes (every device summed)."""
        with self._lock:
            return sum(sum(e) if isinstance(e, tuple) else e
                       for e in self._resident.values())

    def usage_per_device(self) -> List[int]:
        """Per-device fleet load: vector entries add positionally,
        scalars land on device 0.  The widest vector sets the length."""
        with self._lock:
            n = max((len(e) for e in self._resident.values()
                     if isinstance(e, tuple)), default=1)
            out = [0] * n
            for e in self._resident.values():
                if isinstance(e, tuple):
                    for i, b in enumerate(e):
                        out[i] += b
                else:
                    out[0] += e
            return out

    def resident(self) -> Dict[str, int]:
        with self._lock:
            return {k: (sum(e) if isinstance(e, tuple) else e)
                    for k, e in self._resident.items()}

    def over_budget(self) -> int:
        """Bytes past the budget on the MAX-loaded device (0 inside it
        or unbounded).  The budget is per-device HBM: a tp:4 engine
        spreading 1GB over 4 chips prices ~256MB + replication, not
        1GB — and for scalar-only fleets (everything on device 0) the
        max device IS the old fleet sum, so nothing shifts."""
        if self.budget <= 0:
            return 0
        return max(0, max(self.usage_per_device()) - self.budget)


class _ManagedModel:
    """One fleet entry: how to build its engine, where its checkpoints
    live, and its load/eviction state."""

    __slots__ = ('model_id', 'factory', 'engine', 'model_dir', 'pattern',
                 'loader', 'current', 'attempts', 'registry', 'last_used',
                 'pinned', 'leases')

    def __init__(self, model_id, factory, model_dir, pattern, loader,
                 current, pinned):
        self.model_id = model_id
        self.factory = factory
        self.engine = None
        self.model_dir = model_dir
        self.pattern = pattern
        self.loader = loader
        self.current = int(current)
        self.attempts: dict = {}       # blacklist survives evictions
        self.registry: Optional[ModelRegistry] = None
        self.last_used = 0.0
        self.pinned = bool(pinned)
        self.leases = 0                # callers inside lease() blocks


class _DraftAdapter:
    """The ``ModelRegistry`` engine shim for speculative-decode drafts:
    verified checkpoints route into the target ``DecodeEngine``'s draft
    slot (``place_draft_params``/``swap_draft_params``) instead of its
    serving params, so the registry's DETECTED->...->SWAPPED machinery
    applies to drafts unchanged.  The target is resolved THROUGH the
    fleet on every call (never a captured engine reference): if the
    budgeter evicted and reloaded the model in between, the swap lands
    on the LIVE engine instead of a closed husk — and the lease holds
    off eviction for the duration of the swap."""

    __slots__ = ('fleet', 'model_id', 'version')

    def __init__(self, fleet, model_id):
        self.fleet = fleet
        self.model_id = model_id
        self.version = -1

    def place_params(self, host_params):
        with self.fleet.lease(self.model_id) as engine:
            return engine.place_draft_params(host_params)

    def warm_params(self, placed) -> None:
        import jax
        jax.block_until_ready(jax.tree.leaves(placed))

    def swap_params(self, placed, version: object = None) -> None:
        with self.fleet.lease(self.model_id) as engine:
            engine.swap_draft_params(placed, version=version)
        self.version = version


class MultiModelRegistry:
    """N-model registry with a device-memory budgeter: one chip serves a
    fleet of workloads (doc/serving.md "Multi-model serving").

    Each model is registered with a ``factory`` (zero-arg callable
    building its engine — a ``PredictEngine`` or ``DecodeEngine``; the
    factory owns EVERY reference to the model's device state, so
    evicting the entry really frees the memory) and optionally a
    ``model_dir`` to hot-reload from (the per-model ``ModelRegistry``
    machinery — digest verification, newest-first fallback, blacklist —
    applied per model id; blacklists survive evict/reload cycles).

    Policy:

    * ``get(model_id)`` loads on demand and touches the LRU clock,
    * after any load, models are evicted **coldest-first** (oldest
      ``last_used``) until the ledger fits the budget — but never a
      model that is ``busy()`` (serving in-flight work) or pinned, and
      never the one just requested,
    * when nothing evictable remains and the ledger still exceeds the
      budget, the requested load is rolled back and a typed
      ``MemoryBudgetExceededError`` is raised — overload degrades the
      *cold* workload, never the serving one.
    """

    def __init__(self, mem_budget: int = 0, poll_interval: float = 1.0,
                 log: Optional[faults.FailureLog] = None,
                 kv_share_dir: Optional[str] = None):
        self.budgeter = MemoryBudgeter(mem_budget)
        self.poll_interval = float(poll_interval)
        self.log = faults.global_failure_log() if log is None else log
        # fleet root for the tiered KV cache (doc/serving.md "Tiered KV
        # cache"): engine factories route their serve.kv_* wiring
        # through kv_engine_kwargs() so every replica of one model —
        # in this process or another — publishes/adopts the same
        # share directory, while DIFFERENT models never alias
        self.kv_share_dir = (None if kv_share_dir is None
                             else os.fspath(kv_share_dir))
        self._models: Dict[str, _ManagedModel] = {}  # guarded-by: _lock
        self._drafts: List[ModelRegistry] = []       # guarded-by: _lock
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.evictions = 0

    # -- registration / loading -------------------------------------------
    def add_model(self, model_id: str, factory: Callable,
                  model_dir: Optional[str] = None,
                  pattern: Optional[str] = None,
                  loader: Optional[Callable] = None,
                  current: int = -1, pinned: bool = False,
                  load: bool = False) -> None:
        with self._lock:
            if model_id in self._models:
                raise ValueError(f'model {model_id!r} already registered')
            self._models[model_id] = _ManagedModel(
                model_id, factory, model_dir, pattern, loader, current,
                pinned)
        if load:
            self.get(model_id)

    def kv_engine_kwargs(self, model_id: str, kv_host_mb: int = 0,
                         kv_disk_mb: int = 0) -> dict:
        """The ``kv_*`` kwargs an engine factory passes straight to
        ``DecodeEngine``/``DecodeService`` to join the fleet's tiered
        KV cache: a per-process local record dir and a per-MODEL share
        dir under the registry's ``kv_share_dir`` root.  Keeping the
        share dir per model id is load-bearing — spill records are
        keyed by (version, span) with no model identity, so two
        different models at the same checkpoint number would alias in
        one flat directory; replicas of the SAME model (any process)
        share by construction.  Empty dict when the fleet has no kv
        root or both tiers are off."""
        if self.kv_share_dir is None \
                or (kv_host_mb <= 0 and kv_disk_mb <= 0):
            return {}
        kw = {'kv_host_mb': int(kv_host_mb)}
        if kv_disk_mb > 0:
            kw.update(
                kv_disk_mb=int(kv_disk_mb),
                kv_dir=os.path.join(self.kv_share_dir, 'local',
                                    f'{model_id}.{os.getpid()}'),
                kv_share_dir=os.path.join(self.kv_share_dir, 'shared',
                                          model_id))
        return kw

    def models(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def loaded(self) -> List[str]:
        with self._lock:
            return sorted(m for m, e in self._models.items()
                          if e.engine is not None)

    def _entry(self, model_id: str) -> _ManagedModel:  # requires-lock: _lock
        entry = self._models.get(model_id)
        if entry is None:
            raise KeyError(f'unknown model {model_id!r}')
        return entry

    def get(self, model_id: str):
        """The serving engine for ``model_id`` — loaded on demand,
        LRU-touched, budget enforced after a cold load.  NOTE: the
        returned reference is only eviction-safe while the engine
        reports ``busy()``; a caller about to run a forward should use
        :meth:`lease` instead, which holds off eviction for the whole
        block (``get`` alone leaves a window between returning and the
        forward marking the engine in-flight)."""
        with self._lock:
            entry = self._entry(model_id)
            if entry.engine is None:
                self._load(entry)
            entry.last_used = time.monotonic()
            return entry.engine

    def lease(self, model_id: str):
        """Context manager: the engine for ``model_id``, protected from
        eviction until the block exits — closes the get()-then-use race
        where a concurrent cold load could evict the engine between the
        lookup and the forward."""
        import contextlib

        @contextlib.contextmanager
        def _leased():
            with self._lock:
                entry = self._entry(model_id)
                if entry.engine is None:
                    self._load(entry)
                entry.last_used = time.monotonic()
                entry.leases += 1
                engine = entry.engine
            try:
                yield engine
            finally:
                with self._lock:
                    entry.leases -= 1
        return _leased()

    def _load(self, entry: _ManagedModel) -> None:  # requires-lock: _lock
        entry.engine = entry.factory()
        per_dev = getattr(entry.engine, 'resident_bytes_per_device', None)
        self.budgeter.account(
            entry.model_id,
            per_dev() if per_dev is not None
            else int(entry.engine.resident_bytes()))
        if entry.model_dir is not None:
            entry.registry = ModelRegistry(
                entry.engine, entry.model_dir, current=entry.current,
                pattern=entry.pattern, loader=entry.loader,
                attempts=entry.attempts, log=self.log,
                on_swap=lambda c, p, e=entry: setattr(e, 'current', c))
        try:
            self._enforce_budget(protect=entry.model_id)
        except faults.MemoryBudgetExceededError:
            self._evict(entry)      # roll back: the cold load loses
            raise

    def _enforce_budget(self, protect: str) -> None:  # requires-lock: _lock
        while self.budgeter.over_budget():
            victims = [e for e in self._models.values()
                       if e.engine is not None and e.model_id != protect
                       and not e.pinned and e.leases == 0
                       and not getattr(e.engine, 'busy', lambda: False)()]
            if not victims:
                resident = self.budgeter.resident()
                raise faults.MemoryBudgetExceededError(
                    protect, resident.get(protect, 0),
                    self.budgeter.budget, sum(resident.values()))
            coldest = min(victims, key=lambda e: e.last_used)
            self._evict(coldest)

    def _evict(self, entry: _ManagedModel) -> None:  # requires-lock: _lock
        freed = self.budgeter.release(entry.model_id)
        if entry.registry is not None:
            entry.registry.close(timeout=5.0)
            entry.registry = None
        eng = entry.engine
        entry.engine = None
        if eng is not None and hasattr(eng, 'close'):
            eng.close(timeout=5.0)
        self.evictions += 1
        self.log.record('serve_evicted',
                        f'model {entry.model_id!r} evicted '
                        f'({freed} bytes freed)')

    def evict(self, model_id: str) -> None:
        """Explicitly unload a model (it reloads on next ``get``)."""
        with self._lock:
            entry = self._entry(model_id)
            if entry.engine is not None:
                self._evict(entry)

    def evict_coldest(self) -> Optional[str]:
        """Evict the coldest evictable model (the autoscaler's
        memory-pressure relief valve) under the SAME invariants budget
        enforcement obeys: never a busy, pinned, or leased model.
        Returns the evicted model id, or ``None`` if nothing was
        evictable — the caller degrades explicitly instead."""
        with self._lock:
            victims = [e for e in self._models.values()
                       if e.engine is not None and not e.pinned
                       and e.leases == 0
                       and not getattr(e.engine, 'busy', lambda: False)()]
            if not victims:
                return None
            coldest = min(victims, key=lambda e: e.last_used)
            self._evict(coldest)
            return coldest.model_id

    # -- speculative-decode drafts -----------------------------------------
    def attach_draft(self, model_id: str, draft_dir: str,
                     pattern: Optional[str] = None,
                     loader: Optional[Callable] = None,
                     current: int = -1) -> 'ModelRegistry':
        """Watch ``draft_dir`` for newer draft checkpoints and hot-swap
        them into ``model_id``'s decode engine's DRAFT slot through the
        same verify/blacklist machinery every serving model gets
        (serve/decode.py "Speculative decoding" — a rejected draft file
        can no more reach the engine than a rejected target can; a
        GOOD one swaps with drain semantics and can never change a
        stream, only its acceptance rate).  The target engine must have
        been built with a draft model.  Returns the watching registry
        (it polls with the fleet)."""
        with self.lease(model_id) as engine:
            if getattr(engine, '_draft_cfg', None) is None:
                raise ValueError(
                    f'model {model_id!r} has no draft slot (build its '
                    'engine with draft=(params, cfg))')
        adapter = _DraftAdapter(self, model_id)
        reg = ModelRegistry(adapter, draft_dir, current=current,
                            pattern=pattern, loader=loader, log=self.log)
        with self._lock:
            self._drafts.append(reg)
        return reg

    # -- hot swap ----------------------------------------------------------
    def swap_model(self, model_id: str, host_params,
                   version: object = None) -> None:
        """Warm-before-swap a new param tree into a model's live engine
        (decode engines drain in-flight streams first — zero drops)."""
        engine = self.get(model_id)
        placed = engine.place_params(host_params)
        engine.warm_params(placed)
        engine.swap_params(placed, version=version)

    def poll_once(self) -> int:
        """One reload cycle across every loaded, watched model (and
        every attached draft watcher); returns the number of swaps."""
        with self._lock:
            regs = [e.registry for e in self._models.values()
                    if e.registry is not None] + list(self._drafts)
        return sum(1 for r in regs if r.poll_once())

    # -- watcher / observability -------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name='serve-fleet')
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception as e:   # watcher must outlive bad cycles
                self.log.record('serve_reload_error',
                                f'fleet poll failed: {e!r}')

    def close(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)
        with self._lock:
            for reg in self._drafts:
                reg.close(timeout=timeout)
            self._drafts.clear()
            for entry in self._models.values():
                if entry.registry is not None:
                    entry.registry.close(timeout=timeout)
                    entry.registry = None
                if entry.engine is not None and hasattr(entry.engine,
                                                        'close'):
                    entry.engine.close(timeout)

    def report(self, stats=None, name: str = 'fleet') -> str:
        """Budget ledger in eval-line format (optionally onto a shared
        ``StatSet``)."""
        from ..utils.metric import StatSet
        stats = StatSet() if stats is None else stats
        stats.gauge('resident_bytes', self.budgeter.usage())
        per_dev = self.budgeter.usage_per_device()
        if len(per_dev) > 1:  # sharded fleet — per-device load vector
            for i, b in enumerate(per_dev):
                stats.gauge(f'resident_bytes[d{i}]', int(b))
        stats.gauge('budget_bytes', self.budgeter.budget)
        stats.gauge('models_loaded', len(self.loaded()))
        stats.gauge('models_total', len(self.models()))
        stats.gauge('evictions', self.evictions)
        for mid, nb in sorted(self.budgeter.resident().items()):
            stats.gauge(f'bytes[{mid}]', nb)
        # tiered-KV occupancy rides the fleet report as its OWN gauges:
        # host/disk tier bytes are never part of resident_bytes (the
        # budgeter/budget_drift ledger stays HBM-truth only — pinned
        # by a kv_tier regression test)
        with self._lock:
            engines = [(mid, e.engine) for mid, e in
                       sorted(self._models.items())
                       if e.engine is not None]
        kv_host = kv_disk = 0
        kv_any = False
        for mid, eng in engines:
            occ = getattr(eng, 'kv_occupancy', lambda: None)()
            if occ is None:
                continue
            kv_any = True
            kv_host += occ[0]
            kv_disk += occ[1]
            stats.gauge(f'kv_host_bytes[{mid}]', occ[0])
            stats.gauge(f'kv_disk_bytes[{mid}]', occ[1])
        if kv_any:
            stats.gauge('kv_host_bytes', kv_host)
            stats.gauge('kv_disk_bytes', kv_disk)
        drift = self.budget_drift()
        if drift is not None:
            stats.gauge('budget_drift', round(drift, 4))
        return format_report(name, stats)

    def budget_drift(self) -> Optional[float]:
        """Signed relative drift of the budgeter's closed-form resident
        ledger vs the compiled forwards' ``memory_analysis`` truth
        (``engine.ledger_bytes()``, obs/programs.py) summed over every
        loaded engine that has compiled — the fleet-level cross-check
        behind the ``fleet.budget_drift`` gauge.  None until at least
        one loaded engine carries a ledger row."""
        closed = truth = 0
        with self._lock:
            engines = [e.engine for e in self._models.values()
                       if e.engine is not None]
        for eng in engines:
            lb = getattr(eng, 'ledger_bytes', lambda: None)()
            if lb is None or lb <= 0:
                continue
            closed += eng.resident_bytes()
            truth += lb
        if truth <= 0:
            return None
        return closed / truth - 1.0
