"""Model registry: checkpoint hot-reload without dropping requests.

The training side emits ``model_dir/%04d.model`` files via atomic
temp+fsync+rename (``nnet/checkpoint.py``) — a reader can never observe a
partial file.  The ``ModelRegistry`` closes the loop on the serving side:
it watches ``model_dir`` for a newer counter, verifies the file against
its ``.crc32`` digest sidecar (written by the train CLI at save time),
loads the params through the retrying model-file reader, warms them on
device, and atomically swaps them into the live ``PredictEngine``.
In-flight batches finish on the params they started with; every batch
dispatched after the swap serves the new ones — no request is ever
dropped or mixed across versions (engine snapshot semantics,
``serve/engine.py``).

Reload state machine (one cycle per detected counter, transitions
recorded in :attr:`transitions` for tests/observability)::

    IDLE -> DETECTED -> VERIFYING -> LOADING -> WARMING -> SWAPPED
                           |            |
                           +-> REJECTED-+   (recorded to the failure log;
                                             retried up to ``max_attempts``
                                             polls, then blacklisted)

A REJECTED checkpoint never reaches the engine: the previous version
keeps serving — corrupt or half-replicated storage degrades freshness,
not availability.
"""

from __future__ import annotations

import os
import re
import struct
import threading
from typing import Callable, List, Optional, Tuple

from ..nnet import checkpoint
from ..nnet.net_config import NetConfig
from ..runtime import faults

__all__ = ['ModelRegistry', 'load_model_params']

_MODEL_RE = re.compile(r'^(\d+)\.model$')


def load_model_params(engine, path: str, retry=None):
    """Read a model file and return its HOST param tree, validated
    against ``engine``'s net structure (layer count and types must match
    — a hot swap cannot change architecture).  Raises
    ``CheckpointCorruptError`` on a truncated blob, ``ValueError`` on a
    structural mismatch; transient I/O errors retry under ``retry``."""

    def read(f):
        f.read(4)                      # net_type prefix
        cfg = NetConfig()
        cfg.load_net(f)
        f.read(8)                      # epoch_counter, irrelevant here
        (blob_len,) = struct.unpack('<Q', f.read(8))
        blob = f.read(blob_len)
        if len(blob) != blob_len:
            raise faults.CheckpointCorruptError(
                f'{path}: model blob truncated '
                f'({len(blob)}/{blob_len} bytes)')
        return cfg, blob

    cfg, blob = checkpoint.read_model_file(path, read, retry=retry)
    serving = engine.trainer.net_cfg.layers
    if len(cfg.layers) != len(serving) or any(
            a.type != b.type for a, b in zip(cfg.layers, serving)):
        raise ValueError(
            f'{path}: net structure differs from the serving model '
            f'({len(cfg.layers)} vs {len(serving)} layers) — '
            'hot reload cannot change architecture')
    return checkpoint.blob_to_params(engine.trainer.net, blob)


class ModelRegistry:
    """Watch ``model_dir`` and hot-swap newer checkpoints into ``engine``.

    ``current`` is the counter being served (pass the loaded model's
    counter so an already-served checkpoint is not re-loaded on the
    first poll; -1 means "adopt whatever appears first").  ``on_swap``
    (optional) is called as ``on_swap(counter, path)`` after each
    successful swap.
    """

    def __init__(self, engine, model_dir: str, poll_interval: float = 1.0,
                 current: int = -1, retry: Optional[faults.RetryPolicy] = None,
                 log: Optional[faults.FailureLog] = None,
                 on_swap: Optional[Callable[[int, str], None]] = None):
        self.engine = engine
        self.model_dir = os.fspath(model_dir)
        self.poll_interval = float(poll_interval)
        self.current = int(current)
        self.retry = faults.DEFAULT_IO_RETRY if retry is None else retry
        self.log = faults.global_failure_log() if log is None else log
        self.on_swap = on_swap
        self.transitions: List[Tuple[str, str]] = []
        self._attempts: dict = {}          # counter -> failed poll cycles
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        engine.version = self.current

    # -- observability -----------------------------------------------------
    _MAX_TRANSITIONS = 512

    def _note(self, state: str, detail: str) -> None:
        with self._lock:
            self.transitions.append((state, detail))
            # a long-lived server must not grow this without bound
            if len(self.transitions) > self._MAX_TRANSITIONS:
                del self.transitions[:len(self.transitions)
                                     - self._MAX_TRANSITIONS]

    def states(self) -> List[str]:
        with self._lock:
            return [s for s, _ in self.transitions]

    # -- scanning ----------------------------------------------------------
    def candidates_on_disk(self) -> List[Tuple[int, str]]:
        """Model files newer than the serving counter, newest first."""
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.model_dir)
        except OSError:
            return out
        for name in names:
            m = _MODEL_RE.match(name)
            if m and int(m.group(1)) > self.current:
                out.append((int(m.group(1)),
                            os.path.join(self.model_dir, name)))
        out.sort(reverse=True)
        return out

    def latest_on_disk(self) -> Optional[Tuple[int, str]]:
        """Newest (counter, path) model file in ``model_dir``, or None."""
        cand = self.candidates_on_disk()
        return cand[0] if cand else None

    # -- one reload cycle --------------------------------------------------
    def poll_once(self) -> bool:
        """Adopt the newest *loadable* checkpoint past the serving one:
        candidates are tried newest-first, so a blacklisted (persistently
        rejected) newest file falls back to the next-newest good one
        instead of pinning the server on a stale version.  Returns True
        when a swap happened.  Never raises for a bad checkpoint —
        rejection is recorded, counted toward that counter's blacklist,
        and the old version keeps serving."""
        for counter, path in self.candidates_on_disk():
            if self._attempts.get(counter, 0) >= self.retry.max_attempts:
                continue                  # blacklisted: persistent reject
            self._note('DETECTED', path)
            try:
                self._note('VERIFYING', path)
                reason = checkpoint.verify_model_digest(path)
                if reason:
                    raise faults.CheckpointCorruptError(f'{path}: {reason}')
                self._note('LOADING', path)
                params = load_model_params(self.engine, path,
                                           retry=self.retry)
                self._note('WARMING', path)
                placed = self.engine.place_params(params)
                self.engine.warm_params(placed)
            except Exception as e:
                # ANY failure (I/O, structure, device OOM during warm...)
                # must reject-and-count: an uncounted error would re-run
                # the full verify/load/warm cycle every poll forever
                self._attempts[counter] = self._attempts.get(counter, 0) + 1
                self._note('REJECTED', f'{path}: {e!r}')
                self.log.record('serve_reload_reject',
                                f'checkpoint {counter} rejected: {e!r}')
                continue
            self.engine.swap_params(placed, version=counter)
            self.current = counter
            self._note('SWAPPED', path)
            if self.on_swap is not None:
                self.on_swap(counter, path)
            return True
        return False

    # -- watcher lifecycle -------------------------------------------------
    def start(self) -> None:
        """Start the polling watcher thread (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._watch, daemon=True,
                                        name='serve-registry')
        self._thread.start()

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_interval):
            try:
                self.poll_once()
            except Exception as e:       # watcher must outlive bad cycles
                self.log.record('serve_reload_error',
                                f'registry poll failed: {e!r}')

    def close(self, timeout: Optional[float] = None) -> bool:
        """Stop the watcher (idempotent, re-entrant safe)."""
        self._stop.set()
        t = self._thread
        if t is None or t is threading.current_thread():
            return True
        t.join(timeout)
        return not t.is_alive()
