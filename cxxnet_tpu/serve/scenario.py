"""Seeded, deterministic, replayable traffic scenarios
(doc/serving.md "Scenarios and autoscaling").

Every bench before this one drove a single synthetic workload shape, so
the degradation story under real traffic — diurnal swell, flash crowds,
heavy-tail length mixes, multi-tenant fleets, slow clients that walk
away — was untested.  A :class:`ScenarioSpec` freezes one such shape as
a compact config value (``serve.scenario=shape=flash;seed=0;...``, the
``FaultPlan`` grammar's spirit): the *entire* schedule — arrival
offsets, prompt contents, output horizons, tenant assignment, which
clients abandon and after how long — is a pure function of the spec, so
a run is a twin of itself and a regression hunt can replay the exact
storm that broke.

Determinism layering (the house twin discipline):

* ``schedule()`` is pure: spec -> per-request records.  No wall clock,
  no ambient RNG.
* prompt *content* is keyed per request index (seed ⊕ index), never per
  arrival order — so batch composition, autoscaler actions, and wall
  jitter can reorder execution freely without changing a single token.
* the driver (:func:`drive`) paces real threads against the schedule;
  timing jitter moves latency numbers, never streams.

:class:`ScenarioLedger` is the reconciliation half of the bargain:
every submitted request must land in exactly one typed terminal bucket
(served / rejected / expired / abandoned / shed / engine error), and
``reconcile()`` cross-checks the ledger against the service's own
StatSet counters — a drop or double-count anywhere in the batcher or
engine shows up as a hard mismatch here.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import faults
from ..utils.config import parse_kv_list

__all__ = ['ScenarioSpec', 'ScenarioRequest', 'ScenarioLedger', 'drive',
           'drive_scenario', 'SHAPES']

#: traffic shapes the grammar accepts (doc/serving.md scenario table)
SHAPES = ('steady', 'diurnal', 'flash', 'heavy_tail', 'tenants')

#: multiplicative prompt-content key stride — a large odd constant so
#: per-index streams never collide for any practical request count
_PROMPT_KEY = 1000003


@dataclass(frozen=True)
class ScenarioRequest:
    """One scheduled request — a pure function of (spec, index)."""

    index: int
    t_offset: float                  # seconds after scenario start
    prompt_len: int
    max_new: int
    tenant: int = 0
    abandon_after: Optional[float] = None   # slow-client patience (secs)


@dataclass(frozen=True)
class ScenarioSpec:
    """A frozen, replayable traffic scenario.

    Grammar (``serve.scenario=`` config value, ``k=v;k=v...``):

    ``shape=`` one of :data:`SHAPES` · ``seed=`` RNG schedule key ·
    ``requests=`` total count · ``qps=`` base arrival rate ·
    ``burst=`` flash-crowd rate multiplier · ``periods=`` diurnal
    cycles over the run · ``tail=`` Pareto alpha for heavy-tail length
    mixes (smaller = heavier) · ``tenants=`` fleet tenant count ·
    ``abandon=`` slow-client abandonment probability · ``patience=``
    mean seconds an abandoning client waits · ``max_prompt=`` /
    ``max_new=`` length caps.
    """

    shape: str = 'steady'
    seed: int = 0
    requests: int = 64
    qps: float = 50.0
    burst: float = 4.0
    periods: float = 2.0
    tail: float = 1.2
    tenants: int = 1
    abandon: float = 0.0
    patience: float = 0.05
    max_prompt: int = 32
    max_new: int = 16

    #: grammar keys :meth:`parse` accepts — the doc/serving.md scenario
    #: table is drift-tested against this tuple
    KEYS = ('shape', 'seed', 'requests', 'qps', 'burst', 'periods',
            'tail', 'tenants', 'abandon', 'patience', 'max_prompt',
            'max_new')

    @classmethod
    def registered_keys(cls) -> Tuple[str, ...]:
        return cls.KEYS

    @classmethod
    def parse(cls, text: str) -> 'ScenarioSpec':
        ints = {'seed', 'requests', 'tenants', 'max_prompt', 'max_new'}
        kw: Dict[str, object] = {}
        for key, val in parse_kv_list(text):
            if key == 'shape':
                if val not in SHAPES:
                    raise ValueError(
                        f'unknown scenario shape {val!r} '
                        f'(one of {", ".join(SHAPES)})')
                kw[key] = val
            elif key in cls.KEYS:
                kw[key] = int(val) if key in ints else float(val)
            else:
                raise ValueError(f'unknown scenario option: {key!r}')
        spec = cls(**kw)
        if spec.requests <= 0 or spec.qps <= 0:
            raise ValueError('scenario needs requests > 0 and qps > 0')
        if not 0.0 <= spec.abandon <= 1.0:
            raise ValueError('abandon must be a probability in [0, 1]')
        return spec

    def describe(self) -> str:
        """Round-trips through :meth:`parse`."""
        return (f'shape={self.shape};seed={self.seed};'
                f'requests={self.requests};qps={self.qps:g};'
                f'burst={self.burst:g};periods={self.periods:g};'
                f'tail={self.tail:g};tenants={self.tenants};'
                f'abandon={self.abandon:g};patience={self.patience:g};'
                f'max_prompt={self.max_prompt};max_new={self.max_new}')

    # -- the deterministic schedule --------------------------------------

    def _rate(self, i: int) -> float:
        """Instantaneous arrival rate at request index ``i``."""
        frac = i / max(1, self.requests - 1)
        if self.shape == 'diurnal':
            # smooth day curve: trough at 30% of peak
            swell = 0.5 * (1.0 + math.sin(
                2.0 * math.pi * self.periods * frac - math.pi / 2.0))
            return self.qps * (0.3 + 0.7 * swell)
        if self.shape == 'flash':
            # middle third arrives at burst× the base rate
            if 1.0 / 3.0 <= frac < 2.0 / 3.0:
                return self.qps * max(1.0, self.burst)
            return self.qps
        return self.qps

    def schedule(self) -> List[ScenarioRequest]:
        """The full request schedule — a pure function of the spec."""
        rng = np.random.RandomState(self.seed)
        out: List[ScenarioRequest] = []
        t = 0.0
        for i in range(self.requests):
            t += 1.0 / self._rate(i)
            tenant = (i % self.tenants) if self.tenants > 1 else 0
            if self.shape == 'heavy_tail':
                # Pareto-tailed lengths: most requests tiny, a few huge
                draw = rng.pareto(max(0.05, self.tail))
                p_len = 1 + min(self.max_prompt - 1,
                                int(draw * self.max_prompt / 4.0))
                draw = rng.pareto(max(0.05, self.tail))
                m_new = 1 + min(self.max_new - 1,
                                int(draw * self.max_new / 4.0))
            elif self.shape == 'tenants' and self.tenants > 1:
                # per-tenant length profile: tenant t's prompts cluster
                # around its own slice of the cap
                base = 1 + (tenant * self.max_prompt) // self.tenants
                p_len = min(self.max_prompt,
                            base + int(rng.randint(
                                1, max(2, self.max_prompt
                                       // self.tenants + 1))))
                m_new = 1 + int(rng.randint(1, self.max_new + 1)) // 2
            else:
                p_len = 1 + int(rng.randint(self.max_prompt))
                m_new = 1 + int(rng.randint(self.max_new))
            abandon_after = None
            if self.abandon > 0.0 and rng.random_sample() < self.abandon:
                # seeded patience: uniform around the mean, never zero
                abandon_after = self.patience * float(
                    0.5 + rng.random_sample())
            out.append(ScenarioRequest(
                index=i, t_offset=t, prompt_len=p_len, max_new=m_new,
                tenant=tenant, abandon_after=abandon_after))
        return out

    def prompt(self, index: int, vocab: int) -> np.ndarray:
        """Token content for request ``index`` — keyed by (seed, index)
        only, so execution order and batch composition can never change
        a prompt (the twin invariant's foundation)."""
        sched_len = None
        # length comes from the schedule; recompute just this entry
        # cheaply is not possible (the RNG stream is sequential), so
        # callers normally pass through drive(); this standalone path
        # rebuilds the schedule once.
        for r in self.schedule():
            if r.index == index:
                sched_len = r.prompt_len
                break
        if sched_len is None:
            raise ValueError(f'index {index} outside schedule')
        return self.prompt_for(index, sched_len, vocab)

    def prompt_for(self, index: int, length: int,
                   vocab: int) -> np.ndarray:
        rng = np.random.RandomState(
            (self.seed * _PROMPT_KEY + index) % (2 ** 31 - 1))
        return rng.randint(0, vocab, size=(1, int(length)),
                           dtype=np.int64).astype(np.int32)


class ScenarioLedger:
    """Typed terminal accounting for one scenario run.

    Every submitted request lands in exactly one bucket; ``total()``
    must equal ``submitted`` and — when the service shares its StatSet —
    the service's own counters must tell the same story
    (:meth:`reconcile`)."""

    #: terminal buckets, keyed by outcome (the serve taxonomy's names)
    BUCKETS = ('served', 'rejected', 'expired', 'abandoned',
               'shed_inadmissible', 'shed_pages', 'engine_errors')

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0            # guarded-by: _lock
        self.counts = {b: 0 for b in self.BUCKETS}   # guarded-by: _lock
        self.latency_s: List[float] = []             # guarded-by: _lock
        self.streams: Dict[int, np.ndarray] = {}     # guarded-by: _lock

    def note_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def note(self, bucket: str, latency: Optional[float] = None,
             index: Optional[int] = None, stream=None) -> None:
        with self._lock:
            self.counts[bucket] += 1
            if latency is not None:
                self.latency_s.append(float(latency))
            if index is not None and stream is not None:
                self.streams[index] = np.asarray(stream)

    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())

    def quantile(self, q: float) -> Optional[float]:
        with self._lock:
            lat = sorted(self.latency_s)
        if not lat:
            return None
        return lat[min(len(lat) - 1, int(q * len(lat)))]

    def shed(self) -> int:
        """Everything typed-shed (not served, not client-abandoned)."""
        with self._lock:
            c = dict(self.counts)
        return (c['rejected'] + c['expired'] + c['shed_inadmissible']
                + c['shed_pages'] + c['engine_errors'])

    def summary(self) -> Dict[str, object]:
        with self._lock:
            c = dict(self.counts)
            n = self.submitted
        return {'submitted': n, **c,
                'p50_s': self.quantile(0.50),
                'p99_s': self.quantile(0.99)}

    #: service StatSet counters reconcile reads — snapshot these before
    #: a drive to reconcile a SECOND scenario on the same (cumulative)
    #: service via ``base=``
    STAT_KEYS = ('submitted', 'requests', 'completed', 'rejected',
                 'expired', 'abandoned', 'shed_inadmissible',
                 'shed_pages', 'engine_errors')

    @classmethod
    def stat_snapshot(cls, stats) -> Dict[str, int]:
        return {k: int(stats.get(k) or 0) for k in cls.STAT_KEYS}

    def reconcile(self, stats=None,
                  base: Optional[Dict[str, int]] = None) -> None:
        """Hard invariant: submitted == Σ terminal buckets — and when
        ``stats`` (the service StatSet) is given, its single-owner
        counters agree bucket for bucket.  ``base`` (a
        :meth:`stat_snapshot` taken before the drive) subtracts a prior
        run's cumulative counts.  Raises AssertionError with the full
        ledger on any mismatch."""
        with self._lock:
            c = dict(self.counts)
            n = self.submitted
        assert n == sum(c.values()), \
            f'ledger drop/double-count: submitted={n} != {c}'
        if stats is None:
            return
        cur = self.stat_snapshot(stats)
        if base is not None:
            cur = {k: cur[k] - base.get(k, 0) for k in cur}
        assert cur['submitted'] == n, \
            f'service saw {cur["submitted"]} submissions, ledger saw {n}'
        svc = {b: cur[b] for b in self.BUCKETS if b != 'served'}
        svc['served'] = cur['requests'] + cur['completed']
        mism = {b: (c[b], svc[b]) for b in self.BUCKETS
                if c[b] != svc[b]}
        assert not mism, \
            f'ledger vs service counters disagree (ledger, service): {mism}'


def drive(svc, spec: ScenarioSpec, *, vocab: int,
          ledger: Optional[ScenarioLedger] = None,
          deadline: Optional[float] = None,
          on_tick: Optional[Callable[[float], None]] = None,
          time_scale: float = 1.0) -> ScenarioLedger:
    """Run ``spec`` against a :class:`~.decode.DecodeService`.

    Clients honor the schedule's arrival offsets (scaled by
    ``time_scale`` — tests shrink wall time without touching the spec),
    wait for their stream, and abandon through the batcher's typed
    ``abandon()`` path when their patience runs out.  ``on_tick`` (if
    given) is called with the elapsed scenario time after each arrival —
    the autoscaler's manual-evaluation hook, so a test or bench drives
    scaling decisions deterministically against scenario pressure.

    Greedy decoding only (``temperature=0``): streams are a pure
    function of (params, prompt, max_new), which is what lets every
    scenario leg twin-assert against offline ``generate``.
    """
    led = ledger if ledger is not None else ScenarioLedger()
    sched = spec.schedule()
    threads: List[threading.Thread] = []
    t0 = time.monotonic()

    def _client(rec: ScenarioRequest, prompt: np.ndarray) -> None:
        start = time.monotonic()
        try:
            req = svc.submit_async(prompt, rec.max_new, 0.0,
                                   deadline=deadline)
        except faults.ServeOverloadError:
            led.note('rejected')
            return
        # lint: allow(fault-taxonomy): the ledger's catch-all keeps one unexpected client error from wedging the drive
        except Exception:
            led.note('engine_errors')
            return
        try:
            if rec.abandon_after is not None:
                done = req.event.wait(rec.abandon_after * time_scale)
                if not done:
                    # mark intent, then reap the worker's decision: a
                    # request already past admission completes normally
                    # (counted served), one still queued is dropped with
                    # a typed RequestAbandonedError — either way the
                    # single-owner counter and this ledger agree
                    svc.batcher.abandon(req)
            svc.batcher.wait(req)
            led.note('served', latency=time.monotonic() - start,
                     index=rec.index, stream=req.result)
        except faults.RequestAbandonedError:
            led.note('abandoned')
        except faults.DecodeSlotsExhaustedError:
            led.note('shed_inadmissible')
        except faults.DecodePagesExhaustedError:
            led.note('shed_pages')
        except faults.DeadlineExceededError:
            led.note('expired')
        except faults.ServeError:
            led.note('engine_errors')

    for rec in sched:
        delay = t0 + rec.t_offset * time_scale - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        prompt = spec.prompt_for(rec.index, rec.prompt_len, vocab)
        led.note_submit()
        t = threading.Thread(target=_client, args=(rec, prompt),
                             name=f'scenario-client-{rec.index}',
                             daemon=True)
        t.start()
        threads.append(t)
        if on_tick is not None:
            on_tick(time.monotonic() - t0)
    for t in threads:
        t.join(timeout=60.0)
    return led


#: the package-level spelling (``serve.drive_scenario``) — same callable
drive_scenario = drive
