"""grafttune — ledger-driven autotuner: compiler truth picks the config.

Three layers (doc/autotune.md):

* :mod:`~cxxnet_tpu.tune.space` — the ``autotune=`` grammar: declared
  knobs, hard bounds, seeds, budgets.
* :mod:`~cxxnet_tpu.tune.search` — the two-stage engine: stage 1
  prunes candidates from AOT ProgramLedger numbers without executing
  anything, stage 2 measures the survivors through the real execution
  paths under a wall-clock budget, and the result writes a
  byte-deterministic ``tuned_<task>.conf`` plus a JSON receipt.
* :mod:`~cxxnet_tpu.tune.controller` — the online leg: re-plans
  declared-safe knobs on SLO drift, every move gated by the
  ``obs.recompile`` sentinel's remaining compile budget.
"""

from .controller import TuneController
from .search import LedgerGate, TuneResult, TuneSearch
from .space import KNOBS, KnobDecl, KnobRange, TuneSpace

__all__ = ['TuneSpace', 'TuneSearch', 'TuneResult', 'LedgerGate',
           'TuneController', 'KNOBS', 'KnobDecl', 'KnobRange']
