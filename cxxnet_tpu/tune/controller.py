"""grafttune online leg: re-plan within declared-safe bounds on SLO drift.

A :class:`TuneController` rides the PR 16 autoscaler machinery — the
same bounded :class:`~cxxnet_tpu.serve.autoscale.Knob` surfaces, the
same hysteresis-streak + per-knob-cooldown control law, the same
injectable verdict/gauge feeds — but its moves come from the tuner's
declared space, not a fixed policy:

* memory pressure (min ``hbm.headroom_frac`` gauge under the space's
  ``headroom``, or a BREACHED verdict) shrinks the ``mem`` knobs
  (predict bucket ladders, pages, slots) toward their baselines;
* ``decode.spec_accept_rate`` high while MFU is low grows ``spec_k`` —
  acceptance says speculation is free, MFU says the chip is idle.

The recompile-storm guard is the load-bearing difference from plain
autoscaling: any knob bound with a ledger ``program`` is assumed to
recompile on change, and the move is checked against
``program.compile_headroom()`` (the ``obs.recompile`` sentinel's bound
minus compiles so far) BEFORE the setter runs.  A move that would eat
the last compile — or exceed the space's own ``compile_budget`` — is
vetoed and recorded as a
:class:`~cxxnet_tpu.runtime.faults.TuneRecompileVetoError`; the storm
sentinel itself never fires because the controller never lets it get
that far.
"""

import collections
import threading
import time
from typing import Callable, Dict, Optional

from ..runtime import faults
from ..serve.autoscale import BREACHED, Knob, OK, worst_verdict
from ..utils.metric import StatSet
from .space import KNOBS, TuneSpace

__all__ = ['TuneController']


class _BoundKnob:
    """A Knob plus its recompile contract."""

    def __init__(self, knob: Knob, program=None, recompiles: bool = False):
        self.knob = knob
        self.program = program          # LedgerProgram or None
        self.recompiles = bool(recompiles or program is not None)


class TuneController:
    """Online re-planner over declared-safe tuned knobs.

    ``verdicts``/``gauges`` are zero-arg callables (tests inject
    deterministic feeds; production wires ``hub.slos_view`` /
    ``hub.gauge_snapshot``).  :meth:`evaluate` is the whole control
    law — one call per tick, manual unless ``interval`` > 0 (then a
    ``cxxnet-tune-<name>`` daemon ticks it)."""

    def __init__(self, space: TuneSpace, hub=None,
                 verdicts: Optional[Callable[[], dict]] = None,
                 gauges: Optional[Callable[[], dict]] = None,
                 failure_log=None, name: str = 'tune',
                 hysteresis: int = 2, cooldown: float = 0.25,
                 interval: float = 0.0,
                 clock: Callable[[], float] = time.monotonic):
        self.space = space
        self.name = name
        self._hub = hub
        self._verdicts = verdicts
        self._gauges = gauges
        self._log = failure_log
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown = float(cooldown)
        self.clock = clock
        self.stats = StatSet()
        self._lock = threading.Lock()
        self._knobs: Dict[str, _BoundKnob] = {}  # guarded-by: _lock
        self._streak = 0                         # guarded-by: _lock
        self._streak_dir = 0                     # guarded-by: _lock
        self._compiles = 0                       # guarded-by: _lock
        self._history: collections.deque = (
            collections.deque(maxlen=256))       # guarded-by: _lock
        self._closed = False                     # guarded-by: _lock
        self._ticker: Optional[threading.Thread] = None
        if interval > 0:
            self.interval = float(interval)
            self._ticker = threading.Thread(
                target=self._tick_loop, daemon=True,
                name=f'cxxnet-tune-{name}')
            self._ticker.start()
        else:
            self.interval = 0.0

    # -- binding -----------------------------------------------------------
    def bind(self, name: str, setter: Callable[[int], object],
             value: int, lo: Optional[int] = None,
             hi: Optional[int] = None, program=None,
             recompiles: bool = False) -> None:
        """Bind one knob the controller may move.  Bounds default to the
        space's declared range for ``name``; binding a knob the space
        never declared is a :class:`TuneSpecError` — the online leg can
        only ever move inside declared-safe bounds."""
        rng = self.space.knob_range(name)
        if rng is None:
            raise faults.TuneSpecError(
                f'knob {name!r} is not declared in this TuneSpace — '
                f'online re-planning only moves declared-safe knobs')
        lo = rng.lo if lo is None else max(rng.lo, int(lo))
        hi = rng.hi if hi is None else min(rng.hi, int(hi))
        knob = Knob(name, lo, hi, int(value), setter)
        with self._lock:
            self._knobs[name] = _BoundKnob(knob, program, recompiles)

    # -- feeds -------------------------------------------------------------
    def _read_verdict(self) -> str:
        src = self._verdicts
        if src is None and self._hub is not None:
            src = getattr(self._hub, 'slos_view', None)
        if src is None:
            return OK
        return worst_verdict(src() or {})

    def _read_gauges(self) -> dict:
        src = self._gauges
        if src is None and self._hub is not None:
            src = getattr(self._hub, 'gauge_snapshot', None)
        if src is None:
            return {}
        return src() or {}

    @staticmethod
    def _min_headroom(gauges: dict) -> Optional[float]:
        vals = [float(v) for k, v in gauges.items()
                if k.startswith('hbm.headroom_frac')]
        return min(vals) if vals else None

    @staticmethod
    def _gauge(gauges: dict, suffix: str) -> Optional[float]:
        for k, v in gauges.items():
            if k == suffix or k.endswith('.' + suffix):
                return float(v)
        return None

    # -- the control law ---------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> dict:
        now = self.clock() if now is None else now
        verdict = self._read_verdict()
        gauges = self._read_gauges()
        headroom = self._min_headroom(gauges)
        accept = self._gauge(gauges, 'spec_accept_rate')
        mfu_val = self._gauge(gauges, 'mfu')
        pressure = (verdict == BREACHED
                    or (headroom is not None
                        and headroom < self.space.headroom))
        grow_spec = (accept is not None and accept >= 0.6
                     and (mfu_val is None or mfu_val < 0.5))
        with self._lock:
            if self._closed:
                return {'applied': [], 'verdict': verdict}
            direction = -1 if pressure else (1 if grow_spec else 0)
            if direction != self._streak_dir:
                self._streak_dir = direction
                self._streak = 0
            self._streak += 1
            applied = []
            if direction != 0 and self._streak >= self.hysteresis:
                if direction < 0:
                    applied = self._shrink_mem(now)
                else:
                    applied = self._grow_spec(now)
            self._history.append({
                't': now, 'verdict': verdict, 'headroom': headroom,
                'direction': direction, 'applied': list(applied)})
            self.stats.gauge('compiles', self._compiles)
            return {'applied': applied, 'verdict': verdict,
                    'headroom': headroom, 'direction': direction}

    def _shrink_mem(self, now):  # requires-lock: _lock
        out = []
        for name in self.space.mem_knobs():
            bk = self._knobs.get(name)
            if bk is None:
                continue
            # under memory pressure the tuner halves toward the declared
            # FLOOR — unlike Autoscaler recovery, the baseline is not a
            # resting point here, it is what caused the pressure
            target = max(bk.knob.lo, bk.knob.value // 2)
            if self._move(bk, target, now):
                out.append((name, target))
        return out

    def _grow_spec(self, now):  # requires-lock: _lock
        out = []
        for name, bk in sorted(self._knobs.items()):
            if not KNOBS[name].spec:
                continue
            target = bk.knob.target(+1, 2.0)
            if self._move(bk, target, now):
                out.append((name, target))
        return out

    def _move(self, bk: _BoundKnob, target: int, now) -> bool:  # requires-lock: _lock
        knob = bk.knob
        if target == knob.value:
            return False
        if now - knob.last_action < self.cooldown:
            return False
        if bk.recompiles:
            # THE recompile-storm guard: reject BEFORE the setter (and
            # hence before any compile) if either the program's own
            # sentinel bound or the space's declared compile budget
            # would be exhausted by this move.
            head = None
            if bk.program is not None:
                head = bk.program.compile_headroom()
            over_program = head is not None and head < 1
            over_space = self._compiles + 1 > self.space.compile_budget
            if over_program or over_space:
                self.stats.inc('recompile_vetoes')
                err = faults.TuneRecompileVetoError(
                    knob.name,
                    getattr(bk.program, 'name', '<unbound>'),
                    head if head is not None
                    else self.space.compile_budget - self._compiles)
                log = self._log if self._log is not None \
                    else faults.global_failure_log()
                log.record(type(err).__name__, str(err))
                return False
            self._compiles += 1
        knob.setter(target)
        knob.value = target
        knob.last_action = now
        self.stats.inc(f'replan_{knob.name}')
        return True

    # -- introspection / lifecycle -----------------------------------------
    def knob_values(self) -> Dict[str, int]:
        with self._lock:
            return {n: bk.knob.value for n, bk in self._knobs.items()}

    def history(self):
        with self._lock:
            return list(self._history)

    def compiles(self) -> int:
        with self._lock:
            return self._compiles

    def status_view(self) -> dict:
        with self._lock:
            return {
                'name': self.name,
                'spec': self.space.describe(),
                'knobs': {n: {'value': bk.knob.value,
                              'lo': bk.knob.lo, 'hi': bk.knob.hi,
                              'baseline': bk.knob.baseline,
                              'recompiles': bk.recompiles}
                          for n, bk in sorted(self._knobs.items())},
                'compiles': self._compiles,
                'compile_budget': self.space.compile_budget,
                'vetoes': int(self.stats.get('recompile_vetoes')),
                'replans': len([h for h in self._history if h['applied']]),
            }

    def register_into(self, hub, name: Optional[str] = None):
        name = name or f'tune_{self.name}'
        hub.register_stats(name, self.stats)
        hub.register_status(name, self.status_view)
        return self

    def _tick_loop(self):
        while True:
            with self._lock:
                if self._closed:
                    return
            time.sleep(self.interval)
            with self._lock:
                if self._closed:
                    return
            self.evaluate()

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
        t = self._ticker
        if t is not None:
            t.join(timeout)
