"""grafttune two-stage search: compiler truth prunes, measurement picks.

Stage 1 (:class:`LedgerGate`) prices every candidate from AOT
ProgramLedger numbers — predicted live bytes vs the declared ceiling —
WITHOUT executing anything; candidates that cannot fit are pruned and
stamped into the receipt with the ledger numbers that killed them.
Stage 2 (:class:`TuneSearch`) runs short seeded measured probes through
the caller-supplied ``probe_fn`` (the real ExecutionPlan / DecodeEngine
path) under a wall-clock budget.  The default candidate is ALWAYS
measured first, so the search can never return something worse than the
hand-tuned config it started from.

The tuned artifact is two files: ``tuned_<task>.conf`` —
byte-deterministic for a fixed (spec, seed, ledger state), just sorted
knob lines — and a JSON receipt stamping every probe's ledger numbers,
timings, and the pruned-vs-measured counts (timings make the receipt
deliberately non-deterministic; the conf is the reproducible artifact).
"""

import dataclasses
import itertools
import json
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..runtime import faults
from .space import KNOBS, TuneSpace

__all__ = ['LedgerGate', 'TuneSearch', 'TuneResult']

Candidate = Dict[str, int]


class LedgerGate:
    """Stage-1 admission: closed-form byte pricing from compiler truth.

    ``base_bytes`` is the ledger-measured live footprint of the BASELINE
    candidate (peak or argument bytes from analyzed entries — the caller
    picks which programs matter).  A candidate's predicted bytes scale
    the base linearly in each ``mem`` knob's ratio to its baseline
    value; the candidate passes if the prediction stays under
    ``ceiling_bytes`` and any extra ``feasible`` predicate agrees.
    """

    def __init__(self, base_bytes: float, ceiling_bytes: float,
                 baseline: Candidate,
                 mem_knobs: Tuple[str, ...] = (),
                 budgeter=None,
                 feasible: Optional[Callable[[Candidate],
                                             Optional[str]]] = None,
                 mem_inv_knobs: Tuple[str, ...] = ()):
        self.base_bytes = float(base_bytes)
        self.ceiling_bytes = float(ceiling_bytes)
        self.baseline = dict(baseline)
        self.mem_knobs = tuple(mem_knobs)
        self.mem_inv_knobs = tuple(mem_inv_knobs)
        self.budgeter = budgeter
        self.feasible = feasible

    def predicted_bytes(self, cand: Candidate) -> float:
        scale = 1.0
        for name in self.mem_knobs:
            base = max(1, int(self.baseline.get(name, 1)))
            scale *= max(1, int(cand.get(name, base))) / base
        for name in self.mem_inv_knobs:
            # split knobs (micro_batch): a LARGER value DIVIDES the
            # workspace, so the ratio inverts — a candidate that merges
            # splits back (smaller value) prices UP and can be pruned
            base = max(1, int(self.baseline.get(name, 1)))
            scale *= base / max(1, int(cand.get(name, base)))
        return self.base_bytes * scale

    def admit(self, cand: Candidate) -> Tuple[bool, Dict[str, object]]:
        pred = self.predicted_bytes(cand)
        info: Dict[str, object] = {
            'predicted_bytes': int(pred),
            'base_bytes': int(self.base_bytes),
            'ceiling_bytes': int(self.ceiling_bytes),
        }
        if self.ceiling_bytes > 0 and pred > self.ceiling_bytes:
            info['pruned'] = 'ledger_bytes_over_ceiling'
            return False, info
        if self.budgeter is not None:
            extra = pred - self.base_bytes
            if extra > 0 and self.budgeter.over_budget(int(extra)):
                info['pruned'] = 'memory_budgeter'
                return False, info
        if self.feasible is not None:
            why = self.feasible(cand)
            if why:
                info['pruned'] = str(why)
                return False, info
        return True, info


@dataclasses.dataclass
class TuneResult:
    """Everything the search learned, plus the artifact writers."""
    space: TuneSpace
    task: str
    best: Candidate
    best_value: float
    baseline: Candidate
    baseline_value: float
    probes: List[Dict[str, object]]
    stage1_candidates: int
    stage1_pruned: int
    measured: int
    failed: int
    wall_s: float
    budget_honored: bool

    @property
    def speedup(self) -> float:
        if self.baseline_value <= 0:
            return 1.0
        return self.best_value / self.baseline_value

    def conf_text(self) -> str:
        """Byte-deterministic tuned config: header pins the spec + seed
        the bytes were derived from, then one sorted line per knob.
        NO timestamps, NO timings — those live in the receipt only."""
        lines = [f'# tuned_{self.task}.conf — written by grafttune',
                 f'# autotune={self.space.describe()}',
                 f'# seed={self.space.seed}']
        for name in sorted(self.best):
            lines.append(f'{name}={int(self.best[name])}')
        return '\n'.join(lines) + '\n'

    def write_conf(self, path: str) -> str:
        with open(path, 'w') as f:
            f.write(self.conf_text())
        return path

    def receipt(self) -> Dict[str, object]:
        return {
            'artifact': f'tuned_{self.task}.conf',
            'spec': self.space.describe(),
            'seed': self.space.seed,
            'task': self.task,
            'best': {k: int(v) for k, v in sorted(self.best.items())},
            'best_value': self.best_value,
            'baseline': {k: int(v)
                         for k, v in sorted(self.baseline.items())},
            'baseline_value': self.baseline_value,
            'speedup': self.speedup,
            'counts': {
                'stage1_candidates': self.stage1_candidates,
                'stage1_pruned': self.stage1_pruned,
                'measured': self.measured,
                'failed': self.failed,
            },
            'wall_s': self.wall_s,
            'budget_s': self.space.budget,
            'budget_honored': self.budget_honored,
            'probes': self.probes,
        }

    def write_receipt(self, path: str) -> str:
        with open(path, 'w') as f:
            json.dump(self.receipt(), f, indent=1, sort_keys=True)
            f.write('\n')
        return path


class TuneSearch:
    """The two-stage engine.  Deterministic for a fixed (space, gate,
    probe results): candidate enumeration is a sorted cross-product of
    each knob's geometric ladder, probe ORDER is a seeded shuffle
    (baseline first, always), and ties break toward the earlier
    enumeration index."""

    def __init__(self, space: TuneSpace,
                 probe_fn: Callable[[Candidate], float],
                 gate: Optional[LedgerGate] = None,
                 baseline: Optional[Candidate] = None,
                 clock: Callable[[], float] = time.monotonic,
                 failure_log=None):
        self.space = space
        self.probe_fn = probe_fn
        self.gate = gate
        self.clock = clock
        self._log = failure_log
        names = [r.name for r in space.knobs]
        self.baseline: Candidate = dict(baseline or {})
        for r in space.knobs:
            if r.name not in self.baseline:
                d = KNOBS[r.name].default
                self.baseline[r.name] = max(r.lo, min(r.hi, d))
        ladders = [space.ladder(n) for n in names]
        self.candidates: List[Candidate] = [
            dict(zip(names, combo))
            for combo in itertools.product(*ladders)]

    def run(self, task: str = 'train') -> TuneResult:
        space = self.space
        t0 = self.clock()
        probes: List[Dict[str, object]] = []

        # -- stage 1: ledger pruning, no execution -------------------------
        admitted: List[Candidate] = []
        pruned = 0
        for cand in self.candidates:
            if self.gate is not None:
                ok, info = self.gate.admit(cand)
            else:
                ok, info = True, {}
            if ok:
                admitted.append(cand)
            else:
                pruned += 1
                probes.append({'candidate': dict(cand), 'stage': 1,
                               'ledger': info, 'pruned': True})

        # -- stage 2: seeded measured probes under the wall budget ---------
        order = [c for c in admitted if c != self.baseline]
        rng = np.random.RandomState(space.seed)
        rng.shuffle(order)
        # baseline ALWAYS measured, always first — the search result is
        # then >= the hand-tuned default by construction
        order.insert(0, dict(self.baseline))

        measured: List[Tuple[int, Candidate, float]] = []
        failed = 0
        for idx, cand in enumerate(order):
            elapsed = self.clock() - t0
            if idx > 0 and (elapsed >= space.budget
                            or len(measured) >= space.max_probes):
                break
            p_t0 = self.clock()
            try:
                value = float(self.probe_fn(dict(cand)))
            # lint: allow(fault-taxonomy): one broken candidate must not kill the sweep; it is recorded and skipped
            except Exception as e:
                failed += 1
                err = faults.TuneProbeError(repr(sorted(cand.items())), e)
                if self._log is not None:
                    self._log.record(type(err).__name__, str(err))
                probes.append({'candidate': dict(cand), 'stage': 2,
                               'failed': f'{type(e).__name__}: {e}'})
                continue
            wall_ms = (self.clock() - p_t0) * 1e3
            entry: Dict[str, object] = {
                'candidate': dict(cand), 'stage': 2,
                'value': value, 'wall_ms': wall_ms}
            if self.gate is not None:
                entry['ledger'] = self.gate.admit(cand)[1]
            probes.append(entry)
            measured.append((idx, cand, value))

        if not measured:
            raise faults.TuneProbeError(
                'baseline', RuntimeError('no candidate survived stage 2'))
        base_value = measured[0][2]
        # argmax over value; ties break toward the earliest probe (the
        # baseline wins an exact tie — never churn the config for zero)
        best_idx, best, best_value = max(
            measured, key=lambda t: (t[2], -t[0]))
        wall_s = self.clock() - t0
        return TuneResult(
            space=space, task=task,
            best=dict(best), best_value=best_value,
            baseline=dict(self.baseline), baseline_value=base_value,
            probes=probes,
            stage1_candidates=len(self.candidates),
            stage1_pruned=pruned,
            measured=len(measured), failed=failed,
            wall_s=wall_s,
            budget_honored=wall_s <= space.budget)
