"""grafttune search space: the ``autotune=`` grammar (doc/autotune.md).

A :class:`TuneSpace` declares WHICH knobs the tuner may move and HOW FAR,
in the FaultPlan/ScenarioSpec spirit: one ``;``-separated spec string,
parse/describe round-trip, seeded determinism::

    autotune=knobs=steps_per_dispatch:1..8,nworker:1..8;budget=120;mode=train

Every knob a spec may name lives in the :data:`KNOBS` registry with HARD
bounds — a spec asking for a range outside the declared-safe envelope is
a :class:`~cxxnet_tpu.runtime.faults.TuneSpecError` at parse time, before
anything compiles or runs.  ``mem`` marks knobs whose value scales live
accelerator bytes roughly linearly; the stage-1 ledger gate
(search.py) uses that to price candidates from compiler truth alone,
and the online :class:`~cxxnet_tpu.tune.controller.TuneController`
shrinks exactly those knobs under memory pressure.

NOTE the spec string cannot go through ``utils.config.parse_kv_list``:
that helper folds ``,`` into ``;`` (segment separators), which would
tear the comma-separated knob list apart.  :meth:`TuneSpace.parse`
tokenizes the raw text itself — ``;`` separates keys, ``,`` separates
knobs inside the ``knobs=`` value.
"""

import dataclasses
from typing import Dict, Optional, Tuple

from ..runtime import faults

__all__ = ['KnobDecl', 'KNOBS', 'KnobRange', 'TuneSpace']


@dataclasses.dataclass(frozen=True)
class KnobDecl:
    """Registry row: the declared-safe envelope for one tunable knob."""
    name: str
    lo: int          # hard floor — no spec may tune below this
    hi: int          # hard ceiling — no spec may tune above this
    default: int
    mem: bool        # value scales live accelerator bytes ~linearly
    spec: bool = False   # speculative-decoding knob (grow on high accept)
    mem_inv: bool = False   # value scales live bytes ~INVERSELY (splits)


# The full declared-safe knob surface.  Adding a row here is the ONLY way
# to make a knob tunable; doc/autotune.md documents each.
KNOBS: Dict[str, KnobDecl] = {d.name: d for d in (
    KnobDecl('steps_per_dispatch', 1, 64, 1, mem=True),
    KnobDecl('nworker', 1, 16, 1, mem=False),
    KnobDecl('slots', 1, 64, 4, mem=True),
    KnobDecl('pages', 1, 4096, 64, mem=True),
    KnobDecl('page_size', 1, 128, 16, mem=True),
    KnobDecl('spec_k', 0, 8, 0, mem=False, spec=True),
    KnobDecl('max_queue', 1, 1024, 64, mem=False),
    # μ-cuDNN-style convolution microbatching (ops/pallas_cnn.py): a
    # LARGER split shrinks the conv workspace, so it prices inversely
    KnobDecl('micro_batch', 1, 64, 1, mem=False, mem_inv=True),
)}


@dataclasses.dataclass(frozen=True)
class KnobRange:
    """One knob's tuning interval, already clamp-checked vs its decl."""
    name: str
    lo: int
    hi: int

    def describe(self) -> str:
        return f'{self.name}:{self.lo}..{self.hi}'


def _parse_knob(token: str) -> KnobRange:
    token = token.strip()
    name, sep, rng = token.partition(':')
    name = name.strip()
    decl = KNOBS.get(name)
    if decl is None:
        raise faults.TuneSpecError(
            f'unknown knob {name!r} — declared-safe knobs are '
            f'{sorted(KNOBS)}')
    if not sep:
        return KnobRange(name, decl.lo, decl.hi)
    lo_s, dots, hi_s = rng.partition('..')
    try:
        lo = int(lo_s)
        hi = int(hi_s) if dots else lo
    except ValueError:
        raise faults.TuneSpecError(
            f'bad range for knob {name!r}: {rng!r} (want lo..hi)')
    if lo > hi:
        raise faults.TuneSpecError(
            f'empty range for knob {name!r}: {lo}..{hi}')
    if lo < decl.lo or hi > decl.hi:
        raise faults.TuneSpecError(
            f'knob {name!r} range {lo}..{hi} escapes the declared-safe '
            f'envelope {decl.lo}..{decl.hi}')
    return KnobRange(name, lo, hi)


@dataclasses.dataclass(frozen=True)
class TuneSpace:
    """Parsed ``autotune=`` spec.  Frozen; :meth:`parse`/:meth:`describe`
    round-trip exactly (determinism tests pin this)."""

    knobs: Tuple[KnobRange, ...]
    mode: str = 'train'          # train | decode
    budget: float = 60.0         # stage-2 wall-clock budget, seconds
    seed: int = 0
    probe_steps: int = 8         # steps (or decode requests) per probe
    probe_repeats: int = 2       # timed repeats per probe, best-of
    max_probes: int = 16         # stage-2 cap, independent of budget
    headroom: float = 0.1        # required HBM headroom frac, stage 1
    mem_mb: float = 0.0          # per-device byte ceiling (0 = ledger/HBM)
    compile_budget: int = 8      # online re-plan compile budget

    # doc/autotune.md's grammar table is drift-pinned against this tuple.
    KEYS = ('knobs', 'mode', 'budget', 'seed', 'probe_steps',
            'probe_repeats', 'max_probes', 'headroom', 'mem_mb',
            'compile_budget')

    @classmethod
    def registered_keys(cls) -> Tuple[str, ...]:
        return cls.KEYS

    @classmethod
    def parse(cls, text: str) -> 'TuneSpace':
        vals: Dict[str, object] = {}
        seen = set()
        for seg in str(text).split(';'):
            seg = seg.strip()
            if not seg:
                continue
            key, sep, val = seg.partition('=')
            key = key.strip()
            if not sep or not key:
                raise faults.TuneSpecError(
                    f'malformed autotune segment {seg!r} (want key=value)')
            if key not in cls.KEYS:
                raise faults.TuneSpecError(
                    f'unknown autotune key {key!r} — known keys are '
                    f'{list(cls.KEYS)}')
            if key in seen:
                raise faults.TuneSpecError(
                    f'duplicate autotune key {key!r}')
            seen.add(key)
            val = val.strip()
            try:
                if key == 'knobs':
                    ranges = tuple(_parse_knob(t)
                                   for t in val.split(',') if t.strip())
                    if not ranges:
                        raise faults.TuneSpecError('knobs= declared empty')
                    names = [r.name for r in ranges]
                    if len(set(names)) != len(names):
                        raise faults.TuneSpecError(
                            f'knob listed twice in {val!r}')
                    vals['knobs'] = ranges
                elif key == 'mode':
                    if val not in ('train', 'decode'):
                        raise faults.TuneSpecError(
                            f"mode must be 'train' or 'decode', got {val!r}")
                    vals['mode'] = val
                elif key in ('budget', 'headroom', 'mem_mb'):
                    vals[key] = float(val)
                else:
                    vals[key] = int(val)
            except ValueError:
                raise faults.TuneSpecError(
                    f'bad value for autotune key {key!r}: {val!r}')
        if 'knobs' not in vals:
            raise faults.TuneSpecError(
                "autotune spec must declare 'knobs=' — nothing to tune")
        space = cls(**vals)
        if space.budget <= 0:
            raise faults.TuneSpecError('budget must be > 0 seconds')
        if not 0.0 <= space.headroom < 1.0:
            raise faults.TuneSpecError('headroom must be in [0, 1)')
        if space.probe_steps < 1 or space.probe_repeats < 1 \
                or space.max_probes < 1 or space.compile_budget < 1:
            raise faults.TuneSpecError(
                'probe_steps/probe_repeats/max_probes/compile_budget '
                'must be >= 1')
        return space

    def describe(self) -> str:
        """Canonical spelling; ``parse(describe())`` is the identity."""
        knobs = ','.join(r.describe() for r in self.knobs)
        return (f'knobs={knobs};mode={self.mode};budget={self.budget:g};'
                f'seed={self.seed};probe_steps={self.probe_steps};'
                f'probe_repeats={self.probe_repeats};'
                f'max_probes={self.max_probes};headroom={self.headroom:g};'
                f'mem_mb={self.mem_mb:g};'
                f'compile_budget={self.compile_budget}')

    # -- candidate helpers -------------------------------------------------
    def knob_range(self, name: str) -> Optional[KnobRange]:
        for r in self.knobs:
            if r.name == name:
                return r
        return None

    def mem_knobs(self) -> Tuple[str, ...]:
        return tuple(r.name for r in self.knobs if KNOBS[r.name].mem)

    def mem_inv_knobs(self) -> Tuple[str, ...]:
        """Knobs whose value DIVIDES live accelerator bytes (split
        counts like ``micro_batch``) — the stage-1 gate prices these
        inversely, and the online controller GROWS them under memory
        pressure instead of shrinking."""
        return tuple(r.name for r in self.knobs if KNOBS[r.name].mem_inv)

    def ladder(self, name: str) -> Tuple[int, ...]:
        """Deterministic geometric probe ladder for one knob: the range
        endpoints plus the powers of two between them.  Keeps the
        cross-product tractable without giving up the interesting
        doubling points."""
        rng = self.knob_range(name)
        if rng is None:
            raise faults.TuneSpecError(f'knob {name!r} not in this space')
        vals = {rng.lo, rng.hi}
        v = 1
        while v <= rng.hi:
            if v >= rng.lo:
                vals.add(v)
            v *= 2
        return tuple(sorted(vals))
