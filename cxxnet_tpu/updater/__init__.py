"""Optimizers (updaters) — SGD / NAG / Adam with reference semantics."""

from .updaters import (UpdaterHyper, create_updater_hyper, init_opt_state,
                       apply_updates)
