"""Updaters: per-weight optimizers + LR/momentum schedules.

Reference semantics preserved (``src/updater/``):

* ``UpdaterHyper`` mirrors ``UpdaterParam`` (param.h:13-133): lr schedules
  ``constant/expdecay/polydecay/factor`` driven by the *minibatch* counter
  (the reference's ``epoch``), ``lr_minimum`` floor, ``start_epoch`` gate,
  momentum saturation schedule, and **tag-scoped overrides** — ``wmat:lr``
  applies only to updaters whose tag is ``wmat`` (prefix-stripped exactly
  like param.h:100-105).
* SGD (sgd_updater-inl.hpp:73-84): ``m = mom*m - lr*(clip(g) + wd*w);
  w += m`` — the clip functor also zeroes NaN gradients, and is only applied
  when ``clip_gradient != 0``.
* NAG (nag_updater-inl.hpp:58-66): ``w += (1+mom)*m_new - mom*m_old``.
* Adam (adam_updater-inl.hpp:73-82): ``decay1/decay2`` are ``1-beta``;
  bias-corrected lr; **reference applies wd as ``grad -= wd*w``** — we keep
  that exactly for parity (use wd=0 with adam, as the reference examples do).

The whole update is a pure pytree function applied inside the jitted train
step, so the optimizer runs sharded on-device (the TPU equivalent of
``update_on_server``: there is no server).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class UpdaterHyper:
    tag: str = ''
    base_lr: float = 0.01
    wd: float = 0.0
    momentum: float = 0.9
    lr_schedule: int = 0
    momentum_schedule: int = 0
    lr_step: int = 1
    lr_gamma: float = 0.5
    lr_alpha: float = 0.5
    lr_factor: float = 0.1
    lr_minimum: float = 0.00001
    start_epoch: int = 0
    base_momentum: float = 0.5
    final_momentum: float = 0.90
    saturation_epoch: int = 0
    clip_gradient: float = 0.0
    # adam
    decay1: float = 0.1
    decay2: float = 0.001

    def set_param(self, name: str, val: str) -> None:
        # tag-scoped override: 'wmat:lr' reaches only tag=='wmat'
        if self.tag and name.startswith(self.tag + ':'):
            name = name[len(self.tag) + 1:]
        if name in ('lr', 'eta'):
            self.base_lr = float(val)
        if name == 'wd':
            self.wd = float(val)
        if name == 'momentum':
            self.momentum = float(val)
        if name == 'momentum_schedule':
            self.momentum_schedule = int(val)
        if name == 'clip_gradient':
            self.clip_gradient = float(val)
        if name == 'final_momentum':
            self.final_momentum = float(val)
        if name == 'base_momentum':
            self.base_momentum = float(val)
        if name == 'saturation_epoch':
            self.saturation_epoch = int(val)
        if name == 'beta1':
            self.decay1 = float(val)
        if name == 'beta2':
            self.decay2 = float(val)
        if name.startswith('lr:') or name.startswith('eta:'):
            sub = name.split(':', 1)[1]
            if sub == 'schedule':
                table = {'constant': 0, 'expdecay': 1, 'polydecay': 2,
                         'factor': 3}
                if val in table:
                    self.lr_schedule = table[val]
            if sub == 'gamma':
                self.lr_gamma = float(val)
            if sub == 'alpha':
                self.lr_alpha = float(val)
            if sub == 'step':
                self.lr_step = int(val)
            if sub == 'factor':
                self.lr_factor = float(val)
            if sub == 'minimum_lr':
                self.lr_minimum = float(val)
            if sub == 'start_epoch':
                self.start_epoch = int(val)

    def schedule(self, epoch):
        """(lr, momentum) at minibatch counter ``epoch``; traceable so the
        schedule advances inside jit (``ScheduleEpoch``, param.h:76-94)."""
        e = jnp.asarray(epoch, jnp.float32)
        if self.lr_schedule == 0:
            lr = jnp.asarray(self.base_lr, jnp.float32)
        elif self.lr_schedule == 1:
            lr = self.base_lr * jnp.power(self.lr_gamma, e / self.lr_step)
        elif self.lr_schedule == 2:
            lr = self.base_lr * jnp.power(
                1.0 + jnp.floor(e / self.lr_step) * self.lr_gamma,
                -self.lr_alpha)
        elif self.lr_schedule == 3:
            lr = self.base_lr * jnp.power(self.lr_factor,
                                          jnp.floor(e / self.lr_step))
        else:
            raise ValueError('unknown lr schedule type')
        mom = jnp.asarray(self.momentum, jnp.float32)
        if self.momentum_schedule and self.saturation_epoch:
            mom = mom + ((self.final_momentum - self.base_momentum)
                         / self.saturation_epoch * e + self.base_momentum)
        # the reference caps momentum at final_momentum unconditionally
        # (param.h:88) — preserved
        mom = jnp.minimum(mom, self.final_momentum)
        lr = jnp.maximum(lr, self.lr_minimum)
        if self.start_epoch > 0:
            lr = jnp.where(e < self.start_epoch, self.base_lr, lr)
        return lr, mom


def create_updater_hyper(updater_type: str, tag: str, defcfg, layercfg
                         ) -> UpdaterHyper:
    """Build per-weight hyperparameters by replaying global then layer
    config (``neural_net-inl.hpp:186-196``)."""
    if updater_type not in ('sgd', 'nag', 'adam'):
        raise ValueError(f'unknown updater type {updater_type}')
    h = UpdaterHyper(tag=tag)
    for name, val in defcfg:
        h.set_param(name, val)
    for name, val in layercfg:
        h.set_param(name, val)
    return h


def _clip(g, c):
    """Clip to [-c, c] and zero NaNs (``sgd_updater-inl.hpp:15-22``)."""
    g = jnp.where(jnp.isnan(g), 0.0, g)
    return jnp.clip(g, -c, c)


def init_opt_state(updater_type: str, params):
    """Zero-initialized optimizer slots, one pytree per param leaf."""
    zeros = jax.tree.map(jnp.zeros_like, params)
    if updater_type in ('sgd', 'nag'):
        return {'m': zeros}
    if updater_type == 'adam':
        return {'m1': zeros, 'm2': jax.tree.map(jnp.zeros_like, params)}
    raise ValueError(f'unknown updater type {updater_type}')


def _sgd_leaf(w, g, m, lr, mom, h: UpdaterHyper):
    if h.clip_gradient != 0.0:
        g = _clip(g, h.clip_gradient)
    m_new = mom * m - lr * (g + h.wd * w)
    return w + m_new, m_new


def _nag_leaf(w, g, m, lr, mom, h: UpdaterHyper):
    m_new = mom * m - lr * (g + h.wd * w)
    w_new = w + (1 + mom) * m_new - mom * m
    return w_new, m_new


def _adam_leaf(w, g, m1, m2, epoch, h: UpdaterHyper):
    if h.wd > 0.0:
        g = g - h.wd * w          # reference sign kept verbatim
    e = jnp.asarray(epoch, jnp.float32)
    fix1 = 1.0 - jnp.power(1.0 - h.decay1, e + 1)
    fix2 = 1.0 - jnp.power(1.0 - h.decay2, e + 1)
    lr_t = h.base_lr * jnp.sqrt(fix2) / fix1
    m1n = m1 + h.decay1 * (g - m1)
    m2n = m2 + h.decay2 * (g * g - m2)
    w_new = w - lr_t * (m1n / (jnp.sqrt(m2n) + 1e-8))
    return w_new, m1n, m2n


def apply_updates(updater_type: str,
                  hypers: Dict[str, Dict[str, UpdaterHyper]],
                  params, grads, opt_state, epoch):
    """Apply one optimizer step.  ``hypers[layer_key][field]`` carries the
    per-tensor (tag-scoped) hyperparameters; ``epoch`` is the minibatch
    counter driving the schedules.  Pure — call from inside jit."""
    new_params = {}
    if updater_type in ('sgd', 'nag'):
        new_m = {}
        step = _sgd_leaf if updater_type == 'sgd' else _nag_leaf
        for lk, fields in params.items():
            new_params[lk], new_m[lk] = {}, {}
            for fk, w in fields.items():
                h = hypers[lk][fk]
                lr, mom = h.schedule(epoch)
                w2, m2 = step(w, grads[lk][fk], opt_state['m'][lk][fk],
                              lr, mom, h)
                new_params[lk][fk] = w2
                new_m[lk][fk] = m2
        return new_params, {'m': new_m}
    if updater_type == 'adam':
        n1, n2 = {}, {}
        for lk, fields in params.items():
            new_params[lk], n1[lk], n2[lk] = {}, {}, {}
            for fk, w in fields.items():
                h = hypers[lk][fk]
                w2, m1, m2 = _adam_leaf(w, grads[lk][fk],
                                        opt_state['m1'][lk][fk],
                                        opt_state['m2'][lk][fk], epoch, h)
                new_params[lk][fk] = w2
                n1[lk][fk] = m1
                n2[lk][fk] = m2
        return new_params, {'m1': n1, 'm2': n2}
    raise ValueError(f'unknown updater type {updater_type}')
