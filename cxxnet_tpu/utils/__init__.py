"""Runtime services: config grammar, metrics, binary IO, prefetch."""

from . import config, io_stream, metric, thread_buffer  # noqa: F401
from .config import (apply_cli_overrides, cfg_get, parse_config_file,
                     parse_config_string)
from .metric import MetricSet
