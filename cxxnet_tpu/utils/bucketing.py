"""Batch-size bucketing: fit arbitrary request shapes onto a closed set
of compiled batch shapes.

The jitted predict path compiles once per distinct input shape, so a
stream of novel batch sizes (live traffic, ad-hoc ``net_predict_batch``
calls) grows the XLA compile cache without bound — and each miss costs a
full compilation at request latency.  The µ-cuDNN observation (PAPERS.md)
applies directly: pick a small ladder of batch-size *buckets*, pad every
request up to the smallest bucket that fits (oversize requests split into
max-bucket chunks), and the compile cache is provably bounded by
``len(buckets)`` entries per program.

Pure numpy/host helpers — shared by the serving engine
(``serve/engine.py``), the trainer's ``pred_buckets`` net param
(``nnet/trainer.py``), and the batcher's accounting; no jax imports so
anything may depend on it without circularity.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

#: Default ladder: singleton probes, small interactive batches, bulk.
DEFAULT_BUCKETS = (1, 8, 32)


def parse_buckets(text: str) -> Tuple[int, ...]:
    """Parse a ``serve.buckets = 1,8,32`` config value into a sorted,
    de-duplicated tuple of positive ints."""
    out = set()
    for tok in str(text).replace(';', ',').split(','):
        tok = tok.strip()
        if not tok:
            continue
        b = int(tok)
        if b <= 0:
            raise ValueError(f'bucket sizes must be positive, got {b}')
        out.add(b)
    if not out:
        raise ValueError(f'no bucket sizes in {text!r}')
    return tuple(sorted(out))


def bucket_for(n: int, buckets: Sequence[int]) -> Optional[int]:
    """Smallest bucket >= n, or None when n exceeds every bucket."""
    for b in buckets:
        if b >= n:
            return b
    return None


def chunk_plan(n: int, buckets: Sequence[int]) -> List[Tuple[int, int, int]]:
    """Split ``n`` rows into bucket-padded chunks: a list of
    ``(offset, take, bucket)`` where ``take`` rows starting at ``offset``
    run in a ``bucket``-sized program.  Greedy: full max-bucket chunks
    while the remainder overflows the ladder, then the smallest bucket
    that fits the tail.  ``sum(take) == n``; every ``bucket`` is a member
    of ``buckets`` — the compile cache never sees a novel shape."""
    if n <= 0:
        return []
    bmax = buckets[-1]
    plan: List[Tuple[int, int, int]] = []
    off = 0
    while n - off > bmax:
        plan.append((off, bmax, bmax))
        off += bmax
    rest = n - off
    plan.append((off, rest, bucket_for(rest, buckets)))
    return plan


def pad_rows(arr: np.ndarray, b: int) -> np.ndarray:
    """Pad the leading (row) axis of ``arr`` up to ``b`` with zeros,
    preserving dtype (uint8 wire batches stay uint8).  No copy when the
    array is already ``b`` rows."""
    n = arr.shape[0]
    if n == b:
        return arr
    if n > b:
        raise ValueError(f'cannot pad {n} rows down to bucket {b}')
    pad = np.zeros((b - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([np.asarray(arr), pad], axis=0)
