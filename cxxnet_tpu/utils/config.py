"""Configuration tokenizer for the cxxnet ``.conf`` grammar.

Reimplements the grammar accepted by the reference tokenizer
(``/root/reference/src/utils/config.h:20-189``) without translating its code:

* a config is a stream of ``name = value`` triples; tokens are separated by
  whitespace; ``=`` is always its own token,
* ``#`` starts a comment running to end of line,
* ``"..."`` quotes a single-line string (backslash escapes the next char;
  a newline inside is an error),
* ``'...'`` quotes a multi-line string (backslash escapes the next char),
* pairs are yielded **in file order** — downstream consumers replay them into
  ``set_param`` calls, and ordering/scoping quirks are part of the contract
  (see ``/root/reference/src/nnet/nnet_config.h:207-289``).

Unknown keys are silently ignored by consumers, as in the reference.
"""

from __future__ import annotations

import io
from typing import Iterator, List, Tuple

ConfigEntry = Tuple[str, str]


class ConfigError(ValueError):
    """Raised on malformed config input (unterminated string, bad pair)."""


def _tokenize(text: str) -> Iterator[str]:
    """Yield raw tokens: bare words, quoted strings, and ``=``."""
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        if c == '#':
            while i < n and text[i] not in '\r\n':
                i += 1
            continue
        if c in ' \t\r\n':
            i += 1
            continue
        if c == '=':
            yield '='
            i += 1
            continue
        if c in '"\'':
            quote = c
            i += 1
            buf: List[str] = []
            while True:
                if i >= n:
                    raise ConfigError("ConfigReader: unterminated string")
                ch = text[i]
                if ch == '\\':
                    if i + 1 >= n:
                        raise ConfigError("ConfigReader: unterminated string")
                    buf.append(text[i + 1])
                    i += 2
                    continue
                if ch == quote:
                    i += 1
                    break
                if quote == '"' and ch in '\r\n':
                    raise ConfigError("ConfigReader: unterminated string")
                buf.append(ch)
                i += 1
            yield ''.join(buf)
            continue
        # bare token: runs until whitespace, '=', '#', or quote
        j = i
        while j < n and text[j] not in ' \t\r\n=#"\'':
            j += 1
        yield text[i:j]
        i = j


def parse_config_string(text: str) -> List[ConfigEntry]:
    """Parse config text into an ordered list of ``(name, value)`` pairs."""
    out: List[ConfigEntry] = []
    toks = list(_tokenize(text))
    i = 0
    while i < len(toks):
        name = toks[i]
        if name == '=':
            raise ConfigError("ConfigReader: stray '='")
        if i + 2 >= len(toks) or toks[i + 1] != '=':
            raise ConfigError(f"ConfigReader: expected '{name} = value'")
        val = toks[i + 2]
        if val == '=':
            raise ConfigError(f"ConfigReader: missing value for '{name}'")
        out.append((name, val))
        i += 3
    return out


def parse_config_file(path: str) -> List[ConfigEntry]:
    """Parse a ``.conf`` file into ordered ``(name, value)`` pairs."""
    with io.open(path, 'r', encoding='utf-8', errors='replace') as f:
        return parse_config_string(f.read())


def apply_cli_overrides(cfg: List[ConfigEntry], argv: List[str]) -> List[ConfigEntry]:
    """Append ``k=v`` command-line override pairs after the file's pairs.

    Mirrors the reference driver behavior (``cxxnet_main.cpp:67-72``): CLI
    pairs are replayed after the config file so later values win wherever a
    consumer keeps only the last value.
    """
    out = list(cfg)
    for arg in argv:
        if '=' not in arg:
            raise ConfigError(f"CLI override must be k=v, got: {arg}")
        k, v = arg.split('=', 1)
        out.append((k.strip(), v.strip()))
    return out


def parse_kv_list(text: str) -> List[ConfigEntry]:
    """Parse a compact ``k=v[;k=v...]`` list (one config *value*, e.g. the
    ``train.fault_plan=`` grammar) into ordered ``(key, value)`` pairs.

    Separators are ``;`` or ``,``; whitespace around tokens is ignored;
    empty segments are skipped so trailing separators are harmless.  Values
    may carry ``:``-separated arguments (opaque here — consumers split).
    """
    out: List[ConfigEntry] = []
    for seg in text.replace(',', ';').split(';'):
        seg = seg.strip()
        if not seg:
            continue
        if '=' not in seg:
            raise ConfigError(f"kv list segment must be k=v, got: {seg!r}")
        k, v = seg.split('=', 1)
        out.append((k.strip(), v.strip()))
    return out


def cfg_get(cfg: List[ConfigEntry], name: str, default: str | None = None) -> str | None:
    """Last-value-wins lookup, skipping the literal value ``default``.

    The reference ignores assignments whose value is the string ``default``
    (``cxxnet_main.cpp:84``); we reproduce that here.
    """
    val = default
    for k, v in cfg:
        if k == name and v != 'default':
            val = v
    return val


def cfg_get_int(cfg: List[ConfigEntry], name: str, default: int) -> int:
    """Typed :func:`cfg_get`: last-value-wins int lookup (``default``
    literal skipped), with a clear error naming the offending key —
    consumers like ``bench_ckpt.py`` read ``save_async=``/``save_workers=``
    style knobs without replaying the whole config into a task object."""
    val = cfg_get(cfg, name)
    if val is None:
        return default
    try:
        return int(val)
    except ValueError as e:
        raise ConfigError(f"'{name}' must be an int, got {val!r}") from e
