"""Binary stream helpers and the 64MB ``BinaryPage`` container format.

Byte-compatible with the reference on-disk formats so existing ``.bin``
datasets and ``.model`` checkpoints interoperate:

* length-prefixed (uint64 little-endian) strings and POD vectors, matching
  ``IStream::Write``/``Read`` (``src/utils/io.h:43-100``),
* ``BinaryPage``: a fixed 64MB page (``64 << 18`` ints). ``data[0]`` holds the
  object count, ``data[1+i]`` cumulative byte offsets, and object payloads are
  packed backwards from the end of the page (``src/utils/io.h:253-326``).
"""

from __future__ import annotations

import gzip
import struct
from typing import BinaryIO, List

import numpy as np

_U64 = struct.Struct('<Q')


def write_string(f: BinaryIO, s: bytes | str) -> None:
    if isinstance(s, str):
        s = s.encode('utf-8')
    f.write(_U64.pack(len(s)))
    if s:
        f.write(s)


def read_string(f: BinaryIO) -> bytes:
    raw = f.read(8)
    if len(raw) < 8:
        raise EOFError('read_string: truncated stream')
    (n,) = _U64.unpack(raw)
    data = f.read(n)
    if len(data) < n:
        raise EOFError('read_string: truncated stream')
    return data


def write_vector(f: BinaryIO, arr: np.ndarray) -> None:
    arr = np.ascontiguousarray(arr)
    f.write(_U64.pack(arr.size))
    if arr.size:
        f.write(arr.tobytes())


def read_vector(f: BinaryIO, dtype) -> np.ndarray:
    raw = f.read(8)
    if len(raw) < 8:
        raise EOFError('read_vector: truncated stream')
    (n,) = _U64.unpack(raw)
    dtype = np.dtype(dtype)
    data = f.read(n * dtype.itemsize)
    if len(data) < n * dtype.itemsize:
        raise EOFError('read_vector: truncated stream')
    return np.frombuffer(data, dtype=dtype, count=n).copy()


def open_maybe_gz(path: str, mode: str = 'rb'):
    """Open a file, transparently decompressing ``.gz`` (GzFile equivalent)."""
    with open(path, 'rb') as probe:
        magic = probe.read(2)
    if magic == b'\x1f\x8b':
        return gzip.open(path, mode)
    return open(path, mode)


class BinaryPage:
    """One fixed-size page of byte blobs, reference-format-compatible."""

    K_PAGE_SIZE = 64 << 18          # number of int32 slots
    N_BYTES = K_PAGE_SIZE * 4       # 64 MB

    def __init__(self):
        self._head: List[int] = [0, 0]   # head[0]=count, head[1+i]=cum offsets
        self._objs: List[bytes] = []

    def clear(self) -> None:
        self._head = [0, 0]
        self._objs = []

    @property
    def size(self) -> int:
        return self._head[0]

    def _free_bytes(self) -> int:
        return (self.K_PAGE_SIZE - (self.size + 2)) * 4 - self._head[self.size + 1]

    def push(self, blob: bytes) -> bool:
        """Append a blob; returns False when the page is full."""
        if self._free_bytes() < len(blob) + 4:
            return False
        self._head.append(self._head[-1] + len(blob))
        self._head[0] += 1
        self._objs.append(bytes(blob))
        return True

    def __getitem__(self, r: int) -> bytes:
        if r >= self.size:
            raise IndexError('BinaryPage: index exceeds bound')
        return self._objs[r]

    def __iter__(self):
        return iter(self._objs)

    def save(self, f: BinaryIO) -> None:
        buf = np.zeros(self.K_PAGE_SIZE, dtype=np.int32)
        buf[:len(self._head)] = self._head
        raw = buf.tobytes()
        tail = bytearray(raw)
        pos = self.N_BYTES
        for blob in self._objs:
            # objects are packed backwards from the end of the page
            tail[pos - len(blob):pos] = blob
            pos -= len(blob)
        f.write(bytes(tail))

    def load(self, f: BinaryIO) -> bool:
        raw = f.read(self.N_BYTES)
        if len(raw) < self.N_BYTES:
            return False
        head = np.frombuffer(raw, dtype=np.int32, count=self.K_PAGE_SIZE)
        n = int(head[0])
        self._head = [n] + [int(x) for x in head[1:n + 2]]
        self._objs = []
        for r in range(n):
            lo = self.N_BYTES - self._head[2 + r]
            hi = self.N_BYTES - self._head[1 + r]
            self._objs.append(raw[lo:hi])
        return True
