"""Evaluation metrics.

Same metric semantics as the reference set (``src/utils/metric.h:20-236``):
``error`` (argmax mismatch; binary threshold-at-0 when the score vector has a
single column), ``rmse``, ``logloss`` (clipped to [1e-15, 1-1e-15], NaN check
in the binary case), and ``rec@n``.  ``MetricSet`` carries a label-field name
per metric (the ``metric[field] = name`` config syntax) and prints
``\\tevname-metric[field]:value`` like the reference's ``Print``.

Computation is vectorized numpy on host — metrics are an observability
surface, not a device-compute path.
"""

from __future__ import annotations

import threading

import numpy as np


class Metric:
    """Accumulating metric over (predscore, label) instance batches."""

    def __init__(self, name: str):
        self.name = name
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def clear(self) -> None:
        self.sum_metric = 0.0
        self.cnt_inst = 0

    def add_eval(self, pred: np.ndarray, label: np.ndarray) -> None:
        """pred: (n, k) score matrix; label: (n, m) label fields."""
        pred = np.asarray(pred, dtype=np.float64)
        label = np.asarray(label, dtype=np.float64)
        if pred.shape[0] == 0:
            return
        self.sum_metric += float(np.sum(self._calc(pred, label)))
        self.cnt_inst += pred.shape[0]

    def get(self) -> float:
        return self.sum_metric / max(self.cnt_inst, 1)

    def _calc(self, pred: np.ndarray, label: np.ndarray) -> np.ndarray:
        raise NotImplementedError


class MetricRMSE(Metric):
    def __init__(self):
        super().__init__('rmse')

    def _calc(self, pred, label):
        if pred.shape[1] != label.shape[1]:
            raise ValueError('rmse: pred and label width must match')
        return np.sum((pred - label) ** 2, axis=1)

    def get(self) -> float:  # reference reports mean squared sum (no sqrt)
        return self.sum_metric / max(self.cnt_inst, 1)


class MetricError(Metric):
    def __init__(self):
        super().__init__('error')

    def _calc(self, pred, label):
        if pred.shape[1] != 1:
            maxidx = np.argmax(pred, axis=1)
        else:
            maxidx = (pred[:, 0] > 0.0).astype(np.int64)
        return (maxidx != label[:, 0].astype(np.int64)).astype(np.float64)


class MetricLogloss(Metric):
    def __init__(self):
        super().__init__('logloss')

    def _calc(self, pred, label):
        eps = 1e-15
        if pred.shape[1] != 1:
            target = label[:, 0].astype(np.int64)
            p = np.clip(pred[np.arange(pred.shape[0]), target], eps, 1.0 - eps)
            return -np.log(p)
        py = np.clip(pred[:, 0], eps, 1.0 - eps)
        y = label[:, 0]
        res = -(y * np.log(py) + (1.0 - y) * np.log(1.0 - py))
        if np.any(np.isnan(res)):
            raise FloatingPointError('logloss: NaN detected!')
        return res


class MetricRecall(Metric):
    """rec@n: fraction of true labels present in the top-n scores."""

    def __init__(self, name: str):
        super().__init__(name)
        if not name.startswith('rec@'):
            raise ValueError('must specify n for rec@n')
        self.topn = int(name[4:])

    def _calc(self, pred, label):
        n = self.topn
        if pred.shape[1] < n:
            raise ValueError(
                f'rec@{n} meaningless for score list of length {pred.shape[1]}')
        # top-n indices per row (ties broken arbitrarily, matching the
        # reference's shuffle-then-sort which randomizes tie order)
        topidx = np.argpartition(-pred, n - 1, axis=1)[:, :n]
        hits = np.zeros(pred.shape[0], dtype=np.float64)
        for j in range(label.shape[1]):
            hits += np.any(topidx == label[:, j:j + 1].astype(np.int64), axis=1)
        return hits / label.shape[1]


def create_metric(name: str) -> Metric | None:
    if name == 'rmse':
        return MetricRMSE()
    if name == 'error':
        return MetricError()
    if name == 'logloss':
        return MetricLogloss()
    if name.startswith('rec@'):
        return MetricRecall(name)
    return None


class StatSet:
    """Operational counters + latency distributions, printed in the same
    ``\\tname-metric:value`` eval-line format as :class:`MetricSet`.

    Where ``MetricSet`` scores model *quality* over (pred, label) pairs,
    ``StatSet`` observes a *runtime* — the serving subsystem's per-bucket
    latency/throughput/queue counters (``serve/batcher.py``) report
    through one of these at shutdown, so serving telemetry reads like
    every other eval line the framework prints.  Thread-safe: client
    threads and the batcher worker update it concurrently.

    Three kinds of stat, keyed by name:
    * ``inc(name, v)`` — monotone counter,
    * ``gauge(name, v)`` / ``peak(name, v)`` — last-value / max-value,
    * ``observe(name, v)`` — sample a distribution; ``print`` expands it
      into ``name.p50 / name.p99 / name.mean / name.n`` entries
      (exact quantiles over retained samples, capped at the newest
      ``max_samples`` per name to bound memory on long-lived servers).
    """

    def __init__(self, max_samples: int = 100_000):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}       # guarded-by: _lock
        self._samples: dict[str, list[float]] = {}  # guarded-by: _lock
        self._max_samples = int(max_samples)

    def inc(self, name: str, v: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + v

    def gauge(self, name: str, v: float) -> None:
        with self._lock:
            self._counters[name] = float(v)

    def peak(self, name: str, v: float) -> None:
        with self._lock:
            if v > self._counters.get(name, float('-inf')):
                self._counters[name] = float(v)

    def observe(self, name: str, v: float) -> None:
        with self._lock:
            s = self._samples.setdefault(name, [])
            s.append(float(v))
            if len(s) > self._max_samples:
                del s[:len(s) - self._max_samples]

    def get(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._counters.get(name, default)

    def clear(self) -> None:
        """Reset every counter and distribution.  NOTE: a separate
        ``print()``-then-``clear()`` sequence LOSES any update that
        lands between the two lock acquisitions — per-round reporting
        from a live pipeline must use :meth:`drain` /
        :meth:`print_and_clear`, which swap the state out under ONE
        lock hold."""
        with self._lock:
            self._counters.clear()
            self._samples.clear()

    def snapshot(self) -> tuple:
        """Consistent ``(counters, samples)`` copies under one lock
        hold — the read every renderer (eval line, Prometheus, statusz,
        flight dumps) goes through."""
        with self._lock:
            return (dict(self._counters),
                    {k: list(v) for k, v in self._samples.items() if v})

    def tail_view(self, tail: int) -> tuple:
        """Bounded read for high-frequency samplers (the obs gauge
        history): counters copy plus, per distribution, ``(newest
        `tail` samples, total retained count)`` — one lock hold,
        O(tail) per distribution, so a 100k-sample serving latency
        list never rides the sampler tick (a full :meth:`snapshot`
        copy-and-sort at 20 Hz measurably taxed the decode hot path
        through this very lock)."""
        with self._lock:
            return (dict(self._counters),
                    {k: (v[-tail:], len(v))
                     for k, v in self._samples.items() if v})

    def drain(self) -> tuple:
        """Atomic snapshot-and-reset (epoch swap): returns
        ``(counters, samples)`` and leaves the set empty, under ONE
        lock hold — an update racing the drain lands either in the
        returned epoch or the next one, never nowhere."""
        with self._lock:
            counters, self._counters = self._counters, {}
            samples, self._samples = self._samples, {}
            return counters, {k: v for k, v in samples.items() if v}

    def quantile(self, name: str, q: float) -> float:
        with self._lock:
            s = list(self._samples.get(name, ()))
        if not s:
            return float('nan')
        return float(np.quantile(np.asarray(s), q))

    def print(self, evname: str) -> str:
        from ..obs.hub import format_report
        return format_report(evname, self)

    def print_and_clear(self, evname: str) -> str:
        """Render one epoch's stats and reset atomically (see
        :meth:`drain`) — the per-round reporting path
        (``main._write_io_stats``)."""
        from ..obs.hub import format_report_parts
        counters, samples = self.drain()
        return format_report_parts(evname, counters, samples)


class MetricSet:
    """A list of metrics, each bound to a label field name."""

    def __init__(self):
        self.evals: list[Metric] = []
        self.label_fields: list[str] = []

    def add_metric(self, name: str, field: str = 'label') -> None:
        m = create_metric(name)
        if m is None:
            raise ValueError(f'Metric: unknown metric name: {name}')
        self.evals.append(m)
        self.label_fields.append(field)

    def clear(self) -> None:
        for m in self.evals:
            m.clear()

    def add_eval(self, predscores, label_info) -> None:
        """predscores: list of (n,k) arrays, one per metric; label_info
        provides ``.field(name) -> (n,m)`` label arrays."""
        assert len(predscores) == len(self.evals), \
            'Metric: number of predict scores must equal number of metrics'
        for m, field, pred in zip(self.evals, self.label_fields, predscores):
            m.add_eval(pred, label_info.field(field))

    def print(self, evname: str) -> str:
        out = []
        for m, field in zip(self.evals, self.label_fields):
            tag = f'{evname}-{m.name}'
            if field != 'label':
                tag += f'[{field}]'
            out.append(f'\t{tag}:{m.get():g}')
        return ''.join(out)

    def __len__(self) -> int:
        return len(self.evals)
