"""Order-preserving worker pool for per-instance pipeline stages.

The supply-side scaling stage of the input pipeline (doc/io.md): JPEG
decode + augmentation for ONE instance is pure, GIL-releasing host work
(PIL/libjpeg, scipy ``affine_transform``, numpy slicing), so fanning it
across N threads multiplies host throughput — the reference runs exactly
one decode thread (``iter_thread_imbin-inl.hpp``), sized for a 2015 GPU,
which starves a chip consuming 10-30x more images/sec.

Contract that keeps the stream **bitwise identical for any worker
count** (the property ``is_replay_stable`` and supervised bitwise
recovery rely on):

* tasks are numbered in SUBMISSION order and results are reassembled in
  that order — workers race only over who computes what, never over
  what the consumer sees;
* the task function must be deterministic in ``(task payload)`` alone —
  callers seed any per-instance RNG from the epoch-absolute instance
  index they bake into the payload (``io/iter_augment.py``), never from
  shared mutable state.

The consumer thread itself feeds the pool (no feeder thread): it tops
the in-flight window up to ``window`` tasks, then blocks on the next
in-order result.  A task that raised re-raises at its position in the
output order, after every earlier result has been yielded — the pool
analogue of ``ThreadBuffer``'s drain-then-error contract.

Observability: pass a ``utils.metric.StatSet`` and the pool records
``<name>.wait_ms`` (consumer blocked on the next in-order result — the
chip-starved signal), ``<name>.stall`` (count of waits), and
``<name>.occupancy`` (worker busy-time / wall-time, 0..1) for the eval
line / bench receipts.

Worker threads are named ``cxxnet-pool-*`` so the test-suite leak
fixture (tests/conftest.py) can assert every pool retired.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable, Iterator, Optional, TypeVar

T = TypeVar('T')
R = TypeVar('R')

_STOP = object()


class OrderedWorkerPool:
    """Fan ``fn`` over an iterable on ``nworker`` threads, yielding
    results strictly in submission order with a bounded in-flight
    window."""

    def __init__(self, nworker: int, window: Optional[int] = None,
                 stats=None, name: str = 'pool'):
        self.nworker = max(1, int(nworker))
        # window > nworker keeps every worker fed while the consumer
        # drains; window also bounds decoded-instance RAM
        self.window = max(self.nworker + 1,
                          int(window) if window else self.nworker * 4)
        self.stats = stats
        self.name = name

    def imap(self, fn: Callable[[T], R],
             iterable: Iterable[T]) -> Iterator[R]:
        """Generator over ``fn(item)`` in submission order.  Spawns the
        workers on first use and joins them when the generator is
        exhausted, closed (GeneratorExit), or errors."""
        tasks: queue.Queue = queue.Queue()
        results: dict = {}
        cond = threading.Condition()
        busy = [0.0] * self.nworker

        def worker(wid: int) -> None:
            while True:
                task = tasks.get()
                if task is _STOP:
                    return
                seq, item = task
                t0 = time.perf_counter()
                try:
                    ok, val = True, fn(item)
                except BaseException as e:  # re-raised at seq, in order
                    ok, val = False, e
                busy[wid] += time.perf_counter() - t0
                with cond:
                    results[seq] = (ok, val)
                    cond.notify_all()

        threads = [threading.Thread(target=worker, args=(w,), daemon=True,
                                    name=f'cxxnet-pool-{self.name}-{w}')
                   for w in range(self.nworker)]
        for t in threads:
            t.start()
        t_start = time.perf_counter()
        src = iter(iterable)
        submitted = nxt = 0
        exhausted = False
        try:
            while True:
                while not exhausted and submitted - nxt < self.window:
                    try:
                        item = next(src)
                    except StopIteration:
                        exhausted = True
                        break
                    tasks.put((submitted, item))
                    submitted += 1
                if nxt >= submitted:
                    if exhausted:
                        return
                    continue
                with cond:
                    if nxt not in results:
                        t0 = time.perf_counter()
                        while nxt not in results:
                            cond.wait(0.1)
                        if self.stats is not None:
                            self.stats.observe(
                                f'{self.name}.wait_ms',
                                (time.perf_counter() - t0) * 1e3)
                            self.stats.inc(f'{self.name}.stall')
                    ok, val = results.pop(nxt)
                nxt += 1
                if not ok:
                    raise val
                yield val
        finally:
            # retire the workers: discard queued tasks (an abandoned or
            # errored stream must not keep decoding), then sentinel each
            while True:
                try:
                    tasks.get_nowait()
                except queue.Empty:
                    break
            for _ in threads:
                tasks.put(_STOP)
            for t in threads:
                t.join()
            if self.stats is not None:
                wall = max(time.perf_counter() - t_start, 1e-9)
                self.stats.gauge(f'{self.name}.workers', self.nworker)
                self.stats.gauge(f'{self.name}.occupancy',
                                 sum(busy) / (wall * self.nworker))
