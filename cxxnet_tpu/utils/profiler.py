"""Profiling / tracing subsystem.

The reference has only wall-clock second counters printed every
``print_step`` batches (``cxxnet_main.cpp:376-387``, ``utils/timer.h:16-30``)
— no tracer, no per-op timing.  On TPU the idiomatic replacement is the JAX
profiler: it records an XLA trace (per-op device timing, HBM usage, fusion
boundaries) viewable in TensorBoard / Perfetto.

Config surface (global section)::

    profile_dir = traces        # enables tracing; directory for the trace
    profile_start_batch = 10    # first update() covered (default 10,
    profile_stop_batch = 20     #   skipping compile) .. last (exclusive)

The window is batch-based so the first (compiling) steps are excluded by
default.
"""

from __future__ import annotations

from typing import List, Tuple


class TraceWindow:
    """Start/stop ``jax.profiler`` around a window of training batches."""

    def __init__(self):
        self.profile_dir = ''
        self.start_batch = 10
        self.stop_batch = 20
        self._active = False
        self._done = False

    def set_param(self, name: str, val: str) -> None:
        if name == 'profile_dir':
            self.profile_dir = val
        if name == 'profile_start_batch':
            self.start_batch = int(val)
        if name == 'profile_stop_batch':
            self.stop_batch = int(val)

    def configure(self, cfg: List[Tuple[str, str]]) -> None:
        for name, val in cfg:
            self.set_param(name, val)

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    def before_update(self, batch_counter: int) -> None:
        """Call before each ``trainer.update``; ``batch_counter`` counts from 0."""
        if not self.enabled or self._done:
            return
        if not self._active and batch_counter >= self.start_batch:
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
        elif self._active and batch_counter >= self.stop_batch:
            self.stop()

    def stop(self) -> None:
        """Finish the trace (idempotent; also call at end of training)."""
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
