"""Profiling / tracing subsystem.

The reference has only wall-clock second counters printed every
``print_step`` batches (``cxxnet_main.cpp:376-387``, ``utils/timer.h:16-30``)
— no tracer, no per-op timing.  On TPU the idiomatic replacement is the JAX
profiler: it records an XLA trace (per-op device timing, HBM usage, fusion
boundaries) viewable in TensorBoard / Perfetto.

Config surface (global section)::

    profile_dir = traces        # enables tracing; directory for the trace
    profile_start_batch = 10    # first update() covered (default 10,
    profile_stop_batch = 20     #   skipping compile) .. last (exclusive)

The window is batch-based so the first (compiling) steps are excluded by
default.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

# --- single-flight arbitration ---------------------------------------------
# jax.profiler holds ONE global trace per process: the config-driven
# TraceWindow and the on-demand /profile session (obs/programs.py
# ProfilerSession) must never both start one.  Whoever acquires the
# slot owns the profiler until release; the loser observes busy.
_TRACE_LOCK = threading.Lock()
_TRACE_OWNER: Optional[str] = None        # guarded-by: _TRACE_LOCK


def acquire_trace(owner: str) -> bool:
    """Claim the process-wide profiler slot for ``owner``; False when
    ANY owner holds it — deliberately non-reentrant, so a stop racing
    a fresh start can never hand two sessions the same slot (the
    caller must not start a trace on False)."""
    global _TRACE_OWNER
    with _TRACE_LOCK:
        if _TRACE_OWNER is not None:
            return False
        _TRACE_OWNER = owner
        return True


def release_trace(owner: str) -> None:
    """Release the slot (no-op unless ``owner`` holds it)."""
    global _TRACE_OWNER
    with _TRACE_LOCK:
        if _TRACE_OWNER == owner:
            _TRACE_OWNER = None


def trace_owner() -> Optional[str]:
    with _TRACE_LOCK:
        return _TRACE_OWNER


class TraceWindow:
    """Start/stop ``jax.profiler`` around a window of training batches."""

    def __init__(self):
        self.profile_dir = ''
        self.start_batch = 10
        self.stop_batch = 20
        self._active = False
        self._done = False

    def set_param(self, name: str, val: str) -> None:
        if name == 'profile_dir':
            self.profile_dir = val
        if name == 'profile_start_batch':
            self.start_batch = int(val)
        if name == 'profile_stop_batch':
            self.stop_batch = int(val)

    def configure(self, cfg: List[Tuple[str, str]]) -> None:
        for name, val in cfg:
            self.set_param(name, val)

    @property
    def enabled(self) -> bool:
        return bool(self.profile_dir)

    def before_update(self, batch_counter: int) -> None:
        """Call before each ``trainer.update``; ``batch_counter`` counts from 0."""
        if not self.enabled or self._done:
            return
        if not self._active and batch_counter >= self.start_batch:
            # single-flight vs the on-demand /profile session: if one
            # is mid-trace, retry at the next batch instead of stacking
            # a second global trace on the jax profiler
            if not acquire_trace('profile_dir'):
                return
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self._active = True
        elif self._active and batch_counter >= self.stop_batch:
            self.stop()

    def stop(self) -> None:
        """Finish the trace (idempotent; also call at end of training)."""
        if self._active:
            import jax
            jax.profiler.stop_trace()
            self._active = False
            self._done = True
            release_trace('profile_dir')
