"""Double-buffered producer-thread prefetcher.

The TPU-native equivalent of the reference's semaphore-driven
``ThreadBuffer`` (``src/utils/thread_buffer.h:22-202``): a background thread
runs the producer while the consumer drains a small bounded queue, hiding
data-pipeline latency behind device compute.  Python threads are adequate
here because the producers (file IO, JPEG decode via PIL, numpy slicing)
release the GIL in their hot paths; the native C++ loader (runtime/) can be
swapped in for the page-decode stage.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Iterator, TypeVar

T = TypeVar('T')

_STOP = object()


class ThreadBuffer:
    """Wraps a factory of iterators; prefetches ``buffer_size`` items ahead."""

    def __init__(self, make_iter: Callable[[], Iterator[T]], buffer_size: int = 2):
        self._make_iter = make_iter
        self._buffer_size = max(1, buffer_size)

    def _run(self, q: queue.Queue, stop: threading.Event, box: list) -> None:
        try:
            for item in self._make_iter():
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # propagate to consumer
            box.append(e)
        finally:
            # the sentinel must not be dropped: a full queue usually means
            # the consumer is merely slow, and losing _STOP would leave it
            # blocked in q.get() forever once it drains the items.  Keep
            # trying until it lands or the consumer abandons us (stop set).
            while not stop.is_set():
                try:
                    q.put(_STOP, timeout=0.1)
                    break
                except queue.Full:
                    continue

    def __iter__(self):
        # restart semantics = BeforeFirst(): a fresh producer each epoch;
        # if the consumer abandons the generator early (GeneratorExit), the
        # stop event unblocks and retires the producer thread
        q: queue.Queue = queue.Queue(maxsize=self._buffer_size)
        stop = threading.Event()
        box: list = []
        thread = threading.Thread(target=self._run, args=(q, stop, box),
                                  daemon=True)
        thread.start()
        try:
            while True:
                item = q.get()
                if item is _STOP:
                    if box:
                        raise box[0]
                    return
                yield item
        finally:
            stop.set()
