"""Double-buffered producer-thread prefetcher.

The TPU-native equivalent of the reference's semaphore-driven
``ThreadBuffer`` (``src/utils/thread_buffer.h:22-202``): a background thread
runs the producer while the consumer drains a small bounded queue, hiding
data-pipeline latency behind device compute.  Python threads are adequate
here because the producers (file IO, JPEG decode via PIL, numpy slicing)
release the GIL in their hot paths; the native C++ loader (runtime/) can be
swapped in for the page-decode stage.

Fault-tolerance surface (doc/fault_tolerance.md):

* ``deadline=`` — a per-item consumer deadline; missing it raises
  ``runtime.faults.PipelineStallError``, which is how the train supervisor
  detects a stalled input pipeline instead of blocking forever,
* ``close(timeout=)`` — deterministic shutdown that joins every producer
  thread this buffer ever started,
* shutdown never drops the end-of-stream sentinel: the producer blocks
  politely while the consumer is alive and drains-then-signals once the
  consumer abandoned it (``stop`` set), so a consumer can never be left
  hanging in ``q.get()`` after a completed producer,
* ``fault_scope='batch'`` opts the buffer into the deterministic
  stall-injection hook (``runtime.faults.FaultPlan``); page/instance-level
  buffers stay out of scope.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterator, List, Optional, Tuple, TypeVar

T = TypeVar('T')

_STOP = object()


class ThreadBuffer:
    """Wraps a factory of iterators; prefetches ``buffer_size`` items ahead."""

    def __init__(self, make_iter: Callable[[], Iterator[T]],
                 buffer_size: int = 2, deadline: Optional[float] = None,
                 first_deadline: Optional[float] = None,
                 fault_scope: Optional[str] = None,
                 fault_base: int = 0):
        self._make_iter = make_iter
        self._buffer_size = max(1, buffer_size)
        self._deadline = deadline
        # the FIRST item may lawfully take longer than the steady-state
        # per-item deadline (epoch re-wind after a recovery, cold caches);
        # None = same as deadline
        self._first_deadline = first_deadline
        self._fault_scope = fault_scope
        # offset added to the producer-local item index before it reaches
        # the fault-injection hook, so a consumer that re-winds mid-epoch
        # (the supervisor) keeps injected stall indices epoch-absolute
        self._fault_base = fault_base
        # optional utils.metric.StatSet: producer full-queue stalls and
        # consumer empty-queue waits land on the eval line (doc/io.md);
        # assigned late (io chains resolve their StatSet after set_param)
        self.stats = None
        self.stats_name = 'buffer'
        self._lock = threading.Lock()
        # every live (thread, stop, queue) from __iter__, for close()
        self._runs: List[Tuple[threading.Thread, threading.Event,
                               queue.Queue]] = []  # guarded-by: _lock

    def _run(self, q: queue.Queue, stop: threading.Event, box: list) -> None:
        try:
            from ..obs import record_event
            t_prev = time.monotonic_ns()
            for i, item in enumerate(self._make_iter()):
                if self._fault_scope is not None:
                    from ..runtime import faults
                    faults.pipeline_item(self._fault_scope,
                                         self._fault_base + i)
                    # per-batch production interval on the flight
                    # recorder (batch-scoped buffers only — page and
                    # instance buffers would drown the ring)
                    now_ns = time.monotonic_ns()
                    record_event('io.produce', 'io', t_start_ns=t_prev,
                                 dur_ns=now_ns - t_prev,
                                 index=self._fault_base + i)
                    t_prev = now_ns
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        # consumer slower than this producer: benign for
                        # throughput, but counted — a full buffer plus a
                        # starved pool downstream localizes the bottleneck
                        if self.stats is not None:
                            self.stats.inc(f'{self.stats_name}.full_stall')
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # propagate to consumer
            box.append(e)
        finally:
            # The sentinel must never be dropped — losing _STOP leaves the
            # consumer blocked in q.get() forever once it drains the items.
            # While the consumer is alive (stop unset) a full queue just
            # means it is slow: wait for space.  Once the consumer has
            # abandoned us (stop set) nobody will ever free a slot, so
            # drain one ourselves, then signal — we are the sole producer,
            # so each pass either lands the sentinel or makes room for it.
            while True:
                try:
                    q.put_nowait(_STOP)
                    return
                except queue.Full:
                    pass
                if stop.is_set():
                    try:
                        q.get_nowait()
                    except queue.Empty:
                        pass
                else:
                    try:
                        q.put(_STOP, timeout=0.1)
                        return
                    except queue.Full:
                        continue

    def __iter__(self):
        # restart semantics = BeforeFirst(): a fresh producer each epoch;
        # if the consumer abandons the generator early (GeneratorExit), the
        # stop event unblocks and retires the producer thread
        q: queue.Queue = queue.Queue(maxsize=self._buffer_size)
        stop = threading.Event()
        box: list = []
        thread = threading.Thread(target=self._run, args=(q, stop, box),
                                  daemon=True, name='cxxnet-tb-producer')
        with self._lock:
            # prune retired producers so an epoch-per-iteration consumer
            # doesn't grow this list unboundedly
            self._runs = [r for r in self._runs if r[0].is_alive()]
            self._runs.append((thread, stop, q))
        thread.start()
        index = 0
        try:
            while True:
                dl = self._deadline
                if index == 0 and self._first_deadline is not None:
                    dl = self._first_deadline
                # when instrumented and about to block, time the wait:
                # consumer-starved ms is the number that justifies
                # nworker (doc/io.md)
                starved = self.stats is not None and q.empty()
                if starved:
                    t0 = time.perf_counter()
                if dl is None:
                    item = q.get()
                else:
                    try:
                        item = q.get(timeout=dl)
                    except queue.Empty:
                        from ..runtime.faults import PipelineStallError
                        raise PipelineStallError(index, dl) from None
                if starved:
                    self.stats.observe(f'{self.stats_name}.starved_ms',
                                       (time.perf_counter() - t0) * 1e3)
                if item is _STOP:
                    if box:
                        raise box[0]
                    return
                yield item
                index += 1
        finally:
            stop.set()

    def close(self, timeout: Optional[float] = None) -> bool:
        """Shut down every producer this buffer started: set their stop
        events, drain their queues (freeing any producer blocked on a full
        queue), and join the threads.  ``timeout`` bounds the TOTAL wait;
        returns True when every producer thread exited."""
        with self._lock:
            runs, self._runs = self._runs, []
        end = None if timeout is None else time.monotonic() + timeout
        ok = True
        for thread, stop, q in runs:
            stop.set()
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
            remain = None if end is None else max(0.0, end - time.monotonic())
            thread.join(remain)
            if thread.is_alive():
                ok = False
        return ok
