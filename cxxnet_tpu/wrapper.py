"""User-level API with the reference wrapper's surface.

The reference exposed its C++ trainer to Python through a C ABI + ctypes
(``wrapper/cxxnet_wrapper.h:29-225``, ``wrapper/cxxnet.py:64-312``).  Here
the trainer *is* Python/JAX, so the same user API — ``DataIter``, ``Net``
(set_param/init_model/load/save/start_round/update/evaluate/predict/
extract/set_weight/get_weight) and module-level ``train()`` helpers — binds
directly, with no FFI hop on the train path.  Semantics preserved:

* ``Net.update`` accepts a DataIter positioned on a batch or a raw
  ``(batch, channel, y, x)`` numpy array + label,
* ``get_weight``/``set_weight`` use the reference's on-disk weight layouts
  (fullc wmat ``(nhidden, nin)``, conv ``(ngroup, nch/g, nin/g*kh*kw)``),
  addressed by layer name and tag ('wmat'/'bias'),
* model files interoperate with the CLI's ``models/%04d.model`` format.
"""

from __future__ import annotations

import sys
from typing import Iterator, Optional

import jax
import numpy as np

from .io.data import DataBatch, create_iterator
from .nnet import checkpoint
from .nnet.trainer import NetTrainer
from .utils.config import parse_config_string


class DataIter:
    """Config-driven data iterator with the reference's cursor protocol."""

    def __init__(self, cfg: str):
        pairs = parse_config_string(cfg)
        self._it = create_iterator(pairs)
        # pairs after `iter = end` are section defaults (batch_size,
        # input_shape, ...) applied to the whole chain — how the reference
        # wrapper confs are written (example/MNIST/mnist.py)
        seen_end = False
        for name, val in pairs:
            if name == 'iter' and val == 'end':
                seen_end = True
            elif seen_end:
                self._it.set_param(name, val)
        self._it.init()
        self._cursor: Optional[Iterator] = None
        self._batch: Optional[DataBatch] = None
        self.head = True
        self.tail = False

    def before_first(self) -> None:
        self._cursor = iter(self._it)
        self._batch = None
        self.head = True
        self.tail = False

    def next(self) -> bool:
        if self._cursor is None:
            self.before_first()
        try:
            self._batch = next(self._cursor)
            self.head = False
            return True
        except StopIteration:
            self.tail = True
            self._batch = None
            return False

    def check_valid(self) -> None:
        if self.head:
            raise RuntimeError('iterator at head state; call next() first')
        if self.tail:
            raise RuntimeError('iterator reached end')

    @property
    def value(self) -> DataBatch:
        self.check_valid()
        return self._batch

    def get_data(self) -> np.ndarray:
        # CXNIOGetData hands out POST-augment float data (reference
        # wrapper contract).  Under device_normalize=1 the batch carries
        # raw pixels + the deferred spec — apply it here so wrapper
        # consumers see the same values either way.
        batch = self.value
        if batch.norm_spec is not None:
            return batch.norm_spec.apply(batch.data)
        return np.asarray(batch.data, np.float32)

    def get_label(self) -> np.ndarray:
        return np.asarray(self.value.label, np.float32)


class Net:
    """Neural net object (reference ``Net``, wrapper/cxxnet.py:105-280)."""

    def __init__(self, dev: str = 'tpu', cfg: str = ''):
        self._pairs = list(parse_config_string(cfg)) if cfg else []
        if dev:
            self._pairs.append(('dev', dev))
        self._trainer: Optional[NetTrainer] = None
        self._engine = None     # serve.PredictEngine after serve_start
        self._batcher = None    # serve.DynamicBatcher after serve_start
        self._fleet = None      # serve.MultiModelRegistry (models=)
        self._online = None     # online.OnlinePipeline after online_start
        self._online_thread = None
        self._online_result = None

    def _require(self) -> NetTrainer:
        if self._trainer is None:
            raise RuntimeError('call init_model()/load_model() first')
        return self._trainer

    def set_param(self, name, value) -> None:
        self._pairs.append((str(name), str(value)))

    def init_model(self) -> None:
        self._trainer = NetTrainer(self._pairs)
        self._trainer.init_model()

    def load_model(self, fname: str) -> None:
        self._trainer = NetTrainer(self._pairs)
        with open(fname, 'rb') as f:
            f.read(4)   # net_type prefix
            self._trainer.load_model(f)

    def save_model(self, fname: str, net_type: int = 0) -> None:
        with open(fname, 'wb') as f:
            f.write(int(net_type).to_bytes(4, 'little', signed=True))
            self._require().save_model(f)

    def start_round(self, round_counter: int) -> None:
        self._require().start_round(round_counter)

    def update(self, data, label=None) -> None:
        tr = self._require()
        if isinstance(data, DataIter):
            tr.update(data.value)
            return
        data = np.asarray(data, np.float32)
        if data.ndim != 4:
            raise ValueError('Net.update: need 4-d (batch, channel, y, x)')
        if label is None:
            raise ValueError('Net.update: need label')
        label = np.asarray(label, np.float32)
        if label.ndim == 1:
            label = label[:, None]
        if label.shape[0] != data.shape[0]:
            raise ValueError('Net.update: data/label size mismatch')
        tr.update(DataBatch(data, label))

    def evaluate(self, data: 'DataIter', name: str) -> str:
        if not isinstance(data, DataIter):
            raise TypeError('evaluate needs a DataIter')
        data.before_first()
        return self._require().evaluate(iter(data._it), name)

    def predict(self, data) -> np.ndarray:
        tr = self._require()
        if isinstance(data, DataIter):
            return tr.predict(data.value)
        data = np.asarray(data, np.float32)
        if data.ndim != 4:
            raise ValueError('need 4-d tensor to predict')
        return tr.predict(DataBatch(data, np.zeros((data.shape[0], 1),
                                                   np.float32)))

    def extract(self, data, name: str) -> np.ndarray:
        tr = self._require()
        if isinstance(data, DataIter):
            return tr.extract_feature(data.value, name)
        data = np.asarray(data, np.float32)
        return tr.extract_feature(
            DataBatch(data, np.zeros((data.shape[0], 1), np.float32)), name)

    # --- streaming whole-iterator prediction ------------------------------
    def predict_stream(self, data: 'DataIter'):
        """Generator of per-batch prediction vectors over the WHOLE
        iterator (rewound first), pad rows trimmed — the O(batch)-host-
        memory path behind ``CXNNetPredictIter`` (capi.net_predict_iter);
        batches pipeline through ``NetTrainer.predict_stream``."""
        if not isinstance(data, DataIter):
            raise TypeError('predict_stream needs a DataIter')
        tr = self._require()
        data.before_first()
        yield from tr.predict_stream(iter(data._it))

    def extract_stream(self, data: 'DataIter', name: str):
        """Generator of per-batch node activations over the whole
        iterator — the streaming path behind ``CXNNetExtractIter``."""
        if not isinstance(data, DataIter):
            raise TypeError('extract_stream needs a DataIter')
        tr = self._require()
        data.before_first()
        yield from tr.forward_stream(iter(data._it), tr.net.node_index(name))

    # --- online serving (doc/serving.md) ----------------------------------
    def serve_start(self, buckets='1,8,32', max_queue: int = 64,
                    max_wait: float = 0.002, deadline: float = 1.0,
                    warm: bool = True, models=None,
                    mem_budget: int = 0, dtype: str = 'f32',
                    replicas: int = 0, fold_bn: int = 0,
                    fold_batch=None) -> None:
        """Stand up the serving stack over this net's loaded params: a
        bucketed ``PredictEngine`` plus a ``DynamicBatcher``.  Call once;
        ``serve_stop()`` tears down (and must precede a restart).

        ``models`` (optional) is a ``{model_id: model_dir}`` dict of
        sibling checkpoints (same architecture as this net) served
        through a ``MultiModelRegistry`` under ``mem_budget`` bytes —
        route to one with ``serve_scores(..., model=id)``; cold models
        load on demand and evict coldest-first under pressure.
        ``dtype`` selects the quantized-inference storage tier
        (``f32``/``bf16``/``int8`` — doc/serving.md "Quantized
        inference"); it applies to this engine AND every fleet sibling,
        so the ``mem_budget`` ledger fits ~4x more int8 models.
        ``replicas>=2`` serves N per-device data-parallel engine
        replicas behind the one batcher (``serve.replicas``,
        doc/serving.md "Sharded serving").  ``fold_bn=1`` folds conv+BN
        pairs into the conv at engine build (f32 tier only; frozen
        calibration-batch statistics — doc/kernels.md), calibrating on
        ``fold_batch`` (NCHW) or a seeded random batch."""
        from .serve import (DynamicBatcher, PredictEngine,
                            ReplicatedPredictEngine)
        from .utils.bucketing import parse_buckets
        if self._batcher is not None:
            raise RuntimeError('serving already started; serve_stop() first')
        tr = self._require()
        bks = parse_buckets(buckets) if isinstance(buckets, str) \
            else tuple(buckets)
        if replicas >= 2:
            from .utils.metric import StatSet
            self._engine = ReplicatedPredictEngine(
                tr, bks, dtype=dtype, replicas=replicas, stats=StatSet(),
                fold_bn=fold_bn, fold_batch=fold_batch)
        else:
            self._engine = PredictEngine(tr, bks, dtype=dtype,
                                         fold_bn=fold_bn,
                                         fold_batch=fold_batch)
        if warm:
            self._engine.warm()
        self._batcher = DynamicBatcher(self._engine, max_queue=max_queue,
                                       max_wait=max_wait, deadline=deadline,
                                       stats=getattr(self._engine, 'stats',
                                                     None))
        self._fleet = None
        if models:
            from .serve import MultiModelRegistry
            self._fleet = MultiModelRegistry(mem_budget=mem_budget)
            for mid, mdir in dict(models).items():
                self._fleet.add_model(
                    mid, self._fleet_factory(mdir, bks, dtype),
                    model_dir=mdir)

    def _fleet_factory(self, model_dir: str, buckets, dtype: str = 'f32'):
        """Factory closure for one fleet sibling: builds an isolated
        inference-only trainer from this net's config pairs and loads the
        newest checkpoint in ``model_dir`` through the retried reader
        (the factory owns every reference, so eviction really frees the
        device memory)."""
        from .serve import PredictEngine
        from .serve.registry import load_into_trainer, newest_model_file

        def factory():
            best = newest_model_file(model_dir)
            if best is None:
                raise FileNotFoundError(f'no model files in {model_dir}')
            tr = load_into_trainer(
                NetTrainer(self._pairs + [('inference_only', '1')]),
                best[1])
            return PredictEngine(tr, buckets, dtype=dtype)
        return factory

    def _require_serving(self):
        if self._batcher is None:
            raise RuntimeError('call serve_start() first')
        return self._batcher

    def serve_scores(self, data, deadline: Optional[float] = None,
                     model: Optional[str] = None) -> np.ndarray:
        """Submit one request through the batcher; blocks for the final
        node's score rows.  Raises the typed serving errors
        (``ServeOverloadError`` / ``DeadlineExceededError``).
        ``model=`` routes to a fleet sibling (engine-direct: fleet
        models are budget-managed, not micro-batched — a cold model may
        load first, so the path is unbounded and ``deadline`` is
        rejected rather than silently ignored).  The fleet lease holds
        off eviction for the whole forward."""
        if model is not None:
            if self._fleet is None:
                raise RuntimeError('serve_start(models=...) first')
            if deadline is not None:
                raise ValueError(
                    'deadline is not enforced on the fleet path (a cold '
                    'model may need to load); pass deadline=None')
            with self._fleet.lease(model) as engine:
                return engine.predict_scores(np.asarray(data, np.float32))
        return self._require_serving().submit(
            np.asarray(data, np.float32), deadline)

    def serve_predict(self, data, deadline: Optional[float] = None,
                      model: Optional[str] = None) -> np.ndarray:
        """Like :meth:`predict` but through the serving stack (micro-
        batched with concurrent callers, bucket-padded)."""
        return NetTrainer._pred_transform(
            self.serve_scores(data, deadline, model=model))

    def serve_reload(self, fname: str) -> None:
        """Manually hot-swap a checkpoint into the live engine (the
        registry's verify→load→warm→swap cycle, minus the watching)."""
        from .nnet import checkpoint
        from .serve.registry import load_model_params
        if self._engine is None:
            raise RuntimeError('call serve_start() first')
        reason = checkpoint.verify_model_digest(fname)
        if reason:
            from .runtime.faults import CheckpointCorruptError
            raise CheckpointCorruptError(f'{fname}: {reason}')
        placed = self._engine.place_params(
            load_model_params(self._engine, fname))
        self._engine.warm_params(placed)
        self._engine.swap_params(placed, version=fname)

    def serve_stats(self, name: str = 'serve') -> str:
        """Per-bucket latency/throughput counters in eval-line format
        (+ the fleet's memory ledger when ``models=`` is serving)."""
        out = self._require_serving().report(name)
        if self._fleet is not None:
            out += self._fleet.report()
        return out

    def serve_stop(self, timeout: Optional[float] = None) -> None:
        """Drain and tear down the serving stack (idempotent)."""
        if self._batcher is not None:
            self._batcher.close(timeout)
            self._batcher = None
        if self._engine is not None and hasattr(self._engine, 'close'):
            self._engine.close(timeout)   # replica worker threads
        if self._fleet is not None:
            self._fleet.close(timeout)
            self._fleet = None
        self._engine = None

    # --- train-while-serve (doc/online.md) --------------------------------
    def online_start(self, train_data, model_dir: str, rounds: int = 1,
                     save_every: int = 8, freshness_slo: float = 0.0,
                     freshness_strict: bool = False, reload: float = 0.05,
                     buckets='1,8,32', max_queue: int = 64,
                     max_wait: float = 0.002, deadline: float = 1.0,
                     qps: float = 50.0, request_source=None,
                     steps_per_dispatch: int = 1,
                     watchdog_deadline: float = 60.0,
                     dtype: str = 'f32') -> None:
        """Run the train-while-serve loop over this net: training starts
        on a background thread while the colocated serving stack answers
        :meth:`online_scores` / :meth:`online_predict` requests, hot-
        reloading each checkpoint published every ``save_every`` steps.
        ``train_data`` is a ``DataIter`` (or raw iterator chain);
        passing a ``request_source`` arms the built-in traffic driver
        (``qps`` requests/sec) for embedders that don't push their own
        requests.
        ``online_wait()`` joins the training thread and returns the
        summary; ``online_stop()`` tears everything down."""
        import threading

        from .online import OnlineConfig, OnlinePipeline
        from .utils.bucketing import parse_buckets
        if self._online is not None:
            raise RuntimeError('online already started; online_stop() first')
        tr = self._require()
        it = train_data._it if isinstance(train_data, DataIter) \
            else train_data
        bks = parse_buckets(buckets) if isinstance(buckets, str) \
            else tuple(buckets)
        cfg = OnlineConfig(
            model_dir=model_dir, save_every=save_every,
            freshness_slo=freshness_slo, freshness_strict=freshness_strict,
            reload_poll=reload, buckets=bks, max_queue=max_queue,
            max_wait=max_wait, deadline=deadline, dtype=dtype,
            qps=qps, watchdog_deadline=watchdog_deadline or None,
            steps_per_dispatch=steps_per_dispatch, silent=True)
        # a request_source arms the built-in driver at `qps`; without
        # one the embedder pushes its own requests via online_scores
        pipe = OnlinePipeline(
            tr, it,
            lambda: NetTrainer(self._pairs + [('inference_only', '1')]),
            cfg, request_source=request_source)
        pipe.start()                      # serving is live before return
        self._online = pipe
        self._online_result = {}

        def _train():
            try:
                self._online_result['summary'] = pipe.run(rounds)
            except BaseException as e:     # surfaced by online_wait
                self._online_result['error'] = e

        self._online_thread = threading.Thread(
            target=_train, daemon=True, name='online-train')
        self._online_thread.start()

    def _require_online(self):
        if self._online is None:
            raise RuntimeError('call online_start() first')
        return self._online

    def online_scores(self, data, deadline: Optional[float] = None):
        """One request through the live online stack (final-node score
        rows); typed serving errors propagate."""
        return self._require_online().submit(
            np.asarray(data, np.float32), deadline)

    def online_predict(self, data, deadline: Optional[float] = None):
        """Class id per row through the online stack."""
        return NetTrainer._pred_transform(self.online_scores(data, deadline))

    def online_stats(self, name: str = 'online') -> str:
        """Freshness/swap gauges + serving ledger, eval-line format."""
        pipe = self._require_online()
        return pipe.eval_line(name) + pipe.serve_report()

    def online_wait(self, timeout: Optional[float] = None) -> dict:
        """Join the training thread; re-raises its error or returns the
        run summary (freshness p50/p99, swaps, served, dropped...)."""
        self._require_online()
        t = self._online_thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError('online training still running')
        res = self._online_result or {}
        if 'error' in res:
            raise res['error']
        return res.get('summary', self._online.summary())

    def online_stop(self, timeout: Optional[float] = None) -> None:
        """Tear down the online loop (idempotent); joins the training
        thread first so close() never races a live step loop."""
        if self._online is None:
            return
        t = self._online_thread
        if t is not None:
            t.join(timeout)
            if t.is_alive():
                raise TimeoutError(
                    'online training still running — tearing the stack '
                    'down under a live step loop would corrupt the run')
        self._online.close(timeout)
        self._online = None
        self._online_thread = None

    # --- telemetry (doc/observability.md) ---------------------------------
    def obs_stats(self) -> str:
        """One JSON snapshot of the process-wide telemetry hub — the
        same body the ``/statusz`` endpoint serves: uptime, every
        registered StatSet's counters, subsystem status views
        (registry state machine, execution plan, elastic membership),
        and the flight-recorder state.  Works with or without a loaded
        model: the hub is process-wide."""
        import json

        from .obs import get_hub
        return json.dumps(get_hub().status(), sort_keys=True, default=str)

    def obs_slos(self) -> str:
        """The attached SLO engines' typed verdicts as one JSON object —
        the same body the ``/slos`` endpoint serves (state, burn
        ratios, breach counts, window samples, verdict history per
        objective; ``{}`` when no engine is attached).  The embedder's
        portless way to read health the way the future autoscaler will
        (doc/observability.md "SLOs and burn rates")."""
        import json

        from .obs import get_hub
        return json.dumps(get_hub().slos_view(), sort_keys=True,
                          default=str)

    def obs_programs(self) -> str:
        """The compiler-truth program ledger as one JSON object — the
        same body the ``/programs`` endpoint serves: every compiled
        executable's (name, shape-key) row with compile wall-ms, HLO
        flops / bytes-accessed, and argument/output/temp/peak memory,
        plus the recompile-sentinel totals (doc/observability.md
        "Programs, memory, and MFU")."""
        import json

        from .obs.programs import get_ledger
        return json.dumps(get_ledger().view(), sort_keys=True,
                          default=str)

    def autotune(self, spec: str, probe_fn, baseline=None,
                 task: str = 'train') -> str:
        """Run the grafttune two-stage search (doc/autotune.md) over an
        ``autotune=`` spec string with a caller-supplied measured probe
        — ``probe_fn(candidate_dict) -> score`` (higher is better) —
        and return the receipt as one JSON object.  The embedding owns
        probe execution (it knows what a representative workload is);
        stage-1 ledger pruning and the budgeted stage-2 sweep are the
        library's.  The tuned knobs are ``receipt['best']``."""
        import json

        from .tune import TuneSearch, TuneSpace
        space = TuneSpace.parse(spec)
        result = TuneSearch(space, probe_fn,
                            baseline=baseline).run(task)
        return json.dumps(result.receipt(), sort_keys=True, default=str)

    # --- weight access (visitor equivalent) -------------------------------
    def _resolve(self, layer_name: str):
        tr = self._require()
        idx = tr.net_cfg.get_layer_index(layer_name)
        return tr, idx, tr.net_cfg.layers[idx].type

    def get_weight(self, layer_name: str, tag: str) -> Optional[np.ndarray]:
        if tag not in ('bias', 'wmat'):
            raise ValueError('tag must be bias or wmat')
        tr, idx, type_id = self._resolve(layer_name)
        rec = tr.params.get(str(idx), {})
        if tag not in rec:
            return None
        arr = np.asarray(jax.device_get(rec[tag]), np.float32)
        layer = tr.net.layers[idx]
        return checkpoint.to_disk_layout(type_id, tag, arr,
                                         layer.param.num_group)

    def set_weight(self, weight: np.ndarray, layer_name: str,
                   tag: str) -> None:
        if tag not in ('bias', 'wmat'):
            raise ValueError('tag must be bias or wmat')
        tr, idx, type_id = self._resolve(layer_name)
        key = str(idx)
        if key not in tr.params or tag not in tr.params[key]:
            raise KeyError(f'layer {layer_name} has no weight {tag}')
        layer = tr.net.layers[idx]
        mem = checkpoint.from_disk_layout(
            type_id, tag, np.asarray(weight, np.float32), layer)
        if mem.shape != tr.params[key][tag].shape:
            raise ValueError(
                f'set_weight: shape {mem.shape} != '
                f'{tr.params[key][tag].shape}')
        params = dict(tr.params)
        params[key] = dict(params[key])
        params[key][tag] = jax.device_put(mem,
                                          tr.params[key][tag].sharding)
        tr.params = params


def train_iter(cfg: str, data: DataIter, num_round: int, param,
               eval_data: Optional[DataIter] = None) -> Net:
    """Module-level train helper over a DataIter (wrapper/cxxnet.py:281)."""
    net = Net(cfg=cfg)
    if isinstance(param, dict):
        param = param.items()
    for k, v in param:
        net.set_param(k, v)
    net.init_model()
    for r in range(num_round):
        net.start_round(r)
        data.before_first()
        counter = 0
        while data.next():
            net.update(data)
            counter += 1
            if counter % 100 == 0:
                print(f'[{r}] {counter} batch passed')
        if eval_data is not None:
            sys.stderr.write(net.evaluate(eval_data, 'eval') + '\n')
    return net


def train(cfg: str, data, label, num_round: int, param) -> Net:
    """Module-level train helper over a numpy batch (wrapper/cxxnet.py:300)."""
    net = Net(cfg=cfg)
    if isinstance(param, dict):
        param = param.items()
    for k, v in param:
        net.set_param(k, v)
    net.init_model()
    for r in range(num_round):
        net.start_round(r)
        net.update(data=data, label=label)
    return net


class LMServe:
    """Python-embedder surface for the continuous-batching decode stack
    (doc/serving.md "Continuous decode") — the LM counterpart of
    :class:`Net`'s serving surface, and the object the flat C ABI's
    ``lm_serve_*`` calls hand around (capi.py delegates here).

    Built from a compact ``k=v[;k=v...]`` spec: model
    ``vocab``/``d_model``/``heads``/``d_ff``/``stages``/``experts``,
    params from ``model_in`` (a ``%04d.lm`` tree) or ``seed`` init,
    engine shape ``slots``/``pages``/``page_size``/``max_prompt``/
    ``max_new``/``eos``, batcher knobs ``max_queue``/``max_wait``/
    ``deadline``, serving tier ``dtype`` (``f32``/``bf16``/``int8``),
    attention leg ``flash_decode`` (``auto``/``0``/``1``), prefix
    sharing ``prefix_share`` (index page cap, 0 = off; doc/serving.md
    "Prefix sharing"), and greedy speculative decoding ``spec_k`` plus
    ``draft.*`` keys (``draft.d_model=16;draft.stages=1;draft.seed=1``
    or ``draft.model_in=`` — the draft's vocab defaults to the
    target's; doc/serving.md "Speculative decoding"), and the graftcache
    KV tiers ``kv_host_mb`` / ``kv_disk_mb`` / ``kv_dir`` /
    ``kv_share_dir`` (doc/serving.md "Tiered KV cache"; tiers need
    ``prefix_share`` on), plus graftshard's ``shard=tp:N`` tensor-
    parallel decode and ``prefill_workers=N`` disaggregated prefill
    (doc/serving.md "Sharded serving")."""

    def __init__(self, svc):
        self.svc = svc

    @classmethod
    def from_spec(cls, cfg: str) -> 'LMServe':
        from .models import transformer as T
        from .serve.decode import DecodeService, load_lm_params
        from .utils.config import parse_kv_list

        def build_model(kw, model_in, seed):
            tcfg = T.TransformerConfig(**kw)
            params = (load_lm_params(model_in) if model_in
                      else T.init_params(np.random.RandomState(seed),
                                         tcfg))
            return params, tcfg

        cfg_kw = {'attn': 'local'}
        draft_kw = {'attn': 'local'}
        svc_kw = {}
        seed, model_in, eos = 0, None, None
        draft_seed, draft_model_in, has_draft = 0, None, False
        names = {'vocab': 'vocab_size', 'd_model': 'd_model',
                 'heads': 'num_heads', 'd_ff': 'd_ff',
                 'stages': 'num_stages', 'experts': 'num_experts',
                 'seq': 'seq_len'}
        ints = ('slots', 'pages', 'page_size', 'max_prompt', 'max_queue',
                'prefix_share', 'spec_k', 'kv_host_mb', 'kv_disk_mb',
                'prefill_workers')
        for key, val in parse_kv_list(cfg or ''):
            if key in names:
                cfg_kw[names[key]] = int(val)
            elif key in ints:
                svc_kw[key] = int(val)
            elif key == 'max_new':
                svc_kw['max_new_bound'] = int(val)
            elif key in ('max_wait', 'deadline'):
                svc_kw[key] = float(val)
            elif key == 'seed':
                seed = int(val)
            elif key == 'model_in':
                model_in = val
            elif key == 'eos':
                eos = None if int(val) < 0 else int(val)
            elif key == 'dtype':
                svc_kw['dtype'] = val
            elif key == 'flash_decode':
                svc_kw['flash_decode'] = val
            elif key in ('kv_dir', 'kv_share_dir'):
                svc_kw[key] = val
            elif key == 'shard':
                svc_kw['shard'] = val
            elif key.startswith('draft.'):
                has_draft = True
                sub = key[len('draft.'):]
                if sub in names:
                    draft_kw[names[sub]] = int(val)
                elif sub == 'seed':
                    draft_seed = int(val)
                elif sub == 'model_in':
                    draft_model_in = val
                else:
                    raise ValueError(f'unknown lm_serve option: {key!r}')
            else:
                raise ValueError(f'unknown lm_serve option: {key!r}')
        params, tcfg = build_model(cfg_kw, model_in, seed)
        if has_draft:
            draft_kw.setdefault('vocab_size', tcfg.vocab_size)
            svc_kw['draft'] = build_model(draft_kw, draft_model_in,
                                          draft_seed)
        return cls(DecodeService(params, tcfg, eos_id=eos, **svc_kw))

    # --- DecodeService delegation (the capi duck-type surface) ------------
    @property
    def engine(self):
        return self.svc.engine

    @property
    def batcher(self):
        return self.svc.batcher

    def generate(self, prompt, max_new: int, temperature: float = 0.0,
                 rng=None, deadline: Optional[float] = None) -> np.ndarray:
        return self.svc.generate(prompt, max_new, temperature, rng,
                                 deadline)

    def autoscale(self, policy: str):
        """Attach an SLO-driven autoscaler (``serve.autoscale=``
        grammar, doc/serving.md "Scenarios and autoscaling") over this
        service's live admission caps; returns the
        :class:`~cxxnet_tpu.serve.autoscale.Autoscaler` (call its
        ``evaluate()`` per tick when ``interval=0``, or let its
        ``interval>0`` thread run; ``close()`` detaches)."""
        from .obs import get_hub
        from .serve.autoscale import AutoscalePolicy, Autoscaler
        scaler = Autoscaler(AutoscalePolicy.parse(policy))
        scaler.bind_engine(self.svc.engine)
        scaler.bind_batcher(self.svc.batcher)
        scaler.register_into(get_hub())
        return scaler

    def run_scenario(self, spec: str, time_scale: float = 1.0,
                     on_tick=None) -> dict:
        """Drive a seeded traffic scenario (``serve.scenario=``
        grammar) against this service and return the reconciled
        ledger's summary dict (submitted / per-bucket counts / p50 /
        p99).  Deterministic: the same spec replays the same storm."""
        from .serve.scenario import ScenarioLedger, ScenarioSpec, drive
        sspec = ScenarioSpec.parse(spec)
        base = ScenarioLedger.stat_snapshot(self.engine.stats)
        led = drive(self.svc, sspec, vocab=self.engine.cfg.vocab_size,
                    on_tick=on_tick, time_scale=time_scale)
        led.reconcile(self.engine.stats, base=base)
        return led.summary()

    def report(self, name: str = 'decode') -> str:
        return self.svc.report(name)

    def close(self, timeout: Optional[float] = None) -> None:
        self.svc.close(timeout)
