#!/usr/bin/env python3
"""Python-API MNIST walkthrough (reference ``example/MNIST/mnist.py``):
train via the wrapper, inspect weights, predict from a DataIter and from a
raw numpy batch, extract features, evaluate manually, keep training.

Run ``./run.sh`` first to fetch the data, then::

    python mnist.py
"""

import sys

import numpy as np

sys.path.append('../..')
from cxxnet_tpu import wrapper as cxxnet  # noqa: E402

data = cxxnet.DataIter("""
iter = mnist
    path_img = "./data/train-images-idx3-ubyte.gz"
    path_label = "./data/train-labels-idx1-ubyte.gz"
    shuffle = 1
iter = end
input_shape = 1,1,784
batch_size = 100
""")
print('init data iter')

deval = cxxnet.DataIter("""
iter = mnist
    path_img = "./data/t10k-images-idx3-ubyte.gz"
    path_label = "./data/t10k-labels-idx1-ubyte.gz"
iter = end
input_shape = 1,1,784
batch_size = 100
""")

cfg = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 100
  init_sigma = 0.01
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 10
  init_sigma = 0.01
layer[+0] = softmax
netconfig=end

input_shape = 1,1,784
batch_size = 100
random_type = gaussian
"""

param = {'eta': 0.1, 'dev': 'cpu', 'momentum': 0.9,
         'metric[label]': 'error'}

net = cxxnet.train_iter(cfg, data, 1, param, eval_data=deval)

# weight access by layer name + tag (reference on-disk layouts)
weights = [(layer, tag, net.get_weight(layer, tag))
           for layer in ('fc1', 'fc2') for tag in ('wmat', 'bias')]
for layer, tag, w in weights:
    print(f'{layer}.{tag}: {w.shape}')

data.before_first()
data.next()
print('predict')
pred = net.predict(data)                      # from the iterator's batch
dbatch = data.get_data()
print(dbatch.shape)
pred2 = net.predict(dbatch)                   # from a raw numpy batch
print('iter-vs-raw predict diff:', np.sum(np.abs(pred - pred2)))
print('iter-vs-raw extract diff:',
      np.sum(np.abs(net.extract(data, 'sg1') - net.extract(dbatch, 'sg1'))))

# manual evaluation loop
deval.before_first()
werr = wcnt = 0
while deval.next():
    label = deval.get_label()
    pred = net.predict(deval)
    werr += np.sum(label[:, 0] != pred[:])
    wcnt += len(label[:, 0])
print('eval-error=%f' % (float(werr) / wcnt))

# keep training with raw batches
data.before_first()
while data.next():
    net.update(data.get_data(), data.get_label())

deval.before_first()
werr = wcnt = 0
while deval.next():
    label = deval.get_label()
    pred = net.predict(deval)
    werr += np.sum(label[:, 0] != pred[:])
    wcnt += len(label[:, 0])
print('eval-error-after=%f' % (float(werr) / wcnt))
