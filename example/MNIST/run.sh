#!/bin/bash
# download MNIST and train the MLP config
mkdir -p data
cd data
for f in train-images-idx3-ubyte train-labels-idx1-ubyte t10k-images-idx3-ubyte t10k-labels-idx1-ubyte; do
  [ -f $f.gz ] || wget -q http://yann.lecun.com/exdb/mnist/$f.gz
done
cd ..
python -m cxxnet_tpu.main MNIST.conf
