#!/usr/bin/env python3
"""Build a cxxnet .lst (``index \\t label \\t path``) from the NDSB folder
layout (reference ``example/kaggle_bowl/gen_img_list.py``).

Usage::

    python gen_img_list.py train sample_submission.csv train_folder/ img.lst
    python gen_img_list.py test  sample_submission.csv test_folder/  test.lst

Class ids follow the column order of sample_submission.csv (the order the
submission file must use); the list is shuffled with a fixed seed.
"""

import csv
import os
import random
import sys


def main():
    if len(sys.argv) < 5:
        print('Usage: gen_img_list.py train/test sample_submission.csv '
              'image_folder img.lst')
        return 1
    task, sub_csv, folder, out = sys.argv[1:5]
    rng = random.Random(888)
    with open(sub_csv, newline='') as f:
        head = next(csv.reader(f))[1:]       # class names, submission order

    img_lst = []
    if task == 'train':
        for cls_id, cls in enumerate(head):
            cls_dir = os.path.join(folder, cls)
            for img in sorted(os.listdir(cls_dir)):
                img_lst.append((len(img_lst), cls_id,
                                os.path.join(cls_dir, img)))
    else:
        for img in sorted(os.listdir(folder)):
            img_lst.append((len(img_lst), 0, os.path.join(folder, img)))

    rng.shuffle(img_lst)
    with open(out, 'w', newline='') as f:
        w = csv.writer(f, delimiter='\t', lineterminator='\n')
        for item in img_lst:
            w.writerow(item)
    return 0


if __name__ == '__main__':
    sys.exit(main())
