#!/usr/bin/env python3
"""Resize the flat NDSB test folder to 48x48 (reference
``example/kaggle_bowl/gen_test.py``; PIL instead of ImageMagick).

Usage::

    python gen_test.py input_folder/ output_folder/
"""

import os
import sys

from PIL import Image


def main():
    if len(sys.argv) < 3:
        print('Usage: python gen_test.py input_folder output_folder')
        return 1
    src, dst = sys.argv[1], sys.argv[2]
    os.makedirs(dst, exist_ok=True)
    for img in sorted(os.listdir(src)):
        with Image.open(os.path.join(src, img)) as im:
            im.resize((48, 48), Image.BILINEAR).save(os.path.join(dst, img))
    return 0


if __name__ == '__main__':
    sys.exit(main())
