#!/bin/sh
# split a shuffled img.lst into train/validation lists
# (reference example/kaggle_bowl/gen_tr_va.sh)
sed -n '1,20000p' "$1" > tr.lst
sed -n '20000,40000p' "$1" > va.lst
