#!/usr/bin/env python3
"""Resize the NDSB train folder tree to 48x48 (reference
``example/kaggle_bowl/gen_train.py``, which shelled out to ImageMagick
``convert -resize 48x48!``; PIL here — no external tool needed).

Usage::

    python gen_train.py input_folder/ output_folder/
"""

import os
import sys

from PIL import Image


def resize_tree(src, dst, size=(48, 48)):
    for cls in sorted(os.listdir(src)):
        sdir = os.path.join(src, cls)
        if not os.path.isdir(sdir):
            continue
        ddir = os.path.join(dst, cls)
        os.makedirs(ddir, exist_ok=True)
        for img in os.listdir(sdir):
            with Image.open(os.path.join(sdir, img)) as im:
                im.resize(size, Image.BILINEAR).save(os.path.join(ddir, img))


def main():
    if len(sys.argv) < 3:
        print('Usage: python gen_train.py input_folder output_folder')
        return 1
    resize_tree(sys.argv[1], sys.argv[2])
    return 0


if __name__ == '__main__':
    sys.exit(main())
