#!/usr/bin/env python3
"""Assemble the Kaggle submission csv from ``task=pred_raw`` output
(reference ``example/kaggle_bowl/make_submission.py``).

Usage::

    python make_submission.py sample_submission.csv test.lst test.txt out.csv

``test.txt`` is the pred_raw output: one space-separated probability row
per instance, in ``test.lst`` order.
"""

import csv
import os
import sys


def main():
    if len(sys.argv) < 5:
        print('Usage: python make_submission.py sample_submission.csv '
              'test.lst test.txt out.csv')
        return 1
    sub_csv, lst_path, scores_path, out_path = sys.argv[1:5]
    with open(sub_csv, newline='') as f:
        head = next(csv.reader(f))
    names = []
    with open(lst_path, newline='') as f:
        for line in csv.reader(f, delimiter='\t'):
            names.append(os.path.basename(line[-1]))
    with open(out_path, 'w', newline='') as fo:
        w = csv.writer(fo, lineterminator='\n')
        w.writerow(head)
        with open(scores_path) as fi:
            for idx, line in enumerate(fi):
                w.writerow([names[idx]] + line.split())
    return 0


if __name__ == '__main__':
    sys.exit(main())
