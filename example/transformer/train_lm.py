#!/usr/bin/env python
"""Train a tiny causal LM with composed 4D parallelism (pp x dp x sp x tp).

The long-context / distributed side of the framework (beyond the
reference's CNN scope): pipeline stages over the ``pipe`` mesh axis, data
parallelism over ``data``, ring-attention sequence parallelism over
``seq``, tensor-parallel heads/FFN over ``model``, optional switch-MoE
experts over the data axis.  Runs anywhere — on a laptop it uses 8 virtual
CPU devices; on a TPU slice the same code spans the real chips.

  python example/transformer/train_lm.py                # pp2 dp2 sp2 tp1
  python example/transformer/train_lm.py --pp 1 --dp 4 --sp 2 --tp 1
  python example/transformer/train_lm.py --experts 4    # switch-MoE FFN
"""

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument('--pp', type=int, default=2)
    ap.add_argument('--dp', type=int, default=2)
    ap.add_argument('--sp', type=int, default=2)
    ap.add_argument('--tp', type=int, default=1)
    ap.add_argument('--experts', type=int, default=0)
    ap.add_argument('--remat', action='store_true',
                    help='rematerialize blocks in backward (long-context HBM saver)')
    ap.add_argument('--steps', type=int, default=30)
    ap.add_argument('--seq', type=int, default=128)
    ap.add_argument('--batch', type=int, default=8)
    ap.add_argument('--ckpt_dir', default='',
                    help='sharded orbax checkpoint dir; resumes from the '
                         'newest step when one exists')
    ap.add_argument('--save_every', type=int, default=10)
    ap.add_argument('--generate', type=int, default=0, metavar='N',
                    help='after training, greedy-decode N tokens from a '
                         'training prompt (KV-cached transformer.generate '
                         '— the LM analog of task=pred)')
    ap.add_argument('--temperature', type=float, default=0.0,
                    help='sampling temperature for --generate (0=greedy)')
    args = ap.parse_args()
    if args.save_every <= 0:
        ap.error('--save_every must be >= 1')
    n = args.pp * args.dp * args.sp * args.tp

    import jax
    if os.environ.get('JAX_PLATFORMS', '') == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    if len(jax.devices()) < n:
        # virtual CPU mesh for development machines
        from jax.extend import backend as jexb
        jexb.clear_backends()
        jax.config.update('jax_platforms', 'cpu')
        jax.config.update('jax_num_cpu_devices', n)

    import numpy as np
    from cxxnet_tpu.models.transformer import (TransformerConfig,
                                               abstract_params,
                                               build_transformer_mesh,
                                               init_params, make_train_step)

    if args.batch % args.dp:
        ap.error(f'--batch {args.batch} must be divisible by --dp {args.dp}')
    # GPipe microbatches must divide the per-data-rank batch; use the most
    # the local batch allows, capped at the default of 4.  Stage count must
    # equal the pipe axis size (each pipe rank owns exactly one stage).
    local_batch = args.batch // args.dp
    micro = max(m for m in (4, 3, 2, 1) if local_batch % m == 0)
    cfg = TransformerConfig(seq_len=args.seq, num_experts=args.experts,
                            num_stages=args.pp,
                            num_microbatches=micro, remat=args.remat)
    mesh = build_transformer_mesh(n, args.pp, args.dp, args.sp, args.tp)
    print(f'mesh: {dict(mesh.shape)}  experts={args.experts}')
    step = make_train_step(cfg, mesh)
    params, start_step = None, 0
    if args.ckpt_dir:
        from cxxnet_tpu.nnet.sharded_ckpt import (latest_step,
                                                  restore_sharded,
                                                  save_sharded,
                                                  wait_for_saves)
        if latest_step(args.ckpt_dir) is not None:
            # shapes-only restore target: resume never materializes a
            # throwaway full replica
            params, start_step = restore_sharded(
                args.ckpt_dir, abstract_params(None, cfg, mesh))
            start_step += 1
            print(f'resumed from step {start_step - 1}')
    if params is None:
        params = init_params(np.random.RandomState(0), cfg)

    # synthetic copy-task data: predict the previous token
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, cfg.vocab_size,
                         (args.batch, cfg.seq_len)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)

    t0 = time.time()
    for i in range(start_step, args.steps):
        params, loss, aux = step(params, tokens, labels)
        if i % 10 == 0 or i == args.steps - 1:
            moe = (f'  balance {float(aux["balance_loss"]):.3f}'
                   f'  drop {float(aux["drop_frac"]):.3f}'
                   if args.experts else '')
            print(f'step {i:4d}  loss {float(loss):.4f}{moe}  '
                  f'({time.time() - t0:.1f}s)')
        if args.ckpt_dir and ((i + 1) % args.save_every == 0
                              or i == args.steps - 1):
            # async: the commit overlaps the next training steps
            save_sharded(args.ckpt_dir, i, params, block=False)
    if args.ckpt_dir:
        wait_for_saves()
    if args.generate:
        import jax
        from cxxnet_tpu.models.transformer import generate

        # decode happens on replicated single-logical-device params: pull
        # the (tiny example) params off the mesh once
        host_params = jax.tree.map(lambda a: np.asarray(a), params)
        prompt = tokens[:2, :8]
        out = np.asarray(generate(
            host_params, prompt, args.generate, cfg,
            temperature=args.temperature,
            rng=jax.random.PRNGKey(0) if args.temperature > 0 else None))
        for b in range(out.shape[0]):
            print(f'prompt {list(map(int, prompt[b]))} -> '
                  f'decoded {list(map(int, out[b]))}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
