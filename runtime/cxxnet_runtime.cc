// cxxnet_tpu native runtime: binary-page data loader + JPEG decode.
//
// TPU-native counterpart of the reference's native IO stack
// (src/io/iter_thread_imbin-inl.hpp + utils/thread_buffer.h + utils/decoder.h):
// a background reader thread streams fixed 64MB BinaryPages from disk into a
// bounded ring (the double-buffer pipeline), objects are exposed zero-copy,
// and JPEG blobs decode straight to RGB via libjpeg.  Exposed as a plain C
// ABI consumed through ctypes (cxxnet_tpu/runtime/native.py).
//
// Page format (byte-compatible with utils/io.h:253-326):
//   int32 data[64<<18]; data[0]=count, data[1+i]=cumulative byte offsets,
//   object r occupies [PAGE_BYTES - data[r+2], PAGE_BYTES - data[r+1]).

#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <csetjmp>

namespace {

constexpr size_t kPageInts = 64u << 18;
constexpr size_t kPageBytes = kPageInts * 4;

struct Page {
  std::vector<char> buf;
  Page() : buf(kPageBytes) {}
  const int32_t* head() const {
    return reinterpret_cast<const int32_t*>(buf.data());
  }
  int count() const { return head()[0]; }
  const char* obj(int r, size_t* size) const {
    const int32_t* h = head();
    size_t lo = kPageBytes - static_cast<size_t>(h[r + 2]);
    *size = static_cast<size_t>(h[r + 2] - h[r + 1]);
    return buf.data() + lo;
  }
};

// Bounded-ring page prefetcher: one reader thread, consumer pops in order.
// With a page-index order list (imgbinx shuffled epochs) the reader seeks
// page-by-page — pages are fixed-size records, hence random-access — so
// shuffle costs no extra IO and prefetch still runs ahead of decode.
struct PageStream {
  FILE* fp = nullptr;
  std::thread reader;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::deque<std::unique_ptr<Page>> ready;
  size_t max_ready = 2;
  bool eof = false;
  bool stop = false;
  std::unique_ptr<Page> current;
  bool use_order = false;      // explicit: order=[] means read NOTHING
  std::vector<int64_t> order;
  size_t order_pos = 0;        // reader thread only
  bool read_error = false;     // short read mid-order: error, not EOF

  ~PageStream() { Close(); }

  bool Open(const char* path, int prefetch, const int64_t* idx, int n) {
    fp = fopen(path, "rb");
    if (!fp) return false;
    if (idx) {
      use_order = true;
      if (n > 0) order.assign(idx, idx + n);
    }
    max_ready = prefetch > 0 ? static_cast<size_t>(prefetch) : 2;
    reader = std::thread([this] { ReadLoop(); });
    return true;
  }

  void ReadLoop() {
    for (;;) {
      bool ok;
      if (use_order && order_pos >= order.size()) {
        std::lock_guard<std::mutex> lk(mu);
        eof = true;
        cv_get.notify_all();
        return;
      }
      auto page = std::make_unique<Page>();
      if (use_order) {
        int64_t idx = order[order_pos++];
        ok = fseeko(fp, static_cast<off_t>(idx) *
                            static_cast<off_t>(kPageBytes), SEEK_SET) == 0 &&
             fread(page->buf.data(), 1, kPageBytes, fp) == kPageBytes;
      } else {
        ok = fread(page->buf.data(), 1, kPageBytes, fp) == kPageBytes;
      }
      std::unique_lock<std::mutex> lk(mu);
      if (!ok) {
        // sequential mode ends at the first short read (tail) — that is
        // the normal EOF; an ordered read that comes up short points past
        // the file and must surface as an error, not silent truncation
        read_error = use_order;
        eof = true;
        cv_get.notify_all();
        return;
      }
      cv_put.wait(lk, [this] { return ready.size() < max_ready || stop; });
      if (stop) return;
      ready.push_back(std::move(page));
      cv_get.notify_one();
    }
  }

  // returns object count, -1 at end of stream, -2 on read error
  int NextPage() {
    std::unique_lock<std::mutex> lk(mu);
    cv_get.wait(lk, [this] { return !ready.empty() || eof || stop; });
    if (ready.empty()) return read_error ? -2 : -1;
    current = std::move(ready.front());
    ready.pop_front();
    cv_put.notify_one();
    return current->count();
  }

  void Close() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
      cv_put.notify_all();
      cv_get.notify_all();
    }
    if (reader.joinable()) reader.join();
    if (fp) {
      fclose(fp);
      fp = nullptr;
    }
  }
};

// libjpeg error handling: jump back instead of exit()
struct JpegErr {
  jpeg_error_mgr pub;
  jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jb, 1);
}

}  // namespace

extern "C" {

void* cxr_open(const char* path, int prefetch_pages) {
  auto* s = new PageStream();
  if (!s->Open(path, prefetch_pages, nullptr, 0)) {
    delete s;
    return nullptr;
  }
  return s;
}

// Open reading only the given page indices, in that order (seek-based).
void* cxr_open_order(const char* path, const int64_t* order, int n,
                     int prefetch_pages) {
  auto* s = new PageStream();
  if (!s->Open(path, prefetch_pages, order, n)) {
    delete s;
    return nullptr;
  }
  return s;
}

int cxr_next_page(void* handle) {
  return static_cast<PageStream*>(handle)->NextPage();
}

const char* cxr_get_obj(void* handle, int r, size_t* size) {
  auto* s = static_cast<PageStream*>(handle);
  if (!s->current || r >= s->current->count()) {
    *size = 0;
    return nullptr;
  }
  return s->current->obj(r, size);
}

void cxr_close(void* handle) { delete static_cast<PageStream*>(handle); }

// Decode a JPEG blob to tightly-packed RGB (H*W*3 uint8).  Returns 0 on
// success; fills *w/*h.  out may be null to query dimensions only.
int cxr_jpeg_decode(const unsigned char* blob, size_t size,
                    unsigned char* out, size_t out_capacity,
                    int* w, int* h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = jpeg_err_exit;
  if (setjmp(jerr.jb)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(blob),
               static_cast<unsigned long>(size));
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  cinfo.out_color_space = JCS_RGB;
  *w = static_cast<int>(cinfo.image_width);
  *h = static_cast<int>(cinfo.image_height);
  if (out == nullptr) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  size_t need = static_cast<size_t>(*w) * (*h) * 3;
  if (out_capacity < need) {
    jpeg_destroy_decompress(&cinfo);
    return -3;
  }
  jpeg_start_decompress(&cinfo);
  size_t stride = static_cast<size_t>(cinfo.output_width) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = out + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

}  // extern "C"
