// libcxxnetwrapper.so — native C ABI for the TPU-native cxxnet framework.
//
// Mirrors the reference wrapper surface (wrapper/cxxnet_wrapper.h:29-225:
// CXNIO* iterator handles and CXNNet* net handles with identical
// signatures) so existing C/ctypes consumers can rebind.  Architecture is
// inverted relative to the reference: there the C ABI fronted a C++
// trainer; here the trainer is Python/JAX, so this library embeds CPython
// (initializing the interpreter when the host process has none, attaching
// via the GIL when loaded inside one) and forwards every call to the flat
// glue functions in cxxnet_tpu/capi.py.  Returned pointers follow the
// reference contract: they stay valid only until the next call on the same
// handle (the handle owns the backing buffer).
#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef unsigned long cxx_ulong;  // NOLINT
typedef unsigned int cxx_uint;
typedef float cxx_real_t;

namespace {

PyObject* g_capi = nullptr;  // cxxnet_tpu.capi module

// Error model matches the reference wrapper: utils::Error/Check print the
// message and terminate the process (src/utils/utils.h:108-148); callers
// validate inputs before crossing the ABI.
void Fatal(const char* msg) {
  if (PyErr_Occurred()) PyErr_Print();
  std::fprintf(stderr, "[cxxnetwrapper] %s\n", msg);
  std::fflush(stderr);
  std::abort();
}

// Ensure an interpreter exists.  Safe to call from any thread; leaves the
// GIL released.
void EnsurePython() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();  // release GIL acquired by initialization
    }
  });
}

// RAII GIL holder for every ABI entry point.
struct Gil {
  PyGILState_STATE st;
  Gil() { st = PyGILState_Ensure(); }
  ~Gil() { PyGILState_Release(st); }
};

PyObject* Capi() {
  if (g_capi == nullptr) {
    g_capi = PyImport_ImportModule("cxxnet_tpu.capi");
    if (g_capi == nullptr) Fatal("cannot import cxxnet_tpu.capi");
  }
  return g_capi;
}

// Call capi.<fn>(args...); returns a new reference or aborts.
PyObject* Call(const char* fn, PyObject* args) {
  PyObject* f = PyObject_GetAttrString(Capi(), fn);
  if (f == nullptr) Fatal(fn);
  PyObject* res = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (res == nullptr) Fatal(fn);
  return res;
}

PyObject* MemView(const void* ptr, size_t nbytes) {
  PyObject* mv = PyMemoryView_FromMemory(
      reinterpret_cast<char*>(const_cast<void*>(ptr)),
      static_cast<Py_ssize_t>(nbytes), PyBUF_READ);
  if (mv == nullptr) Fatal("memoryview");
  return mv;
}

PyObject* ShapeTuple(const cxx_uint* shape, int ndim) {
  PyObject* t = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    PyTuple_SET_ITEM(t, i, PyLong_FromUnsignedLong(shape[i]));
  }
  return t;
}

size_t NumElems(const cxx_uint* shape, int ndim) {
  size_t n = 1;
  for (int i = 0; i < ndim; ++i) n *= shape[i];
  return n;
}

// Copy a float32 ndarray (via buffer protocol) into `out`; returns the
// shape through oshape/ondim (up to 4 dims, left-padded contract handled
// Python-side).  Consumes the reference to `arr`.
void CopyArray(PyObject* arr, std::vector<float>* out, cxx_uint oshape[4],
               cxx_uint* ondim) {
  Py_buffer view;
  if (PyObject_GetBuffer(arr, &view, PyBUF_C_CONTIGUOUS | PyBUF_FORMAT)
      != 0) {
    Fatal("array buffer");
  }
  if (view.itemsize != sizeof(float)) Fatal("expected float32 array");
  size_t n = static_cast<size_t>(view.len) / sizeof(float);
  out->resize(n);
  std::memcpy(out->data(), view.buf, view.len);
  if (ondim != nullptr) {
    if (view.ndim > 4) Fatal("array rank > 4");
    *ondim = static_cast<cxx_uint>(view.ndim);
    for (int i = 0; i < view.ndim; ++i) {
      oshape[i] = static_cast<cxx_uint>(view.shape[i]);
    }
  }
  PyBuffer_Release(&view);
  Py_DECREF(arr);
}

struct IterHandle {
  PyObject* obj;
  std::vector<float> dbuf, lbuf;
};

struct NetHandle {
  PyObject* obj;
  std::vector<float> buf;
  std::string sbuf;
};

}  // namespace

extern "C" {

// ---- iterator API --------------------------------------------------------

void* CXNIOCreateFromConfig(const char* cfg) {
  EnsurePython();
  Gil gil;
  auto* h = new IterHandle();
  h->obj = Call("io_create", Py_BuildValue("(s)", cfg));
  return h;
}

int CXNIONext(void* handle) {
  Gil gil;
  auto* h = static_cast<IterHandle*>(handle);
  PyObject* r = Call("io_next", Py_BuildValue("(O)", h->obj));
  int ret = static_cast<int>(PyLong_AsLong(r));
  Py_DECREF(r);
  return ret;
}

void CXNIOBeforeFirst(void* handle) {
  Gil gil;
  auto* h = static_cast<IterHandle*>(handle);
  Py_DECREF(Call("io_before_first", Py_BuildValue("(O)", h->obj)));
}

const cxx_real_t* CXNIOGetData(void* handle, cxx_uint oshape[4],
                               cxx_uint* ostride) {
  Gil gil;
  auto* h = static_cast<IterHandle*>(handle);
  PyObject* arr = Call("io_get_data", Py_BuildValue("(O)", h->obj));
  cxx_uint ndim = 0;
  CopyArray(arr, &h->dbuf, oshape, &ndim);
  *ostride = oshape[3];
  return h->dbuf.data();
}

const cxx_real_t* CXNIOGetLabel(void* handle, cxx_uint oshape[2],
                                cxx_uint* ostride) {
  Gil gil;
  auto* h = static_cast<IterHandle*>(handle);
  PyObject* arr = Call("io_get_label", Py_BuildValue("(O)", h->obj));
  cxx_uint shape4[4] = {0, 0, 0, 0};
  cxx_uint ndim = 0;
  CopyArray(arr, &h->lbuf, shape4, &ndim);
  oshape[0] = shape4[0];
  oshape[1] = shape4[1];
  *ostride = shape4[1];
  return h->lbuf.data();
}

void CXNIOFree(void* handle) {
  Gil gil;
  auto* h = static_cast<IterHandle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
}

// ---- net API -------------------------------------------------------------

void* CXNNetCreate(const char* device, const char* cfg) {
  EnsurePython();
  Gil gil;
  auto* h = new NetHandle();
  h->obj = Call("net_create",
                Py_BuildValue("(ss)", device == nullptr ? "" : device, cfg));
  return h;
}

void CXNNetFree(void* handle) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_XDECREF(h->obj);
  delete h;
}

void CXNNetSetParam(void* handle, const char* name, const char* val) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_DECREF(Call("net_set_param", Py_BuildValue("(Oss)", h->obj, name, val)));
}

void CXNNetInitModel(void* handle) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_DECREF(Call("net_init_model", Py_BuildValue("(O)", h->obj)));
}

void CXNNetSaveModel(void* handle, const char* fname) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_DECREF(Call("net_save_model", Py_BuildValue("(Os)", h->obj, fname)));
}

void CXNNetLoadModel(void* handle, const char* fname) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_DECREF(Call("net_load_model", Py_BuildValue("(Os)", h->obj, fname)));
}

void CXNNetStartRound(void* handle, int round) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  Py_DECREF(Call("net_start_round", Py_BuildValue("(Oi)", h->obj, round)));
}

void CXNNetSetWeight(void* handle, cxx_real_t* p_weight,
                     cxx_uint size_weight, const char* layer_name,
                     const char* wtag) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  PyObject* mv = MemView(p_weight, size_weight * sizeof(float));
  Py_DECREF(Call("net_set_weight",
                 Py_BuildValue("(ONIss)", h->obj, mv, size_weight,
                               layer_name, wtag)));
}

const cxx_real_t* CXNNetGetWeight(void* handle, const char* layer_name,
                                  const char* wtag, cxx_uint wshape[4],
                                  cxx_uint* out_dim) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  PyObject* arr = Call("net_get_weight",
                       Py_BuildValue("(Oss)", h->obj, layer_name, wtag));
  if (arr == Py_None) {
    Py_DECREF(arr);
    *out_dim = 0;
    return nullptr;
  }
  CopyArray(arr, &h->buf, wshape, out_dim);
  return h->buf.data();
}

void CXNNetUpdateIter(void* handle, void* data_handle) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  auto* it = static_cast<IterHandle*>(data_handle);
  Py_DECREF(Call("net_update_iter", Py_BuildValue("(OO)", h->obj, it->obj)));
}

void CXNNetUpdateBatch(void* handle, cxx_real_t* p_data,
                       const cxx_uint dshape[4], cxx_real_t* p_label,
                       const cxx_uint lshape[2]) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  PyObject* dmv = MemView(p_data, NumElems(dshape, 4) * sizeof(float));
  PyObject* lmv = MemView(p_label, NumElems(lshape, 2) * sizeof(float));
  Py_DECREF(Call("net_update_batch",
                 Py_BuildValue("(ONNNN)", h->obj, dmv, ShapeTuple(dshape, 4),
                               lmv, ShapeTuple(lshape, 2))));
}

const cxx_real_t* CXNNetPredictBatch(void* handle, cxx_real_t* p_data,
                                     const cxx_uint dshape[4],
                                     cxx_uint* out_size) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  PyObject* dmv = MemView(p_data, NumElems(dshape, 4) * sizeof(float));
  PyObject* arr = Call("net_predict_batch",
                       Py_BuildValue("(ONN)", h->obj, dmv,
                                     ShapeTuple(dshape, 4)));
  cxx_uint shape4[4] = {0, 0, 0, 0};
  cxx_uint ndim = 0;
  CopyArray(arr, &h->buf, shape4, &ndim);
  *out_size = static_cast<cxx_uint>(h->buf.size());
  return h->buf.data();
}

const cxx_real_t* CXNNetPredictIter(void* handle, void* data_handle,
                                    cxx_uint* out_size) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  auto* it = static_cast<IterHandle*>(data_handle);
  PyObject* arr = Call("net_predict_iter",
                       Py_BuildValue("(OO)", h->obj, it->obj));
  cxx_uint shape4[4] = {0, 0, 0, 0};
  cxx_uint ndim = 0;
  CopyArray(arr, &h->buf, shape4, &ndim);
  *out_size = static_cast<cxx_uint>(h->buf.size());
  return h->buf.data();
}

const cxx_real_t* CXNNetExtractBatch(void* handle, cxx_real_t* p_data,
                                     const cxx_uint dshape[4],
                                     const char* node_name,
                                     cxx_uint oshape[4]) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  PyObject* dmv = MemView(p_data, NumElems(dshape, 4) * sizeof(float));
  PyObject* arr = Call("net_extract_batch",
                       Py_BuildValue("(ONNs)", h->obj, dmv,
                                     ShapeTuple(dshape, 4), node_name));
  cxx_uint ndim = 0;
  CopyArray(arr, &h->buf, oshape, &ndim);
  return h->buf.data();
}

const cxx_real_t* CXNNetExtractIter(void* handle, void* data_handle,
                                    const char* node_name,
                                    cxx_uint oshape[4]) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  auto* it = static_cast<IterHandle*>(data_handle);
  PyObject* arr = Call("net_extract_iter",
                       Py_BuildValue("(OOs)", h->obj, it->obj, node_name));
  cxx_uint ndim = 0;
  CopyArray(arr, &h->buf, oshape, &ndim);
  return h->buf.data();
}

const char* CXNNetEvaluate(void* handle, void* data_handle,
                           const char* data_name) {
  Gil gil;
  auto* h = static_cast<NetHandle*>(handle);
  auto* it = static_cast<IterHandle*>(data_handle);
  PyObject* s = Call("net_evaluate",
                     Py_BuildValue("(OOs)", h->obj, it->obj, data_name));
  const char* c = PyUnicode_AsUTF8(s);
  h->sbuf = c == nullptr ? "" : c;
  Py_DECREF(s);
  return h->sbuf.c_str();
}

}  // extern "C"
