// im2bin — native image packer (counterpart of reference tools/im2bin.cpp).
//
// Packs the raw encoded bytes of every image listed in a .lst file
// ("index \t label(s) \t filename" per line) into a stream of 64MB
// BinaryPages (format notes in cxxnet_runtime.cc; byte-compatible with the
// reference utils/io.h:253-326 and cxxnet_tpu.utils.io_stream.BinaryPage).
//
//   im2bin image.lst image_root_dir output.bin

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <fstream>
#include <string>
#include <vector>

namespace {

constexpr size_t kPageInts = 64u << 18;
constexpr size_t kPageBytes = kPageInts * 4;

// Write-side BinaryPage: int32 header at the front (head[0]=count,
// head[1+i]=cumulative sizes), blobs packed backwards from the page end.
struct PageWriter {
  std::vector<char> buf;
  int32_t* head;
  size_t tail;  // byte offset of the lowest packed blob

  PageWriter() : buf(kPageBytes) { Clear(); }

  void Clear() {
    std::memset(buf.data(), 0, kPageBytes);
    head = reinterpret_cast<int32_t*>(buf.data());
    tail = kPageBytes;
  }

  int Count() const { return head[0]; }

  size_t FreeBytes() const {
    size_t header_end = (static_cast<size_t>(Count()) + 2) * 4;
    return tail - header_end;
  }

  bool Push(const std::vector<char>& blob) {
    if (FreeBytes() < blob.size() + 4) return false;
    int n = Count();
    head[n + 2] = head[n + 1] + static_cast<int32_t>(blob.size());
    tail -= blob.size();
    std::memcpy(buf.data() + tail, blob.data(), blob.size());
    head[0] = n + 1;
    return true;
  }

  bool Save(FILE* fo) const {
    return fwrite(buf.data(), 1, kPageBytes, fo) == kPageBytes;
  }
};

// .lst line: "index \t label [label ...] \t filename".  Same rule as the
// Python parser (cxxnet_tpu/io/iter_img.py parse_lst_line): split on tabs
// when that yields >= 3 fields (filename = last field, may hold spaces);
// otherwise fall back to whitespace splitting (filename = last token).
bool ParseLstLine(const std::string& line, std::string* fname) {
  size_t end = line.find_last_not_of(" \t\r\n");
  if (end == std::string::npos) return false;
  size_t begin = line.find_first_not_of(" \t\r\n");
  std::string body = line.substr(begin, end - begin + 1);
  int tab_fields = 1;
  for (char c : body) tab_fields += (c == '\t');
  size_t sep = tab_fields >= 3 ? body.find_last_of('\t')
                               : body.find_last_of(" \t");
  if (sep == std::string::npos || sep + 1 >= body.size()) return false;
  *fname = body.substr(sep + 1);
  return true;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "Usage: im2bin image.lst image_root_dir output_file\n");
    return 1;
  }
  std::string root = argv[2];
  if (!root.empty() && root != "." && root.back() != '/') root += '/';
  if (root == ".") root.clear();

  std::ifstream flst(argv[1]);
  if (!flst) { fprintf(stderr, "cannot open %s\n", argv[1]); return 1; }
  FILE* fo = fopen(argv[3], "wb");
  if (!fo) { fprintf(stderr, "cannot open %s\n", argv[3]); return 1; }

  PageWriter pg;
  long imcnt = 0, pgcnt = 0;
  time_t start = time(nullptr);
  printf("create image binary pack from %s...\n", argv[1]);

  std::string line;
  while (std::getline(flst, line)) {
    std::string fname;
    if (line.find_first_not_of(" \t\r\n") == std::string::npos) continue;
    if (!ParseLstLine(line, &fname)) {
      fprintf(stderr, "malformed .lst line: %s\n", line.c_str());
      return 1;
    }
    std::string path = root + fname;
    FILE* fi = fopen(path.c_str(), "rb");
    if (!fi) { fprintf(stderr, "cannot open image %s\n", path.c_str()); return 1; }
    fseek(fi, 0, SEEK_END);
    long sz = ftell(fi);
    fseek(fi, 0, SEEK_SET);
    std::vector<char> blob(static_cast<size_t>(sz));
    if (fread(blob.data(), 1, blob.size(), fi) != blob.size()) {
      fprintf(stderr, "read error on %s\n", path.c_str());
      return 1;
    }
    fclose(fi);

    if (!pg.Push(blob)) {
      if (!pg.Save(fo)) { fprintf(stderr, "write error\n"); return 1; }
      ++pgcnt;
      pg.Clear();
      if (!pg.Push(blob)) {
        fprintf(stderr, "image %s too large for one page\n", path.c_str());
        return 1;
      }
    }
    if (++imcnt % 1000 == 0) {
      printf("\r[%8ld] images -> %ld pages, %ld sec elapsed", imcnt, pgcnt,
             static_cast<long>(time(nullptr) - start));
      fflush(stdout);
    }
  }
  if (pg.Count() != 0) {
    if (!pg.Save(fo)) { fprintf(stderr, "write error\n"); return 1; }
    ++pgcnt;
  }
  printf("\nfinished: [%8ld] images -> %ld pages, %ld sec elapsed\n", imcnt,
         pgcnt, static_cast<long>(time(nullptr) - start));
  fclose(fo);
  return 0;
}
