"""Test environment: force an 8-device virtual CPU mesh.

Tests validate multi-chip sharding logic without TPU hardware via
``xla_force_host_platform_device_count`` (the driver dry-runs the real
multi-chip path separately through ``__graft_entry__.dryrun_multichip``).

Note: the container's sitecustomize imports jax and registers the TPU
(axon) PJRT plugin before pytest loads this conftest, so setting env vars
alone is not enough — we also update the live jax config before any
backend is initialized by a test.
"""

import gc
import os
import threading
import time

import pytest

os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')


def pytest_configure(config):
    config.addinivalue_line('markers',
                            'slow: long-running end-to-end tests')
    config.addinivalue_line(
        'markers',
        'faults: deterministic fault-injection / recovery suite '
        '(seeded, tier-1: runs under -m "not slow"; select with -m faults)')
    config.addinivalue_line(
        'markers',
        'serve: online inference serving suite — engine/batcher/registry, '
        'CPU-only, no network, in-process client threads '
        '(tier-1: runs under -m "not slow"; select with -m serve)')
    config.addinivalue_line(
        'markers',
        'async_ckpt: asynchronous checkpointing suite — snapshot/writer/'
        'double-buffer/barrier semantics, CPU-only, deterministic '
        '(tier-1: runs under -m "not slow"; select with -m async_ckpt)')
    config.addinivalue_line(
        'markers',
        'io_perf: parallel input pipeline + scanned step-loop dispatch '
        'suite — worker-pool determinism, thread lifecycle, '
        'steps_per_dispatch bitwise equality; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m io_perf)')
    config.addinivalue_line(
        'markers',
        'serve_decode: continuous-batching decode suite — paged KV '
        'cache, slot join/leave, offline-generate stream twins, '
        'multi-model budgeter; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m serve_decode)')
    config.addinivalue_line(
        'markers',
        'online: train-while-serve suite — streaming imgbin source, '
        'freshness SLO, hot-swap-under-traffic pipeline, chaos drill; '
        'CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m online)')
    config.addinivalue_line(
        'markers',
        'lint: graftlint static-analysis suite — the five AST invariant '
        'checkers over seeded fixtures AND the live codebase, plus the '
        'shrink-only baseline ratchet; pure host code, no device '
        '(tier-1: runs under -m "not slow"; select with -m lint)')
    config.addinivalue_line(
        'markers',
        'execution: ExecutionPlan / composable step-loop suite — '
        'scanned K-dispatch composed with update_period, train metrics, '
        'supervision and chaos recovery, bitwise twins + demotion-matrix '
        'drift; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m execution)')
    config.addinivalue_line(
        'markers',
        'quant: quantized-inference tier suite — int8/bf16 storage, '
        'W8A8 qdot Pallas-vs-XLA bitwise twin, PredictEngine/DecodeEngine '
        'exact + pinned-tolerance twins vs f32; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m quant)')
    config.addinivalue_line(
        'markers',
        'serve_spec: prefix-shared paged KV cache + greedy speculative '
        'decoding suite — content-addressed prefix index, refcounted '
        'pages, CoW, tail prefill bitwise twins, verify-window '
        'token-equality, draft hot-swap; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m serve_spec)')
    config.addinivalue_line(
        'markers',
        'obs: graftscope telemetry suite — hub registration, span '
        'nesting + trace-id propagation, flight-recorder ring + '
        'fault-triggered dumps, Prometheus/statusz endpoints, Chrome '
        'trace export; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m obs)')
    config.addinivalue_line(
        'markers',
        'slo: graftwatch SLO suite — gauge-history rings + sampler, '
        'the slo.<name>= grammar, multi-window burn-rate verdicts '
        '(OK/AT_RISK/BREACHED), freshness-through-the-engine '
        'equivalence, /slos + degraded /healthz endpoints, '
        'breach-triggered postmortems, fleet scrape/merge units; '
        'CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m slo)')
    config.addinivalue_line(
        'markers',
        'scenario: graftstorm suite — seeded adversarial traffic '
        'scenarios (diurnal/flash/heavy-tail/tenants/abandonment), '
        'exactly-reconciling scenario ledger, SLO-driven autoscaler '
        'hysteresis/degradation, live-cap shrink safety under '
        'refcounted prefix pages; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m scenario)')
    config.addinivalue_line(
        'markers',
        'dist: elastic multi-host training suite — coordinator/client '
        'membership, host-sharded stream bitwise twins, and the '
        'multi-process chaos drills (real worker subprocesses over '
        'localhost; host_loss/partition recovery bitwise-equal to '
        'fault-free twins); CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m dist)')
    config.addinivalue_line(
        'markers',
        'shard: graftshard suite — mesh-sharded decode serving '
        '(serve.shard=tp:N head-sharded params + KV pool, bitwise '
        'stream twins at every shard count), disaggregated prefill '
        'workers, data-parallel PredictEngine replicas, per-device '
        'budgeter/gauge reconciliation; CPU-only (8 virtual devices; '
        'tier-1: runs under -m "not slow"; select with -m shard)')
    config.addinivalue_line(
        'markers',
        'kv_tier: graftcache suite — tiered KV prefix cache (HBM page '
        'pool -> bounded host RAM -> crc32-digested disk records), '
        'demote/promote bitwise stream twins, LRU + byte-budget '
        'enforcement, cross-replica share-dir adopt, corrupt-record '
        'quarantine drills; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m kv_tier)')
    config.addinivalue_line(
        'markers',
        'tune: grafttune autotuner suite — autotune= grammar '
        'round-trips, ledger-gated stage-1 pruning, seeded measured '
        'probes with byte-deterministic tuned_<task>.conf artifacts, '
        'tuned-vs-hand-written bitwise twins, online TuneController '
        're-plan bounds + recompile-storm guard drill; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m tune)')
    config.addinivalue_line(
        'markers',
        'cnn_fused: graftfuse suite — fused Pallas conv+bias+act '
        'blocks (interpret-mode bitwise/pinned-tolerance twins vs the '
        'XLA composition, fwd+grad, every stride/pad/group leg), '
        'inference conv+BN folding through a real PredictEngine '
        '(hot-swap re-fold + double-fold identity guard), μ-cuDNN '
        'conv microbatching bitwise at every declared split with '
        'ledger peak-bytes bounds; CPU-only '
        '(tier-1: runs under -m "not slow"; select with -m cnn_fused)')


# every pipeline thread the framework starts carries a cxxnet- name
# prefix (utils/thread_buffer.py producers, utils/parallel_pool.py
# workers, serve/decode.py loop threads, parallel/elastic.py
# coordinator/heartbeat threads) precisely so this fixture can hold the
# line on lifecycle
_PIPELINE_THREAD_PREFIXES = ('cxxnet-tb-', 'cxxnet-pool-', 'cxxnet-decode-',
                             'cxxnet-elastic-', 'cxxnet-obs-',
                             'cxxnet-scale-', 'cxxnet-kv-',
                             'cxxnet-prefill-', 'cxxnet-replica-',
                             'cxxnet-tune-')


def _pipeline_threads():
    return {t for t in threading.enumerate()
            if t.is_alive() and t.name.startswith(_PIPELINE_THREAD_PREFIXES)}


@pytest.fixture(autouse=True)
def _no_pipeline_thread_leaks():
    """No stray ThreadBuffer producer / pool worker survives a test.

    Abandoned iterator generators retire their threads from the
    generator's ``finally`` (ThreadBuffer stop event, pool sentinel
    drain), which on CPython fires at refcount-zero — so the check
    collects garbage and grants a grace window before calling leak."""
    before = _pipeline_threads()
    yield
    deadline = time.time() + 5.0
    while True:
        leaked = _pipeline_threads() - before
        if not leaked:
            return
        # only pay a full collection when a candidate leak exists — an
        # abandoned generator's finally (which retires its threads) may
        # just not have run yet
        gc.collect()
        leaked = _pipeline_threads() - before
        if not leaked:
            return
        if time.time() > deadline:
            pytest.fail(
                'pipeline threads leaked past the test: '
                f'{sorted(t.name for t in leaked)} — close() the '
                'ThreadBuffer/iterator or let its generator be collected')
        time.sleep(0.05)
