"""Test environment: force an 8-device virtual CPU mesh.

Tests validate multi-chip sharding logic without TPU hardware via
``xla_force_host_platform_device_count`` (the driver dry-runs the real
multi-chip path separately through ``__graft_entry__.dryrun_multichip``).

Note: the container's sitecustomize imports jax and registers the TPU
(axon) PJRT plugin before pytest loads this conftest, so setting env vars
alone is not enough — we also update the live jax config before any
backend is initialized by a test.
"""

import os

os.environ['JAX_PLATFORMS'] = 'cpu'
flags = os.environ.get('XLA_FLAGS', '')
if 'xla_force_host_platform_device_count' not in flags:
    os.environ['XLA_FLAGS'] = (
        flags + ' --xla_force_host_platform_device_count=8').strip()

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')


def pytest_configure(config):
    config.addinivalue_line('markers',
                            'slow: long-running end-to-end tests')
    config.addinivalue_line(
        'markers',
        'faults: deterministic fault-injection / recovery suite '
        '(seeded, tier-1: runs under -m "not slow"; select with -m faults)')
    config.addinivalue_line(
        'markers',
        'serve: online inference serving suite — engine/batcher/registry, '
        'CPU-only, no network, in-process client threads '
        '(tier-1: runs under -m "not slow"; select with -m serve)')
    config.addinivalue_line(
        'markers',
        'async_ckpt: asynchronous checkpointing suite — snapshot/writer/'
        'double-buffer/barrier semantics, CPU-only, deterministic '
        '(tier-1: runs under -m "not slow"; select with -m async_ckpt)')
