"""Clean twin of clock_wall.py: monotonic deadline arithmetic, plus
one allowed wall-clock use for a calendar stamp."""

import time


def wait_until(flag, timeout):
    end = time.monotonic() + timeout
    while not flag.is_set():
        if time.monotonic() > end:
            return False
    return True


def receipt_stamp():
    # a calendar timestamp on a receipt is the ONE lawful wall-clock use
    return time.time()  # lint: allow(monotonic-clock): calendar stamp for the receipt ledger
