"""Seeded violation: a deadline computed from wall-clock time.
Twin: clock_clean.py."""

import time


def wait_until(flag, timeout):
    end = time.time() + timeout
    while not flag.is_set():
        if time.time() > end:
            return False
    return True
