"""Clean twin of config_undocumented.py: every parsed key has a row or
backtick mention in config_doc.md."""


class Task:
    def set_param(self, name, val):
        simple = {
            'num_round': ('num_round', int),
            'model_dir': ('model_dir', str),
        }
        if name in simple:
            attr, typ = simple[name]
            setattr(self, attr, typ(val))
        if name == 'data':
            self.section = val
