"""Seeded violation: ``set_param`` parses a key (``io.mystery``) that
the fixture doc table (config_doc.md) never mentions — config-key
drift.  Twin: config_clean.py."""


class Task:
    def set_param(self, name, val):
        simple = {
            'num_round': ('num_round', int),
            'model_dir': ('model_dir', str),
            'io.mystery': ('mystery', int),
        }
        if name in simple:
            attr, typ = simple[name]
            setattr(self, attr, typ(val))
        if name == 'data':
            self.section = val
