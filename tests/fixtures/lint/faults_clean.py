"""Clean twin of faults_raw_raise.py: typed taxonomy raise, ValueError
argument validation, a broad except that routes to the FailureLog, and
one deliberate swallow carrying an explicit allow."""

from cxxnet_tpu.runtime import faults

log = faults.global_failure_log()


def serve_one(req):
    if req is None:
        raise ValueError('req must not be None')
    if req.expired:
        raise faults.DeadlineExceededError(1.0, 2.0, 1)
    try:
        return req.run()
    except Exception as e:           # watcher must outlive bad cycles
        log.record('serve_error', f'{e!r}')
        return None


def probe(req):
    try:
        return req.run()
    except Exception:  # lint: allow(fault-taxonomy): capability probe; absence is the signal
        return None
