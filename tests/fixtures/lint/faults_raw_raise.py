"""Seeded violations: a raw RuntimeError raise in serve-scoped code and
a broad ``except Exception`` that swallows without routing to the
FailureLog.  Twin: faults_clean.py."""


def serve_one(req):
    if req is None:
        raise RuntimeError('no request')     # untyped: invisible to policy
    try:
        return req.run()
    except Exception:
        return None                          # swallowed, unrouted
