"""Clean twin of history_unguarded.py: the ring declares its guard and
both the sampler thread and the public reader hold it — the shape
obs/history.py ships."""

import threading
import time


class HistoryPump:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.ring = []                   # guarded-by: _lock
        self._thread = threading.Thread(target=self._sample, daemon=True)

    def _sample(self):
        while not self._stop.wait(0.05):
            with self._lock:
                self.ring = (self.ring
                             + [(time.monotonic(), 1.0)])[-256:]

    def window(self):
        with self._lock:
            return list(self.ring)
