"""Seeded violation: a sampler thread rebinds a bounded history ring
that a public window() reader walks, with no guard declared and no
lock held — the torn-ring regression class the lock-discipline checker
must catch on graftwatch-shaped code (a reader can observe the list
mid-rebind and lose the tail).  Twin: history_clean.py."""

import threading
import time


class HistoryPump:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.ring = []
        self._thread = threading.Thread(target=self._sample, daemon=True)

    def _sample(self):
        while not self._stop.wait(0.05):
            # worker write, no lock: rebind-to-bound loses the race
            self.ring = (self.ring + [(time.monotonic(), 1.0)])[-256:]

    def window(self):
        return list(self.ring)           # public read, no lock
