"""Seeded violation: direct ``jax.jit`` call sites in a trainer/serve
module — four spellings (call, decorator-factory via partial, aliased
from-import, bare decorator), all invisible to the program ledger.
Twin: jit_ledger_clean.py."""

from functools import partial

import jax
from jax import jit as jjit


def build_forward(net):
    # plain call spelling
    return jax.jit(lambda p, x: net(p, x))


@partial(jax.jit, static_argnames=('k',))
def windowed(x, k):
    # decorator-factory spelling
    return x * k


def build_step():
    # aliased from-import spelling
    return jjit(lambda x: x + 1)


@jax.jit
def forward_step(params, data):
    # bare decorator spelling — an ast.Attribute, not a Call
    return params @ data
