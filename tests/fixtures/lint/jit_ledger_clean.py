"""Clean twin of jit_ledger_caught.py: the same programs routed
through the ProgramLedger wrap (the /programs + sentinel surface),
plus one lawful allowed direct jit for a trivial restage helper."""

import jax

from cxxnet_tpu.obs.programs import get_ledger


def build_forward(net, buckets):
    prog = get_ledger().program('serve.predict', bound=len(buckets))
    return prog.jit(lambda p, x: net(p, x),
                    key_fn=lambda a, _k: f'b{a[1].shape[0]}')


def build_step():
    prog = get_ledger().program('decode.step', bound=1)
    return prog.jit(lambda x: x + 1, fixed=True)


def build_stacker():
    # a two-op device-side restage: nothing a ledger row would say
    return jax.jit(lambda *xs: jax.numpy.stack(xs))  # lint: allow(jit-ledger): trivial restage helper, no flops worth a row
