"""Clean twin of lock_unguarded.py: the counter declares its guard and
every access holds it; a caller-holds-the-lock helper carries the
``# requires-lock:`` annotation."""

import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0               # guarded-by: _lock
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _bump(self):                 # requires-lock: _lock
        self.count += 1

    def _run(self):
        while not self._stop.wait(0.01):
            with self._lock:
                self._bump()

    def progress(self):
        with self._lock:
            return self.count
