"""Clean twin of lock_order_inverted.py: both paths acquire the locks
in the same global order, so the acquisition graph stays acyclic."""

import threading


class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0                   # guarded-by: _alock
        self.b = 0                   # guarded-by: _block

    def a_to_b(self):
        with self._alock:
            with self._block:
                self.b += self.a

    def b_to_a(self):
        with self._alock:
            with self._block:
                self.a -= self.b
