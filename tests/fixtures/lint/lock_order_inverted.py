"""Seeded violation: two code paths acquire the same pair of locks in
opposite orders — the classic ABBA deadlock.  Twin: lock_order_clean.py."""

import threading


class Transfer:
    def __init__(self):
        self._alock = threading.Lock()
        self._block = threading.Lock()
        self.a = 0                   # guarded-by: _alock
        self.b = 0                   # guarded-by: _block

    def a_to_b(self):
        with self._alock:
            with self._block:
                self.b += self.a

    def b_to_a(self):
        with self._block:
            with self._alock:
                self.a += self.b
