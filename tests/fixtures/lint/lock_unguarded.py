"""Seeded violation: the worker thread writes a counter that public
methods read, with no guard declared and no lock held — the exact
'unguarded counter' regression class the lock-discipline checker
exists to catch.  Twin: lock_clean.py."""

import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while not self._stop.wait(0.01):
            self.count += 1          # worker write, no lock

    def progress(self):
        return self.count            # public read, no lock
