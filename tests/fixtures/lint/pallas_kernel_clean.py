"""Clean twin of pallas_kernel_sync.py: the same two kernel shapes with
the host work done right — scalars stay refs, constants bind at build
time on the host side, every op in the body is traced jnp."""
import functools

import jax.numpy as jnp
from jax.experimental import pallas as pl


def _scale_kernel(x_ref, peak_ref, o_ref):
    o_ref[:] = x_ref[:] * peak_ref[0]


def _stamp_kernel(x_ref, o_ref, *, gain):
    o_ref[:] = x_ref[:] * gain


def scale(x, peak):
    return pl.pallas_call(_scale_kernel, out_shape=x)(x, peak)


def stamp(x, gain):
    kernel = functools.partial(_stamp_kernel, gain=float(gain))
    return pl.pallas_call(kernel, out_shape=x)(x)
