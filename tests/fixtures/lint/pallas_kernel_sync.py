"""Seeded violation twin: host syncs inside Pallas kernel bodies.

Two kernels, one per resolution path the checker must handle — a kernel
handed to ``pl.pallas_call`` by NAME, and one wrapped in a local
``functools.partial`` assignment first (the kernel modules' idiom).
A host sync in a kernel body "works" under ``interpret=True`` on CPU and
breaks Mosaic compilation on real hardware, which is exactly why the
rule exists.
"""
import functools
import time

import jax.numpy as jnp
from jax.experimental import pallas as pl


def _scale_kernel(x_ref, o_ref):
    peak = float(x_ref[0, 0])          # BAD: device->host sync
    o_ref[:] = x_ref[:] * peak


def _stamp_kernel(x_ref, o_ref, *, gain):
    # BAD: wall clock baked in at trace time
    o_ref[:] = x_ref[:] * gain * time.monotonic()


def scale(x):
    return pl.pallas_call(_scale_kernel, out_shape=x)(x)


def stamp(x, gain):
    kernel = functools.partial(_stamp_kernel, gain=gain)
    return pl.pallas_call(kernel, out_shape=x)(x)
