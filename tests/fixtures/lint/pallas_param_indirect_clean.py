"""Clean twin of pallas_param_indirect_sync.py: the same forwarding
helpers with kernels that keep every op traced — AND a host-side builder
whose ``float()`` must NOT be flagged just because it calls a helper
(only the argument matching the forwarded parameter is traced)."""
import functools

import jax.numpy as jnp
from jax.experimental import pallas as pl


def _call(kernel, x):
    return pl.pallas_call(kernel, out_shape=x)(x)


def _call_kw(x, kernel=None):
    return pl.pallas_call(functools.partial(kernel), out_shape=x)(x)


def _scale_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] * 2.0


def _gain_kernel(x_ref, o_ref):
    o_ref[:] = x_ref[:] + jnp.float32(1.0)


def scale(x, gain):
    gain = float(gain)                 # host code: gain is a host scalar
    return _call(_scale_kernel, x) * gain


def stamp(x):
    return _call_kw(x, kernel=_gain_kernel)
