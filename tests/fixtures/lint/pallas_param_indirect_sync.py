"""Seeded violation twin: a kernel reaching ``pallas_call`` through a
helper's PARAMETER — the ``_lrn_call(kernel, ...)`` indirection that was
this rule's documented soundness hole.  The helper itself is clean; the
violation lives in the kernel body the caller hands it, positionally in
one case and by keyword (through a ``partial`` wrapper) in the other.
"""
import functools
import time

import jax.numpy as jnp
from jax.experimental import pallas as pl


def _call(kernel, x):
    # clean forwarding helper: the parameter lands in pallas_call's
    # function position, so the CALLER's argument is the traced body
    return pl.pallas_call(kernel, out_shape=x)(x)


def _call_kw(x, kernel=None):
    # keyword-passed kernel, forwarded through an inline partial
    return pl.pallas_call(functools.partial(kernel), out_shape=x)(x)


def _sync_kernel(x_ref, o_ref):
    peak = float(x_ref[0, 0])          # BAD: device->host sync
    o_ref[:] = x_ref[:] * peak


def _clock_kernel(x_ref, o_ref):
    # BAD: wall clock baked in at trace time
    o_ref[:] = x_ref[:] * time.monotonic()


def scale(x):
    return _call(_sync_kernel, x)


def stamp(x):
    return _call_kw(x, kernel=_clock_kernel)
