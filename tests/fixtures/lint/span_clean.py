"""Fixture twin: span-hygiene-clean instrumentation — spans bracket
the dispatch from the host side, context-manager (or decorator) form
only."""
from jax import lax

from cxxnet_tpu.obs import span


def _body(c, x):
    return c + x, x


def dispatch(xs, scan_fn):
    with span('train.dispatch', 'train', k=4):
        return scan_fn(xs)


@span('train.round', 'train')
def round_loop(xs):
    return lax.scan(_body, 0, xs)
