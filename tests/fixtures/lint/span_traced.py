"""Fixture: span-hygiene violations — a span inside a scanned body
(host sync in the trace) and a manually-entered span (leaks on any
exception before the end)."""
from jax import lax

from cxxnet_tpu.obs import span


def train(xs):
    def body(c, x):
        with span('bad.step', 'train'):     # inside the lax.scan trace
            return c + x, x
    return lax.scan(body, 0, xs)


def manual_begin(h):
    s = span('leaky', 'io')                 # no `with`: manual begin
    s.__enter__()
    return s
