"""Clean twin of tracer_item.py: device arithmetic stays on device, the
host-side timestamp lives OUTSIDE the traced function, and numpy is
used only on untraced host code."""

import time

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def good_step(x, scale):
    return x * scale


def scan_loss(xs):
    def body(carry, x):
        return carry + x, x

    return jax.lax.scan(body, jnp.float32(0), xs)


def drive(xs):
    t0 = time.monotonic()            # host code: fine
    out, _ = scan_loss(jnp.asarray(np.asarray(xs)))
    return out, time.monotonic() - t0
