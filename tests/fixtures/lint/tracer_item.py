"""Seeded violations: a ``.item()`` host sync inside a scanned body, a
``float()`` sync plus wall-clock nondeterminism inside a jitted
function, and a print of a traced value.  Twin: tracer_clean.py."""

import time

import jax
import jax.numpy as jnp


@jax.jit
def bad_step(x):
    t = time.time()                  # trace-time constant
    print(x)                         # host sync + retrace
    return x * float(t)              # host sync


def scan_loss(xs):
    def body(carry, x):
        carry = carry + x.item()     # host sync inside the scan
        return carry, x

    return jax.lax.scan(body, jnp.float32(0), xs)
