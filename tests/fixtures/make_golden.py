"""Generate the committed golden checkpoint fixtures.

Run from the repo root (CPU):

    JAX_PLATFORMS=cpu python tests/fixtures/make_golden.py

``golden_v2`` covers every risky branch of ``checkpoint.to_disk_layout`` /
``from_disk_layout``: grouped-conv im2col round-trip, batch_norm and prelu
tensor-only records, the no_bias fullc zero bias slot, and a ``share[tag]``
net (shared layers must not duplicate their record in the blob).  The
fixture bytes are generated ONCE and committed; the stability test only
loads them — regenerating after a format change defeats the guarantee.
"""

import os
import struct
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), '..', '..'))

from cxxnet_tpu.nnet.trainer import NetTrainer                  # noqa: E402
from cxxnet_tpu.io.data import DataBatch                        # noqa: E402
from cxxnet_tpu.utils.config import parse_config_string         # noqa: E402

GOLDEN_V2_CONF = """
netconfig = start
layer[0->1] = conv:c1
  nchannel = 8
  kernel_size = 3
  ngroup = 2
  pad = 1
layer[1->2] = batch_norm:bn1
layer[2->3] = prelu:pr1
layer[3->4] = max_pooling
  kernel_size = 2
  stride = 2
layer[4->5] = flatten
layer[5->6] = fullc:fs
  nhidden = 128
layer[6->7] = sigmoid
layer[7->8] = share[fs]
layer[8->9] = fullc:out
  nhidden = 3
  no_bias = 1
layer[9->9] = softmax
netconfig = end
input_shape = 4,8,8
batch_size = 4
dev = cpu
seed = 11
"""


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    tr = NetTrainer(parse_config_string(GOLDEN_V2_CONF))
    tr.init_model()
    tr.epoch_counter = 7
    with open(os.path.join(here, 'golden_v2.model'), 'wb') as f:
        f.write(struct.pack('<i', 0))        # net_type prefix
        tr.save_model(f)
    rng = np.random.RandomState(3)
    x = rng.rand(4, 4, 8, 8).astype(np.float32)
    np.save(os.path.join(here, 'golden_v2_input.npy'), x)
    batch = DataBatch(x, np.zeros((4, 1), np.float32))
    pred = tr.predict(batch)
    np.save(os.path.join(here, 'golden_v2_pred.npy'), pred)
    # raw softmax scores: catches weight-layout scrambles that happen to
    # preserve the argmax
    scores = tr.extract_feature(batch, 'top[-1]')
    np.save(os.path.join(here, 'golden_v2_scores.npy'), scores)
    w = np.asarray(tr.params['0']['wmat'])
    print('conv wmat shape', w.shape, 'sum', repr(float(w.sum())))
    print('pred', pred)
    print('scores[0]', scores.reshape(4, -1)[0])


if __name__ == '__main__':
    main()
