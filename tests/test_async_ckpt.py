"""Asynchronous checkpointing (``runtime/async_ckpt.py``): the save path
off the step loop.

The headline contracts, each proven deterministically on CPU:

* an async-written checkpoint restores **bitwise-identical** to a sync
  twin saved at the same step — and training continued from either stays
  bitwise-identical;
* a fault injected into the background writer (``raise_on_write`` firing
  on the writer thread) never corrupts or removes the previous good
  checkpoint, and surfaces through the ``FailureLog`` + the next barrier;
* double-buffering: at most one save in flight — a second submit blocks
  until the previous commit lands, never mid-step;
* the supervisor resolves the NaN-streak "never save a poisoned
  checkpoint" gate at SNAPSHOT time, so deferred writes cannot launder a
  poisoned tree into the newest restore target;
* supervisor restore barriers on a pending save (the mid-commit newest
  step is restored, not skipped).

Select with ``-m async_ckpt``; tier-1 (runs under ``-m "not slow"``).
"""

import os
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.nnet import sharded_ckpt
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.runtime import async_ckpt, faults
from cxxnet_tpu.runtime.async_ckpt import AsyncCheckpointer
from cxxnet_tpu.runtime.supervisor import SupervisorConfig, TrainSupervisor
from cxxnet_tpu.utils.config import (ConfigError, cfg_get_int,
                                     parse_config_string)

from test_device_normalize import assert_params_equal, snap_params
from test_net_mnist import MLP_CONF, synth_batches

pytestmark = pytest.mark.async_ckpt

NO_WAIT = faults.NO_WAIT_RETRY
ONE_SHOT = faults.RetryPolicy(max_attempts=1, base_delay=0.0, jitter=0.0,
                              sleep=lambda _t: None)


@pytest.fixture(autouse=True)
def _clean_plan():
    prev = faults.install_plan(None)
    yield
    faults.install_plan(prev)


def _fresh(extra=''):
    tr = NetTrainer(parse_config_string(MLP_CONF + extra))
    tr.init_model()
    return tr


def _sup_config(**kw):
    base = dict(batch_deadline=0.3, max_restarts=3, nan_breaker=0,
                save_every=2, buffer_size=2, retry=NO_WAIT,
                save_async=1, save_workers=3)
    base.update(kw)
    return SupervisorConfig(**base)


# --- snapshot semantics ---------------------------------------------------

def test_snapshot_survives_donating_steps():
    """The compiled train step donates params/opt_state/grad_acc; a
    snapshot taken at a boundary must keep its values through later
    updates (fresh buffers, not aliases of the donated ones)."""
    tr = _fresh()
    batches = synth_batches(n_batches=4)
    tr.update(batches[0])
    snap = tr.snapshot_training_state()
    want = [np.array(x) for x in
            [np.asarray(v) for v in _leaves(snap['params'])]]
    for b in batches[1:]:
        tr.update(b)                      # donates the live buffers
    got = [np.asarray(v) for v in _leaves(snap['params'])]
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    assert int(snap['counters']['sample']) == 1


def _leaves(tree):
    import jax
    return jax.tree.leaves(tree)


# --- native format --------------------------------------------------------

def test_native_roundtrip_packed_and_typed(tmp_path):
    """Small leaves pack into one blob; dtypes (f32/int64, scalar and
    shaped) survive; the digest sidecar verifies; restore is bitwise."""
    import jax.numpy as jnp
    tree = {'w': jnp.arange(8, dtype=jnp.float32),
            'big': jnp.asarray(
                np.random.RandomState(0).randn(512, 200), jnp.float32),
            'c': {'step': np.asarray(3, np.int64),
                  'vec': np.arange(5, dtype=np.int64)}}
    path = sharded_ckpt.save_tree_native(str(tmp_path / 'ck'), 1, tree,
                                         retry=NO_WAIT)
    assert sharded_ckpt.verify_step_dir(path) is None
    names = set(os.listdir(path))
    assert 'tree_manifest.json' in names and 'ckpt_digest.json' in names
    assert 'packed_leaves.bin' in names        # small leaves coalesced
    got, step = sharded_ckpt.restore_sharded(str(tmp_path / 'ck'), tree,
                                             retry=NO_WAIT)
    assert step == 1
    for a, b in zip(_leaves(tree), _leaves(got)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert np.asarray(a).dtype == np.asarray(b).dtype


def test_native_digest_detects_truncation(tmp_path):
    import jax.numpy as jnp
    tree = {'big': jnp.asarray(
        np.random.RandomState(0).randn(512, 600), jnp.float32)}
    path = sharded_ckpt.save_tree_native(str(tmp_path / 'ck'), 1, tree,
                                         retry=NO_WAIT)
    victim = max((os.path.join(path, f) for f in os.listdir(path)
                  if f not in ('ckpt_digest.json',)), key=os.path.getsize)
    with open(victim, 'r+b') as f:
        f.truncate(os.path.getsize(victim) // 2)
    assert sharded_ckpt.verify_step_dir(path) is not None


# --- the acceptance pair: bitwise twin + writer-fault isolation -----------

def test_async_restore_bitwise_identical_to_sync_twin(tmp_path):
    """Acceptance: restore from an async-written checkpoint ==(bitwise)
    restore from the same step saved synchronously — both immediately
    and after continuing training from each."""
    batches = synth_batches(n_batches=6)
    tr = _fresh()
    for b in batches[:3]:
        tr.update(b)
    tr.save_training_state(str(tmp_path / 'sync'), 3)         # sync twin
    ck = AsyncCheckpointer(workers=3)
    ck.save_sharded_async(str(tmp_path / 'async'), 3,
                          tr.snapshot_training_state(), retry=NO_WAIT)
    for b in batches[3:]:
        tr.update(b)          # write overlaps live (donating) training
    ck.wait()
    ck.close()

    t_sync, t_async = _fresh(), _fresh()
    assert t_sync.load_training_state(str(tmp_path / 'sync'),
                                      restore_params=True) == 3
    assert t_async.load_training_state(str(tmp_path / 'async'),
                                       restore_params=True) == 3
    assert_params_equal(snap_params(t_async), snap_params(t_sync),
                        rtol=0, atol=0)
    assert (t_async.epoch_counter, t_async.sample_counter) == \
        (t_sync.epoch_counter, t_sync.sample_counter)
    for b in batches[3:]:
        t_sync.update(b)
        t_async.update(b)
    assert_params_equal(snap_params(t_async), snap_params(t_sync),
                        rtol=0, atol=0)


def test_writer_fault_preserves_previous_checkpoint(tmp_path):
    """Crash-consistency: kill the background writer mid-flight
    (``raise_on_write`` fires on the WRITER thread, retry budget 1) —
    the failed step never appears, no temp litter survives, the fault is
    in the failure log, the deferred error surfaces at the next barrier,
    and the PREVIOUS checkpoint still verifies and restores bitwise."""
    d = str(tmp_path / 'ck')
    batches = synth_batches(n_batches=4)
    tr = _fresh()
    tr.update(batches[0])
    good = snap_params(tr)
    log = faults.FailureLog()
    ck = AsyncCheckpointer(workers=2, failure_log=log)
    ck.save_sharded_async(d, 1, tr.snapshot_training_state(),
                          retry=ONE_SHOT)
    ck.wait()                                        # good step committed

    # the plan is installed after the good save, so its process-wide
    # write counter starts here: write #1 is the step-2 attempt
    faults.install_plan(faults.FaultPlan(raise_on_write=(1,)))
    tr.update(batches[1])
    ck.save_sharded_async(d, 2, tr.snapshot_training_state(),
                          retry=ONE_SHOT)
    with pytest.raises(faults.RetryError):
        ck.wait()                                    # deferred error
    assert len(log.records('async_save_failed')) == 1
    assert sharded_ckpt.all_steps(d) == [1]          # step 2 never appears
    litter = [n for n in os.listdir(d) if '.tmp.' in n]
    assert litter == []
    path1 = sharded_ckpt.step_dir(d, 1)
    assert sharded_ckpt.verify_step_dir(path1) is None
    t2 = _fresh()
    assert t2.load_training_state(d, restore_params=True,
                                  fallback=True) == 1
    assert_params_equal(snap_params(t2), good, rtol=0, atol=0)
    ck.close()


def test_injected_writer_fault_rides_retry_and_recovers(tmp_path):
    """Same injection, default-style retry budget: the writer's retry
    absorbs the one-shot fault — the save commits, nothing raises (the
    sync path's recovery semantics, on the background thread)."""
    d = str(tmp_path / 'ck')
    tr = _fresh()
    tr.update(synth_batches(n_batches=1)[0])
    plan = faults.FaultPlan(raise_on_write=(1,))
    faults.install_plan(plan)
    ck = AsyncCheckpointer(workers=2)
    ck.save_sharded_async(d, 1, tr.snapshot_training_state(),
                          retry=NO_WAIT)
    ck.wait()
    assert plan.fired() == ['raise_on_write=1']
    assert sharded_ckpt.all_steps(d) == [1]
    assert sharded_ckpt.verify_step_dir(sharded_ckpt.step_dir(d, 1)) is None
    ck.close()


def test_corrupt_shard_fires_in_writer_and_falls_back(tmp_path):
    """``corrupt_shard`` fires AFTER the background commit (same hook as
    the sync path): the corrupted async step must fail verification and
    ``restore_resilient`` must quarantine it and fall back."""
    d = str(tmp_path / 'ck')
    tr = _fresh()
    batches = synth_batches(n_batches=2)
    tr.update(batches[0])
    ck = AsyncCheckpointer(workers=2)
    ck.save_sharded_async(d, 1, tr.snapshot_training_state(),
                          retry=NO_WAIT)
    ck.wait()
    good = snap_params(tr)
    plan = faults.FaultPlan(seed=5, corrupt_shard=(2,))
    faults.install_plan(plan)
    tr.update(batches[1])
    ck.save_sharded_async(d, 2, tr.snapshot_training_state(),
                          retry=NO_WAIT)
    ck.wait()
    ck.close()
    assert plan.fired() == ['corrupt_shard=2']
    t2 = _fresh()
    assert t2.load_training_state(d, restore_params=True,
                                  fallback=True) == 1
    assert_params_equal(snap_params(t2), good, rtol=0, atol=0)
    assert os.path.isdir(os.path.join(d, 'step_2.corrupt'))


# --- double buffering -----------------------------------------------------

def test_double_buffer_blocks_second_submit_until_commit():
    """At most one save in flight: submit #2 returns only after #1's
    write committed (event-gated, no timing races)."""
    ck = AsyncCheckpointer(workers=2)
    gate = threading.Event()
    done = []

    def slow():
        gate.wait(5.0)
        done.append('first')

    ck.submit(slow, label='first')
    assert ck.pending()
    releaser = threading.Timer(0.2, gate.set)
    releaser.start()
    ck.submit(lambda: done.append('second'), label='second')
    # the second submit could only return after the first committed —
    # but 'second' may already be running on the committer, so assert
    # ORDER, not absence
    assert done[0] == 'first'
    assert ck.in_flight() <= 1
    ck.wait()
    assert done == ['first', 'second']
    releaser.cancel()
    ck.close()


def test_submit_resurfaces_previous_failure_then_recovers():
    log = faults.FailureLog()
    ck = AsyncCheckpointer(workers=1, failure_log=log)
    ck.submit(lambda: (_ for _ in ()).throw(OSError('disk gone')),
              label='bad')
    with pytest.raises(OSError):
        ck.submit(lambda: 'fine', label='next')
    # the error is consumed at its barrier; the path is usable again
    f = ck.submit(lambda: 'fine', label='next')
    ck.wait()
    assert f.result() == 'fine'
    assert len(log.records('async_save_failed')) == 1
    ck.close()


def test_stall_write_event_parse_and_fire():
    plan = faults.FaultPlan.parse('stall_write=1:0.05;stall_write=3')
    assert 'stall_write=1:0.05' in plan.describe()
    t0 = time.monotonic()
    plan.on_checkpoint_write('p')
    assert time.monotonic() - t0 >= 0.05
    plan.on_checkpoint_write('p')               # un-armed write: no stall
    assert plan.fired() == ['stall_write=1:0.05']


# --- supervisor integration -----------------------------------------------

def test_supervisor_async_recovers_write_fault_and_stall_bitwise(tmp_path):
    """The PR-1 acceptance drill re-run with save_async=1: a checkpoint
    write fault (now firing inside the background writer) AND a pipeline
    stall still end bitwise-identical to an uninterrupted run."""
    batches = synth_batches(n_batches=8)
    t_ref = _fresh()
    for b in batches:
        t_ref.update(b)
    ref = snap_params(t_ref)

    plan = faults.FaultPlan(seed=1, raise_on_write=(2,),
                            stall_batch=((5, 4.0),))
    faults.install_plan(plan)
    tr = _fresh()
    log = faults.FailureLog()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'), _sup_config(),
                          failure_log=log)
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 8
    assert sorted(plan.fired()) == ['raise_on_write=2', 'stall_batch=5:4']
    assert len(log.records('restored')) == 1
    assert_params_equal(snap_params(tr), ref, rtol=0, atol=0)
    # the final save barriered: the last step is committed and verified
    last = sharded_ckpt.all_steps(str(tmp_path / 'sup'))[0]
    assert last == 8
    assert sharded_ckpt.verify_step_dir(
        sharded_ckpt.step_dir(str(tmp_path / 'sup'), 8)) is None


def test_supervisor_restore_barriers_on_pending_save(tmp_path):
    """A fault arriving while a save is still mid-commit: restore must
    wait for that commit and restore THAT step — not race the writer and
    roll back further than necessary.  The in-flight save is slowed with
    the deterministic ``stall_write`` event; the assertion holds however
    long the stall takes, because drain() blocks."""
    batches = synth_batches(n_batches=8)
    t_ref = _fresh()
    for b in batches:
        t_ref.update(b)
    ref = snap_params(t_ref)

    # write #1 = anchor; write #2 = the step-2 periodic save -> stalled
    # 1.5s; nan at step 2 trips the breaker (deferred one step) while
    # that save is still in flight
    plan = faults.FaultPlan(stall_write=((2, 1.5),), nan_at_step=(2,))
    faults.install_plan(plan)
    tr = _fresh()
    log = faults.FailureLog()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(nan_breaker=1, batch_deadline=30.0),
                          failure_log=log)
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 8
    assert 'stall_write=2:1.5' in plan.fired()
    restored = log.records('restored')
    assert len(restored) == 1 and restored[0].step == 2
    assert_params_equal(snap_params(tr), ref, rtol=0, atol=0)


def test_nan_streak_gate_resolved_at_snapshot_time(tmp_path):
    """Deferred writes must not launder a poisoned tree: the NaN-streak
    save gate is resolved at SNAPSHOT time, so mid-streak boundaries
    produce no checkpoint at all — even after every async write lands."""
    batches = synth_batches(n_batches=6)
    faults.install_plan(faults.FaultPlan(nan_at_step=(2, 3)))
    tr = _fresh('nan_breaker = 3\n')     # armed, but streak peaks at 2
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(save_every=1, nan_breaker=0,
                                      keep_last=0))
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 6
    sup.wait_for_saves()
    steps = set(sharded_ckpt.all_steps(str(tmp_path / 'sup')))
    assert not {3, 4} & steps            # mid-streak boundaries skipped
    assert {1, 2, 5, 6} <= steps         # finite-streak saves landed


def test_supervisor_async_prunes_to_keep_last(tmp_path):
    batches = synth_batches(n_batches=8)
    tr = _fresh()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(save_every=1, keep_last=2))
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 8
    sup.wait_for_saves()
    assert sharded_ckpt.all_steps(str(tmp_path / 'sup')) == [8, 7]


# --- CLI / config surface -------------------------------------------------

def test_cli_save_async_knobs_parse():
    from cxxnet_tpu.main import LearnTask
    lt = LearnTask()
    lt.set_param('save_async', '1')
    lt.set_param('save_workers', '6')
    assert (lt.save_async, lt.save_workers) == (1, 6)


def test_cfg_get_int_typed_lookup():
    cfg = [('steps', '5'), ('steps', '9'), ('w', 'default')]
    assert cfg_get_int(cfg, 'steps', 1) == 9     # last value wins
    assert cfg_get_int(cfg, 'w', 7) == 7         # 'default' skipped
    assert cfg_get_int(cfg, 'absent', 3) == 3
    with pytest.raises(ConfigError):
        cfg_get_int([('steps', 'notanint')], 'steps', 1)
