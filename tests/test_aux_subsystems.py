"""Aux subsystems: profiler trace window, fullc_gather surface, launcher."""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cxxnet_tpu.layers import create_layer, get_layer_type
from cxxnet_tpu.utils.profiler import TraceWindow


def test_fullc_gather_param_accepted():
    layer = create_layer(get_layer_type('fullc'))
    layer.set_param('nhidden', '8')
    layer.set_param('fullc_gather', '1')
    assert layer.fullc_gather == 1


def test_trace_window_disabled_noop():
    tw = TraceWindow()
    tw.configure([('eta', '0.1')])
    assert not tw.enabled
    for i in range(30):
        tw.before_update(i)
    tw.stop()


def test_trace_window_records(tmp_path):
    tw = TraceWindow()
    tw.configure([('profile_dir', str(tmp_path)),
                  ('profile_start_batch', '1'),
                  ('profile_stop_batch', '3')])
    assert tw.enabled
    x = jnp.ones((8, 8))
    for i in range(5):
        tw.before_update(i)
        jnp.dot(x, x).block_until_ready()
    tw.stop()
    # jax writes  <dir>/plugins/profile/<ts>/*  — assert something landed
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found += files
    assert found, 'profiler produced no trace files'
    # window is one-shot: re-entering does not restart
    tw.before_update(1)
    assert not tw._active


def test_launcher_conf_parse():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), 'tools'))
    from launch_dist import parse_launcher_conf
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), 'example', 'MNIST', 'dist.conf')
    cfg = parse_launcher_conf(path)
    assert cfg['num_workers'] == '2'
    assert cfg['app_conf'] == 'MNIST.conf'
    assert 'param_server=dist' in cfg['arg']


def test_weight_consistency_check():
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string
    from tests.test_net_mnist import MLP_CONF, synth_batches
    conf = MLP_CONF + '\ntest_on_server = 1\ndev = cpu:0-7\n'
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    batches = synth_batches()
    trainer.start_round(1)          # runs the consistency assert
    for b in batches[:4]:
        trainer.update(b)
    trainer.start_round(2)          # replicas still bitwise identical
    assert trainer.check_weight_consistency() == 0
