"""Cross-input block-diagonal conv fusion (``fuse_blockdiag``).

The fused path must be mathematically identical to the unfused graph:
each member conv's contraction only ever sees its own input block (the
off-diagonal weight blocks are zero) and the spatial zero-embedding of
a smaller kernel with grown input padding leaves the output grid
untouched.  These tests pin equality of forwards, losses, and gradients
against the plain per-layer execution, plus the scheduling validator's
rejections (the mechanism ships OFF by default; the GoogLeNet default
flip is gated on the per-tower breakdown receipt — BASELINE.md).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string


# An inception-v1-shaped module: two reduce convs off the trunk, then a
# 3x3(pad1) and a 5x5(pad2) tower conv on DIFFERENT inputs.  The config
# order interleaves the 5x5 reduce between the tower convs, exactly like
# models/builders._inception_v1 — so fusing t3+t5 also exercises the
# schedule reorder + validator.
_MODULE_CONF = """
netconfig = start
layer[0->r3] = conv:r3
  nchannel = 6
  kernel_size = 1
layer[r3->r3] = relu
layer[r3->t3] = conv:t3
  nchannel = 8
  kernel_size = 3
  pad = 1
layer[t3->t3] = relu
layer[0->r5] = conv:r5
  nchannel = 4
  kernel_size = 1
layer[r5->r5] = relu
layer[r5->t5] = conv:t5
  nchannel = 5
  kernel_size = 5
  pad = 2
layer[t5->t5] = relu
layer[0->t0] = conv:t0
  nchannel = 2
  kernel_size = 3
layer[t0->t0] = relu
layer[t3,t5->cat] = ch_concat
layer[cat->flat] = flatten
layer[flat->fc] = fullc:fc
  nhidden = 3
layer[fc->fc] = softmax
netconfig = end
%s
input_shape = 3,9,9
batch_size = 4
dev = cpu
eta = 0.05
momentum = 0.0
metric[label] = error
"""


def _make_trainer(extra: str) -> NetTrainer:
    tr = NetTrainer(parse_config_string(_MODULE_CONF % extra))
    tr.init_model()
    return tr


def _batch(seed=0, n=4):
    rng = np.random.RandomState(seed)
    return DataBatch(rng.rand(n, 3, 9, 9).astype(np.float32),
                     rng.randint(0, 3, n).astype(np.float32).reshape(-1, 1))


def _copy_params(src: NetTrainer, dst: NetTrainer) -> None:
    # real copies: the train step donates param buffers, so aliasing the
    # source trainer's arrays would delete them out from under it
    dst.params = jax.tree_util.tree_map(
        lambda x: jnp.array(x, copy=True), src.params)


class TestBlockdiagEquivalence:
    def test_forward_and_loss_match_unfused(self):
        plain = _make_trainer('')
        fused = _make_trainer('fuse_blockdiag = t3+t5')
        assert fused.net._blockdiag_groups, 'group did not form'
        _copy_params(plain, fused)
        b = _batch()
        pp = np.asarray(plain.predict(b))
        pf = np.asarray(fused.predict(b))
        np.testing.assert_allclose(pf, pp, rtol=0, atol=0)

    def test_training_trajectories_match(self):
        # gradients flow through the block-diagonal assembly (at[].set is
        # linear): several SGD steps must track the unfused run to fp eps
        plain = _make_trainer('')
        fused = _make_trainer('fuse_blockdiag = t3+t5')
        _copy_params(plain, fused)
        for i in range(3):
            b = _batch(seed=i)
            plain.update(b)
            fused.update(b)
        for kp, kf in zip(jax.tree_util.tree_leaves(plain.params),
                          jax.tree_util.tree_leaves(fused.params)):
            np.testing.assert_allclose(np.asarray(kf), np.asarray(kp),
                                       rtol=1e-5, atol=1e-6)

    def test_schedule_reorder_validated(self):
        # the 5x5 reduce sits between t3 and t5 in config order; the
        # reorder must pull it before the fused block and push t3's
        # in-place relu after it
        fused = _make_trainer('fuse_blockdiag = t3+t5')
        order = fused.net._exec_order
        assert order != list(range(len(order))), 'reorder must have moved'
        names = [fused.net.cfg.layers[i].name for i in order]
        # members contiguous in the new order
        i3, i5 = names.index('t3'), names.index('t5')
        assert abs(i3 - i5) == 1
        # t5's producer chain (the r5 reduce conv) moved before the block
        assert names.index('r5') < min(i3, i5)

    def test_eval_path_matches(self):
        plain = _make_trainer('')
        fused = _make_trainer('fuse_blockdiag = t3+t5')
        _copy_params(plain, fused)
        b = _batch(seed=7)
        ep = plain.evaluate(iter([b]), 'test')
        ef = fused.evaluate(iter([b]), 'test')
        assert ep == ef


class TestBlockdiagRejections:
    def test_unknown_layer_name(self):
        with pytest.raises(ValueError, match='no layer named'):
            _make_trainer('fuse_blockdiag = t3+nope')

    def test_grid_mismatch(self):
        # t3 (3x3 pad1, 2p-k=-1) and t0 (3x3 pad0, 2p-k=-3): the padded
        # output grids differ, no zero-embedding can reconcile them
        with pytest.raises(ValueError, match='output grid mismatch'):
            _make_trainer('fuse_blockdiag = t3+t0')

    def test_same_padded_one_by_one_fuses_with_3x3(self):
        # 1x1 pad0 and 3x3 pad1 share 2p-k=-1: the 1x1 zero-embeds into
        # the 3x3 center — a real inception pairing (pool-proj vs tower)
        plain = _make_trainer('')
        fused = _make_trainer('fuse_blockdiag = r3+t5')
        _copy_params(plain, fused)
        b = _batch(seed=11)
        np.testing.assert_allclose(np.asarray(fused.predict(b)),
                                   np.asarray(plain.predict(b)),
                                   rtol=1e-6, atol=1e-6)

    def test_chain_fusion_rejected(self):
        # r5 feeds t5 (through an in-place relu): members may not consume
        # each other's outputs
        with pytest.raises(ValueError, match='chain fusion|different node'):
            _make_trainer('fuse_blockdiag = r5+t5')

    def test_duplicate_membership_rejected(self):
        with pytest.raises(ValueError, match='appears in two groups'):
            _make_trainer('fuse_blockdiag = t3+t5;t5+r3')

    def test_tensor_parallel_conflict_raises(self):
        with pytest.raises(ValueError, match='tensor_parallel'):
            _make_trainer('fuse_blockdiag = t3+t5\ntensor_parallel = 2')

    def test_cross_group_tear_apart_rejected(self):
        # order X, A, B, Y with B reading X's output and spec 'a+b;x+y':
        # group {X,Y}'s reorder classifies A 'before' and B 'after'
        # (A, X, Y, B), splitting the already-registered {A,B} — the
        # final-order verification must refuse
        from cxxnet_tpu.nnet.net import Net
        from cxxnet_tpu.nnet.net_config import NetConfig
        conf = """
netconfig = start
layer[0->x1] = conv:xc
  nchannel = 2
  kernel_size = 1
layer[0->a1] = conv:ac
  nchannel = 2
  kernel_size = 1
layer[x1->b1] = conv:bc
  nchannel = 2
  kernel_size = 1
layer[0->y1] = conv:yc
  nchannel = 2
  kernel_size = 1
netconfig = end
fuse_blockdiag = ac+bc;xc+yc
input_shape = 3,5,5
"""
        cfg = NetConfig()
        cfg.configure(parse_config_string(conf))
        with pytest.raises(ValueError, match='torn apart|not produced'):
            Net(cfg)

    def test_off_by_default(self):
        plain = _make_trainer('')
        assert plain.net._blockdiag_groups == {}
        assert plain.net._exec_order == list(range(len(plain.net.layers)))


class TestBlockdiagAuto:
    def test_auto_groups_form_on_module_conf(self):
        # auto: one candidate per concat; t3 (8) and t5 (5) feed cat and
        # are narrow; the reduces are not concat producers
        fused = _make_trainer('fuse_blockdiag = auto')
        groups = {tuple(g) for g in fused.net._blockdiag_groups.values()}
        assert len(groups) == 1
        (g,) = groups
        names = {fused.net.cfg.layers[m].name for m in g}
        assert names == {'t3', 't5'}

    def test_auto_matches_unfused(self):
        plain = _make_trainer('')
        fused = _make_trainer('fuse_blockdiag = auto')
        _copy_params(plain, fused)
        b = _batch(seed=5)
        np.testing.assert_allclose(np.asarray(fused.predict(b)),
                                   np.asarray(plain.predict(b)),
                                   rtol=0, atol=0)

    def test_auto_width_filter(self):
        # auto:4 excludes t3 (8 channels) -> no group of >=2 remains
        fused = _make_trainer('fuse_blockdiag = auto:4')
        assert fused.net._blockdiag_groups == {}

    def test_auto_is_silent_on_concat_free_nets(self):
        from cxxnet_tpu.models.builders import alexnet_conf
        tr = NetTrainer(parse_config_string(
            alexnet_conf(num_class=4)
            + '\nbatch_size = 1\ndev = cpu\nfuse_blockdiag = auto\n'))
        tr.init_model()
        assert tr.net._blockdiag_groups == {}

    def test_auto_on_googlenet_groups_every_module(self):
        from cxxnet_tpu.models.builders import googlenet_conf
        tr = NetTrainer(parse_config_string(
            googlenet_conf(num_class=4, aux_heads=False)
            + '\nbatch_size = 1\ndev = cpu\nfuse_blockdiag = auto\n'))
        tr.init_model()
        groups = {tuple(g) for g in tr.net._blockdiag_groups.values()}
        # the six modules whose 5x5+proj towers are <= 96 wide (in4e/
        # in5a/in5b are 128-wide — correctly above the default cutoff)
        assert len(groups) == 6
        names = {frozenset(tr.net.cfg.layers[m].name for m in g)
                 for g in groups}
        assert names == {
            frozenset({f'{p}_5x5', f'{p}_proj'})
            for p in ('in3a', 'in3b', 'in4a', 'in4b', 'in4c', 'in4d')}


class TestBlockdiagRandomizedProperty:
    """Property: for ANY graph and ANY requested group, the mechanism
    either refuses loudly (labeled ValueError) or produces bit-level
    plan-equivalent results.  Randomized over branching graphs with
    in-place rewrites, chained convs (direct and through relus), and
    random member picks — the adversarial inputs for the schedule
    reorder + version validator + final cross-checks."""

    def _random_conf(self, rng):
        lines = ['netconfig = start']
        nodes = ['0']
        convs = []
        n = rng.randint(3, 7)
        for i in range(n):
            src = nodes[rng.randint(len(nodes))]
            name = f'c{i}'
            k = int(rng.choice([1, 3]))
            lines += [f'layer[{src}->{name}] = conv:{name}',
                      f'  nchannel = {int(rng.choice([2, 3, 4]))}',
                      f'  kernel_size = {k}']
            if k == 3:
                lines += ['  pad = 1']
            if rng.rand() < 0.5:
                lines += [f'layer[{name}->{name}] = relu']
            convs.append(name)
            nodes.append(name)
        cat = ','.join(convs[-min(4, len(convs)):])
        lines += [f'layer[{cat}->cc] = ch_concat',
                  'layer[cc->fl] = flatten',
                  'layer[fl->fc] = fullc:fc', '  nhidden = 3',
                  'layer[fc->fc] = softmax', 'netconfig = end']
        return '\n'.join(lines), convs

    def test_random_graphs_fused_or_refused(self):
        rng = np.random.RandomState(42)
        built = refused = 0
        for trial in range(20):
            conf, convs = self._random_conf(rng)
            pick = list(rng.choice(convs, size=2, replace=False))
            base = conf + """
input_shape = 2,7,7
batch_size = 3
dev = cpu
eta = 0.1
metric[label] = error
"""
            plain = NetTrainer(parse_config_string(base))
            plain.init_model()
            try:
                fused = NetTrainer(parse_config_string(
                    base + f'fuse_blockdiag = {pick[0]}+{pick[1]}\n'))
                fused.init_model()
            except ValueError as e:
                assert 'fuse_blockdiag' in str(e), (
                    f'trial {trial}: unlabeled rejection: {e}')
                refused += 1
                continue
            built += 1
            _copy_params(plain, fused)
            x = rng.rand(3, 2, 7, 7).astype(np.float32)
            b = DataBatch(x, np.zeros((3, 1), np.float32))
            np.testing.assert_allclose(
                np.asarray(fused.predict(b)), np.asarray(plain.predict(b)),
                rtol=1e-5, atol=1e-6,
                err_msg=f'trial {trial}: fused {pick} diverged')
        # the generator must actually exercise both outcomes
        assert built >= 3, f'only {built} fusable graphs in 20 trials'
        assert refused >= 3, f'only {refused} refusals in 20 trials'


class TestBlockdiagOnGoogLeNetModule:
    def test_builder_module_fuses_and_matches(self):
        # the real builder emits in-place relus and lazy reduces; fuse the
        # 3x3+5x5 towers of one module from the actual GoogLeNet conf and
        # compare logits on tiny inputs
        from cxxnet_tpu.models.builders import googlenet_conf
        conf = googlenet_conf(num_class=4, aux_heads=False)
        plain = NetTrainer(parse_config_string(
            conf + '\nbatch_size = 1\ndev = cpu\n'))
        plain.init_model()
        fused = NetTrainer(parse_config_string(
            conf + '\nbatch_size = 1\ndev = cpu\n'
            'fuse_blockdiag = in3a_3x3+in3a_5x5\n'))
        fused.init_model()
        assert fused.net._blockdiag_groups
        _copy_params(plain, fused)
        rng = np.random.RandomState(3)
        b = DataBatch(rng.rand(1, 3, 224, 224).astype(np.float32),
                      np.zeros((1, 1), np.float32))
        np.testing.assert_allclose(np.asarray(fused.predict(b)),
                                   np.asarray(plain.predict(b)),
                                   rtol=1e-5, atol=1e-6)
