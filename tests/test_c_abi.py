"""Native C ABI wrapper tests (runtime/libcxxnetwrapper.so).

Two consumption modes, both exercised:
* ctypes from an already-running Python process (the library attaches to
  the live interpreter through the GIL instead of re-initializing),
* a standalone C program linking the library, which embeds CPython itself
  — the reference's "wrapper for other languages" use case
  (wrapper/cxxnet_wrapper.h:1-8).
"""

import ctypes
import os
import pathlib
import subprocess

import numpy as np
import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent
RUNTIME = REPO / 'runtime'
LIB = RUNTIME / 'libcxxnetwrapper.so'

TINY_CONF = """
netconfig=start
layer[0->1] = fullc:fc1
  nhidden = 16
layer[1->2] = sigmoid
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 1,1,8
batch_size = 16
eta = 0.3
momentum = 0.9
metric = error
"""


def _build():
    if LIB.exists():
        return True
    r = subprocess.run(['make', 'libcxxnetwrapper.so'], cwd=RUNTIME,
                       capture_output=True, text=True)
    return r.returncode == 0 and LIB.exists()


pytestmark = pytest.mark.skipif(not _build(),
                                reason='cannot build libcxxnetwrapper.so')


@pytest.fixture(scope='module')
def lib():
    L = ctypes.CDLL(str(LIB))
    u = ctypes.c_uint
    L.CXNNetCreate.restype = ctypes.c_void_p
    L.CXNNetCreate.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    L.CXNNetFree.argtypes = [ctypes.c_void_p]
    L.CXNNetSetParam.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_char_p]
    L.CXNNetInitModel.argtypes = [ctypes.c_void_p]
    L.CXNNetSaveModel.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.CXNNetLoadModel.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    L.CXNNetStartRound.argtypes = [ctypes.c_void_p, ctypes.c_int]
    F = ctypes.POINTER(ctypes.c_float)
    L.CXNNetUpdateBatch.argtypes = [ctypes.c_void_p, F, u * 4, F, u * 2]
    L.CXNNetPredictBatch.restype = F
    L.CXNNetPredictBatch.argtypes = [ctypes.c_void_p, F, u * 4,
                                     ctypes.POINTER(u)]
    L.CXNNetExtractBatch.restype = F
    L.CXNNetExtractBatch.argtypes = [ctypes.c_void_p, F, u * 4,
                                     ctypes.c_char_p, u * 4]
    L.CXNNetGetWeight.restype = F
    L.CXNNetGetWeight.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_char_p, u * 4, ctypes.POINTER(u)]
    L.CXNNetSetWeight.argtypes = [ctypes.c_void_p, F, u, ctypes.c_char_p,
                                  ctypes.c_char_p]
    return L


def _fptr(arr):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def test_ctypes_train_predict_weights(lib, tmp_path):
    u4 = (ctypes.c_uint * 4)
    net = lib.CXNNetCreate(b'cpu', TINY_CONF.encode())
    assert net
    lib.CXNNetInitModel(net)
    lib.CXNNetStartRound(net, 0)

    rng = np.random.RandomState(0)
    data = rng.randn(16, 1, 1, 8).astype(np.float32)
    label = (rng.randint(0, 4, (16, 1))).astype(np.float32)
    for _ in range(3):
        lib.CXNNetUpdateBatch(net, _fptr(data), u4(16, 1, 1, 8),
                              _fptr(label), (ctypes.c_uint * 2)(16, 1))

    out_size = ctypes.c_uint(0)
    p = lib.CXNNetPredictBatch(net, _fptr(data), u4(16, 1, 1, 8),
                               ctypes.byref(out_size))
    assert out_size.value == 16
    preds = np.ctypeslib.as_array(p, (16,))
    assert set(np.unique(preds)).issubset({0., 1., 2., 3.})

    # extract a hidden node by name
    oshape = u4(0, 0, 0, 0)
    p = lib.CXNNetExtractBatch(net, _fptr(data), u4(16, 1, 1, 8), b'2',
                               oshape)
    assert list(oshape) == [16, 1, 1, 16]

    # weight get/set roundtrip in disk layout (nhidden, nin)
    wshape = u4(0, 0, 0, 0)
    wdim = ctypes.c_uint(0)
    wp = lib.CXNNetGetWeight(net, b'fc1', b'wmat', wshape, ctypes.byref(wdim))
    assert wdim.value == 2 and list(wshape)[:2] == [16, 8]
    w = np.ctypeslib.as_array(wp, (16, 8)).copy()
    w2 = w * 2.0
    lib.CXNNetSetWeight(net, _fptr(w2), ctypes.c_uint(w2.size), b'fc1',
                        b'wmat')
    wp = lib.CXNNetGetWeight(net, b'fc1', b'wmat', wshape, ctypes.byref(wdim))
    got = np.ctypeslib.as_array(wp, (16, 8))
    np.testing.assert_allclose(got, w2, rtol=1e-6)

    # save / load through a second handle
    fname = str(tmp_path / 'm.model').encode()
    lib.CXNNetSaveModel(net, fname)
    net2 = lib.CXNNetCreate(b'cpu', TINY_CONF.encode())
    lib.CXNNetLoadModel(net2, fname)
    wp = lib.CXNNetGetWeight(net2, b'fc1', b'wmat', wshape,
                             ctypes.byref(wdim))
    got = np.ctypeslib.as_array(wp, (16, 8))
    np.testing.assert_allclose(got, w2, rtol=1e-6)
    lib.CXNNetFree(net2)
    lib.CXNNetFree(net)


C_DRIVER = r'''
#include <stdio.h>
#include <stdlib.h>

typedef unsigned int cxx_uint;
typedef float cxx_real_t;

void *CXNNetCreate(const char *device, const char *cfg);
void CXNNetFree(void *handle);
void CXNNetInitModel(void *handle);
void CXNNetStartRound(void *handle, int round);
void CXNNetUpdateBatch(void *handle, cxx_real_t *p_data,
                       const cxx_uint dshape[4], cxx_real_t *p_label,
                       const cxx_uint lshape[2]);
const cxx_real_t *CXNNetPredictBatch(void *handle, cxx_real_t *p_data,
                                     const cxx_uint dshape[4],
                                     cxx_uint *out_size);
const cxx_real_t *CXNNetGetWeight(void *handle, const char *layer_name,
                                  const char *wtag, cxx_uint wshape[4],
                                  cxx_uint *out_dim);

static const char *kConf = "%CONF%";

int main(void) {
  void *net = CXNNetCreate("cpu", kConf);
  if (!net) return 1;
  CXNNetInitModel(net);
  CXNNetStartRound(net, 0);
  float data[16 * 8];
  float label[16];
  unsigned seed = 7;
  for (int i = 0; i < 16 * 8; ++i) {
    seed = seed * 1103515245u + 12345u;
    data[i] = (float)(seed % 1000) / 500.0f - 1.0f;
  }
  for (int i = 0; i < 16; ++i) label[i] = (float)(i % 4);
  cxx_uint dshape[4] = {16, 1, 1, 8};
  cxx_uint lshape[2] = {16, 1};
  for (int step = 0; step < 3; ++step)
    CXNNetUpdateBatch(net, data, dshape, label, lshape);
  cxx_uint out_size = 0;
  const float *pred = CXNNetPredictBatch(net, data, dshape, &out_size);
  if (out_size != 16 || pred == NULL) return 2;
  cxx_uint wshape[4];
  cxx_uint wdim = 0;
  const float *w = CXNNetGetWeight(net, "fc1", "wmat", wshape, &wdim);
  if (wdim != 2 || wshape[0] != 16 || wshape[1] != 8 || w == NULL) return 3;
  CXNNetFree(net);
  printf("C_ABI_OK\n");
  return 0;
}
'''


def test_standalone_c_program(tmp_path):
    src = tmp_path / 'driver.c'
    conf = TINY_CONF.replace('\n', '\\n')
    src.write_text(C_DRIVER.replace('%CONF%', conf))
    exe = tmp_path / 'driver'
    r = subprocess.run(
        ['gcc', '-O1', str(src), '-o', str(exe),
         f'-L{RUNTIME}', '-lcxxnetwrapper', f'-Wl,-rpath,{RUNTIME}'],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    env = dict(os.environ)
    env['PYTHONPATH'] = str(REPO) + os.pathsep + env.get('PYTHONPATH', '')
    env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run([str(exe)], capture_output=True, text=True, env=env,
                       timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert 'C_ABI_OK' in r.stdout
