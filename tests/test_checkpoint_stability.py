"""Checkpoint-format stability + NaN failure detection.

``tests/fixtures/golden_v1.model`` is a committed model file (net_type
prefix + NetConfig + epoch + layer blobs, the reference layout —
``nnet_impl-inl.hpp:82-87``).  Loading it must keep working bit-exactly
across refactors; this is the interop guarantee SURVEY §7 hard-part (d)
asks for.
"""

import os

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        'fixtures')

GOLDEN_CONF = """
netconfig = start
layer[0->1] = conv:c1
  nchannel = 4
  kernel_size = 3
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:f1
  nhidden = 5
layer[4->4] = softmax
netconfig = end
input_shape = 3,8,8
batch_size = 4
dev = cpu
"""


def test_golden_checkpoint_loads():
    # like the reference pred/continue tasks, loading re-reads the conf
    # (cxxnet_main.cpp:108-133); the model file carries architecture,
    # epoch counter, and the weight blobs
    tr = NetTrainer(parse_config_string(GOLDEN_CONF))
    with open(os.path.join(FIXTURES, 'golden_v1.model'), 'rb') as f:
        assert int.from_bytes(f.read(4), 'little', signed=True) == 0
        tr.load_model(f)
    assert tr.epoch_counter == 42
    w = np.asarray(tr.params['3']['wmat'])
    assert w.shape == (144, 5)
    np.testing.assert_allclose(float(w.sum()), -0.24319136142730713,
                               rtol=1e-6)
    x = np.load(os.path.join(FIXTURES, 'golden_v1_input.npy'))
    want = np.load(os.path.join(FIXTURES, 'golden_v1_pred.npy'))
    got = tr.predict(DataBatch(x, np.zeros((4, 1), np.float32)))
    np.testing.assert_array_equal(got, want)


GOLDEN_V2_CONF = """
netconfig = start
layer[0->1] = conv:c1
  nchannel = 8
  kernel_size = 3
  ngroup = 2
  pad = 1
layer[1->2] = batch_norm:bn1
layer[2->3] = prelu:pr1
layer[3->4] = max_pooling
  kernel_size = 2
  stride = 2
layer[4->5] = flatten
layer[5->6] = fullc:fs
  nhidden = 128
layer[6->7] = sigmoid
layer[7->8] = share[fs]
layer[8->9] = fullc:out
  nhidden = 3
  no_bias = 1
layer[9->9] = softmax
netconfig = end
input_shape = 4,8,8
batch_size = 4
dev = cpu
"""


def test_golden_v2_risky_layouts_load():
    """golden_v2.model pins the risky disk layouts: grouped-conv im2col
    round-trip (checkpoint.to_disk_layout conv branch), batch_norm and
    prelu tensor-only records, the no_bias fullc zero bias slot, and a
    share[tag] net (shared layer contributes no blob record).  Loading
    must stay bit-exact across refactors."""
    tr = NetTrainer(parse_config_string(GOLDEN_V2_CONF))
    with open(os.path.join(FIXTURES, 'golden_v2.model'), 'rb') as f:
        assert int.from_bytes(f.read(4), 'little', signed=True) == 0
        tr.load_model(f)
    assert tr.epoch_counter == 7
    w = np.asarray(tr.params['0']['wmat'])
    assert w.shape == (3, 3, 2, 8)             # HWIO, grouped: cin_g=4/2
    np.testing.assert_allclose(float(w.sum()), -0.14391812682151794,
                               rtol=1e-6)
    assert set(tr.params['1']) == {'wmat', 'bias'}    # BN gamma/beta
    assert set(tr.params['2']) == {'bias'}            # prelu slope
    assert 'bias' not in tr.params['8']               # no_bias fullc
    assert '7' not in tr.params                       # share[fs] aliases 5
    x = np.load(os.path.join(FIXTURES, 'golden_v2_input.npy'))
    batch = DataBatch(x, np.zeros((4, 1), np.float32))
    want = np.load(os.path.join(FIXTURES, 'golden_v2_pred.npy'))
    np.testing.assert_array_equal(tr.predict(batch), want)
    want_scores = np.load(os.path.join(FIXTURES, 'golden_v2_scores.npy'))
    got_scores = tr.extract_feature(batch, 'top[-1]')
    np.testing.assert_allclose(got_scores, want_scores, rtol=1e-5)


def test_golden_v2_blob_roundtrip_bitexact():
    """save(load(golden)) reproduces the golden bytes exactly — every
    to_disk_layout branch is the inverse of its from_disk_layout."""
    import io as _io
    tr = NetTrainer(parse_config_string(GOLDEN_V2_CONF))
    with open(os.path.join(FIXTURES, 'golden_v2.model'), 'rb') as f:
        golden = f.read()
    tr.load_model(_io.BytesIO(golden[4:]))
    out = _io.BytesIO()
    out.write((0).to_bytes(4, 'little'))
    tr.save_model(out)
    assert out.getvalue() == golden


NAN_CONF = """
netconfig = start
layer[0->1] = fullc:f1
  nhidden = 4
layer[1->1] = softmax
netconfig = end
input_shape = 1,1,6
batch_size = 4
input_flat = 1
dev = cpu
eta = 0.1
nan_action = skip
"""


def test_nan_action_skip_drops_poisoned_batch():
    tr = NetTrainer(parse_config_string(NAN_CONF))
    tr.init_model()
    before = np.asarray(tr.params['0']['wmat'])
    bad = DataBatch(np.full((4, 1, 1, 6), np.inf, np.float32),
                    np.zeros((4, 1), np.float32))
    tr.update(bad)
    after = np.asarray(tr.params['0']['wmat'])
    np.testing.assert_array_equal(before, after)
    assert np.isfinite(after).all()
    # a healthy batch still updates
    rng = np.random.RandomState(0)
    good = DataBatch(rng.rand(4, 1, 1, 6).astype(np.float32),
                     rng.randint(0, 4, (4, 1)).astype(np.float32))
    tr.update(good)
    assert not np.array_equal(after, np.asarray(tr.params['0']['wmat']))
    assert np.isfinite(np.asarray(tr.params['0']['wmat'])).all()


def test_nan_action_skip_keeps_train_metrics_clean():
    conf = NAN_CONF + '\nmetric = logloss\neval_train = 1\n'
    tr = NetTrainer(parse_config_string(conf))
    tr.init_model()
    bad = DataBatch(np.full((4, 1, 1, 6), np.inf, np.float32),
                    np.zeros((4, 1), np.float32))
    rng = np.random.RandomState(0)
    good = DataBatch(rng.rand(4, 1, 1, 6).astype(np.float32),
                     rng.randint(0, 4, (4, 1)).astype(np.float32))
    tr.update(good)
    tr.update(bad)          # must not poison the round's train metric
    res = tr.evaluate(None, 'train')
    assert 'nan' not in res, res
