"""graftfuse suite (doc/kernels.md): the fused Pallas conv+bias+act
block, inference conv+BN folding, and μ-cuDNN convolution microbatching.

Three contracts, each pinned here:

* the fused block equals the XLA reference composition within the
  tolerances pinned in ``ops/pallas_cnn`` (``_FUSED_RTOL``/``_FUSED_ATOL``
  — pinned-tolerance, never silently looser), forward AND gradients,
  on every stride/pad/group/bias/activation leg, in interpret mode;
* a ``fold_bn=1`` PredictEngine serves scores equal (``FOLD_RTOL``/
  ``FOLD_ATOL``) to the unfolded engine on the calibration batch, and
  keeps that equality through hot swaps (re-fold) and re-placed trees
  (the double-fold identity guard);
* a ``micro_batch=k`` training step is a **bitwise** twin of the
  unsplit step at every declared split, composes with
  ``steps_per_dispatch`` scan dispatch, and bounds the ``train.step``
  program's ledger peak bytes.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from cxxnet_tpu.layers.conv import _conv_im2col_mb, _conv_native_mb
from cxxnet_tpu.nnet.fold import FOLD_ATOL, FOLD_RTOL
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.obs.programs import get_ledger
from cxxnet_tpu.ops.pallas_cnn import (_FUSED_ATOL, _FUSED_RTOL, _conv_ref,
                                       conv_use_fused, fused_conv_bias_act,
                                       microbatched_conv)
from cxxnet_tpu.serve.engine import PredictEngine
from cxxnet_tpu.utils.config import parse_config_string

pytestmark = pytest.mark.cnn_fused


def _ref_composition(x, w, b, strides, pad, groups, act):
    y = _conv_ref(x, w, strides, pad, groups)
    if b is not None:
        y = y + b
    return jnp.maximum(y, 0.0) if act == 'relu' else y


def _leg_data(key, cin, cout, groups, hw=9):
    kx, kw_, kb = jax.random.split(jax.random.PRNGKey(key), 3)
    x = jax.random.normal(kx, (4, hw, hw, cin), jnp.float32)
    w = jax.random.normal(kw_, (3, 3, cin // groups, cout), jnp.float32)
    b = jax.random.normal(kb, (cout,), jnp.float32)
    return x, w, b


# --- the fused block's twins (fwd + grad, every leg) -----------------------

@pytest.mark.parametrize(
    'stride,pad,groups,act,bias',
    [(1, 1, 1, 'relu', True),        # the paired-layer fast path
     (1, 1, 1, 'relu', False),       # no_bias conv
     (1, 1, 1, 'identity', True),    # fuse=1 solo conv (no relu reader)
     (2, 1, 1, 'relu', True),        # strided
     (1, 0, 1, 'relu', True),        # valid padding
     (2, 2, 1, 'identity', False),   # strided + wide pad, bare conv
     (1, 1, 2, 'relu', True),        # grouped
     (2, 1, 4, 'identity', True)],   # grouped + strided
    ids=['base', 'nobias', 'identity', 'stride2', 'pad0',
         's2p2bare', 'group2', 'group4s2'])
def test_fused_block_matches_reference(stride, pad, groups, act, bias):
    x, w, b = _leg_data(7 * stride + pad + groups, 4 * groups, 8, groups)
    b = b if bias else None
    strides, padding = (stride, stride), ((pad, pad), (pad, pad))

    y_fused = fused_conv_bias_act(x, w, b, strides, padding, groups, act)
    y_ref = _ref_composition(x, w, b, strides, padding, groups, act)
    np.testing.assert_allclose(np.asarray(y_fused), np.asarray(y_ref),
                               rtol=_FUSED_RTOL, atol=_FUSED_ATOL)

    def loss_fused(x, w, b):
        return jnp.sum(jnp.cos(
            fused_conv_bias_act(x, w, b, strides, padding, groups, act)))

    def loss_ref(x, w, b):
        return jnp.sum(jnp.cos(
            _ref_composition(x, w, b, strides, padding, groups, act)))

    args = (x, w) if b is None else (x, w, b)
    nums = (0, 1) if b is None else (0, 1, 2)
    gf = jax.grad(loss_fused, argnums=nums)(*args, *(() if b is not None
                                                     else (None,)))
    gr = jax.grad(loss_ref, argnums=nums)(*args, *(() if b is not None
                                                   else (None,)))
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=_FUSED_RTOL, atol=_FUSED_ATOL)


def test_fused_relu_grad_matches_reference_at_exact_ties():
    """The reference relu is ``jnp.maximum(x, 0)``, whose XLA gradient
    at an EXACT z==0 tie is 0.5 — and zero-padded integer images with a
    zero-init bias tie densely at step 0, so the fused backward must
    mirror that convention bitwise, not just a.e."""
    # all-zero input + zero bias => every pre-activation is exactly 0
    x = jnp.zeros((2, 5, 5, 3), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(3), (3, 3, 3, 4), jnp.float32)
    b = jnp.zeros((4,), jnp.float32)
    strides, padding = (1, 1), ((1, 1), (1, 1))

    def loss_fused(x, w, b):
        return jnp.sum(
            fused_conv_bias_act(x, w, b, strides, padding, 1, 'relu')
            * jnp.arange(1.0, 5.0))

    def loss_ref(x, w, b):
        return jnp.sum(
            _ref_composition(x, w, b, strides, padding, 1, 'relu')
            * jnp.arange(1.0, 5.0))

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(r))
    # the tie convention is the half-gradient, not a dead unit
    assert float(jnp.abs(gf[2]).max()) > 0.0


def test_conv_use_fused_gate_tristate():
    """``fuse=1`` forces the block on (the CPU validation path),
    ``fuse=0`` kills it, auto defers to ``pallas_mode()`` — which on a
    cpu host (interpret mode) stays off, and under GSPMD stays off."""
    assert conv_use_fused('1') is True
    assert conv_use_fused('0') is False
    assert conv_use_fused('auto') is False          # cpu = interpret mode
    assert conv_use_fused('auto', spmd_devices=8) is False
    assert conv_use_fused(None) is False


# --- net-level fusion pass -------------------------------------------------

_CNN_CONF = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->1] = relu
layer[1->2] = max_pooling
  kernel_size = 2
  stride = 2
layer[2->3] = conv:c2
  kernel_size = 3
  pad = 1
  nchannel = 16
layer[3->3] = relu
layer[3->4] = flatten
layer[4->5] = fullc:fc1
  nhidden = 10
layer[5->6] = softmax
netconfig = end

input_shape = 3,12,12
batch_size = 8
eta = 0.01
momentum = 0.9
metric = error
eval_train = 0
random_type = xavier
"""


def _trainer(extra=''):
    tr = NetTrainer(parse_config_string(_CNN_CONF + extra))
    tr.init_model()
    return tr


def _batch(rng):
    data = rng.randn(8, 3, 12, 12).astype(np.float32)
    label = rng.randint(0, 10, (8, 1)).astype(np.float32)
    return data, label


def _param_maxerr(a, b):
    return max(float(np.max(np.abs(
        np.asarray(a.params[lk][f], np.float32)
        - np.asarray(b.params[lk][f], np.float32))))
        for lk in a.params for f in a.params[lk])


def test_fusion_pass_pairs_inplace_relus():
    tr = _trainer('fuse = 1\n')
    assert tr.net._convact_pairs == {0: 1, 3: 4}
    assert tr.net._convact_solo == set()
    tr0 = _trainer('fuse = 0\n')
    assert tr0.net._convact_pairs == {}
    assert tr0.net._convact_solo == set()


def test_fusion_excluded_under_microbatching():
    """The fused block has its own tiling — ``micro_batch>1`` convs must
    fall out of the pairing (they take the microbatched path instead)."""
    tr = _trainer('fuse = 1\nmicro_batch = 2\n')
    assert tr.net._convact_pairs == {}
    assert tr.net._convact_solo == set()


def test_fused_training_twin():
    """fuse=1 and fuse=0 trainers fed the identical update stream stay
    within the fused block's pinned tolerance — on the f32 cpu interpret
    path they are in practice bitwise (err 0.0), and any drift past the
    pinned envelope is a bug, not a tolerance to widen."""
    rng = np.random.RandomState(0)
    data, label = _batch(rng)
    t_on, t_off = _trainer('fuse = 1\n'), _trainer('fuse = 0\n')
    for t in (t_on, t_off):
        d = t._shard_batch(data)
        lb = t._shard_batch(label, cast=False)
        for _ in range(3):
            t.update_on_device(d, lb)
    err = _param_maxerr(t_on, t_off)
    assert err <= _FUSED_ATOL, f'fused training drifted: {err}'


# --- conv+BN folding through a real PredictEngine --------------------------

_FOLD_CONF = """
netconfig = start
layer[0->1] = conv:c1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = batch_norm:bn1
layer[2->3] = relu
layer[3->4] = conv:c2
  kernel_size = 3
  pad = 1
  stride = 2
  nchannel = 16
layer[4->5] = batch_norm:bn2
layer[5->6] = relu
layer[6->7] = flatten
layer[7->8] = fullc:fc1
  nhidden = 10
layer[8->9] = softmax
netconfig = end

input_shape = 3,12,12
batch_size = 8
random_type = xavier
"""


@pytest.fixture()
def fold_engines():
    tr = NetTrainer(parse_config_string(_FOLD_CONF))
    tr.init_model()
    calib = np.random.RandomState(3).randn(8, 3, 12, 12).astype(np.float32)
    plain = PredictEngine(tr, (8,))
    folded = PredictEngine(tr, (8,), fold_bn=1, fold_batch=calib)
    return tr, calib, plain, folded


def test_fold_engine_serves_equal_scores(fold_engines):
    """The pinned fold contract: ON the calibration batch (BN here uses
    incoming-batch statistics even at eval — the reference quirk — so
    the frozen-stats fold is exact only where its statistics came from)
    the folded engine's scores equal the unfolded engine's."""
    _, calib, plain, folded = fold_engines
    view = folded.fold_view()
    assert view['pairs'] == [('c1', 'bn1'), ('c2', 'bn2')]
    assert view['max_abs_err'] <= FOLD_ATOL + FOLD_RTOL
    s_plain = plain.predict_scores(calib)
    s_fold = folded.predict_scores(calib)
    np.testing.assert_allclose(s_fold, s_plain,
                               rtol=FOLD_RTOL, atol=FOLD_ATOL)


def test_fold_ledger_key_carries_fold_suffix(fold_engines):
    """/programs must show the FOLDED program as its own compiler-truth
    row — the '+fold' shape-key suffix keeps it from aliasing the
    unfolded forward's entry."""
    _, calib, plain, folded = fold_engines
    folded.predict_scores(calib)
    led = get_ledger()
    keys = [e.shape_key for e in led.entries_for(folded._program.name,
                                                 analyze=False)]
    assert any(k.endswith('+fold') for k in keys), keys


def test_fold_hot_swap_refolds(fold_engines):
    """A hot swap hands the engine RAW conv+BN weights: the placement
    path must re-fold them (a sharding-match shortcut would serve
    unfolded weights through the identity-BN forward)."""
    tr, calib, _, folded = fold_engines
    s0 = folded.predict_scores(calib)
    folded.swap_params(tr.params)
    s1 = folded.predict_scores(calib)
    np.testing.assert_array_equal(s0, s1)


def test_fold_double_pass_identity_guard(fold_engines):
    """Re-passing the engine's OWN placed tree must be the identity —
    folding twice would corrupt the weights (the `_last_placed` object
    identity guard, serve/engine.py)."""
    tr, calib, _, folded = fold_engines
    s0 = folded.predict_scores(calib)
    placed = folded.place_params(tr.params)
    assert folded.place_params(placed) is placed
    folded.swap_params(placed)
    s1 = folded.predict_scores(calib)
    np.testing.assert_array_equal(s0, s1)


# --- μ-cuDNN convolution microbatching -------------------------------------

@pytest.mark.parametrize('split', [2, 4, 8])
@pytest.mark.parametrize('conv_fn', [_conv_native_mb, _conv_im2col_mb],
                         ids=['native', 'im2col'])
def test_microbatched_conv_bitwise(split, conv_fn):
    """Forward, dx AND dw of the microbatched conv are bitwise-equal to
    the unsplit op at every declared split, on both lowerings."""
    kx, kw_ = jax.random.split(jax.random.PRNGKey(split), 2)
    x = jax.random.normal(kx, (8, 9, 9, 4), jnp.float32)
    w = jax.random.normal(kw_, (3, 3, 4, 8), jnp.float32)
    strides, pad = (1, 1), ((1, 1), (1, 1))

    y_mb = jax.jit(lambda x, w: microbatched_conv(
        x, w, strides, pad, 1, split, conv_fn))(x, w)
    y_ref = jax.jit(lambda x, w: conv_fn(x, w, strides, pad, 1))(x, w)
    np.testing.assert_array_equal(np.asarray(y_mb), np.asarray(y_ref))

    def loss_mb(x, w):
        return jnp.sum(jnp.sin(microbatched_conv(
            x, w, strides, pad, 1, split, conv_fn)))

    def loss_ref(x, w):
        return jnp.sum(jnp.sin(conv_fn(x, w, strides, pad, 1)))

    dx_mb, dw_mb = jax.jit(jax.grad(loss_mb, argnums=(0, 1)))(x, w)
    dx_rf, dw_rf = jax.jit(jax.grad(loss_ref, argnums=(0, 1)))(x, w)
    np.testing.assert_array_equal(np.asarray(dx_mb), np.asarray(dx_rf))
    np.testing.assert_array_equal(np.asarray(dw_mb), np.asarray(dw_rf))


@pytest.mark.parametrize('split', [2, 4, 8])
def test_micro_batch_trainer_step_bitwise(split):
    """A full optimizer step (fwd + bwd + momentum update) with
    ``micro_batch=k`` is bitwise-equal to the unsplit step."""
    rng = np.random.RandomState(1)
    data, label = _batch(rng)
    t1 = _trainer('fuse = 0\nmicro_batch = 1\n')
    tk = _trainer(f'fuse = 0\nmicro_batch = {split}\n')
    for t in (t1, tk):
        d = t._shard_batch(data)
        lb = t._shard_batch(label, cast=False)
        for _ in range(3):
            t.update_on_device(d, lb)
    assert _param_maxerr(t1, tk) == 0.0


def test_micro_batch_composes_with_steps_per_dispatch():
    """``micro_batch`` composes with the scanned K-step dispatch
    (steps_per_dispatch machinery) without touching its values: the
    scanned run at split k is bitwise-equal to the scanned run unsplit,
    exactly as the sequential runs are.  (Scan-vs-sequential itself is
    a *separate* program XLA may compile to a different-rounding HLO
    for conv nets — that cross-path envelope is not this knob's
    contract, and the split must not move it either way.)"""
    rng = np.random.RandomState(2)
    batches = [_batch(rng) for _ in range(2)]
    n_steps = 4

    def seq_run(extra):
        tr = _trainer(extra)
        for t in range(n_steps):
            data, label = batches[t % 2]
            tr.update_on_device(tr._shard_batch(data),
                                tr._shard_batch(label, cast=False))
        return tr

    def scan_run(extra):
        tr = _trainer(extra)
        dstack = tr.shard_batch_stack(np.stack([d for d, _ in batches]))
        lstack = tr.shard_batch_stack(np.stack([lb for _, lb in batches]),
                                      cast=False)
        fn = tr.compile_multi_step(n_steps)
        tr.update_n_on_device(fn, dstack, lstack, n_steps)
        return tr

    seq_1 = seq_run('fuse = 0\nmicro_batch = 1\n')
    seq_k = seq_run('fuse = 0\nmicro_batch = 2\n')
    scan_1 = scan_run('fuse = 0\nmicro_batch = 1\n')
    scan_k = scan_run('fuse = 0\nmicro_batch = 2\n')
    assert _param_maxerr(seq_1, seq_k) == 0.0
    assert _param_maxerr(scan_1, scan_k) == 0.0
    assert scan_1.epoch_counter == scan_k.epoch_counter == n_steps


def test_micro_batch_bounds_ledger_peak_bytes():
    """The knob's whole point: the split bounds the compiled step's
    ``memory_analysis`` peak bytes (compiler truth on the ProgramLedger
    — the number grafttune's mem_inv pricing scales) while the math
    stays bitwise (asserted above)."""
    rng = np.random.RandomState(4)
    data, label = _batch(rng)
    led = get_ledger()
    peaks = {}
    for split in (1, 4):
        tr = _trainer(f'fuse = 0\nmicro_batch = {split}\n')
        tr.update_on_device(tr._shard_batch(data),
                            tr._shard_batch(label, cast=False))
        entries = led.entries_for(tr._prog_step.name)
        peaks[split] = max(int(e.peak_bytes) for e in entries)
    assert peaks[4] <= peaks[1], peaks
    assert peaks[4] > 0


# --- bench self-heal covers BENCH_CNN (satellite) --------------------------

def test_self_heal_covers_cnn_fused_receipts(tmp_path, monkeypatch):
    """A BENCH_CNN receipt stamped cpu-fallback is a heal candidate the
    first time a real chip is up, and the healed rerun lands in THIS
    script's receipt slot (receipts/bench_cnn_fused.json) — not in the
    bench_serve namespace."""
    import json as _json
    import os
    import sys
    sys.path.insert(0, os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    import bench
    monkeypatch.setenv('JAX_PLATFORMS', 'tpu,cpu')
    monkeypatch.delenv('CXXNET_BENCH_NO_HEAL', raising=False)
    stale = {'metric': 'cnn_fused_speedup', 'value': 1.1,
             'platform': 'cpu-fallback'}
    (tmp_path / 'BENCH_CNN_r01.json').write_text(_json.dumps(stale))
    cands = bench.heal_candidates(str(tmp_path))
    assert [(m, s) for _, m, s in cands] == \
        [('cnn_fused_speedup', ('bench.py', 'cnn_fused'))]

    healed = bench.self_heal_receipts(
        str(tmp_path),
        runner=lambda s, m: {'metric': 'cnn_fused_speedup', 'value': 1.4,
                             'platform': 'tpu'})
    assert len(healed) == 1
    receipt = tmp_path / 'receipts' / 'bench_cnn_fused.json'
    assert receipt.exists()
    assert _json.loads(receipt.read_text())['heals'].endswith(
        'BENCH_CNN_r01.json')
    # the healed receipt supersedes the stale trajectory entry
    assert bench.heal_candidates(str(tmp_path)) == []


# --- doc drift (satellite 5) -----------------------------------------------

def _repo_doc(rel):
    import os
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(here, 'doc', rel)) as f:
        return f.read()


def test_tasks_doc_documents_the_fusion_surface():
    text = _repo_doc('tasks.md')
    assert '`fuse`' in text
    assert '`micro_batch`' in text
    assert 'serve.fold_bn' in text


def test_kernels_doc_exists_and_is_linked():
    """tasks.md/autotune.md link kernels.md for the fusion story — the
    target must exist and cover the three graftfuse contracts."""
    text = _repo_doc('kernels.md')
    for needle in ('fused_conv_bias_act', 'micro_batch', 'fold_bn',
                   'bitwise', 'interpret'):
        assert needle in text, f'doc/kernels.md missing {needle!r}'
    assert 'kernels.md' in _repo_doc('README.md')
