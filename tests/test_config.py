"""Config tokenizer grammar tests (quirks from src/utils/config.h)."""

import pytest

from cxxnet_tpu.utils.config import (ConfigError, apply_cli_overrides,
                                     cfg_get, parse_config_string)


def test_basic_pairs_in_order():
    cfg = parse_config_string('a = 1\nb=2\n  c   =   3\n')
    assert cfg == [('a', '1'), ('b', '2'), ('c', '3')]


def test_comments_stripped():
    cfg = parse_config_string('# full line comment\na = 1  # trailing\n')
    assert cfg == [('a', '1')]


def test_quoted_strings_with_spaces_and_escapes():
    cfg = parse_config_string('path = "a b/c.gz"\nq = "x\\"y"\n')
    assert cfg == [('path', 'a b/c.gz'), ('q', 'x"y')]


def test_multiline_single_quote():
    cfg = parse_config_string("s = 'line1\nline2'\nnext = 1\n")
    assert cfg == [('s', 'line1\nline2'), ('next', '1')]


def test_unterminated_string_raises():
    with pytest.raises(ConfigError):
        parse_config_string('a = "oops\n')


def test_layer_bracket_names():
    cfg = parse_config_string('layer[0->1] = conv:c1\nmetric[label] = error\n')
    assert cfg == [('layer[0->1]', 'conv:c1'), ('metric[label]', 'error')]


def test_duplicate_keys_preserved_in_order():
    cfg = parse_config_string('a = 1\na = 2\n')
    assert cfg == [('a', '1'), ('a', '2')]
    assert cfg_get(cfg, 'a') == '2'


def test_default_value_skipped():
    cfg = parse_config_string('a = 1\na = default\n')
    assert cfg_get(cfg, 'a') == '1'


def test_cli_overrides_append():
    cfg = apply_cli_overrides([('a', '1')], ['a=9', 'b=x'])
    assert cfg == [('a', '1'), ('a', '9'), ('b', 'x')]
