"""Config tokenizer grammar tests (quirks from src/utils/config.h)."""

import pytest

from cxxnet_tpu.utils.config import (ConfigError, apply_cli_overrides,
                                     cfg_get, parse_config_string)


def test_basic_pairs_in_order():
    cfg = parse_config_string('a = 1\nb=2\n  c   =   3\n')
    assert cfg == [('a', '1'), ('b', '2'), ('c', '3')]


def test_comments_stripped():
    cfg = parse_config_string('# full line comment\na = 1  # trailing\n')
    assert cfg == [('a', '1')]


def test_quoted_strings_with_spaces_and_escapes():
    cfg = parse_config_string('path = "a b/c.gz"\nq = "x\\"y"\n')
    assert cfg == [('path', 'a b/c.gz'), ('q', 'x"y')]


def test_multiline_single_quote():
    cfg = parse_config_string("s = 'line1\nline2'\nnext = 1\n")
    assert cfg == [('s', 'line1\nline2'), ('next', '1')]


def test_unterminated_string_raises():
    with pytest.raises(ConfigError):
        parse_config_string('a = "oops\n')


def test_layer_bracket_names():
    cfg = parse_config_string('layer[0->1] = conv:c1\nmetric[label] = error\n')
    assert cfg == [('layer[0->1]', 'conv:c1'), ('metric[label]', 'error')]


def test_duplicate_keys_preserved_in_order():
    cfg = parse_config_string('a = 1\na = 2\n')
    assert cfg == [('a', '1'), ('a', '2')]
    assert cfg_get(cfg, 'a') == '2'


def test_default_value_skipped():
    cfg = parse_config_string('a = 1\na = default\n')
    assert cfg_get(cfg, 'a') == '1'


def test_cli_overrides_append():
    cfg = apply_cli_overrides([('a', '1')], ['a=9', 'b=x'])
    assert cfg == [('a', '1'), ('a', '9'), ('b', 'x')]


def test_roundtrip_random_pairs_property():
    """Property test: any sequence of k=v pairs serialized to conf text
    parses back to the same ordered pairs (values with spaces/# quoted),
    pinning the tokenizer against the reference's ordered-replay
    contract (src/utils/config.h:20-189)."""
    from hypothesis import given, settings, strategies as st

    keys = st.text('abcdefghijklmnopqrstuvwxyz_0123456789[]->:',
                   min_size=1, max_size=12).filter(
        lambda s: s not in ('data', 'eval', 'iter', 'pred'))
    plain_vals = st.text(
        'abcdefghijklmnopqrstuvwxyz0123456789.,-/', min_size=1, max_size=16)
    spaced_vals = st.text(
        'abcdefghijklmnopqrstuvwxyz #', min_size=1, max_size=16).filter(
        lambda s: s.strip() == s and s)

    @settings(max_examples=60, deadline=None)
    @given(st.lists(st.tuples(keys, st.one_of(plain_vals, spaced_vals)),
                    min_size=1, max_size=12))
    def run(pairs):
        lines = []
        for k, v in pairs:
            needs_quote = (' ' in v) or ('#' in v)
            lines.append(f'{k} = "{v}"' if needs_quote else f'{k} = {v}')
        got = parse_config_string('\n'.join(lines) + '\n')
        assert got == pairs

    run()
