"""conv_lowering experiment knob: im2col (patches GEMM) and split
(per-group convs) must be numerically equivalent to the native
lax.conv_general_dilated lowering — forward AND gradients — so the
on-chip A/B (tools/conv_lowering_bench.py) compares pure performance.
Reference precedent: the im2col-GEMM convolution itself
(``convolution_layer-inl.hpp:70-106``)."""

import numpy as np
import pytest

from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.io.data import DataBatch


def _conf(lowering, ngroup):
    return f"""
netconfig=start
layer[+1] = conv:cv1
  kernel_size = 5
  stride = 2
  pad = 1
  nchannel = 8
  ngroup = {ngroup}
  conv_lowering = {lowering}
  init_sigma = 0.1
layer[+1] = relu:rl1
layer[+1] = flatten:fl1
layer[+1] = fullc:fc1
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = {2 * ngroup},12,12
batch_size = 8
dev = cpu
eta = 0.1
metric[label] = error
"""


def _run(lowering, ngroup, steps=3):
    rng = np.random.RandomState(0)
    trainer = NetTrainer(parse_config_string(_conf(lowering, ngroup)))
    trainer.init_model()
    for _ in range(steps):
        x = rng.randn(8, 2 * ngroup, 12, 12).astype(np.float32)
        y = rng.randint(0, 3, (8, 1)).astype(np.float32)
        trainer.update(DataBatch(x, y))
    from test_device_normalize import snap_params
    return snap_params(trainer)


@pytest.mark.parametrize('lowering,ngroup', [('im2col', 1), ('split', 2)])
def test_lowering_matches_native(lowering, ngroup):
    ref = _run('native', ngroup)
    got = _run(lowering, ngroup)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_allclose(got[k][f], ref[k][f],
                                       rtol=1e-5, atol=1e-6)


def test_im2col_grouped_falls_back_to_native():
    """Each lowering degrades to native off-target, so the knob works as
    a netconfig GLOBAL on mixed nets (im2col on AlexNet only touches the
    ungrouped conv1; the grouped convs run native, bit-identically)."""
    ref = _run('native', 2, steps=2)
    got = _run('im2col', 2, steps=2)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_array_equal(got[k][f], ref[k][f])


def test_unknown_lowering_rejected():
    with pytest.raises(ValueError, match='conv_lowering'):
        _run('imcol', 1, steps=1)


@pytest.mark.parametrize('lowering,ngroup', [('im2col', 1), ('split', 2)])
def test_lowering_on_sharded_mesh(lowering, ngroup):
    """The alternative lowerings must survive GSPMD: im2col's
    (b*oy*ox, k) reshape merges the data-sharded batch axis into the GEMM
    row dim — numerics must still match the 1-device native result on an
    8-device data-parallel mesh (layout cost is the chip A/B's concern,
    correctness is this test's)."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 2 * ngroup, 12, 12).astype(np.float32)
    y = rng.randint(0, 3, (8, 1)).astype(np.float32)

    def run(lower, dev_line):
        conf = _conf(lower, ngroup).replace('dev = cpu', dev_line)
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        for _ in range(2):
            trainer.update(DataBatch(x.copy(), y.copy()))
        from test_device_normalize import snap_params
        return snap_params(trainer)

    ref = run('native', 'dev = cpu')
    got = run(lowering, 'dev = tpu:0-7')
    from test_device_normalize import assert_params_equal
    assert_params_equal(got, ref, rtol=2e-5, atol=1e-6)


def test_auto_is_native_for_now():
    ref = _run('native', 2, steps=2)
    got = _run('auto', 2, steps=2)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_array_equal(got[k][f], ref[k][f])
