"""conv_lowering experiment knob: im2col (patches GEMM) and split
(per-group convs) must be numerically equivalent to the native
lax.conv_general_dilated lowering — forward AND gradients — so the
on-chip A/B (tools/conv_lowering_bench.py) compares pure performance.
Reference precedent: the im2col-GEMM convolution itself
(``convolution_layer-inl.hpp:70-106``)."""

import numpy as np
import pytest

from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.io.data import DataBatch


def _conf(lowering, ngroup):
    return f"""
netconfig=start
layer[+1] = conv:cv1
  kernel_size = 5
  stride = 2
  pad = 1
  nchannel = 8
  ngroup = {ngroup}
  conv_lowering = {lowering}
  init_sigma = 0.1
layer[+1] = relu:rl1
layer[+1] = flatten:fl1
layer[+1] = fullc:fc1
  nhidden = 3
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = {2 * ngroup},12,12
batch_size = 8
dev = cpu
eta = 0.1
metric[label] = error
"""


def _run(lowering, ngroup, steps=3):
    rng = np.random.RandomState(0)
    trainer = NetTrainer(parse_config_string(_conf(lowering, ngroup)))
    trainer.init_model()
    for _ in range(steps):
        x = rng.randn(8, 2 * ngroup, 12, 12).astype(np.float32)
        y = rng.randint(0, 3, (8, 1)).astype(np.float32)
        trainer.update(DataBatch(x, y))
    from test_device_normalize import snap_params
    return snap_params(trainer)


@pytest.mark.parametrize('lowering,ngroup', [('im2col', 1), ('split', 2)])
def test_lowering_matches_native(lowering, ngroup):
    ref = _run('native', ngroup)
    got = _run(lowering, ngroup)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_allclose(got[k][f], ref[k][f],
                                       rtol=1e-5, atol=1e-6)


def test_im2col_grouped_falls_back_to_native():
    """Each lowering degrades to native off-target, so the knob works as
    a netconfig GLOBAL on mixed nets (im2col on AlexNet only touches the
    ungrouped conv1; the grouped convs run native, bit-identically)."""
    ref = _run('native', 2, steps=2)
    got = _run('im2col', 2, steps=2)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_array_equal(got[k][f], ref[k][f])


def test_unknown_lowering_rejected():
    with pytest.raises(ValueError, match='conv_lowering'):
        _run('imcol', 1, steps=1)


class TestSpaceToDepth:
    """conv_s2d: stride-s conv reborn as a stride-1 conv over s*s pixel
    blocks folded into channels (the TPU entry-conv trick) — must be
    exact vs native, forward and gradients, across awkward geometry."""

    @pytest.mark.parametrize('shape', [
        # (in_y, in_x, cin, cout, k, stride, pad)
        (23, 23, 3, 8, 11, 4, 0),    # conv1 class: k not divisible by s
        (12, 12, 3, 8, 5, 2, 2),     # pad aligned to stride
        (12, 12, 3, 8, 5, 2, 1),     # pad % stride != 0: legal — the
                                     # _lowering gate's alignment clause
                                     # is policy, not correctness
        (13, 17, 2, 4, 4, 2, 0),     # rectangular, k divisible by s
        (9, 9, 3, 4, 3, 3, 3),       # k == s, pad == s
    ])
    def test_matches_native_fwd_and_grad(self, shape):
        import jax
        import jax.numpy as jnp

        from cxxnet_tpu.layers.conv import conv_native, conv_s2d
        iy, ix, cin, cout, k, s, p = shape
        rng = np.random.RandomState(1)
        x = jnp.asarray(rng.randn(4, iy, ix, cin), jnp.float32)
        w = jnp.asarray(rng.randn(k, k, cin, cout) * 0.1, jnp.float32)
        strides, pad = (s, s), ((p, p), (p, p))
        ref = conv_native(x, w, strides, pad)
        got = conv_s2d(x, w, strides, pad)
        assert got.shape == ref.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

        def loss(fn, x, w):
            return jnp.sum(fn(x, w, strides, pad) ** 2)

        gx_r, gw_r = jax.grad(lambda a, b: loss(conv_native, a, b),
                              argnums=(0, 1))(x, w)
        gx_s, gw_s = jax.grad(lambda a, b: loss(conv_s2d, a, b),
                              argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx_s), np.asarray(gx_r),
                                   rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(np.asarray(gw_s), np.asarray(gw_r),
                                   rtol=1e-4, atol=1e-4)

    def test_asymmetric_pad_matches_native(self):
        # the function-level signature accepts full (lo, hi) pairs like
        # its siblings; both sides must be honored
        import jax.numpy as jnp

        from cxxnet_tpu.layers.conv import conv_native, conv_s2d
        rng = np.random.RandomState(2)
        x = jnp.asarray(rng.randn(2, 11, 11, 3), jnp.float32)
        w = jnp.asarray(rng.randn(4, 4, 3, 5) * 0.1, jnp.float32)
        pad = ((1, 2), (3, 0))
        ref = conv_native(x, w, (2, 2), pad)
        got = conv_s2d(x, w, (2, 2), pad)
        assert got.shape == ref.shape
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_net_level_matches_native(self):
        # stride 2 pad 2: eligible end-to-end through the trainer
        def run(lowering):
            rng = np.random.RandomState(0)
            conf = _conf(lowering, 1).replace('pad = 1', 'pad = 2')
            trainer = NetTrainer(parse_config_string(conf))
            trainer.init_model()
            for _ in range(3):
                x = rng.randn(8, 2, 12, 12).astype(np.float32)
                y = rng.randint(0, 3, (8, 1)).astype(np.float32)
                trainer.update(DataBatch(x, y))
            from test_device_normalize import snap_params
            return snap_params(trainer)

        ref, got = run('native'), run('s2d')
        for kk in ref:
            for f in ref[kk]:
                np.testing.assert_allclose(got[kk][f], ref[kk][f],
                                           rtol=1e-5, atol=1e-6)

    def test_degrades_off_target(self):
        # pad 1 % stride 2 != 0 -> native bit-identically (knob stays
        # usable as a netconfig global); stride 1 likewise
        ref = _run('native', 1, steps=2)
        got = _run('s2d', 1, steps=2)
        for kk in ref:
            for f in ref[kk]:
                np.testing.assert_array_equal(got[kk][f], ref[kk][f])


@pytest.mark.parametrize('lowering,ngroup',
                         [('im2col', 1), ('split', 2), ('s2d', 1)])
def test_lowering_on_sharded_mesh(lowering, ngroup):
    """The alternative lowerings must survive GSPMD: im2col's
    (b*oy*ox, k) reshape merges the data-sharded batch axis into the GEMM
    row dim — numerics must still match the 1-device native result on an
    8-device data-parallel mesh (layout cost is the chip A/B's concern,
    correctness is this test's)."""
    rng = np.random.RandomState(0)
    x = rng.randn(8, 2 * ngroup, 12, 12).astype(np.float32)
    y = rng.randint(0, 3, (8, 1)).astype(np.float32)

    def run(lower, dev_line):
        conf = _conf(lower, ngroup).replace('dev = cpu', dev_line)
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        for _ in range(2):
            trainer.update(DataBatch(x.copy(), y.copy()))
        from test_device_normalize import snap_params
        return snap_params(trainer)

    ref = run('native', 'dev = cpu')
    got = run(lowering, 'dev = tpu:0-7')
    from test_device_normalize import assert_params_equal
    assert_params_equal(got, ref, rtol=2e-5, atol=1e-6)


def test_auto_is_native_for_now():
    ref = _run('native', 2, steps=2)
    got = _run('auto', 2, steps=2)
    for k in ref:
        for f in ref[k]:
            np.testing.assert_array_equal(got[k][f], ref[k][f])
