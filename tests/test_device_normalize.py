"""device_normalize=1: decoded uint8 stays on the wire and the augment
stage's (x - mean) * scale (``iter_augment_proc-inl.hpp:199-231``) runs
inside the jitted step instead of per-instance on host.

Beyond-reference TPU redesign: the reference always ships float32 batches
to the device (``nnet_impl-inl.hpp:141-185`` Copy of a host float batch);
shipping uint8 halves H2D bytes and removes the host-side cast, which the
e2e receipt showed dominating the wall on a slow host link.  These tests
pin the contract: the deferred path must produce the SAME f32 pixels the
host path produces, through train, eval and predict.
"""

import os

import numpy as np

from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

from test_io import make_img_dataset


def snap_params(trainer):
    return {k: {f: np.asarray(v) for f, v in layer.items()}
            for k, layer in trainer.params.items()}


def assert_params_equal(got, ref, rtol=1e-5, atol=1e-7):
    for k in ref:
        for f in ref[k]:
            np.testing.assert_allclose(got[k][f], ref[k][f],
                                       rtol=rtol, atol=atol)

CONV_CONF = """
netconfig=start
layer[+1] = conv:cv1
  kernel_size = 3
  stride = 1
  nchannel = 4
  init_sigma = 0.05
layer[+1] = relu:rl1
layer[+1] = flatten:fl1
layer[+1] = fullc:fc1
  nhidden = 3
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 3,16,16
batch_size = 4
dev = cpu
eta = 0.1
momentum = 0.9
metric[label] = error
"""


def _chain(lst, root, dev_norm, extra=()):
    cfg = [('iter', 'img'), ('image_list', lst), ('image_root', root),
           ('input_shape', '3,16,16'), ('batch_size', '4'),
           ('round_batch', '1'), ('silent', '1'),
           ('mean_value', '120,118,122'), ('scale', '0.0078125')]
    cfg += list(extra)
    if dev_norm:
        cfg.append(('device_normalize', '1'))
    it = create_iterator(cfg)
    it.init()
    return it


def test_uint8_wire_and_spec_math(tmp_path):
    """Deferred batches are uint8 + spec; applying the spec on host
    reproduces the host-normalized f32 pixels exactly."""
    lst = make_img_dataset(str(tmp_path))
    dev_batches = list(_chain(lst, str(tmp_path), True))
    host_batches = list(_chain(lst, str(tmp_path), False))
    assert len(dev_batches) == len(host_batches) == 3
    spec = dev_batches[0].norm_spec
    assert spec is not None and spec.mean_vals is not None
    assert spec.scale == 0.0078125
    for db, hb in zip(dev_batches, host_batches):
        assert db.data.dtype == np.uint8
        assert hb.data.dtype == np.float32
        assert hb.norm_spec is None
        applied = (db.data.astype(np.float32)
                   - spec.mean_vals[:, None, None]) * spec.scale
        np.testing.assert_allclose(applied, hb.data, rtol=0, atol=1e-6)
        np.testing.assert_array_equal(db.label, hb.label)


def test_random_contrast_forces_host_path(tmp_path):
    """Per-instance contrast/illumination draws bake host RNG into the
    pixels, so device_normalize must fall back to the host path."""
    lst = make_img_dataset(str(tmp_path))
    it = _chain(lst, str(tmp_path), True,
                extra=[('max_random_contrast', '0.2')])
    b = next(iter(it))
    assert b.data.dtype == np.float32
    assert b.norm_spec is None


def test_train_eval_predict_equivalence(tmp_path):
    """Same data through host-normalize and device-normalize chains:
    identical training trajectory, eval metrics, and predictions
    (f32 CPU — exact up to float associativity)."""
    lst = make_img_dataset(str(tmp_path))

    def run(dev_norm):
        trainer = NetTrainer(parse_config_string(CONV_CONF))
        trainer.init_model()
        batches = list(_chain(lst, str(tmp_path), dev_norm))
        for b in batches:
            trainer.update(b)
        ev = trainer.evaluate(iter(batches), 'x')
        preds = np.concatenate([trainer.predict(b) for b in batches])
        return ev, preds, snap_params(trainer)

    ev_h, preds_h, params_h = run(False)
    ev_d, preds_d, params_d = run(True)
    assert ev_d == ev_h
    np.testing.assert_array_equal(preds_d, preds_h)
    for k in params_h:
        for f in params_h[k]:
            np.testing.assert_allclose(params_d[k][f], params_h[k][f],
                                       rtol=1e-5, atol=1e-7)


def test_affine_warp_uint8_matches_float32():
    """The affine warp must compute in float32 regardless of source dtype:
    uint8 input would quantize interpolated pixels and wrap cubic-spline
    overshoot (review finding on the uint8-at-source change)."""
    from cxxnet_tpu.io.iter_augment import ImageAugmenter
    aug = ImageAugmenter()
    aug.set_param('rotate', '30')
    aug.set_param('max_rotate_angle', '30')
    rng_img = np.random.RandomState(0)
    img_u8 = rng_img.randint(0, 255, (3, 20, 20)).astype(np.uint8)
    out_u8 = aug.process(img_u8, np.random.RandomState(1), 20, 20)
    out_f32 = aug.process(img_u8.astype(np.float32),
                          np.random.RandomState(1), 20, 20)
    assert out_u8.dtype == np.float32
    np.testing.assert_allclose(out_u8, out_f32, rtol=0, atol=1e-4)


def test_mean_image_shape_mismatch_skipped(tmp_path):
    """Host path silently skips a mean image whose shape mismatches the
    input; the deferred spec must drop it the same way (not crash the
    jitted broadcast)."""
    from cxxnet_tpu.io.iter_augment import AugmentIterator, _save_mean
    lst = make_img_dataset(str(tmp_path))
    mean_path = str(tmp_path / 'wrong_mean.bin')
    _save_mean(mean_path, np.zeros((3, 8, 8), np.float32))
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)),
           ('input_shape', '3,16,16'), ('batch_size', '4'),
           ('round_batch', '1'), ('silent', '1'),
           ('image_mean', mean_path), ('device_normalize', '1')]
    it = create_iterator(cfg)
    it.init()
    b = next(iter(it))
    assert b.data.dtype == np.uint8
    assert b.norm_spec is not None
    assert b.norm_spec.mean_img is None     # mismatched -> skipped, as host


def test_per_spec_norm_constants(tmp_path):
    """Train and eval chains may normalize differently: the trainer's
    device constants are keyed per spec, not cached once."""
    from cxxnet_tpu.io.data import DataBatch, NormSpec
    trainer = NetTrainer(parse_config_string(CONV_CONF))
    trainer.init_model()
    data = np.zeros((4, 3, 16, 16), np.uint8)
    label = np.zeros((4, 1), np.float32)
    spec_a = NormSpec(mean_vals=np.asarray([1., 2., 3.], np.float32),
                      scale=0.5)
    spec_b = NormSpec(mean_vals=np.asarray([9., 9., 9.], np.float32),
                      scale=0.25)
    norm_a = trainer._norm_args(DataBatch(data, label, norm_spec=spec_a))
    norm_b = trainer._norm_args(DataBatch(data, label, norm_spec=spec_b))
    np.testing.assert_allclose(np.asarray(norm_a[0]).ravel(), [1., 2., 3.])
    np.testing.assert_allclose(np.asarray(norm_b[0]).ravel(), [9., 9., 9.])
    assert float(norm_a[1]) == 0.5 and float(norm_b[1]) == 0.25
    # cached per spec instance
    assert trainer._norm_args(
        DataBatch(data, label, norm_spec=spec_a))[1] is norm_a[1]


def test_multi_step_applies_norm(tmp_path):
    """compile_multi_step / update_n_on_device must apply the deferred
    normalization to raw stacks — a raw uint8 stack with the norm consts
    must land on the same params as pre-normalized f32 steps."""
    lst = make_img_dataset(str(tmp_path))
    dev_batches = list(_chain(lst, str(tmp_path), True))
    host_batches = list(_chain(lst, str(tmp_path), False))
    spec = dev_batches[0].norm_spec

    # reference trajectory: per-batch updates on the host-normalized data
    t_ref = NetTrainer(parse_config_string(CONV_CONF))
    t_ref.init_model()
    for b in host_batches[:2]:
        t_ref.update(b)
    ref = snap_params(t_ref)

    # multi-step trajectory: one dispatch over the raw uint8 stack + norm
    t_dev = NetTrainer(parse_config_string(CONV_CONF))
    t_dev.init_model()
    stack = np.stack([b.data for b in dev_batches[:2]])
    labels = np.stack([b.label for b in dev_batches[:2]])
    multi_fn = t_dev.compile_multi_step(2)
    norm = t_dev._norm_args(dev_batches[0])
    t_dev.update_n_on_device(
        multi_fn, t_dev.shard_batch_stack(stack),
        t_dev.shard_batch_stack(labels, cast=False), norm=norm)
    assert_params_equal(snap_params(t_dev), ref)


def test_update_period_accumulation_equivalence(tmp_path):
    """device_normalize composed with update_period>1: the deferred
    normalize happens per-minibatch inside grad accumulation, so the
    accumulated update must match the host-normalized path exactly."""
    lst = make_img_dataset(str(tmp_path))
    conf = CONV_CONF + 'update_period = 3\n'

    def run(dev_norm):
        trainer = NetTrainer(parse_config_string(conf))
        trainer.init_model()
        for b in _chain(lst, str(tmp_path), dev_norm):
            trainer.update(b)
        return snap_params(trainer)

    ref, got = run(False), run(True)
    assert_params_equal(got, ref)


def test_imgbinx_chain_uint8_wire(tmp_path):
    """The production e2e chain (imgbinx -> augment -> batch ->
    threadbuffer) carries uint8 + spec through every wrapper — the exact
    configuration bench.py e2e_alexnet runs."""
    import subprocess
    import sys as _sys
    lst = make_img_dataset(str(tmp_path), n=10)
    out_bin = str(tmp_path / 'a.bin')
    tool = os.path.join(os.path.dirname(__file__), '..', 'tools',
                        'im2bin.py')
    subprocess.check_call([_sys.executable, tool, lst, str(tmp_path),
                           out_bin])
    cfg = [('iter', 'imgbinx'), ('image_list', lst),
           ('image_bin', out_bin),
           ('input_shape', '3,16,16'), ('batch_size', '4'),
           ('round_batch', '1'), ('silent', '1'),
           ('mean_value', '100,100,100'), ('device_normalize', '1'),
           ('iter', 'threadbuffer')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert len(batches) == 3
    for b in batches:
        assert b.data.dtype == np.uint8
        assert b.norm_spec is not None
        assert b.norm_spec.mean_vals is not None


def test_mean_image_spec(tmp_path):
    """image_mean file: the spec carries the cached mean image and the
    deferred math matches the host path."""
    lst = make_img_dataset(str(tmp_path))
    mean_path = str(tmp_path / 'mean.bin')
    base = [('iter', 'img'), ('image_list', lst),
            ('image_root', str(tmp_path)),
            ('input_shape', '3,16,16'), ('batch_size', '4'),
            ('round_batch', '1'), ('silent', '1'),
            ('image_mean', mean_path)]
    host_it = create_iterator(list(base))
    host_it.init()          # builds + caches mean.bin
    dev_it = create_iterator(base + [('device_normalize', '1')])
    dev_it.init()
    spec = next(iter(dev_it)).norm_spec
    assert spec is not None and spec.mean_img is not None
    assert spec.mean_img.shape == (3, 16, 16)
    for db, hb in zip(dev_it, host_it):
        applied = (db.data.astype(np.float32) - spec.mean_img) * spec.scale
        np.testing.assert_allclose(applied, hb.data, rtol=0, atol=1e-5)
