"""End-to-end CLI drives (the reference's example-configs-as-tests idea,
SURVEY §4.4): full subprocess runs of ``python -m cxxnet_tpu.main``."""

import os
import re
import subprocess
import sys

import numpy as np
import pytest
from PIL import Image

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(conf_path, cwd, *overrides, timeout=240):
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', conf_path, *overrides],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, (r.stdout, r.stderr)
    return r


def _final_eval(stderr: str, name: str) -> float:
    vals = re.findall(rf'{name}-error:([0-9.eE+-]+)', stderr)
    assert vals, stderr
    return float(vals[-1])


def make_quadrant_images(root, n, size=24, fmt='png'):
    rng = np.random.RandomState(0)
    lines = []
    for i in range(n):
        c = i % 4
        img = np.zeros((size, size, 3), np.uint8)
        r0, c0 = (c // 2) * (size // 2), (c % 2) * (size // 2)
        img[r0:r0 + size // 2, c0:c0 + size // 2] = \
            rng.randint(120, 255, (size // 2, size // 2, 3))
        Image.fromarray(img).save(os.path.join(root, f'im{i}.{fmt}'))
        lines.append(f'{i}\t{c}\tim{i}.{fmt}')
    lst = os.path.join(root, 'train.lst')
    with open(lst, 'w') as f:
        f.write('\n'.join(lines) + '\n')
    return lst


def test_cli_imgbin_conv_train(tmp_path):
    """Native im2bin pack -> imgbin + threadbuffer -> conv net -> pred."""
    make_quadrant_images(str(tmp_path), 32)
    tool = os.path.join(REPO, 'runtime', 'im2bin')
    if not os.path.exists(tool):
        tool = [sys.executable, os.path.join(REPO, 'tools', 'im2bin.py')]
    else:
        tool = [tool]
    subprocess.check_call(tool + ['train.lst', '.', 'train.bin'],
                          cwd=str(tmp_path))
    conf = tmp_path / 'conv.conf'
    conf.write_text("""
data = train
iter = imgbin
  image_list = train.lst
  image_bin = train.bin
  shuffle = 1
iter = threadbuffer
iter = end
eval = trainset
iter = imgbin
  image_list = train.lst
  image_bin = train.bin
iter = end
netconfig = start
layer[0->1] = conv:c1
  nchannel = 8
  kernel_size = 5
  stride = 2
layer[1->2] = relu
layer[2->3] = max_pooling
  kernel_size = 2
  stride = 2
layer[3->4] = flatten
layer[4->5] = fullc:f1
  nhidden = 4
layer[5->5] = softmax
netconfig = end
input_shape = 3,24,24
batch_size = 8
dev = cpu
eta = 0.01
momentum = 0.9
num_round = 3
metric[label] = error
divideby = 256
""")
    r = _run_cli(str(conf), str(tmp_path))
    assert _final_eval(r.stderr, 'trainset') < 0.2
    # pred task against the saved model
    pred_conf = tmp_path / 'pred.conf'
    pred_conf.write_text(conf.read_text().replace('data = train', 'pred = out.txt', 1)
                         + '\ntask = pred\nmodel_in = ./models/0003.model\n')
    _run_cli(str(pred_conf), str(tmp_path))
    preds = np.loadtxt(tmp_path / 'out.txt')
    labels = np.arange(32) % 4
    assert (preds == labels).mean() > 0.8


def test_cli_augmented_training(tmp_path):
    """kaggle_bowl-style heavy augmentation (rotate/shear/crop/mirror)
    through the img iterator — the run must parse, augment, and learn."""
    make_quadrant_images(str(tmp_path), 24, size=32)
    conf = tmp_path / 'aug.conf'
    conf.write_text("""
data = train
iter = img
  image_list = train.lst
  image_root = .
  shuffle = 1
  rand_crop = 1
  rand_mirror = 1
  max_rotate_angle = 15
  max_shear_ratio = 0.1
  min_crop_size = 24
  max_crop_size = 28
iter = end
eval = trainset
iter = img
  image_list = train.lst
  image_root = .
iter = end
netconfig = start
layer[0->1] = conv:c1
  nchannel = 6
  kernel_size = 5
  stride = 2
layer[1->2] = relu
layer[2->3] = flatten
layer[3->4] = fullc:f1
  nhidden = 4
layer[4->4] = softmax
netconfig = end
input_shape = 3,24,24
batch_size = 8
dev = cpu
eta = 0.02
momentum = 0.9
num_round = 4
metric[label] = error
divideby = 256
""")
    r = _run_cli(str(conf), str(tmp_path))
    assert _final_eval(r.stderr, 'trainset') < 0.3


@pytest.mark.slow
def test_two_worker_distributed_launch(tmp_path):
    """2-process jax.distributed data-parallel run via the launcher
    (the reference's mpi.conf 2-worker topology, SURVEY §4.4)."""
    import gzip
    import struct
    rng = np.random.RandomState(0)

    def blobs(n):
        y = rng.randint(0, 4, n)
        x = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(y):
            r0, c0 = (c // 2) * 14, (c % 2) * 14
            x[i, r0:r0 + 14, c0:c0 + 14] = rng.randint(128, 255, (14, 14))
        return x, y

    for tag, cnt in (('train', 800), ('t10k', 200)):
        x, y = blobs(cnt)
        with gzip.open(tmp_path / f'{tag}-images.gz', 'wb') as f:
            f.write(struct.pack('>iiii', 2051, cnt, 28, 28))
            f.write(x.tobytes())
        with gzip.open(tmp_path / f'{tag}-labels.gz', 'wb') as f:
            f.write(struct.pack('>ii', 2049, cnt))
            f.write(y.astype(np.uint8).tobytes())
    (tmp_path / 'mlp.conf').write_text("""
data = train
iter = mnist
  path_img = train-images.gz
  path_label = train-labels.gz
  shuffle = 1
iter = end
eval = test
iter = mnist
  path_img = t10k-images.gz
  path_label = t10k-labels.gz
iter = end
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = sigmoid
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig = end
input_shape = 1,1,784
batch_size = 100
input_flat = 1
dev = cpu
eta = 0.1
momentum = 0.9
num_round = 2
metric[label] = error
""")
    import socket
    with socket.socket() as s:       # grab a free coordinator port
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    (tmp_path / 'dist.conf').write_text(
        'num_workers = 2\napp_conf = mlp.conf\n'
        f'coordinator = 127.0.0.1:{port}\n'
        'arg = param_server=dist silent=1\n')
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'launch_dist.py'),
         str(tmp_path / 'dist.conf')],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert _final_eval(r.stderr, 'test') < 0.1


def test_transformer_example_runs(tmp_path):
    """The composed-parallelism LM example must run (and reduce loss) on
    the virtual CPU mesh."""
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'example', 'transformer',
                                      'train_lm.py'),
         '--steps', '6', '--seq', '32', '--batch', '4'],
        capture_output=True, text=True, timeout=240, env=env)
    assert r.returncode == 0, (r.stdout, r.stderr)
    losses = re.findall(r'loss ([0-9.]+)', r.stdout)
    assert len(losses) >= 2 and float(losses[-1]) < float(losses[0])


def test_partition_maker_multipart_dataset(tmp_path):
    """imgbin_partition_maker splits + packs; the multi-part dataset reads
    back through image_conf_prefix/image_conf_ids (both imgbin and the
    two-stage imgbinx)."""
    make_quadrant_images(str(tmp_path), 24)
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    subprocess.check_call(
        [sys.executable, os.path.join(REPO, 'tools',
                                      'imgbin_partition_maker.py'),
         '--img_list', 'train.lst', '--img_root', './',
         '--prefix', 'part%02d', '--out', 'parts',
         '--partition_size', '1', '--shuffle', '1', '--pack'],
        cwd=str(tmp_path), env=env)
    parts = sorted(os.listdir(tmp_path / 'parts'))
    nbin = sum(p.endswith('.bin') for p in parts)
    assert nbin >= 1
    assert (tmp_path / 'Gen.mk').exists()
    from cxxnet_tpu.io.data import create_iterator
    for kind in ('imgbin', 'imgbinx'):
        cfg = [('iter', kind),
               ('image_conf_prefix', str(tmp_path / 'parts' / 'part%02d')),
               ('image_conf_ids', f'1-{nbin}'),
               ('input_shape', '3,24,24'), ('batch_size', '4'),
               ('silent', '1')]
        it = create_iterator(cfg)
        it.init()
        seen = [int(i) for b in it
                for i in b.inst_index[:b.batch_size - b.num_batch_padd]]
        assert sorted(seen) == list(range(24)), kind


def test_kaggle_bowl_workflow(tmp_path):
    """The full kaggle_bowl predict workflow: gen_img_list over a class
    folder tree -> im2bin -> train -> task=pred_raw raw probability rows ->
    make_submission.py csv (reference example/kaggle_bowl)."""
    import csv
    bowl = os.path.join(REPO, 'example', 'kaggle_bowl')
    rng = np.random.RandomState(1)
    # class folder tree + sample_submission head
    classes = ['acantharia', 'copepod', 'diatom']
    for ci, cls in enumerate(classes):
        d = tmp_path / 'train' / cls
        d.mkdir(parents=True)
        for k in range(6):
            img = np.zeros((24, 24, 3), np.uint8)
            img[ci * 8:(ci + 1) * 8, :, :] = rng.randint(130, 255, (8, 24, 3))
            Image.fromarray(img).save(d / f'{cls}{k}.png')
    with open(tmp_path / 'sample_submission.csv', 'w') as f:
        f.write('image,' + ','.join(classes) + '\n')
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')

    def run(script, *args):
        subprocess.check_call([sys.executable, os.path.join(bowl, script),
                               *args], cwd=str(tmp_path), env=env)

    run('gen_img_list.py', 'train', 'sample_submission.csv',
        str(tmp_path / 'train'), 'img.lst')
    subprocess.check_call(
        [sys.executable, os.path.join(REPO, 'tools', 'im2bin.py'),
         'img.lst', './', 'train.bin'], cwd=str(tmp_path), env=env)
    conf = tmp_path / 'bowl_mini.conf'
    conf.write_text("""
data = train
iter = imgbin
  image_list = img.lst
  image_bin = train.bin
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:f1
  nhidden = 16
layer[2->3] = relu
layer[3->4] = fullc:f2
  nhidden = 3
layer[4->4] = softmax
netconfig = end
input_shape = 3,24,24
batch_size = 6
dev = cpu
eta = 0.05
momentum = 0.9
num_round = 6
metric = error
divideby = 256
""")
    _run_cli(str(conf), str(tmp_path))
    pred_conf = tmp_path / 'predraw.conf'
    pred_conf.write_text(conf.read_text().replace(
        'data = train', 'pred = test.txt', 1)
        + '\ntask = pred_raw\nmodel_in = ./models/0006.model\n')
    _run_cli(str(pred_conf), str(tmp_path))
    rows = np.loadtxt(tmp_path / 'test.txt')
    assert rows.shape == (18, 3)
    np.testing.assert_allclose(rows.sum(axis=1), 1.0, atol=1e-4)
    run('make_submission.py', 'sample_submission.csv', 'img.lst',
        'test.txt', 'out.csv')
    with open(tmp_path / 'out.csv', newline='') as f:
        got = list(csv.reader(f))
    assert got[0] == ['image'] + classes
    assert len(got) == 19
    # predictions should have learned the class structure: argmax matches
    # the lst labels for most rows
    lst = {os.path.basename(l.rstrip('\n').split('\t')[2]):
           int(l.split('\t')[1]) for l in open(tmp_path / 'img.lst')}
    hits = sum(int(np.argmax([float(v) for v in row[1:]])) == lst[row[0]]
               for row in got[1:])
    assert hits >= 14, hits


def test_cli_rec_at_5_on_1000_classes(tmp_path):
    """rec@1/rec@5 metrics through the CLI on synthetic 1000-class data
    (the ImageNet metric pair, utils/metric.h:147-171): a memorizing net
    must reach rec@5 ~ 1.0 on its train set while an untrained net sits
    near 5/1000."""
    rng = np.random.RandomState(9)
    lines = []
    for i in range(40):
        img = rng.randint(0, 255, (12, 12, 3), np.uint8)
        Image.fromarray(img).save(tmp_path / f'i{i}.png')
        lines.append(f'{i}\t{rng.randint(0, 1000)}\ti{i}.png')
    (tmp_path / 'a.lst').write_text('\n'.join(lines) + '\n')
    conf = tmp_path / 'rec.conf'
    conf.write_text("""
data = train
iter = img
  image_list = a.lst
  image_root = ./
iter = end
eval = trainset
iter = img
  image_list = a.lst
  image_root = ./
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:f1
  nhidden = 128
layer[2->3] = relu
layer[3->4] = fullc:f2
  nhidden = 1000
layer[4->4] = softmax
netconfig = end
input_shape = 3,12,12
batch_size = 8
dev = cpu
eta = 0.05
momentum = 0.9
num_round = 60
metric[label] = rec@5
metric[label] = rec@1
divideby = 256
silent = 1
""")
    r = _run_cli(str(conf), str(tmp_path))
    rec5 = re.findall(r'trainset-rec@5:([0-9.eE+-]+)', r.stderr)
    rec1 = re.findall(r'trainset-rec@1:([0-9.eE+-]+)', r.stderr)
    assert rec5 and rec1, r.stderr
    assert float(rec5[0]) < 0.3, 'untrained rec@5 should be near chance'
    assert float(rec5[-1]) > 0.9, (rec5[0], rec5[-1])
    assert float(rec1[-1]) <= float(rec5[-1]) + 1e-9


def test_cli_attachtxt_extra_data_trains(tmp_path):
    """attachtxt side features flow into extra_data nodes (in_1) through
    the CLI trainer: labels here are a function of the attached vector
    ONLY, so reaching 0 error proves the extra input is consumed
    (iter_attach_txt-inl.hpp:15-99, data.h extra_data contract)."""
    rng = np.random.RandomState(11)
    lines, rows = [], []
    for i in range(15):
        c = rng.randint(0, 4)
        img = rng.randint(0, 255, (8, 8, 3), np.uint8)   # pure noise
        Image.fromarray(img).save(tmp_path / f'x{i}.png')
        vec = rng.rand(6) * 0.1
        vec[c] += 2.0                                     # signal in extra
        rows.append(' '.join(f'{v:.5f}' for v in vec))
        lines.append(f'{i}\t{c}\tx{i}.png')
    (tmp_path / 'a.lst').write_text('\n'.join(lines) + '\n')
    (tmp_path / 'attach.txt').write_text('\n'.join(rows) + '\n')
    conf = tmp_path / 'extra.conf'
    conf.write_text("""
data = train
iter = img
  image_list = a.lst
  image_root = ./
iter = attachtxt
  attach_file = attach.txt
iter = end
eval = trainset
iter = img
  image_list = a.lst
  image_root = ./
iter = attachtxt
  attach_file = attach.txt
iter = end
extra_data_num = 1
extra_data_shape[0] = 1,1,6
netconfig = start
layer[in_1->2] = fullc:fx
  nhidden = 4
layer[2->2] = softmax
netconfig = end
input_shape = 3,8,8
batch_size = 5
dev = cpu
eta = 0.5
momentum = 0.9
num_round = 30
metric = error
silent = 1
""")
    r = _run_cli(str(conf), str(tmp_path))
    assert _final_eval(r.stderr, 'trainset') == 0.0, r.stderr[-500:]


def test_cli_test_io_mode(tmp_path):
    """test_io=1 pumps the data pipeline without compute
    (cxxnet_main.cpp:98,362-375); with test_skipread=1 one cached batch is
    re-served to bound max throughput (iter_batch_proc-inl.hpp:46,72-74)."""
    make_quadrant_images(str(tmp_path), 12)
    conf = tmp_path / 'io.conf'
    conf.write_text("""
data = train
iter = img
  image_list = train.lst
  image_root = ./
  test_skipread = 1
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:f1
  nhidden = 4
layer[2->2] = softmax
netconfig = end
input_shape = 3,24,24
batch_size = 4
dev = cpu
num_round = 1
test_io = 1
metric = error
""")
    r = _run_cli(str(conf), str(tmp_path))
    assert 'start I/O test' in r.stdout
    assert 'error' not in r.stderr.lower()
    # like the reference, the round-end SaveModel runs even in test_io
    # mode (cxxnet_main.cpp TaskTrain saves unconditionally)
    assert (tmp_path / 'models' / '0001.model').exists()


def test_transformer_example_cli(tmp_path):
    """example/transformer/train_lm.py runs the composed 4-axis mesh from
    the command line (virtual CPU devices), with remat, and reports a
    finite decreasing loss."""
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, 'example', 'transformer',
                                      'train_lm.py'),
         '--pp', '2', '--dp', '1', '--sp', '2', '--tp', '2',
         '--steps', '4', '--seq', '32', '--batch', '2', '--remat'],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=600)
    assert r.returncode == 0, (r.stdout, r.stderr)
    losses = [float(m) for m in
              re.findall(r'loss ([0-9.]+)', r.stdout)]
    assert losses and all(np.isfinite(losses))
    assert losses[-1] < losses[0]
