"""Elastic multi-host training suite (`-m dist`, tier-1, CPU-only).

Three layers, mirroring doc/fault_tolerance.md "Multi-host recovery":

* protocol/membership units — framing, rendezvous, push/pull assembly,
  barrier value exchange, rollback on peer death, heartbeat-timeout
  membership (threads, no subprocess, no jax device work),
* the input-sharding invariant — per-host streams through the nworker
  pool interleave back into the 1-host stream bitwise at 1/2/4 hosts,
* the chaos drills — REAL multi-process workers over localhost
  (``python -m cxxnet_tpu.main`` under the ElasticLauncher): a worker
  killed mid-epoch (``host_loss``), a network partition + divergence in
  one run, at 1, 2, and 4 hosts — every run's final params BITWISE
  equal to the fault-free single-host twin's.
"""

from __future__ import annotations

import io
import os
import socket
import subprocess
import sys
import threading

import numpy as np
import pytest

from cxxnet_tpu.parallel.elastic import (ElasticClient, ElasticConfig,
                                         ElasticCoordinator,
                                         ElasticLauncher, recv_frame,
                                         send_frame)
from cxxnet_tpu.runtime import faults

pytestmark = pytest.mark.dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_INST = 32          # instances in the shared dataset
BATCH = 16           # GLOBAL batch size -> 2 steps/epoch
ROUNDS = 4           # -> 8 optimizer steps end-to-end
FINAL_MODEL = f'{ROUNDS:04d}.model'

CONF = f"""
data = train
iter = imgbin
  image_list = train.lst
  image_bin = train.bin
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:f1
  nhidden = 8
layer[2->3] = sigmoid
layer[3->4] = fullc:f2
  nhidden = 4
layer[4->4] = softmax
netconfig = end
input_shape = 3,12,12
batch_size = {BATCH}
dev = cpu
eta = 0.05
momentum = 0.9
num_round = {ROUNDS}
divideby = 256
train.save_every = 4
train.watchdog_deadline = 60
dist.shards = 4
dist.heartbeat = 1.0
silent = 1
"""


# --- shared dataset / helpers ----------------------------------------------


@pytest.fixture(scope='module')
def workdir(tmp_path_factory):
    """One imgbin dataset (a single standard 64MB page, so worker
    subprocesses read it with the stock reader — no page-size games)
    plus the conf every drill shares."""
    from PIL import Image

    from cxxnet_tpu.io.iter_stream import append_records
    root = tmp_path_factory.mktemp('elastic')
    rng = np.random.RandomState(7)
    recs = []
    for i in range(N_INST):
        cls = i % 4
        img = np.zeros((12, 12, 3), np.uint8)
        r0, c0 = (cls // 2) * 6, (cls % 2) * 6
        img[r0:r0 + 6, c0:c0 + 6] = rng.randint(100, 255, (6, 6, 3))
        buf = io.BytesIO()
        Image.fromarray(img).save(buf, format='JPEG', quality=92)
        recs.append((i, [float(cls)], buf.getvalue()))
    append_records(str(root / 'train.bin'), str(root / 'train.lst'), recs)
    (root / 'elastic.conf').write_text(CONF)
    return root


def _sub_env():
    env = dict(os.environ)
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    env['JAX_PLATFORMS'] = 'cpu'
    # workers are plain 1-device CPU processes (the pytest parent's
    # 8-device virtual mesh flag must not leak in)
    env['XLA_FLAGS'] = ''
    return env


def _launch(workdir, model_dir, hosts, *overrides, rejoin=2):
    la = ElasticLauncher(
        argv=['elastic.conf', f'model_dir={model_dir}', *overrides],
        hosts=hosts, rejoin=rejoin, heartbeat=1.0, env=_sub_env(),
        cwd=str(workdir))
    rc = la.run()
    return rc, la


def _run_single_host_inprocess(workdir, model_dir, *overrides):
    """The fault-free single-host twin, run in THIS process (the
    dist.hosts=1 path spins its own local coordinator)."""
    from cxxnet_tpu.main import main as cli_main
    old = os.getcwd()
    os.chdir(workdir)
    try:
        rc = cli_main(['elastic.conf', 'dist.hosts=1',
                       f'model_dir={model_dir}', *overrides])
    finally:
        os.chdir(old)
    assert rc == 0


def _final_params(workdir, model_dir):
    """Params of the run's final model file, as host arrays."""
    import jax

    from cxxnet_tpu.nnet import checkpoint as model_io
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_file
    cfg = parse_config_file(str(workdir / 'elastic.conf'))
    out = {}

    def _read(f):
        f.read(4)
        tr = NetTrainer(cfg)
        tr.load_model(f)
        out['params'] = jax.device_get(tr.params)

    path = str(workdir / model_dir / FINAL_MODEL)
    model_io.read_model_file(path, _read)
    return out['params']


def _assert_params_equal(a, b):
    import jax
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.fixture(scope='module')
def twin(workdir):
    """Fault-free single-host twin params — the reference every drill's
    final params must equal BITWISE."""
    _run_single_host_inprocess(workdir, 'm_twin')
    return _final_params(workdir, 'm_twin')


# --- protocol / membership units -------------------------------------------


def test_frame_roundtrip():
    a, b = socket.socketpair()
    try:
        payload = np.arange(7, dtype=np.float32)
        send_frame(a, {'op': 'push', 'step': 3},
                   (payload.tobytes(), b'\x01\x02'))
        hdr, bufs = recv_frame(b)
        assert hdr['op'] == 'push' and hdr['step'] == 3
        np.testing.assert_array_equal(
            np.frombuffer(bufs[0], np.float32), payload)
        assert bufs[1] == b'\x01\x02'
    finally:
        a.close()
        b.close()


def _client(addr, rank, nhosts, **kw):
    c = ElasticClient(addr, rank, nhosts, heartbeat=0.2,
                      sync_timeout=10.0, rendezvous_timeout=10.0, **kw)
    c.connect()
    return c


def test_coordinator_rendezvous_push_barrier_and_rollback():
    coord = ElasticCoordinator(2, heartbeat_timeout=30.0)
    addr = coord.start()
    c0 = c1 = None
    try:
        c0 = _client(addr, 0, 2)
        c1 = _client(addr, 1, 2)
        gens = [None, None]
        t = threading.Thread(
            target=lambda: gens.__setitem__(1, c1.rendezvous()))
        t.start()
        gens[0] = c0.rendezvous()
        t.join(10)
        assert gens == [0, 0]

        # push/pull: each host one shard; both receive the full set,
        # byte-identical to what was pushed
        g0 = np.array([1.0, 2.0], np.float32)
        g1 = np.array([3.0, 4.0], np.float32)
        out = [None, None]

        def push1():
            out[1] = c1.all_shards(0, [1], [g1],
                                   [np.array([0.5], np.float32)])

        t = threading.Thread(target=push1)
        t.start()
        out[0] = c0.all_shards(0, [0], [g0],
                               [np.array([0.25], np.float32)])
        t.join(10)
        for full, losses in out:
            assert sorted(full) == [0, 1]
            np.testing.assert_array_equal(full[0], g0)
            np.testing.assert_array_equal(full[1], g1)
            assert losses[0] == np.float32(0.25)
            assert losses[1] == np.float32(0.5)

        # barrier exchanges values by rank
        vals = [None, None]
        t = threading.Thread(
            target=lambda: vals.__setitem__(1, c1.barrier('v', value='b')))
        t.start()
        vals[0] = c0.barrier('v', value='a')
        t.join(10)
        assert vals[0] == {0: 'a', 1: 'b'} == vals[1]

        # peer death mid-step: c1 vanishes ABRUPTLY (no goodbye), c0's
        # next push gets a rollback -> HostLossError, generation moves
        c1.abort()
        c1 = None
        with pytest.raises(faults.HostLossError):
            c0.all_shards(1, [0], [g0], [np.array([0.0], np.float32)])
        assert coord.generation() == 1

        # resync: survivor + a fresh rank-1 rendezvous into gen 1
        c1 = _client(addr, 1, 2)
        got = [None, None]
        t = threading.Thread(
            target=lambda: got.__setitem__(1, c1.rendezvous()))
        t.start()
        got[0] = c0.resync('test', 1)
        t.join(10)
        assert got == [1, 1]
    finally:
        for c in (c0, c1):
            if c is not None:
                c.close()
        coord.stop()


def test_heartbeat_timeout_declares_host_lost():
    coord = ElasticCoordinator(2, heartbeat_timeout=0.6)
    addr = coord.start()
    c0 = None
    raw = None
    try:
        c0 = _client(addr, 0, 2)
        # rank 1 joins WITHOUT ever heartbeating (raw hello socket)
        host, _, port = addr.rpartition(':')
        raw = socket.create_connection((host, int(port)))
        done = []

        def hello():
            send_frame(raw, {'op': 'hello', 'rank': 1})
            done.append(recv_frame(raw)[0])

        t = threading.Thread(target=hello)
        t.start()
        assert c0.rendezvous() == 0
        t.join(10)
        assert done and done[0]['op'] == 'welcome'
        # the silent member is declared lost; the survivor's next op
        # rolls back
        with pytest.raises(faults.HostLossError):
            c0.barrier('fence', value=1, timeout=15.0)
        assert any('missed heartbeats' in e for e in coord.events())
    finally:
        if raw is not None:
            raw.close()
        if c0 is not None:
            c0.close()
        coord.stop()


def test_elastic_config_validation():
    with pytest.raises(faults.DistInitError):
        ElasticConfig(hosts=2, rank=2, batch_size=16).resolve()
    with pytest.raises(ValueError):
        ElasticConfig(hosts=2, rank=0, shards=3, batch_size=16).resolve()
    with pytest.raises(ValueError):
        ElasticConfig(hosts=2, rank=0, shards=4, batch_size=18).resolve()
    cfg = ElasticConfig(hosts=2, rank=1, shards=4, batch_size=16).resolve()
    assert cfg.owned_shards == [1, 3]


# --- fault-plan grammar -----------------------------------------------------


def test_fault_plan_host_loss_partition_grammar():
    p = faults.FaultPlan.parse(
        'host_loss=10;host_loss@every=7:1;partition=5:3.5;'
        'partition@every=9')
    d = p.describe()
    assert 'host_loss=10' in d and 'host_loss@every=7:1' in d
    assert 'partition=5:3.5' in d and 'partition@every=9:30' in d
    # partition fires once per distinct step (replays converge)
    assert p.on_elastic_step(5, 0, 2) == 3.5
    assert p.on_elastic_step(5, 0, 2) is None
    # host_loss default target is the highest rank; a non-target rank
    # never fires
    assert p.on_elastic_step(10, 0, 2) is None
    # disarmed on incarnation > 0 (allow_kill=False): recorded, no kill
    p2 = faults.FaultPlan.parse('host_loss=3')
    assert p2.on_elastic_step(3, 1, 2, allow_kill=False) is None
    assert p2.fired() == ['host_loss=3:1#disarmed']


# --- host-sharded input stream ---------------------------------------------


def _aug_stage(workdir, hosts, rank, nworker=2):
    from cxxnet_tpu.io.iter_augment import AugmentIterator
    from cxxnet_tpu.io.iter_imbin import ImageBinIterator
    src = ImageBinIterator()
    it = AugmentIterator(src)
    for k, v in (('image_list', str(workdir / 'train.lst')),
                 ('image_bin', str(workdir / 'train.bin')),
                 ('input_shape', '3,12,12'), ('divideby', '256'),
                 ('silent', '1'), ('nworker', str(nworker)),
                 ('elastic_hosts', str(hosts)),
                 ('elastic_rank', str(rank))):
        it.set_param(k, v)
    it.init()
    return it


def _collect(it):
    return [(inst.index, inst.data.tobytes(), inst.label.tobytes())
            for inst in it]


def test_global_stream_bitwise_identical_across_host_counts(workdir):
    """THE input invariant: per-host streams (nworker pool active on
    every host) interleave round-robin back into the 1-host stream,
    bitwise, at 2 and 4 hosts."""
    ref = _collect(_aug_stage(workdir, 1, 0))
    assert len(ref) == N_INST
    for hosts in (2, 4):
        streams = [_collect(_aug_stage(workdir, hosts, r))
                   for r in range(hosts)]
        merged = []
        for i in range(N_INST):
            merged.append(streams[i % hosts][i // hosts])
        assert merged == ref


def test_serial_path_rejects_elastic_sharding(workdir):
    it = _aug_stage(workdir, 2, 0, nworker=0)
    with pytest.raises(ValueError, match='nworker'):
        next(iter(it))


def test_stream_fence_pins_pass_length(workdir):
    """stream_fence ends an imgbin_stream pass after exactly N
    instances — the host-agreed pass length for growing files."""
    from cxxnet_tpu.io.iter_stream import ImageBinStreamIterator
    it = ImageBinStreamIterator()
    for k, v in (('image_list', str(workdir / 'train.lst')),
                 ('image_bin', str(workdir / 'train.bin')),
                 ('silent', '1'), ('stream_fence', '10')):
        it.set_param(k, v)
    it.init()
    first = [inst.index for inst in it]
    second = [inst.index for inst in it]
    assert first == list(range(10)) == second


# --- the chaos drills (real multi-process workers) -------------------------


def test_host_loss_drill_two_hosts_bitwise_twin(workdir, twin):
    """Headline: kill rank 1 mid-epoch; survivor restores-last-good,
    the replacement rejoins, final params == the fault-free single-host
    twin, bitwise."""
    rc, la = _launch(workdir, 'm_kill2', 2,
                     'train.fault_plan=host_loss=5:1')
    assert rc == 0
    assert (1, 1) in la.respawns
    assert any('lost rank 1' in e for e in la.coordinator.events())
    _assert_params_equal(_final_params(workdir, 'm_kill2'), twin)


def test_host_loss_drill_one_and_four_hosts(workdir, twin):
    """The same drill at the matrix edges: a single-host run whose only
    worker dies (launcher respawns it), and a 4-host run losing its
    highest rank."""
    rc, la = _launch(workdir, 'm_kill1', 1,
                     'train.fault_plan=host_loss=5')
    assert rc == 0 and (0, 1) in la.respawns
    _assert_params_equal(_final_params(workdir, 'm_kill1'), twin)

    rc, la = _launch(workdir, 'm_kill4', 4,
                     'train.fault_plan=host_loss=5')
    assert rc == 0 and (3, 1) in la.respawns
    _assert_params_equal(_final_params(workdir, 'm_kill4'), twin)


def test_partition_and_divergence_drill_two_hosts(workdir, twin):
    """One run, two faults: a 6s full network partition at step 3
    (outliving the 5s heartbeat window -> declared lost, all roll
    back), then an injected NaN at step 6 (every host trips the breaker
    deterministically, one generation bump).  Still bitwise-twin."""
    rc, la = _launch(workdir, 'm_chaos', 2,
                     'train.fault_plan=partition=3:6;nan_at_step=6',
                     'train.nan_breaker=1')
    assert rc == 0
    assert la.respawns == []          # nobody died: both faults rejoin
    events = la.coordinator.events()
    assert sum('rendezvous complete' in e for e in events) >= 3
    _assert_params_equal(_final_params(workdir, 'm_chaos'), twin)


def test_cli_launcher_end_to_end(workdir, twin):
    """The full CLI surface: ``python -m cxxnet_tpu.main conf
    dist.hosts=2`` IS the launcher — coordinator, spawn, kill, respawn,
    rejoin, and the final model, in one command."""
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', 'elastic.conf',
         'dist.hosts=2', 'model_dir=m_cli', 'silent=0',
         'train.fault_plan=host_loss=5:1'],
        cwd=str(workdir), env=_sub_env(), capture_output=True,
        text=True, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert 'respawning' in r.stdout
    assert '[elastic] rank 0 done' in r.stdout
    # both workers print the same params crc — and the model file
    # equals the twin byte-for-byte
    crcs = {line.split('params_crc=')[1].split()[0]
            for line in r.stdout.splitlines() if 'params_crc=' in line}
    assert len(crcs) == 1
    _assert_params_equal(_final_params(workdir, 'm_cli'), twin)


# --- fleet observability (graftwatch, doc/observability.md "Fleet view") ---


def test_fleet_obs_merged_metrics_slos_and_trace(workdir, twin, tmp_path):
    """Acceptance: 2 REAL worker ranks under the launcher with fleet
    observability on — the merged /metrics carries both ranks' gauges
    under rank labels, a fleet-scoped SLO evaluates to a typed verdict,
    the merged Chrome trace loads with one lane per host, and the
    scrape survives rank 1's mid-run death (host_loss drill) — all
    while the run stays bitwise-twin."""
    import json
    import time as _time
    import urllib.request

    trace_out = str(tmp_path / 'fleet_trace.json')
    la = ElasticLauncher(
        argv=['elastic.conf', 'model_dir=m_fleet',
              'train.fault_plan=host_loss=5:1'],
        hosts=2, rejoin=2, heartbeat=1.0, env=_sub_env(),
        cwd=str(workdir), fleet_port=0, sample_every=0.3,
        slo_specs=[('progress', 'fleet.elastic_steps.max.rate>=0.01@6'),
                   ('membership', 'fleet.ranks_alive>=1@3:10')],
        trace_merge=trace_out)
    rc_box = {}
    t = threading.Thread(target=lambda: rc_box.setdefault('rc', la.run()))
    t.start()
    try:
        deadline = _time.monotonic() + 180
        while la.fleet_server is None and t.is_alive() \
                and _time.monotonic() < deadline:
            _time.sleep(0.05)
        assert la.fleet_server is not None, 'fleet endpoint never came up'
        url = la.fleet_server.url
        text = ''
        while t.is_alive() and _time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(f'{url}/metrics',
                                            timeout=5) as r:
                    text = r.read().decode()
            except OSError:
                _time.sleep(0.1)
                continue
            if ('cxxnet_elastic_steps{rank="0"}' in text
                    and 'cxxnet_elastic_steps{rank="1"}' in text):
                break
            _time.sleep(0.2)
        assert 'cxxnet_elastic_steps{rank="0"}' in text, text[:2000]
        assert 'cxxnet_elastic_steps{rank="1"}' in text, text[:2000]
        assert 'cxxnet_fleet_ranks_alive' in text
        # the live /slos serves the typed fleet verdicts mid-run
        with urllib.request.urlopen(f'{url}/slos', timeout=5) as r:
            slos = json.loads(r.read())
        assert set(slos) == {'progress', 'membership'}
    finally:
        t.join(300)
    assert rc_box.get('rc') == 0
    # the drill killed rank 1 mid-run; the scrape survived it and the
    # respawned incarnation re-announced into the same port file
    assert (1, 1) in la.respawns
    assert 'cxxnet_elastic_steps{rank="0"}' in la.fleet_metrics
    assert 'cxxnet_elastic_steps{rank="1"}' in la.fleet_metrics
    # fleet-scoped verdicts captured at run end, typed states only
    assert set(la.fleet_verdicts) == {'progress', 'membership'}
    for v in la.fleet_verdicts.values():
        assert v['state'] in ('OK', 'AT_RISK', 'BREACHED')
    # burn=10 demands a SUSTAINED membership hole; the drill's dip (and
    # any shutdown-window sample) must never read as a breach
    assert la.fleet_verdicts['membership']['state'] in ('OK', 'AT_RISK')
    # merged Perfetto trace: pid = rank = one lane group per host
    with open(trace_out) as f:
        trace = json.load(f)
    events = trace['traceEvents']
    assert {e['pid'] for e in events} == {0, 1}
    lanes = {(e['pid'], e['args']['name']) for e in events
             if e.get('ph') == 'M' and e['name'] == 'process_name'}
    assert lanes == {(0, 'host rank 0'), (1, 'host rank 1')}
    assert any(e['name'].startswith('elastic.') for e in events
               if e.get('ph') == 'X')
    # fleet observability never perturbs training: still the twin
    _assert_params_equal(_final_params(workdir, 'm_fleet'), twin)


# --- hardened jax.distributed init (satellite) -----------------------------


def test_init_distributed_validates_rank_typed():
    from cxxnet_tpu.parallel.distributed import init_distributed
    with pytest.raises(faults.DistInitError):
        init_distributed('127.0.0.1:1', nproc=2, rank=2)
    with pytest.raises(faults.DistInitError):
        init_distributed('127.0.0.1:1', nproc=0, rank=0)


def test_maybe_init_distributed_warns_on_solo_coordinator(monkeypatch,
                                                          capsys):
    from cxxnet_tpu.parallel.distributed import maybe_init_distributed
    monkeypatch.setenv('CXXNET_COORDINATOR', '127.0.0.1:9999')
    monkeypatch.delenv('CXXNET_NUM_WORKER', raising=False)
    monkeypatch.delenv('PS_RANK', raising=False)
    assert maybe_init_distributed([('param_server', 'dist')]) is False
    assert 'single-process' in capsys.readouterr().err


def test_init_distributed_retries_slow_coordinator(monkeypatch):
    """A flaky initialize is a retry (with shutdown between attempts),
    not a hang; exhaustion is a typed DistInitError."""
    import jax

    from cxxnet_tpu.parallel.distributed import init_distributed
    calls = {'init': 0, 'shutdown': 0}

    def flaky_init(**kw):
        calls['init'] += 1
        assert kw['initialization_timeout'] == 7
        if calls['init'] < 3:
            raise RuntimeError('coordinator not up yet')

    monkeypatch.setattr(jax.distributed, 'initialize', flaky_init)
    monkeypatch.setattr(jax.distributed, 'shutdown',
                        lambda: calls.__setitem__(
                            'shutdown', calls['shutdown'] + 1))
    policy = faults.RetryPolicy(retry_on=(RuntimeError,), base_delay=0.0,
                                max_delay=0.0, jitter=0.0,
                                sleep=lambda _t: None)
    init_distributed('127.0.0.1:1', nproc=2, rank=0, timeout=7,
                     retry=policy)
    assert calls['init'] == 3 and calls['shutdown'] == 2

    calls['init'] = 0

    def always_down(**kw):
        calls['init'] += 1
        raise RuntimeError('nope')

    monkeypatch.setattr(jax.distributed, 'initialize', always_down)
    with pytest.raises(faults.DistInitError):
        init_distributed('127.0.0.1:1', nproc=2, rank=0, timeout=7,
                         retry=policy)
    assert calls['init'] == policy.max_attempts


def test_real_jax_distributed_two_process_world():
    """The hardened init against a REAL 2-process jax.distributed world
    over localhost (the satellite's 'real multi-process jax.distributed
    workers' leg — the elastic drills above use the coordinator
    transport precisely so kills stay drillable)."""
    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    code = (
        'import sys\n'
        'from cxxnet_tpu.parallel.distributed import init_distributed\n'
        'import jax\n'
        f'init_distributed("127.0.0.1:{port}", nproc=2, '
        'rank=int(sys.argv[1]))\n'
        'print("pid", jax.process_index(), "of", jax.process_count(), '
        'flush=True)\n'
        'assert jax.process_count() == 2\n')
    procs = [subprocess.Popen(
        [sys.executable, '-c', code, str(r)],
        env=_sub_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True) for r in range(2)]
    outs = [p.communicate(timeout=120) for p in procs]
    assert [p.returncode for p in procs] == [0, 0], outs
    assert 'of 2' in outs[0][0] and 'of 2' in outs[1][0]


# --- bench self-healing receipts (satellite) -------------------------------


def test_bench_self_heal_receipts(tmp_path, monkeypatch):
    sys.path.insert(0, REPO)
    import bench
    monkeypatch.setenv('JAX_PLATFORMS', 'tpu,cpu')
    monkeypatch.delenv('CXXNET_BENCH_NO_HEAL', raising=False)
    stale = {'metric': 'decode_int8_resident_reduction', 'value': 3.2,
             'platform': 'cpu-fallback'}
    (tmp_path / 'BENCH_SERVE_r03.json').write_text(
        __import__('json').dumps(stale))
    cands = bench.heal_candidates(str(tmp_path))
    assert [(m, s[1]) for _, m, s in cands] == \
        [('decode_int8_resident_reduction', 'decode_matrix')]

    ran = []

    def fake_runner(script, mode):
        ran.append((script, mode))
        return {'metric': 'decode_int8_resident_reduction', 'value': 9.9,
                'platform': 'tpu'}

    healed = bench.self_heal_receipts(str(tmp_path), runner=fake_runner)
    assert ran == [('bench_serve.py', 'decode_matrix')]
    assert len(healed) == 1
    receipt = tmp_path / 'receipts' / 'bench_serve_decode_matrix.json'
    assert receipt.exists()
    # the healed receipt supersedes the stale ledger entry: nothing
    # left to heal
    assert bench.heal_candidates(str(tmp_path)) == []

    # a rerun that silently landed back on CPU must NOT count as healed
    (tmp_path / 'receipts' / 'bench_serve_decode_matrix.json').unlink()
    healed = bench.self_heal_receipts(
        str(tmp_path),
        runner=lambda s, m: {'value': 1.0, 'platform': 'cpu-fallback'})
    assert healed == []

    # explicit CPU-only runs never try to heal
    monkeypatch.setenv('JAX_PLATFORMS', 'cpu')
    assert bench.self_heal_receipts(str(tmp_path),
                                    runner=fake_runner) == []
    monkeypatch.setenv('JAX_PLATFORMS', 'tpu,cpu')
    monkeypatch.setenv('CXXNET_BENCH_NO_HEAL', '1')
    assert bench.self_heal_receipts(str(tmp_path),
                                    runner=fake_runner) == []


# --- lint surface ----------------------------------------------------------


def test_fault_taxonomy_covers_parallel_package():
    from cxxnet_tpu.analysis import fault_taxonomy
    assert 'cxxnet_tpu/parallel/' in fault_taxonomy.TARGET_DIRS
    from cxxnet_tpu.analysis.core import Repo
    repo = Repo(REPO)
    allowed = fault_taxonomy.fault_class_names(repo)
    assert {'HostLossError', 'CoordinatorUnreachableError',
            'ElasticSyncError', 'DistInitError'} <= allowed
    findings = [f for f in fault_taxonomy.run(repo)
                if f.path.startswith('cxxnet_tpu/parallel/')
                and not repo.module(f.path).allowed(f.rule, f.line)]
    assert findings == [], [f.format() for f in findings]
