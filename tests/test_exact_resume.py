"""Exact-resume sidecar (beyond reference): optimizer state + counters
survive a save/restore, so continue=1 reproduces the uninterrupted
trajectory bit-for-bit.  The reference model file drops momentum by
design (``nnet_impl:82-87`` saves layer blobs only) — resuming from it
mid-momentum diverges; the sidecar closes that gap.
"""

import io

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string

from test_device_normalize import assert_params_equal, snap_params
from test_net_mnist import MLP_CONF, synth_batches


def _fresh():
    tr = NetTrainer(parse_config_string(MLP_CONF))
    tr.init_model()
    return tr


def test_exact_resume_reproduces_trajectory(tmp_path):
    batches = synth_batches(n_batches=8)

    # uninterrupted reference trajectory (momentum=0.9 per MLP_CONF)
    t_ref = _fresh()
    for b in batches:
        t_ref.update(b)

    # interrupted at step 4, exact state saved + restored
    t_a = _fresh()
    for b in batches[:4]:
        t_a.update(b)
    t_a.save_training_state(str(tmp_path / 'exact'), 4)

    t_b = _fresh()
    # no model file here: adopt the sidecar's params too
    step = t_b.load_training_state(str(tmp_path / 'exact'),
                                   restore_params=True)
    assert step == 4
    assert t_b.epoch_counter == t_a.epoch_counter
    assert t_b.sample_counter == 4
    for b in batches[4:]:
        t_b.update(b)
    assert_params_equal(snap_params(t_b), snap_params(t_ref),
                        rtol=0, atol=0)          # bit-exact

    # contrast: the reference model file loses momentum -> diverges
    t_c = _fresh()
    for b in batches[:4]:
        t_c.update(b)
    buf = io.BytesIO()
    t_c.save_model(buf)
    buf.seek(0)
    t_d = NetTrainer(parse_config_string(MLP_CONF))
    t_d.load_model(buf)
    t_d.sample_counter = 4                      # align RNG stream
    for b in batches[4:]:
        t_d.update(b)
    ref, got = snap_params(t_ref), snap_params(t_d)
    diverged = any(
        not np.array_equal(got[k][f], ref[k][f])
        for k in ref for f in ref[k])
    assert diverged, 'momentum-free resume should not be bit-exact'
