"""Every example config must parse, build, and shape-infer."""

import glob
import os

import pytest

from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.utils.config import parse_config_file

def _is_net_conf(path: str) -> bool:
    """Launcher configs (dist.conf, the reference's mpi.conf analog) have
    no netconfig section."""
    with open(path) as f:
        return 'netconfig' in f.read()


EXAMPLES = sorted(p for p in glob.glob(os.path.join(
    os.path.dirname(__file__), '..', 'example', '*', '*.conf'))
    if _is_net_conf(p))


@pytest.mark.parametrize('conf', EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_conf_builds(conf):
    pairs = parse_config_file(conf)
    cfg = NetConfig()
    cfg.configure(pairs)
    assert cfg.num_layers > 0
    net = Net(cfg)
    # final node exists and has positive size
    last = cfg.layers[-1].nindex_out[-1]
    assert net.node_specs[last].flat_size > 0


# The north-star compatibility claim: the LITERAL, unmodified reference
# example confs parse, build a net graph, and shape-infer in this framework
# (BASELINE.json: "driven by the unmodified example/ .conf files").  A
# parser regression cannot silently break verbatim-conf compatibility.
REFERENCE_EXAMPLES = sorted(
    p for p in glob.glob('/root/reference/example/*/*.conf')
    if _is_net_conf(p))


@pytest.mark.skipif(not REFERENCE_EXAMPLES,
                    reason='reference tree not present')
@pytest.mark.parametrize('conf', REFERENCE_EXAMPLES,
                         ids=[p.split('/example/')[-1]
                              for p in REFERENCE_EXAMPLES])
def test_reference_conf_builds_verbatim(conf):
    pairs = parse_config_file(conf)
    cfg = NetConfig()
    cfg.configure(pairs)
    assert cfg.num_layers > 0
    net = Net(cfg)
    last = cfg.layers[-1].nindex_out[-1]
    assert net.node_specs[last].flat_size > 0
    # the known layer counts of the reference model zoo, pinned so a
    # grammar change that silently drops layers is caught
    expected = {'ImageNet.conf': 24, 'MNIST.conf': 4, 'MNIST_CONV.conf': 8,
                'bowl.conf': 17, 'pred.conf': 17}
    name = os.path.basename(conf)
    if name in expected:
        assert cfg.num_layers == expected[name], name
