"""Every example config must parse, build, and shape-infer."""

import glob
import os

import pytest

from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.utils.config import parse_config_file

EXAMPLES = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), '..', 'example', '*', '*.conf')))


@pytest.mark.parametrize('conf', EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_conf_builds(conf):
    pairs = parse_config_file(conf)
    cfg = NetConfig()
    cfg.configure(pairs)
    assert cfg.num_layers > 0
    net = Net(cfg)
    # final node exists and has positive size
    last = cfg.layers[-1].nindex_out[-1]
    assert net.node_specs[last].flat_size > 0
