"""Every example config must parse, build, and shape-infer."""

import glob
import os

import pytest

from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.utils.config import parse_config_file

def _is_net_conf(path: str) -> bool:
    """Launcher configs (dist.conf, the reference's mpi.conf analog) have
    no netconfig section."""
    with open(path) as f:
        return 'netconfig' in f.read()


EXAMPLES = sorted(p for p in glob.glob(os.path.join(
    os.path.dirname(__file__), '..', 'example', '*', '*.conf'))
    if _is_net_conf(p))


@pytest.mark.parametrize('conf', EXAMPLES, ids=[os.path.basename(p) for p in EXAMPLES])
def test_example_conf_builds(conf):
    pairs = parse_config_file(conf)
    cfg = NetConfig()
    cfg.configure(pairs)
    assert cfg.num_layers > 0
    net = Net(cfg)
    # final node exists and has positive size
    last = cfg.layers[-1].nindex_out[-1]
    assert net.node_specs[last].flat_size > 0
