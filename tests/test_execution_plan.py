"""ExecutionPlan: one composable step loop (``-m execution``).

The properties this suite pins down (doc/trainer.md "The execution
plan"):

* the scanned K-dispatch window composes with everything the PR 5
  fallback matrix excluded — ``update_period>1`` (grad accumulator in
  the scan carry), ``eval_train=1`` train metrics (one readback per
  dispatch), and ``train.supervise=1`` (recovery at window granularity)
  — and every leg is **bitwise identical** to the per-step path;
* the remaining demotions are profiling/test_io-only (static) plus the
  per-round ``extra_data`` case, ``scan_strict=1`` turns any of them
  into a typed error, fallback notes print once PER REASON, and the
  documented matrix cannot drift from ``execution.DEMOTION_REASONS``.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet import execution
from cxxnet_tpu.nnet.execution import (DEMOTION_REASONS, ExecutionPlan,
                                       WindowedStepper)
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.runtime.supervisor import SupervisorConfig, TrainSupervisor
from cxxnet_tpu.utils.config import parse_config_string

from test_device_normalize import assert_params_equal, snap_params
from test_io_perf import (DROPOUT_MLP, MNIST_CONF, _mlp_batches, _run_cli,
                          _write_mnist)

pytestmark = pytest.mark.execution

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NO_WAIT = faults.NO_WAIT_RETRY


@pytest.fixture(autouse=True)
def _clean_plan():
    prev = faults.install_plan(None)
    yield
    faults.install_plan(prev)


def _trainer(extra=''):
    tr = NetTrainer(parse_config_string(DROPOUT_MLP + extra))
    tr.init_model()
    return tr


def _run_per_step(tr, batches):
    for b in batches:
        tr.update_staged(tr.stage_batch(b))


def _run_windowed(tr, batches, k):
    plan = ExecutionPlan.resolve(requested_k=k, silent=True)
    stepper = plan.round_stepper(tr)
    for b in batches:
        stepper.feed(b)
    stepper.finish()
    return stepper


# --- composition: update_period rides the scan carry ----------------------

@pytest.mark.parametrize('pad_last', [False, True])
def test_update_period_scan_bitwise_matches_per_step(pad_last):
    """K=4 windows == per-step micro-steps under update_period=2,
    bitwise — with a DROPOUT layer and (pad_last leg) a synthetic-pad
    tail batch whose loss mask rides the stack."""
    batches = _mlp_batches(pad_last=pad_last)
    per = _trainer('update_period = 2\n')
    _run_per_step(per, batches)
    win = _trainer('update_period = 2\n')
    _run_windowed(win, batches, 4)
    assert win.epoch_counter == per.epoch_counter == len(batches) // 2
    assert win.sample_counter == per.sample_counter == len(batches)
    assert_params_equal(snap_params(win), snap_params(per), rtol=0, atol=0)


def test_update_period_straddles_window_boundaries():
    """P=3 with K=2: no window aligns with an accumulation boundary, so
    the partial gradient sum must carry ACROSS dispatches (through the
    trainer's live grad_acc) and the per-step tail must continue a
    mid-window accumulation — still bitwise."""
    batches = _mlp_batches(n=7)
    per = _trainer('update_period = 3\n')
    _run_per_step(per, batches)
    win = _trainer('update_period = 3\n')
    stepper = _run_windowed(win, batches, 2)
    assert stepper.updates == 7
    assert win.epoch_counter == per.epoch_counter == 7 // 3
    assert_params_equal(snap_params(win), snap_params(per), rtol=0, atol=0)
    # the open accumulation (7 % 3 = 1 step) matches too
    np.testing.assert_array_equal(
        np.asarray(win.grad_acc['0']['wmat']),
        np.asarray(per.grad_acc['0']['wmat']))


# --- composition: eval_train metrics, one readback per dispatch -----------

@pytest.mark.parametrize('pad_last', [False, True])
def test_train_metrics_scan_bitwise_matches_per_step(pad_last):
    """eval_train=1 with train metrics scans: the stacked eval outputs
    feed the identical host-side metric math in step order, so the
    round's train-metric line is byte-equal to the per-step path's (pad
    rows excluded on both)."""
    conf = 'eval_train = 1\n'
    batches = _mlp_batches(pad_last=pad_last)
    per = _trainer(conf)
    _run_per_step(per, batches)
    win = _trainer(conf)
    _run_windowed(win, batches, 4)
    for t in (per, win):
        t.flush_train_metrics()
    line_per = per.train_metric.print('train')
    line_win = win.train_metric.print('train')
    assert line_per == line_win and 'train-error' in line_win
    assert_params_equal(snap_params(win), snap_params(per), rtol=0, atol=0)


def test_window_requires_train_eval_compiled_fn():
    """A metric-armed trainer driven through a multi_fn compiled without
    train_eval=True would silently lose the window's metrics — typed
    refusal instead."""
    tr = _trainer('eval_train = 1\n')
    fn = tr.compile_multi_step(2, train_eval=False)
    staged = [tr.stage_batch(b) for b in _mlp_batches(n=2)]
    with pytest.raises(ValueError, match='train_eval=True'):
        tr.update_staged_window(fn, staged)


# --- composition: supervision at window granularity -----------------------

def _sup(tr, ckpt_dir, **kw):
    base = dict(batch_deadline=60.0, max_restarts=3, nan_breaker=0,
                save_every=2, buffer_size=2, retry=NO_WAIT)
    base.update(kw)
    return TrainSupervisor(tr, ckpt_dir, SupervisorConfig(**base))


def test_supervised_scan_bitwise_twin(tmp_path):
    """Supervised K=4 == supervised per-step == unsupervised per-step,
    bitwise — the flagship composition: the watchdog buffer, anchor +
    periodic saves, and recovery machinery change nothing about the
    math, and the scanned window survives them."""
    batches = _mlp_batches(n=10)     # 2 windows + a 2-step tail
    ref = _trainer()
    _run_per_step(ref, batches)

    t1 = _trainer()
    n1 = _sup(t1, str(tmp_path / 's1')).run(lambda k: iter(batches[k:]))
    tk = _trainer()
    plan = ExecutionPlan.resolve(requested_k=4, silent=True)
    nk = _sup(tk, str(tmp_path / 'sk')).run(
        lambda k: iter(batches[k:]),
        make_stepper=lambda: plan.round_stepper(tk, lookahead=0))
    assert n1 == nk == 10
    assert_params_equal(snap_params(t1), snap_params(ref), rtol=0, atol=0)
    assert_params_equal(snap_params(tk), snap_params(ref), rtol=0, atol=0)


def test_supervised_scan_chaos_recovers_bitwise(tmp_path):
    """The chaos drill through a scanned window boundary: a NaN injected
    mid-window trips the breaker DURING a K-dispatch, recovery restores
    the last window-boundary checkpoint, re-winds the stream by
    dispatched steps, and the run still ends bitwise-identical to an
    unfaulted per-step run."""
    batches = _mlp_batches(n=8)
    ref = _trainer()
    _run_per_step(ref, batches)

    plan_f = faults.FaultPlan(seed=3, nan_at_step=(6,))
    faults.install_plan(plan_f)
    tr = _trainer()
    log = faults.FailureLog()
    sup = TrainSupervisor(
        tr, str(tmp_path / 'sup'),
        SupervisorConfig(batch_deadline=60.0, max_restarts=3, nan_breaker=1,
                         save_every=2, retry=NO_WAIT), failure_log=log)
    plan = ExecutionPlan.resolve(requested_k=4, silent=True)
    n = sup.run(lambda k: iter(batches[k:]),
                make_stepper=lambda: plan.round_stepper(tr, lookahead=0))
    assert n == 8
    assert plan_f.fired() == ['nan_at_step=6']
    assert len(log.records('DivergenceError')) == 1
    # the restore landed on a window boundary (multiple of K=4)
    assert log.records('restored')[0].step % 4 == 0
    assert_params_equal(snap_params(tr), snap_params(ref), rtol=0, atol=0)


def test_supervised_scan_stall_recovers_bitwise(tmp_path):
    """Watchdog leg of the chaos drill: the producer stalls while a
    window is FILLING — staged-but-undispatched batches are abandoned
    and re-pulled after the restore, bitwise."""
    batches = _mlp_batches(n=8)
    ref = _trainer()
    _run_per_step(ref, batches)

    plan_f = faults.FaultPlan(seed=4, stall_batch=((5, 4.0),))
    faults.install_plan(plan_f)
    tr = _trainer()
    log = faults.FailureLog()
    sup = TrainSupervisor(
        tr, str(tmp_path / 'sup'),
        SupervisorConfig(batch_deadline=0.3, max_restarts=3, nan_breaker=1,
                         save_every=2, retry=NO_WAIT), failure_log=log)
    plan = ExecutionPlan.resolve(requested_k=4, silent=True)
    n = sup.run(lambda k: iter(batches[k:]),
                make_stepper=lambda: plan.round_stepper(tr, lookahead=0))
    assert n == 8
    assert plan_f.fired() == ['stall_batch=5:4']
    assert len(log.records('PipelineStallError')) == 1
    assert_params_equal(snap_params(tr), snap_params(ref), rtol=0, atol=0)


def test_supervised_n_steps_budget_bounded_overshoot(tmp_path):
    """n_steps with a windowed stepper: the budget check can only move at
    dispatch boundaries, so overshoot is bounded to the window that
    crossed the line — and the staged leftovers are DISCARDED, never
    dispatched as a tail."""
    batches = _mlp_batches(n=10)
    tr = _trainer()
    plan = ExecutionPlan.resolve(requested_k=4, silent=True)
    n = _sup(tr, str(tmp_path / 's'), save_every=0).run(
        lambda k: iter(batches[k:]), n_steps=2,
        make_stepper=lambda: plan.round_stepper(tr, lookahead=0))
    assert n == 4                       # one K=4 window, nothing more
    assert tr.sample_counter == 4


# --- the demotion matrix ---------------------------------------------------

def test_static_demotions_and_strict():
    plan = ExecutionPlan.resolve(requested_k=4, profiling=True, silent=True)
    assert plan.k == 1 and plan.requested_k == 4
    plan = ExecutionPlan.resolve(requested_k=4, test_io=True, silent=True)
    assert plan.k == 1
    with pytest.raises(faults.ScanStrictError) as ei:
        ExecutionPlan.resolve(requested_k=4, profiling=True, strict=True,
                              silent=True)
    assert ei.value.reason == 'profile_dir'
    # no demotion: strict is satisfied, K stands
    plan = ExecutionPlan.resolve(requested_k=4, strict=True, silent=True)
    assert plan.k == 4


def test_fallback_note_printed_once_per_reason(capsys):
    """A run that demotes for reason A must still report a later,
    different reason B — one note PER REASON, not one note per run."""
    plan = ExecutionPlan.resolve(requested_k=4, profiling=True)
    assert plan.note('profile_dir') is None          # already noted
    msg = plan.note('extra_data')
    assert msg and 'falls back to per-step' in msg
    assert plan.note('extra_data') is None
    out = capsys.readouterr().out
    assert out.count('falls back to per-step') == 2


class _StubTrainer:
    """Just enough surface for WindowedStepper/round_stepper: records
    which dispatch path each staged batch took."""

    def __init__(self, extra=False):
        self.eval_train = 0
        self.train_metric = ()
        self.extra = extra
        self.calls = []

    def compile_multi_step(self, k, train_eval=False):
        def fn(*_a, **_kw):
            raise AssertionError('stub scan_fn should not be invoked raw')
        fn.n_steps = k
        fn.train_eval = train_eval
        return fn

    def stage_batch(self, batch):
        return (batch, None, (1,) if self.extra else (), None, None,
                0, 0, ())

    def update_staged(self, staged):
        self.calls.append(('step', staged[0]))

    def update_staged_window(self, fn, window):
        self.calls.append(('window', [s[0] for s in window]))


def test_extra_data_demotes_current_round_only():
    """The mid-epoch extra_data demotion is a ROUND property: the plan is
    not mutated, and the next round's stepper re-probes and scans."""
    plan = ExecutionPlan.resolve(requested_k=2, silent=True)
    tr = _StubTrainer(extra=True)
    s1 = plan.round_stepper(tr)
    for i in range(3):
        s1.feed(i)
    s1.finish()
    assert s1.demoted
    assert [c[0] for c in tr.calls] == ['step'] * 3
    assert plan.k == 2                         # no permanent mutation
    tr2 = _StubTrainer(extra=False)
    s2 = plan.round_stepper(tr2)
    for i in range(4):
        s2.feed(i)
    s2.finish()
    assert not s2.demoted
    assert [c[0] for c in tr2.calls] == ['window', 'window']
    assert s2.updates == 4


def test_extra_data_strict_raises_mid_round():
    plan = ExecutionPlan.resolve(requested_k=2, strict=True, silent=True)
    stepper = plan.round_stepper(_StubTrainer(extra=True))
    with pytest.raises(faults.ScanStrictError) as ei:
        stepper.feed(0)
    assert ei.value.reason == 'extra_data'


def test_stepper_k1_keeps_one_batch_lookahead():
    """K=1 IS the classic plain loop: exactly one staged batch rides
    ahead of the dispatch, and finish() drains it."""
    tr = _StubTrainer()
    s = WindowedStepper(tr, k=1, lookahead=1)
    assert s.feed('a') == 0                    # staged, not dispatched
    assert s.feed('b') == 1                    # dispatches 'a'
    assert tr.calls == [('step', 'a')]
    assert s.finish() == 1                     # drains 'b'
    assert tr.calls == [('step', 'a'), ('step', 'b')]


def test_demotion_matrix_matches_documented_table():
    """doc/trainer.md's fallback matrix cannot silently rot: its reason
    keys — and their static/runtime split — must equal the programmatic
    registry in nnet/execution.py.  Parsed through the shared doc-table
    extractor (cxxnet_tpu.analysis.config_keys) rather than a private
    regex: one extractor, every drift test a consumer."""
    from cxxnet_tpu.analysis.config_keys import backtick_key, doc_table_rows
    doc = open(os.path.join(REPO, 'doc', 'trainer.md')).read()
    # everything after the matrix heading: the matrix is the last table
    # in the file, so backtick-keyed rows below the marker are its rows
    rows = [(backtick_key(r[0]), r[1])
            for r in doc_table_rows(doc, after='Fallback matrix')
            if len(r) >= 2 and backtick_key(r[0])]
    assert {k for k, _ in rows} == set(DEMOTION_REASONS)
    assert set(execution.STATIC_REASONS) | set(execution.RUNTIME_REASONS) \
        == set(DEMOTION_REASONS)
    for key, cond in rows:
        expect = ('static' if key in execution.STATIC_REASONS
                  else 'runtime')
        assert expect in cond, (key, cond)


# --- CLI end-to-end twins --------------------------------------------------

def test_cli_supervised_scan_bitwise_twin(tmp_path):
    """The acceptance run: train.supervise=1 steps_per_dispatch=4 keeps
    the scanned path (no fallback note) and bitwise-matches the
    supervised per-step twin — model files AND eval lines."""
    _write_mnist(tmp_path)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF)
    r1 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m1',
                  'train.supervise=1')
    r4 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m4',
                  'train.supervise=1', 'steps_per_dispatch=4',
                  'scan_strict=1')
    assert 'falls back' not in r4.stdout
    evals1 = [l for l in r1.stderr.splitlines() if l.startswith('[')]
    evals4 = [l for l in r4.stderr.splitlines() if l.startswith('[')]
    assert evals1 == evals4 and len(evals1) == 2
    for rd in (1, 2):
        a = (tmp_path / 'm1' / f'{rd:04d}.model').read_bytes()
        b = (tmp_path / 'm4' / f'{rd:04d}.model').read_bytes()
        assert a == b, f'round {rd} diverged under supervised scan'


def test_cli_update_period_and_metrics_scan_twin(tmp_path):
    """update_period=2 + eval_train=1 train metrics — the two remaining
    production demotions — now scan: K=4 vs per-step twin runs produce
    identical models and identical train-metric eval lines."""
    _write_mnist(tmp_path)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF.replace('eval_train = 0', 'eval_train = 1'))
    r1 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m1',
                  'update_period=2')
    r4 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m4',
                  'update_period=2', 'steps_per_dispatch=4',
                  'scan_strict=1')
    assert 'falls back' not in r4.stdout
    evals1 = [l for l in r1.stderr.splitlines() if l.startswith('[')]
    evals4 = [l for l in r4.stderr.splitlines() if l.startswith('[')]
    assert evals1 == evals4 and len(evals1) == 2
    assert all('train-error' in l for l in evals4)
    for rd in (1, 2):
        a = (tmp_path / 'm1' / f'{rd:04d}.model').read_bytes()
        b = (tmp_path / 'm4' / f'{rd:04d}.model').read_bytes()
        assert a == b, f'round {rd} diverged under update_period scan'


def test_cli_supervised_chaos_scan_twin(tmp_path):
    """The CLI chaos drill: a NaN fired inside a scanned window under
    train.supervise=1 recovers through the window boundary and the final
    models bitwise-match an unfaulted supervised per-step run."""
    _write_mnist(tmp_path)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF)
    r1 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m1',
                  'train.supervise=1')
    rf = _run_cli('mlp.conf', str(tmp_path), 'model_dir=mf',
                  'train.supervise=1', 'steps_per_dispatch=4',
                  'train.save_every=2', 'train.nan_breaker=1',
                  'train.fault_plan=nan_at_step=2')
    assert 'fault plan fired: nan_at_step=2' in rf.stdout
    for rd in (1, 2):
        a = (tmp_path / 'm1' / f'{rd:04d}.model').read_bytes()
        b = (tmp_path / 'mf' / f'{rd:04d}.model').read_bytes()
        assert a == b, f'round {rd} diverged after scanned-window recovery'


def test_cli_scan_strict_raises_typed_error(tmp_path):
    """scan_strict=1 on a config that would demote (test_io=1) fails
    loudly with the typed error instead of silently losing the
    K-dispatch win."""
    _write_mnist(tmp_path, n_train=200)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF.replace('num_round = 2', 'num_round = 1'))
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', 'mlp.conf',
         'steps_per_dispatch=4', 'scan_strict=1', 'test_io=1'],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=240)
    assert r.returncode != 0
    assert 'ScanStrictError' in r.stderr
    assert 'test_io' in r.stderr
