"""Fault-tolerant training runtime: retry/backoff, atomic checkpoints,
pipeline watchdog, divergence breaker, and supervised end-to-end recovery.

Everything here is deterministic: fault plans are seeded one-shot event
sets, retry jitter is a pure function of (seed, op_name), and the
supervisor's recovery restores the EXACT-resume sidecar — so the headline
assertions are *bitwise* equality between a faulted-and-recovered run and
an uninterrupted run with the same seed.

Select with ``-m faults``; the suite is tier-1 (runs under ``-m "not
slow"`` with no extra infrastructure — see doc/fault_tolerance.md).
"""

import os
import time

import numpy as np
import pytest

from cxxnet_tpu.nnet import checkpoint, sharded_ckpt
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.runtime.supervisor import SupervisorConfig, TrainSupervisor
from cxxnet_tpu.utils.config import ConfigError, parse_kv_list
from cxxnet_tpu.utils.thread_buffer import ThreadBuffer

from test_device_normalize import assert_params_equal, snap_params
from test_net_mnist import MLP_CONF, synth_batches

pytestmark = pytest.mark.faults

NO_WAIT = faults.NO_WAIT_RETRY


@pytest.fixture(autouse=True)
def _clean_plan():
    prev = faults.install_plan(None)
    yield
    faults.install_plan(prev)


def _fresh(extra=''):
    from cxxnet_tpu.utils.config import parse_config_string
    tr = NetTrainer(parse_config_string(MLP_CONF + extra))
    tr.init_model()
    return tr


# --- retry policy ---------------------------------------------------------

def test_retry_schedule_deterministic_and_bounded():
    pol = faults.RetryPolicy(max_attempts=4, base_delay=0.1, multiplier=2.0,
                             max_delay=0.3, jitter=0.1, seed=7)
    a, b = pol.delays('save_model:x'), pol.delays('save_model:x')
    assert a == b                       # pure function of (seed, op_name)
    assert a != pol.delays('other_op')  # jitter stream is op-scoped
    assert len(a) == 3
    for k, d in enumerate(a):
        nominal = min(0.3, 0.1 * 2.0 ** k)
        assert nominal * 0.9 <= d <= nominal * 1.1


def test_retry_recovers_from_transient_and_logs():
    sleeps = []
    pol = faults.RetryPolicy(max_attempts=3, base_delay=0.05, jitter=0.0,
                             sleep=sleeps.append)
    log = faults.FailureLog()
    calls = {'n': 0}

    def flaky():
        calls['n'] += 1
        if calls['n'] < 3:
            raise OSError('transient')
        return 42

    assert pol.call(flaky, op_name='op', log=log) == 42
    assert calls['n'] == 3
    assert sleeps == [0.05, 0.10]       # exponential, jitter-free
    assert len(log.records('io_retry')) == 2


def test_retry_exhausts_then_raises_with_cause():
    def broken():
        raise OSError('still down')

    with pytest.raises(faults.RetryError) as ei:
        NO_WAIT.call(broken, op_name='op', log=faults.FailureLog())
    assert isinstance(ei.value.__cause__, OSError)
    assert ei.value.attempts == NO_WAIT.max_attempts


def test_retry_does_not_catch_programming_errors():
    with pytest.raises(ValueError):
        NO_WAIT.call(lambda: (_ for _ in ()).throw(ValueError('bug')),
                     op_name='op', log=faults.FailureLog())


# --- fault plan grammar ---------------------------------------------------

def test_fault_plan_parse_roundtrip():
    plan = faults.FaultPlan.parse(
        'seed=3; raise_on_write=2; stall_batch=5:0.75; '
        'corrupt_shard=1; nan_at_step=7')
    assert plan.describe() == ('seed=3;raise_on_write=2;stall_batch=5:0.75;'
                               'corrupt_shard=1;nan_at_step=7')
    assert plan.fired() == []


def test_fault_plan_rejects_unknown_event():
    with pytest.raises(ValueError):
        faults.FaultPlan.parse('explode_at=3')
    with pytest.raises(ConfigError):
        parse_kv_list('not a pair')


def test_fault_plan_events_fire_once():
    plan = faults.FaultPlan(raise_on_write=(2,), nan_at_step=(5,))
    plan.on_checkpoint_write('p')                      # write #1: clean
    with pytest.raises(faults.FaultInjected):
        plan.on_checkpoint_write('p')                  # write #2: injected
    plan.on_checkpoint_write('p')                      # write #3: clean
    assert np.isnan(plan.on_loss(5, 1.0))
    assert plan.on_loss(5, 1.0) == 1.0                 # one-shot
    assert plan.fired() == ['raise_on_write=2', 'nan_at_step=5']


# --- recurring (@every=K) events ------------------------------------------

def test_fault_plan_recurring_parse_roundtrip():
    """The ``kind@every=K`` grammar parses next to one-shot specs of the
    SAME kind, round-trips through describe(), and rejects junk."""
    plan = faults.FaultPlan.parse(
        'seed=2; raise_on_write=3; raise_on_write@every=5; '
        'stall_batch@every=50:0.2; nan_at_step@every=7; '
        'corrupt_model=1; corrupt_model@every=4')
    assert plan.describe() == (
        'seed=2;raise_on_write=3;raise_on_write@every=5;'
        'stall_batch@every=50:0.2;corrupt_model=1;corrupt_model@every=4;'
        'nan_at_step@every=7')
    assert plan.fired() == []
    with pytest.raises(ValueError):
        faults.FaultPlan.parse('raise_on_write@often=3')
    with pytest.raises(ValueError):
        faults.FaultPlan.parse('explode@every=3')
    with pytest.raises(ValueError):
        faults.FaultPlan.parse('raise_on_write@every=0')


def test_fault_plan_recurring_write_fires_every_k():
    """Periodic writer faults fire on every K-th attempt, forever —
    alongside (not consuming) a one-shot on a different attempt."""
    plan = faults.FaultPlan(raise_on_write=(2,), raise_on_write_every=(5,))
    hits = []
    for n in range(1, 16):
        try:
            plan.on_checkpoint_write('p')
        except faults.FaultInjected:
            hits.append(n)
    assert hits == [2, 5, 10, 15]
    assert plan.fired() == ['raise_on_write=2', 'raise_on_write@every=5#5',
                            'raise_on_write@every=5#10',
                            'raise_on_write@every=5#15']


def test_fault_plan_recurring_stall_batch(monkeypatch):
    """stall_batch@every=K stalls every K-th batch (1-based: 0-based
    indices K-1, 2K-1, ...); non-batch scopes pass through."""
    slept = []
    monkeypatch.setattr(faults.time, 'sleep', slept.append)
    plan = faults.FaultPlan(stall_batch_every=((3, 0.25),))
    for idx in range(9):
        plan.on_pipeline_item('batch', idx)
        plan.on_pipeline_item('page', idx)             # other scope: no-op
    assert slept == [0.25, 0.25, 0.25]
    assert plan.fired() == ['stall_batch@every=3#2', 'stall_batch@every=3#5',
                            'stall_batch@every=3#8']


def test_fault_plan_recurring_nan_fires_once_per_step():
    """Periodic NaNs fire at every K-th step — but only ONCE per distinct
    step: a supervised restore replays step numbers, and re-firing on
    the replay would turn every recovery into a death loop."""
    plan = faults.FaultPlan(nan_at_step_every=(4,))
    assert plan.has_nan_events()
    assert np.isnan(plan.on_loss(4, 1.0))
    assert np.isnan(plan.on_loss(8, 1.0))
    # the replay after a restore sees the same steps clean
    assert plan.on_loss(4, 1.0) == 1.0
    assert plan.on_loss(8, 1.0) == 1.0
    assert np.isnan(plan.on_loss(12, 1.0))             # fresh step: fires
    assert plan.on_loss(0, 1.0) == 1.0                 # step 0 never fires


def test_fault_plan_corrupt_model_truncates_after_commit(tmp_path):
    """corrupt_model=N truncates the N-th committed model file AFTER its
    digest sidecar landed, so digest verification must reject it."""
    from cxxnet_tpu.nnet import checkpoint
    plan = faults.FaultPlan(corrupt_model=(2,))
    faults.install_plan(plan)
    try:
        paths = []
        for i in (1, 2, 3):
            p = str(tmp_path / f'{i:04d}.model')
            with open(p, 'wb') as f:
                f.write(b'model-payload-' * 8)
            checkpoint.write_model_digest(p)
            paths.append(p)
    finally:
        faults.clear_plan()
    assert plan.fired() == ['corrupt_model=2']
    assert checkpoint.verify_model_digest(paths[0]) is None
    assert checkpoint.verify_model_digest(paths[2]) is None
    reason = checkpoint.verify_model_digest(paths[1])
    assert reason is not None and 'size' in reason


# --- atomic model-file I/O ------------------------------------------------

def test_atomic_write_commits_complete_file(tmp_path):
    path = str(tmp_path / 'm' / '0001.model')
    with checkpoint.atomic_write(path) as f:
        f.write(b'payload')
    with open(path, 'rb') as f:
        assert f.read() == b'payload'
    assert os.listdir(os.path.dirname(path)) == ['0001.model']  # no temps


def test_atomic_write_crash_leaves_no_partial_under_final_name(tmp_path):
    """Crash-simulation: the writer dies mid-stream AFTER bytes hit the
    temp file; the final name must never appear and the temp is cleaned."""
    path = str(tmp_path / '0001.model')
    with pytest.raises(RuntimeError):
        with checkpoint.atomic_write(path) as f:
            f.write(b'half a checkp')
            raise RuntimeError('simulated kill mid-checkpoint')
    assert not os.path.exists(path)
    assert os.listdir(tmp_path) == []


def test_atomic_write_crash_preserves_previous_checkpoint(tmp_path):
    path = str(tmp_path / '0001.model')
    checkpoint.save_model_file(path, lambda f: f.write(b'good-v1'),
                               retry=NO_WAIT)
    with pytest.raises(RuntimeError):
        checkpoint.save_model_file(
            path, lambda f: (_ for _ in ()).throw(RuntimeError('kill')),
            retry=NO_WAIT)
    with open(path, 'rb') as f:
        assert f.read() == b'good-v1'   # old checkpoint intact, bitwise


def test_save_model_file_injected_fault_rides_retry(tmp_path):
    plan = faults.FaultPlan(raise_on_write=(1,))
    faults.install_plan(plan)
    path = str(tmp_path / '0002.model')
    checkpoint.save_model_file(path, lambda f: f.write(b'v2'), retry=NO_WAIT)
    assert plan.fired() == ['raise_on_write=1']
    with open(path, 'rb') as f:
        assert f.read() == b'v2'


def test_read_model_file_ignores_stray_partial_temp(tmp_path):
    path = str(tmp_path / '0003.model')
    checkpoint.save_model_file(path, lambda f: f.write(b'v3'), retry=NO_WAIT)
    # a stray partial temp (e.g. a SIGKILLed writer from another process)
    (tmp_path / '.0003.model.tmp.999').write_bytes(b'par')
    assert checkpoint.read_model_file(path, lambda f: f.read(),
                                      retry=NO_WAIT) == b'v3'
    with pytest.raises(FileNotFoundError):
        checkpoint.read_model_file(str(tmp_path / 'absent.model'),
                                   lambda f: f.read(), retry=NO_WAIT)


# --- thread buffer: shutdown, sentinel, watchdog --------------------------

def test_thread_buffer_full_drain_and_error_propagation():
    buf = ThreadBuffer(lambda: iter([1, 2, 3]), buffer_size=1)
    assert list(buf) == [1, 2, 3]

    def boom():
        yield 1
        raise ValueError('producer died')

    buf = ThreadBuffer(boom, buffer_size=1)
    with pytest.raises(ValueError):
        list(buf)


def test_thread_buffer_sentinel_survives_full_queue_abandonment():
    """Consumer takes one item of many and walks away: the producer must
    land its sentinel (drain-then-signal) and close() must join it."""
    buf = ThreadBuffer(lambda: iter(range(100)), buffer_size=1)
    it = iter(buf)
    assert next(it) == 0
    it.close()                       # abandon: GeneratorExit sets stop
    assert buf.close(timeout=5.0)    # every producer thread joined


def test_thread_buffer_close_joins_slow_producer():
    def slow():
        for i in range(50):
            time.sleep(0.01)
            yield i

    buf = ThreadBuffer(slow, buffer_size=1)
    it = iter(buf)
    assert next(it) == 0
    assert buf.close(timeout=5.0)


def test_thread_buffer_deadline_raises_pipeline_stall():
    def stalling():
        yield 'a'
        time.sleep(1.5)
        yield 'b'

    buf = ThreadBuffer(stalling, buffer_size=2, deadline=0.2)
    it = iter(buf)
    assert next(it) == 'a'
    with pytest.raises(faults.PipelineStallError) as ei:
        next(it)
    assert ei.value.batch_index == 1
    assert ei.value.deadline == 0.2
    buf.close(timeout=5.0)


def test_thread_buffer_first_deadline_tolerates_rewind():
    """The first item may lawfully take longer (epoch re-wind after a
    recovery): it gets its own deadline, steady-state items keep the
    tight one."""
    def rewinding():
        time.sleep(0.5)              # the re-wind
        yield 'a'
        time.sleep(0.5)              # a REAL stall
        yield 'b'

    buf = ThreadBuffer(rewinding, buffer_size=1, deadline=0.2,
                       first_deadline=2.0)
    it = iter(buf)
    assert next(it) == 'a'           # slow first item passes
    with pytest.raises(faults.PipelineStallError):
        next(it)                     # steady-state stall still trips
    buf.close(timeout=5.0)


def test_thread_buffer_injected_stall_is_batch_scoped():
    plan = faults.FaultPlan(stall_batch=((1, 0.6),))
    faults.install_plan(plan)
    # non-batch scope: the plan must NOT see these items
    inner = ThreadBuffer(lambda: iter(range(3)), fault_scope='page')
    assert list(inner) == [0, 1, 2]
    assert plan.fired() == []
    # batch scope: item 1 stalls past the deadline
    outer = ThreadBuffer(lambda: iter(range(3)), deadline=0.15,
                         fault_scope='batch')
    with pytest.raises(faults.PipelineStallError):
        list(outer)
    assert plan.fired() == ['stall_batch=1:0.6']
    outer.close(timeout=5.0)


# --- divergence gate ------------------------------------------------------

def test_nan_action_halt_raises_divergence_with_context():
    tr = _fresh('nan_action = halt\n')
    faults.install_plan(faults.FaultPlan(nan_at_step=(2,)))
    batches = synth_batches(n_batches=4)
    tr.update(batches[0])
    tr.update(batches[1])
    tr.update(batches[2])           # NaN produced (gate defers one step)
    with pytest.raises(faults.DivergenceError) as ei:
        tr.update(batches[3])       # step 2's loss checked here
    assert ei.value.step == 2
    assert not np.isfinite(ei.value.loss)
    assert 'step 2' in str(ei.value)


def test_flush_divergence_check_settles_final_step():
    """A NaN on the LAST update of a loop has no next step to surface
    it — flush_divergence_check must."""
    tr = _fresh('nan_action = halt\n')
    faults.install_plan(faults.FaultPlan(nan_at_step=(1,)))
    batches = synth_batches(n_batches=2)
    tr.update(batches[0])
    tr.update(batches[1])           # NaN pending
    with pytest.raises(faults.DivergenceError) as ei:
        tr.flush_divergence_check()
    assert ei.value.step == 1


def test_nan_action_rejects_unknown_value():
    with pytest.raises(ValueError):
        NetTrainer([('nan_action', 'explode')])


def test_nan_breaker_trips_on_consecutive_not_isolated():
    tr = _fresh('nan_action = skip\nnan_breaker = 2\n')
    faults.install_plan(faults.FaultPlan(nan_at_step=(1, 3, 4)))
    batches = synth_batches(n_batches=6)
    tr.update(batches[0])
    tr.update(batches[1])           # NaN #1 produced
    tr.update(batches[2])           # checks step 1: streak 1
    assert tr.nan_streak == 1
    tr.update(batches[3])           # checks step 2 (finite): reset
    assert tr.nan_streak == 0       # (step 3's NaN still pending)
    tr.update(batches[4])           # checks step 3: streak 1
    with pytest.raises(faults.DivergenceError) as ei:
        tr.update(batches[5])       # checks step 4: streak 2 -> trips
    assert ei.value.streak == 2


# --- sharded checkpoint integrity ----------------------------------------

def _tiny_tree():
    import jax.numpy as jnp
    return {'w': jnp.arange(8, dtype=jnp.float32),
            'c': {'step': np.asarray(3, np.int64)}}


def test_digest_written_and_detects_truncation(tmp_path):
    d = str(tmp_path / 'ck')
    path = sharded_ckpt.save_sharded(d, 1, _tiny_tree(), retry=NO_WAIT)
    assert os.path.exists(os.path.join(path, 'ckpt_digest.json'))
    assert sharded_ckpt.verify_step_dir(path) is None
    # truncate the largest payload file
    victim = max((os.path.join(r, f) for r, _, fs in os.walk(path)
                  for f in fs if f != 'ckpt_digest.json'),
                 key=os.path.getsize)
    with open(victim, 'r+b') as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))
    assert sharded_ckpt.verify_step_dir(path) is not None


def test_restore_resilient_falls_back_past_corrupt_step(tmp_path):
    d = str(tmp_path / 'ck')
    tree = _tiny_tree()
    sharded_ckpt.save_sharded(d, 1, tree, retry=NO_WAIT)
    plan = faults.FaultPlan(seed=5, corrupt_shard=(2,))
    faults.install_plan(plan)
    sharded_ckpt.save_sharded(d, 2, tree, retry=NO_WAIT)
    assert plan.fired() == ['corrupt_shard=2']
    got, step = sharded_ckpt.restore_resilient(d, tree, retry=NO_WAIT)
    assert step == 1                                 # newest INTACT wins
    np.testing.assert_array_equal(np.asarray(got['w']), np.arange(8))
    # the bad step is quarantined out of future scans
    assert sharded_ckpt.all_steps(d) == [1]
    assert os.path.isdir(os.path.join(d, 'step_2.corrupt'))


def test_restore_resilient_no_quarantine_when_digest_verifies(
        tmp_path, monkeypatch):
    """A restore failure on a digest-intact checkpoint (an outage
    outlasting the retry budget, a caller-side mismatch) must NOT
    quarantine it — renaming would destroy the only good recovery point
    over a fault that may clear."""
    d = str(tmp_path / 'ck')
    sharded_ckpt.save_sharded(d, 1, _tiny_tree(), retry=NO_WAIT)

    class _Outage:
        def restore(self, *a, **k):
            raise OSError('synthetic storage outage')

    monkeypatch.setattr(sharded_ckpt, '_shared_ck', lambda: _Outage())
    # with ZERO quarantines the diagnosis must be the environmental
    # error, not a corruption verdict
    with pytest.raises(faults.RetryError):
        sharded_ckpt.restore_resilient(d, _tiny_tree(), retry=NO_WAIT)
    # the intact checkpoint survived the outage un-renamed...
    assert sharded_ckpt.all_steps(d) == [1]
    assert not os.path.isdir(os.path.join(d, 'step_1.corrupt'))
    # ...and restores fine once the fault clears
    monkeypatch.undo()
    got, step = sharded_ckpt.restore_resilient(d, _tiny_tree(),
                                               retry=NO_WAIT)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(got['w']), np.arange(8))


def test_restore_sharded_missing_step_fails_fast(tmp_path):
    """Absence is a state, not a transient: an explicit step with no dir
    raises immediately instead of sleeping through the backoff schedule
    and logging spurious io_retry records."""
    d = str(tmp_path / 'ck')
    sharded_ckpt.save_sharded(d, 1, _tiny_tree(), retry=NO_WAIT)
    log_before = len(faults.global_failure_log())
    with pytest.raises(FileNotFoundError):
        sharded_ckpt.restore_sharded(d, _tiny_tree(), step=99)
    assert len(faults.global_failure_log()) == log_before


def test_restore_resilient_raises_when_nothing_intact(tmp_path):
    d = str(tmp_path / 'ck')
    tree = _tiny_tree()
    faults.install_plan(faults.FaultPlan(seed=5, corrupt_shard=(1,)))
    sharded_ckpt.save_sharded(d, 1, tree, retry=NO_WAIT)
    with pytest.raises(faults.CheckpointCorruptError):
        sharded_ckpt.restore_resilient(d, tree, retry=NO_WAIT)
    with pytest.raises(FileNotFoundError):
        sharded_ckpt.restore_resilient(str(tmp_path / 'empty'), tree)


def test_step_scan_skips_temp_and_quarantined_dirs(tmp_path):
    d = tmp_path / 'ck'
    for name in ('step_3', 'step_7.corrupt', 'step_9.tmp.123',
                 'tmp_step_11'):
        (d / name).mkdir(parents=True)
    assert sharded_ckpt.latest_step(str(d)) == 3
    assert sharded_ckpt.all_steps(str(d)) == [3]


# --- supervised end-to-end recovery ---------------------------------------

def _sup_config(**kw):
    base = dict(batch_deadline=0.3, max_restarts=3, nan_breaker=0,
                save_every=2, buffer_size=2, retry=NO_WAIT)
    base.update(kw)
    return SupervisorConfig(**base)


def test_supervisor_recovers_write_fault_and_stall_bitwise(tmp_path):
    """Acceptance: a FaultPlan that kills a checkpoint write AND stalls
    the data pipeline still completes all N steps, and the final params
    are bitwise-identical to an uninterrupted run with the same seed."""
    batches = synth_batches(n_batches=8)

    t_ref = _fresh()
    for b in batches:
        t_ref.update(b)
    ref = snap_params(t_ref)

    # The stall must out-last the consumer's worst-case arrival delay at
    # batch 5 (three updates + two fsync'd periodic saves) by more than
    # the 0.3s deadline, or a loaded machine absorbs it and the watchdog
    # lawfully never trips — hence 4s, not something snappier.
    plan = faults.FaultPlan(seed=1, raise_on_write=(2,),
                            stall_batch=((5, 4.0),))
    faults.install_plan(plan)
    tr = _fresh()
    log = faults.FailureLog()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'), _sup_config(),
                          failure_log=log)
    n = sup.run(lambda k: iter(batches[k:]))

    assert n == 8
    assert tr.sample_counter == 8
    assert sorted(plan.fired()) == ['raise_on_write=2', 'stall_batch=5:4']
    assert len(log.records('PipelineStallError')) == 1
    assert len(log.records('restored')) == 1
    assert sup.state == 'IDLE'
    assert_params_equal(snap_params(tr), ref, rtol=0, atol=0)   # bit-exact


def test_supervisor_recovers_corrupt_shard_and_divergence_bitwise(tmp_path):
    """Satellite: corrupt the newest checkpoint shard, then diverge — the
    supervisor must fall back to the older intact checkpoint, replay, and
    still end bitwise-identical."""
    batches = synth_batches(n_batches=8)

    t_ref = _fresh()
    for b in batches:
        t_ref.update(b)
    ref = snap_params(t_ref)

    plan = faults.FaultPlan(seed=2, corrupt_shard=(6,), nan_at_step=(6,))
    faults.install_plan(plan)
    tr = _fresh()
    log = faults.FailureLog()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(nan_breaker=1), failure_log=log)
    n = sup.run(lambda k: iter(batches[k:]))

    assert n == 8
    assert sorted(plan.fired()) == ['corrupt_shard=6', 'nan_at_step=6']
    assert len(log.records('DivergenceError')) == 1
    # restore skipped the corrupt step_6 and landed on step_4
    restored = log.records('restored')
    assert len(restored) == 1 and restored[0].step == 4
    assert os.path.isdir(str(tmp_path / 'sup' / 'step_6.corrupt'))
    assert_params_equal(snap_params(tr), ref, rtol=0, atol=0)   # bit-exact


def test_supervisor_gives_up_after_max_restarts(tmp_path):
    batches = synth_batches(n_batches=6)
    # an unrecoverable plan: each replay (restored to the anchor, so
    # epoch-absolute indices restart near 0) reaches the next armed
    # stall before outrunning the event chain.  4s stalls for the same
    # reason as the bitwise test above: a loaded machine's consumer-side
    # latency must not absorb the stall
    plan = faults.FaultPlan(stall_batch=((0, 4.0), (1, 4.0), (2, 4.0)))
    faults.install_plan(plan)
    tr = _fresh()
    log = faults.FailureLog()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(max_restarts=2), failure_log=log)
    with pytest.raises(faults.PipelineStallError):
        sup.run(lambda k: iter(batches[k:]))
    assert len(log.records('giving_up')) == 1
    assert sup.restarts_total == 3      # two restores + the fatal third


def test_supervisor_prunes_checkpoints_to_keep_last(tmp_path):
    batches = synth_batches(n_batches=8)
    tr = _fresh()
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(save_every=1, keep_last=2))
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 8
    # anchor + 8 periodic saves, bounded to the 2 newest
    assert sharded_ckpt.all_steps(str(tmp_path / 'sup')) == [8, 7]


def test_periodic_save_skipped_mid_nan_streak(tmp_path):
    """A periodic save never checkpoints mid-NaN-streak state: a
    poisoned checkpoint would become the newest restore target (CRC
    digests cannot see NaNs) and wedge recovery in a restore-diverge
    loop."""
    batches = synth_batches(n_batches=6)
    faults.install_plan(faults.FaultPlan(nan_at_step=(2, 3)))
    tr = _fresh('nan_breaker = 3\n')    # armed, but streak peaks at 2
    sup = TrainSupervisor(tr, str(tmp_path / 'sup'),
                          _sup_config(save_every=1, nan_breaker=0,
                                      keep_last=0))
    n = sup.run(lambda k: iter(batches[k:]))
    assert n == 6
    steps = set(sharded_ckpt.all_steps(str(tmp_path / 'sup')))
    assert not {3, 4} & steps           # mid-streak boundaries skipped
    assert {1, 2, 5, 6} <= steps        # finite-streak saves landed


def test_supervisor_prunes_quarantined_dirs_too(tmp_path):
    """keep_last bounds `.corrupt` post-mortem dirs as well — unbounded
    quarantine growth would fill exactly the degraded disks that
    produce it."""
    d = str(tmp_path / 'sup')
    for step in range(1, 5):
        sharded_ckpt.save_sharded(d, step, _tiny_tree(), retry=NO_WAIT)
        sharded_ckpt.quarantine_step(d, step, 'synthetic bit rot')
    assert sharded_ckpt.quarantined_steps(d) == [4, 3, 2, 1]
    tr = _fresh()
    sup = TrainSupervisor(tr, d, _sup_config(keep_last=2))
    sup.save()
    assert sharded_ckpt.quarantined_steps(d) == [4, 3]


def test_replay_stability_contract():
    """Supervised bitwise recovery needs `is_replay_stable`; shuffling
    imgbin passes must report False, once-at-init mnist stays True, and
    wrappers delegate."""
    from cxxnet_tpu.io.data import ThreadBufferIterator
    from cxxnet_tpu.io.iter_imbin import ImageBinIterator
    from cxxnet_tpu.io.iter_mnist import MNISTIterator
    imbin = ImageBinIterator()
    assert imbin.is_replay_stable()
    imbin.set_param('shuffle', '1')
    assert not imbin.is_replay_stable()
    mnist = MNISTIterator()
    mnist.set_param('shuffle', '1')      # shuffles once at init: stable
    assert mnist.is_replay_stable()
    assert not ThreadBufferIterator(imbin).is_replay_stable()


def test_exact_resume_unharmed_by_partial_sidecar_litter(tmp_path):
    """Exact resume still works when the checkpoint dir is littered with
    the debris a kill leaves behind: a partial temp dir and a quarantined
    step must both be invisible to restore."""
    batches = synth_batches(n_batches=6)
    t_a = _fresh()
    for b in batches[:3]:
        t_a.update(b)
    d = str(tmp_path / 'exact')
    t_a.save_training_state(d, 3)
    os.makedirs(os.path.join(d, 'step_9.tmp.42'))      # killed mid-write
    os.makedirs(os.path.join(d, 'step_8.corrupt'))     # quarantined earlier
    for b in batches[3:]:
        t_a.update(b)

    t_b = _fresh()
    step = t_b.load_training_state(d, restore_params=True, fallback=True)
    assert step == 3
    for b in batches[3:]:
        t_b.update(b)
    assert_params_equal(snap_params(t_b), snap_params(t_a), rtol=0, atol=0)


# --- CLI / config surface -------------------------------------------------

def test_cli_knobs_parse_into_learn_task():
    from cxxnet_tpu.main import LearnTask
    lt = LearnTask()
    lt.set_param('train.fault_plan', 'nan_at_step=3;stall_batch=2:0.5')
    lt.set_param('train.supervise', '1')
    lt.set_param('train.watchdog_deadline', '7.5')
    lt.set_param('train.max_restarts', '5')
    lt.set_param('train.nan_breaker', '4')
    lt.set_param('train.save_every', '10')
    assert lt.fault_plan == 'nan_at_step=3;stall_batch=2:0.5'
    assert (lt.supervise, lt.watchdog_deadline, lt.max_restarts,
            lt.nan_breaker, lt.save_every) == (1, 7.5, 5, 4, 10)
    plan = faults.FaultPlan.parse(lt.fault_plan)
    assert plan.describe() == 'seed=0;stall_batch=2:0.5;nan_at_step=3'
