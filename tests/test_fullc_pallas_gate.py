"""The fc8-class eval-path Pallas forward gate (``fullc_use_pallas``).

The auto gate may only engage where the receipt measured a win:
forward-only (no backward will run), single-device, real TPU, at
lane-ragged N big enough to matter (micro_matmul.json fc8 row, 4.28x).
Everything else — training, SPMD, interpret/CPU, aligned or small
shapes — stays on XLA.
"""

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.ops.pallas_kernels import fullc_use_pallas
from cxxnet_tpu.utils.config import parse_config_string


class TestGateDecisions:
    def test_training_never_engages(self):
        assert not fullc_use_pallas(256, 4096, 1000, is_train=True)

    def test_spmd_never_engages(self):
        assert not fullc_use_pallas(256, 4096, 1000, is_train=False,
                                    spmd_devices=8)

    def test_aligned_n_stays_xla(self):
        # fc6/fc7: N % 128 == 0 — XLA is at parity or better there
        assert not fullc_use_pallas(256, 9216, 4096, is_train=False)
        assert not fullc_use_pallas(256, 4096, 4096, is_train=False)

    def test_small_ragged_shapes_stay_xla(self):
        # 10-class MNIST head: ragged but narrow — never measured
        assert not fullc_use_pallas(100, 128, 10, is_train=False)
        assert not fullc_use_pallas(64, 512, 1000, is_train=False)

    def test_forced_modes_win(self, monkeypatch):
        monkeypatch.setenv('CXXNET_PALLAS', '1')
        assert fullc_use_pallas(1, 1, 1, is_train=True)
        monkeypatch.setenv('CXXNET_PALLAS', '0')
        assert not fullc_use_pallas(256, 4096, 1000, is_train=False)

    def test_fc8_shape_class_predicate(self):
        # the environment-independent half of the gate: the measured fc8
        # class is in, fc6/fc7/narrow heads are out
        from cxxnet_tpu.ops.pallas_kernels import fullc_pallas_shape_class
        assert fullc_pallas_shape_class(256, 4096, 1000)
        assert not fullc_pallas_shape_class(256, 9216, 4096)
        assert not fullc_pallas_shape_class(100, 128, 10)

    def test_interpret_hosts_keep_auto_off(self):
        import jax
        if jax.default_backend() != 'cpu':
            import pytest
            pytest.skip('gate legitimately engages on a real TPU backend')
        assert not fullc_use_pallas(256, 4096, 1000, is_train=False)

    def test_fullc_only_kill_switch(self, monkeypatch):
        # the eval bench's off leg: disables this gate without touching
        # pallas_mode (the LRN winners stay as-is)
        from cxxnet_tpu.ops.pallas_kernels import pallas_mode
        monkeypatch.setenv('CXXNET_FULLC_PALLAS', '0')
        assert pallas_mode() == 'auto'
        assert not fullc_use_pallas(256, 4096, 1000, is_train=False)


_CONF = """
netconfig = start
layer[0->1] = fullc:fc1
  nhidden = 32
layer[1->2] = relu
layer[2->3] = fullc:fc2
  nhidden = 4
layer[3->3] = softmax
netconfig = end
input_shape = 1,1,64
batch_size = 8
dev = cpu
eta = 0.1
metric[label] = error
"""


class TestMultiForward:
    def test_multi_forward_matches_repeated_predict_path(self):
        # the scanned forward-only loop is the eval-bench compute path;
        # its checksum over a 1-batch stack must equal the plain forward
        tr = NetTrainer(parse_config_string(_CONF))
        tr.init_model()
        rng = np.random.RandomState(0)
        data = rng.rand(8, 1, 1, 64).astype(np.float32)
        stack = tr.shard_batch_stack(data[None])
        fwd1 = tr.compile_multi_forward(1)
        fwd3 = tr.compile_multi_forward(3)
        a = float(np.asarray(fwd1(tr.params, stack)))
        b = float(np.asarray(fwd3(tr.params, stack)))
        # same batch scanned 3x: checksum triples exactly (eval path is
        # deterministic — no dropout rng, no param mutation)
        np.testing.assert_allclose(b, 3 * a, rtol=1e-5)
        # and the checksum agrees with the ordinary predict-path forward
        vals = tr._forward_nodes(DataBatch(data, None),
                                 [tr.net.cfg.layers[-1].nindex_out[-1]])
        np.testing.assert_allclose(a, np.asarray(vals[0],
                                                 np.float32).sum(),
                                   rtol=1e-5)
