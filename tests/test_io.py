"""IO pipeline tests: BinaryPage format, iterator chains, augmentation."""

import gzip
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from cxxnet_tpu.io.data import create_iterator
from cxxnet_tpu.utils.io_stream import BinaryPage


def write_mnist(tmpdir, n=50, rows=8, cols=8, seed=0):
    rng = np.random.RandomState(seed)
    img = rng.randint(0, 255, (n, rows, cols)).astype(np.uint8)
    y = rng.randint(0, 3, n).astype(np.uint8)
    pi = os.path.join(tmpdir, 'img.gz')
    pl = os.path.join(tmpdir, 'lbl.gz')
    with gzip.open(pi, 'wb') as f:
        f.write(struct.pack('>iiii', 2051, n, rows, cols))
        f.write(img.tobytes())
    with gzip.open(pl, 'wb') as f:
        f.write(struct.pack('>ii', 2049, n))
        f.write(y.tobytes())
    return pi, pl, img, y


def test_binary_page_roundtrip(tmp_path):
    page = BinaryPage()
    blobs = [b'hello', b'x' * 1000, b'', b'last']
    for b in blobs:
        assert page.push(b)
    path = tmp_path / 'page.bin'
    with open(path, 'wb') as f:
        page.save(f)
    assert path.stat().st_size == BinaryPage.N_BYTES
    page2 = BinaryPage()
    with open(path, 'rb') as f:
        assert page2.load(f)
        assert not BinaryPage().load(f)   # EOF
    assert list(page2) == blobs


def test_mnist_iterator_chain(tmp_path):
    pi, pl, img, y = write_mnist(str(tmp_path))
    cfg = [('iter', 'mnist'), ('path_img', pi), ('path_label', pl),
           ('input_flat', '1'), ('iter', 'threadbuffer'),
           ('batch_size', '16'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    # 50 // 16; the mnist source itself drops the tail remainder exactly
    # like the reference (iter_mnist-inl.hpp:63)
    assert len(batches) == 3
    assert batches[0].data.shape == (16, 1, 1, 64)
    np.testing.assert_allclose(batches[0].data[0].ravel(),
                               img[0].ravel() / 256.0, rtol=1e-6)
    assert batches[0].label[0, 0] == y[0]
    # second epoch identical (no per-epoch reshuffle when shuffle=0)
    batches2 = list(it)
    np.testing.assert_array_equal(batches[1].data, batches2[1].data)


def test_tail_batch_emitted_with_padd(tmp_path):
    """round_batch=0 through the batch adapter keeps the short final batch,
    padded to full size with num_batch_padd = batch_size - top
    (iter_batch_proc-inl.hpp:101-103) — no instance is silently dropped."""
    lst = make_img_dataset(str(tmp_path), n=10)
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)),
           ('input_shape', '3,20,20'), ('batch_size', '4'),
           ('round_batch', '0'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert [b.num_batch_padd for b in batches] == [0, 0, 2]
    # every batch keeps the full static shape (jit-friendly)
    assert all(b.data.shape[0] == 4 for b in batches)
    # all 10 instances appear exactly once among the non-pad rows
    seen = np.concatenate([b.inst_index[:4 - b.num_batch_padd]
                           for b in batches])
    assert sorted(seen.tolist()) == list(range(10))


def _write_png(path, arr):
    from PIL import Image
    Image.fromarray(arr).save(path)


def make_img_dataset(tmpdir, n=12, size=20):
    rng = np.random.RandomState(1)
    lst = os.path.join(tmpdir, 'a.lst')
    with open(lst, 'w') as f:
        for i in range(n):
            arr = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
            fname = f'im{i}.png'
            _write_png(os.path.join(tmpdir, fname), arr)
            f.write(f'{i}\t{i % 3}\t{fname}\n')
    return lst


def test_img_iterator_with_crop_and_batch(tmp_path):
    lst = make_img_dataset(str(tmp_path))
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)),
           ('input_shape', '3,16,16'), ('batch_size', '4'),
           ('rand_crop', '1'), ('rand_mirror', '1'), ('silent', '1'),
           ('round_batch', '1'), ('iter', 'end')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data.shape == (4, 3, 16, 16)
    assert batches[0].label.shape == (4, 1)


def test_img_round_batch_pads_with_next_epoch(tmp_path):
    lst = make_img_dataset(str(tmp_path), n=10)
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)),
           ('input_shape', '3,20,20'), ('batch_size', '4'),
           ('round_batch', '1'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert len(batches) == 3
    assert batches[2].num_batch_padd == 2
    # padded tail contains wrapped instances 0,1
    assert list(batches[2].inst_index) == [8, 9, 0, 1]


def test_imgbin_roundtrip_via_im2bin(tmp_path):
    lst = make_img_dataset(str(tmp_path), n=8)
    out_bin = str(tmp_path / 'a.bin')
    root = str(tmp_path)
    tool = os.path.join(os.path.dirname(__file__), '..', 'tools', 'im2bin.py')
    subprocess.check_call([sys.executable, tool, lst, root, out_bin])
    cfg = [('iter', 'imgbin'), ('image_list', lst), ('image_bin', out_bin),
           ('input_shape', '3,20,20'), ('batch_size', '4'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data.shape == (4, 3, 20, 20)
    # decode matches the original pixels (png is lossless)
    from PIL import Image
    ref = np.asarray(Image.open(tmp_path / 'im0.png').convert('RGB'),
                     np.float32).transpose(2, 0, 1)
    np.testing.assert_array_equal(batches[0].data[0], ref)


def test_mean_image_created_and_cached(tmp_path, capsys):
    lst = make_img_dataset(str(tmp_path), n=6)
    mean_path = str(tmp_path / 'mean.bin')
    cfg = [('iter', 'img'), ('image_list', lst),
           ('image_root', str(tmp_path)),
           ('input_shape', '3,20,20'), ('batch_size', '2'),
           ('image_mean', mean_path), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    assert os.path.exists(mean_path)
    b1 = list(it)[0]
    # reloading uses cached mean
    it2 = create_iterator(cfg)
    it2.init()
    b2 = list(it2)[0]
    np.testing.assert_allclose(b1.data, b2.data, rtol=1e-5)
    # mean-subtracted data should be roughly centered
    assert abs(b1.data.mean()) < 30


def test_augment_affine_rotation_180(tmp_path):
    # rotate=180 flips the image both ways; content preserved
    from cxxnet_tpu.io.iter_augment import ImageAugmenter
    rng = np.random.RandomState(0)
    img = np.zeros((3, 11, 11), np.float32)
    img[:, 2, 3] = 100.0
    aug = ImageAugmenter()
    aug.set_param('rotate', '180')
    aug.set_param('fill_value', '0')
    out = aug.process(img, rng, 11, 11)
    assert out.shape[1] >= 11
    # bright pixel moves to (9,8): 180° about the reference's size/2 center
    pos = np.unravel_index(np.argmax(out[0]), out[0].shape)
    assert pos == (9, 8), pos


def test_threadbuffer_slow_consumer_terminates():
    """Regression: producer finishing against a full queue must still
    deliver the stop sentinel (a slow consumer previously hung forever)."""
    import time as _time
    from cxxnet_tpu.utils.thread_buffer import ThreadBuffer
    buf = ThreadBuffer(lambda: iter([1, 2, 3]), buffer_size=1)
    got = []
    for item in buf:
        _time.sleep(0.3)     # let the producer finish while the queue is full
        got.append(item)
    assert got == [1, 2, 3]


def test_native_im2bin_matches_python_tool(tmp_path):
    """runtime/im2bin output must be byte-identical to tools/im2bin.py,
    for both tab- and space-separated .lst files."""
    root_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native_tool = os.path.join(root_dir, 'runtime', 'im2bin')
    if not os.path.exists(native_tool):
        pytest.skip('runtime/im2bin not built')
    py_tool = os.path.join(root_dir, 'tools', 'im2bin.py')
    lst = make_img_dataset(str(tmp_path), n=8)
    # space-separated variant of the same list
    lst_sp = str(tmp_path / 'space.lst')
    with open(lst) as f, open(lst_sp, 'w') as g:
        g.write(f.read().replace('\t', ' '))
    for lst_file, tag in ((lst, 'tab'), (lst_sp, 'sp')):
        py_bin = str(tmp_path / f'py_{tag}.bin')
        nat_bin = str(tmp_path / f'nat_{tag}.bin')
        subprocess.check_call([sys.executable, py_tool, lst_file,
                               str(tmp_path), py_bin])
        subprocess.check_call([native_tool, lst_file, str(tmp_path), nat_bin])
        with open(py_bin, 'rb') as a, open(nat_bin, 'rb') as b:
            assert a.read() == b.read()


# --- imgbinx: two-stage shuffled pipeline --------------------------------

def _encode_png(arr):
    import io as _io
    from PIL import Image
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format='PNG')
    return buf.getvalue()


def _write_bin_dataset(tmpdir, n, size=6):
    """Write a .bin/.lst pair in-process (page size may be monkeypatched
    by the caller) and return (lst, bin) paths."""
    rng = np.random.RandomState(7)
    lst = os.path.join(tmpdir, 'd.lst')
    binp = os.path.join(tmpdir, 'd.bin')
    page = BinaryPage()
    with open(binp, 'wb') as fb, open(lst, 'w') as fl:
        for i in range(n):
            arr = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
            blob = _encode_png(arr)
            if not page.push(blob):
                page.save(fb)
                page.clear()
                assert page.push(blob)
            fl.write(f'{i}\t{i % 5}\t x\n')
        if page.size:
            page.save(fb)
    return lst, binp


def _instance_order(cfg):
    it = create_iterator(cfg)
    it.init()
    return [int(i) for b in it
            for i in b.inst_index[:b.batch_size - b.num_batch_padd]]


@pytest.fixture
def small_pages(monkeypatch):
    """Shrink BinaryPage to 2KB so multi-page datasets are test-sized;
    disable the native reader (its page size is the real 64MB)."""
    monkeypatch.setattr(BinaryPage, 'K_PAGE_SIZE', 512)
    monkeypatch.setattr(BinaryPage, 'N_BYTES', 512 * 4)
    from cxxnet_tpu.runtime import native
    monkeypatch.setattr(native, 'native_available', lambda: False)
    monkeypatch.setattr(native, 'native_order_available', lambda: False)


def test_imgbinx_matches_imgbin_when_unshuffled(tmp_path, small_pages):
    lst, binp = _write_bin_dataset(str(tmp_path), n=24)
    base = [('image_list', lst), ('image_bin', binp),
            ('input_shape', '3,6,6'), ('batch_size', '4'), ('silent', '1')]
    a = _instance_order([('iter', 'imgbin')] + base)
    b = _instance_order([('iter', 'imgbinx')] + base)
    assert a == list(range(24))
    assert b == a


def test_imgbinx_shuffles_pages_and_instances(tmp_path, small_pages):
    """shuffle=1 randomizes page order AND within-page instance order
    (iter_thread_imbin_x-inl.hpp:195-197,316-318); every instance appears
    exactly once; epochs continue the RNG stream (different orders)."""
    lst, binp = _write_bin_dataset(str(tmp_path), n=30)
    from cxxnet_tpu.io.iter_imbin import scan_page_table
    counts = scan_page_table(binp)
    assert len(counts) >= 3, 'dataset must span multiple pages'
    cfg = [('iter', 'imgbinx'), ('image_list', lst), ('image_bin', binp),
           ('input_shape', '3,6,6'), ('batch_size', '5'),
           ('shuffle', '1'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    flat = lambda batches: [int(i) for b in batches for i in b.inst_index]
    e1 = flat(it)
    e2 = flat(it)
    assert sorted(e1) == list(range(30))
    assert sorted(e2) == list(range(30))
    assert e1 != list(range(30)), 'shuffle produced identity order'
    assert e1 != e2, 'epochs replayed the same permutation'
    # within-page shuffle: some page's instances are not consecutive-sorted
    starts = np.concatenate([[0], np.cumsum(counts)]).astype(int)
    page_of = np.zeros(30, int)
    for p in range(len(counts)):
        page_of[starts[p]:starts[p + 1]] = p
    runs = [list(g) for g in np.split(np.asarray(e1),
            np.where(np.diff(page_of[e1]) != 0)[0] + 1)]
    assert any(r != sorted(r) for r in runs), 'within-page order untouched'


def test_imgbin_single_file_shuffle_randomizes_pages(tmp_path, small_pages):
    """Plain imgbin shuffle=1 on a single multi-page .bin shuffles page
    order (fix for the round-2 no-op); labels stay paired."""
    lst, binp = _write_bin_dataset(str(tmp_path), n=30)
    cfg = [('iter', 'imgbin'), ('image_list', lst), ('image_bin', binp),
           ('input_shape', '3,6,6'), ('batch_size', '5'),
           ('shuffle', '1'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    orders, batches = [], []
    for _ in range(3):   # page permutations continue the RNG stream
        epoch = list(it)
        batches += epoch
        orders.append([int(i) for b in epoch for i in b.inst_index])
    assert all(sorted(o) == list(range(30)) for o in orders)
    assert any(o != list(range(30)) for o in orders), 'page shuffle no-op'
    labels = {int(i): float(l[0]) for b in batches
              for i, l in zip(b.inst_index, b.label)}
    assert all(labels[i] == i % 5 for i in range(30)), 'labels unpaired'


@pytest.mark.slow
def test_io_throughput_imgbin_vs_imgbinx(tmp_path):
    """The decoupled imgbinx decode stage should not be slower than plain
    imgbin on the same data (test_io-style pump; both complete, rates
    printed for the record)."""
    import time
    lst = make_img_dataset(str(tmp_path), n=64, size=32)
    out_bin = str(tmp_path / 'a.bin')
    tool = os.path.join(os.path.dirname(__file__), '..', 'tools', 'im2bin.py')
    subprocess.check_call([sys.executable, tool, lst, str(tmp_path), out_bin])
    rates = {}
    for kind in ('imgbin', 'imgbinx'):
        cfg = [('iter', kind), ('image_list', lst), ('image_bin', out_bin),
               ('input_shape', '3,32,32'), ('batch_size', '8'),
               ('shuffle', '1'), ('silent', '1')]
        it = create_iterator(cfg)
        it.init()
        t0 = time.perf_counter()
        cnt = sum(b.batch_size - b.num_batch_padd
                  for ep in range(2) for b in it)
        rates[kind] = cnt / (time.perf_counter() - t0)
        assert cnt == 128
    print(f'test_io throughput inst/s: {rates}')
    assert rates['imgbinx'] > 0.3 * rates['imgbin']


def test_imgbin_worker_sharding_partitions_pages(tmp_path, small_pages):
    """dist_num_worker=N on a single file: workers own disjoint pages
    covering the whole dataset, shuffled or not (the sharded paths seek
    only owned pages)."""
    lst, binp = _write_bin_dataset(str(tmp_path), n=30)
    for shuffle in ('0', '1'):
        per_worker = []
        for rank in (0, 1):
            cfg = [('iter', 'imgbin'), ('image_list', lst),
                   ('image_bin', binp), ('input_shape', '3,6,6'),
                   ('batch_size', '1'), ('shuffle', shuffle),
                   ('dist_num_worker', '2'), ('dist_worker_rank', str(rank)),
                   ('silent', '1')]
            it = create_iterator(cfg)
            it.init()
            per_worker.append({int(i) for b in it for i in b.inst_index})
        assert per_worker[0].isdisjoint(per_worker[1]), shuffle
        assert per_worker[0] | per_worker[1] == set(range(30)), shuffle


def test_membuffer_caches_and_loops(tmp_path):
    """membuffer caches the first max_nbatch batches and replays them
    every epoch (iter_mem_buffer-inl.hpp:16-75)."""
    pi, pl, img, y = write_mnist(str(tmp_path), n=64)
    cfg = [('iter', 'mnist'), ('path_img', pi), ('path_label', pl),
           ('input_flat', '1'), ('batch_size', '16'),
           ('iter', 'membuffer'), ('max_nbatch', '2'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    e1 = list(it)
    e2 = list(it)
    assert len(e1) == 2 and len(e2) == 2     # capped at max_nbatch
    for a, b in zip(e1, e2):
        np.testing.assert_array_equal(a.data, b.data)


def test_imgbinx_decode_pool_order_identical(tmp_path, small_pages):
    """The decode thread pool must yield the exact instance stream of the
    serial path for any thread count (order-preserving submission
    window) — shuffle permutations included."""
    lst, binp = _write_bin_dataset(str(tmp_path), 37)

    def stream(threads):
        cfg = [('iter', 'imgbinx'), ('image_list', lst),
               ('image_bin', binp), ('shuffle', '1'),
               ('decode_threads', str(threads)), ('silent', '1'),
               ('seed_data', '5'), ('batch_size', '8'),
               ('input_shape', '3,6,6'), ('round_batch', '0')]
        return _instance_order(cfg)

    base = stream(1)
    assert sorted(base) == list(range(37))
    for t in (3, 8):
        assert stream(t) == base, f'decode_threads={t} changed the stream'


def test_binary_page_property_roundtrip():
    """Property test: any blob sequence (incl. empty blobs and an
    exact-fit final blob) survives push -> save -> load -> iterate with
    order and bytes intact, and a full page refuses further pushes —
    the bit-compatibility contract behind imgbin interop
    (src/utils/io.h:253-326)."""
    import io as _io

    from hypothesis import given, settings, strategies as st

    @settings(max_examples=12, deadline=None)
    @given(st.lists(st.binary(min_size=0, max_size=4096), max_size=40),
           st.booleans())
    def run(blobs, exact_fill):
        page = BinaryPage()
        pushed = []
        for b in blobs:
            if page.push(b):
                pushed.append(b)
        if exact_fill and page._free_bytes() >= 4:
            fill = b'z' * (page._free_bytes() - 4)
            assert page.push(fill)
            pushed.append(fill)
            assert page._free_bytes() == 0
            assert not page.push(b'')   # even b'' needs a 4-byte header
        buf = _io.BytesIO()
        page.save(buf)
        assert buf.tell() == BinaryPage.N_BYTES
        buf.seek(0)
        p2 = BinaryPage()
        assert p2.load(buf)
        assert list(p2) == pushed

    run()
