"""Parallel input pipeline + scanned step-loop dispatch (``-m io_perf``).

The two host-side performance ceilings this suite pins down
(doc/io.md, doc/trainer.md):

* ``nworker`` — per-instance decode+augment fans across an
  order-preserving worker pool (``utils/parallel_pool.py``) whose output
  must be **bitwise identical for any worker count**: per-instance RNG
  is seeded from the epoch-absolute instance index, results reassemble
  in submission order.
* ``steps_per_dispatch`` — K staged batches drive ONE
  ``compile_multi_step`` dispatch (lax.scan), and the result must be
  **bitwise identical to K per-step dispatches** (params, losses,
  dropout keys, tail-batch masks).

Plus the ``utils/thread_buffer.py`` lifecycle regressions (exception
propagation order, GeneratorExit retirement) that the conftest
thread-leak fixture backstops suite-wide.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch, create_iterator
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.utils.metric import StatSet
from cxxnet_tpu.utils.parallel_pool import OrderedWorkerPool
from cxxnet_tpu.utils.thread_buffer import ThreadBuffer

pytestmark = pytest.mark.io_perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- OrderedWorkerPool ----------------------------------------------------

def test_pool_preserves_order_under_racing_durations():
    pool = OrderedWorkerPool(8)

    def f(i):
        time.sleep(0.01 if i % 7 == 0 else 0.0005)  # deliberate races
        return i * i

    assert list(pool.imap(f, range(200))) == [i * i for i in range(200)]


def test_pool_single_worker_equals_many():
    def f(i):
        return (i, i % 3)

    a = list(OrderedWorkerPool(1).imap(f, range(100)))
    b = list(OrderedWorkerPool(7).imap(f, range(100)))
    assert a == b


def test_pool_error_raised_at_position_after_earlier_results():
    pool = OrderedWorkerPool(4)

    def f(i):
        if i == 5:
            raise ValueError('boom at 5')
        time.sleep(0.001)
        return i

    got = []
    with pytest.raises(ValueError, match='boom at 5'):
        for v in pool.imap(f, range(10)):
            got.append(v)
    assert got == [0, 1, 2, 3, 4]     # everything before the error, in order


def test_pool_generator_exit_joins_workers():
    pool = OrderedWorkerPool(4, name='exit')

    def f(i):
        time.sleep(0.005)
        return i

    it = pool.imap(f, range(500))
    assert next(it) == 0
    it.close()                         # GeneratorExit -> finally joins
    assert not [t for t in threading.enumerate()
                if t.name.startswith('cxxnet-pool-exit')]


def test_pool_stats_surface():
    stats = StatSet()
    pool = OrderedWorkerPool(2, stats=stats, name='pool')

    def f(i):
        time.sleep(0.001)
        return i

    list(pool.imap(f, range(50)))
    assert stats.get('pool.workers') == 2
    assert 0.0 < stats.get('pool.occupancy') <= 1.0


def test_pool_window_bounds_inflight():
    """The consumer never runs more than ``window`` tasks ahead of the
    yield point — the backpressure that bounds decoded-instance RAM."""
    seen = []
    lock = threading.Lock()
    pool = OrderedWorkerPool(2, window=4)

    def f(i):
        with lock:
            seen.append(i)
        return i

    it = pool.imap(f, range(100))
    next(it)
    time.sleep(0.2)                    # let workers drain whatever was fed
    with lock:
        high_water = max(seen)
    # yielded item 0; submission may lead by at most window + 1 fills
    assert high_water <= 0 + 4 + 1
    it.close()


# --- ThreadBuffer lifecycle regressions -----------------------------------

def test_thread_buffer_error_raised_only_after_queued_items_drain():
    """A producer that fails AFTER yielding items still in the queue:
    the consumer receives every one of them before the error."""
    def boom():
        yield 1
        yield 2
        yield 3
        raise RuntimeError('late failure')

    buf = ThreadBuffer(boom, buffer_size=8)
    got = []
    with pytest.raises(RuntimeError, match='late failure'):
        for v in buf:
            got.append(v)
    assert got == [1, 2, 3]


def test_thread_buffer_error_wins_over_sentinel():
    """box[0] beats the end-of-stream sentinel: a failing producer can
    never be mistaken for a clean end of epoch."""
    def boom():
        yield 1
        raise ValueError('producer died')

    buf = ThreadBuffer(boom, buffer_size=1)
    it = iter(buf)
    assert next(it) == 1
    with pytest.raises(ValueError, match='producer died'):
        next(it)


def test_thread_buffer_generator_exit_retires_producer():
    buf = ThreadBuffer(lambda: iter(range(10000)), buffer_size=2)
    it = iter(buf)
    assert next(it) == 0
    it.close()                         # abandon mid-epoch
    deadline = time.time() + 5.0
    while time.time() < deadline:
        alive = [t for t in threading.enumerate()
                 if t.name == 'cxxnet-tb-producer' and t.is_alive()]
        if not alive:
            break
        time.sleep(0.01)
    assert buf.close(timeout=5.0)      # and close() can always join it


# --- pooled augment determinism ------------------------------------------

def _pack_imgbin(tmp_path, n=37, size=40):
    from PIL import Image
    rng = np.random.RandomState(0)
    lines = []
    for i in range(n):
        c = i % 4
        img = np.zeros((size, size, 3), np.uint8)
        r0, c0 = (c // 2) * (size // 2), (c % 2) * (size // 2)
        img[r0:r0 + size // 2, c0:c0 + size // 2] = \
            rng.randint(100, 255, (size // 2, size // 2, 3))
        Image.fromarray(img).save(str(tmp_path / f'im{i}.jpg'), quality=90)
        lines.append(f'{i}\t{c}\tim{i}.jpg')
    lst = tmp_path / 'train.lst'
    lst.write_text('\n'.join(lines) + '\n')
    subprocess.check_call(
        [sys.executable, os.path.join(REPO, 'tools', 'im2bin.py'),
         'train.lst', '.', 'train.bin'],
        cwd=str(tmp_path), stdout=subprocess.DEVNULL)
    return str(lst), str(tmp_path / 'train.bin')


def _aug_chain(lst, binp, nworker, dev_norm=False, source='imgbin',
               affine=True):
    cfg = [('iter', source), ('image_list', lst), ('image_bin', binp),
           ('shuffle', '1'), ('rand_crop', '1'), ('rand_mirror', '1'),
           ('input_shape', '3,32,32'),
           ('batch_size', '8'), ('round_batch', '1'), ('silent', '1')]
    if affine:
        cfg.append(('max_rotate_angle', '10'))
    if dev_norm:
        cfg.append(('device_normalize', '1'))
    cfg += [('iter', 'threadbuffer'), ('nworker', str(nworker))]
    it = create_iterator(cfg)
    it.init()
    return it


def _collect(it, epochs=2):
    out = []
    for _ in range(epochs):
        for b in it:
            out.append((b.data.tobytes(), b.label.tobytes(),
                        b.inst_index.tobytes(), b.num_batch_padd))
    return out


def test_pooled_imgbin_bitwise_identical_across_worker_counts(tmp_path):
    """The acceptance property: an augmented (affine+crop+mirror,
    shuffled) imgbin stream yields byte-identical batch sequences for
    nworker=1 vs nworker=4, across two epochs."""
    lst, binp = _pack_imgbin(tmp_path)
    a = _collect(_aug_chain(lst, binp, 1))
    b = _collect(_aug_chain(lst, binp, 4))
    assert len(a) == len(b) > 0
    assert a == b


def test_pooled_imgbinx_bitwise_identical_across_worker_counts(tmp_path):
    """Same property through imgbinx (within-page instance shuffle,
    page reads behind their own buffer)."""
    lst, binp = _pack_imgbin(tmp_path)
    a = _collect(_aug_chain(lst, binp, 1, source='imgbinx'))
    b = _collect(_aug_chain(lst, binp, 4, source='imgbinx'))
    assert len(a) == len(b) > 0
    assert a == b


def test_pooled_device_normalize_keeps_uint8_wire(tmp_path):
    """nworker composes with device_normalize=1: raw uint8 on the wire,
    still bitwise identical across worker counts."""
    lst, binp = _pack_imgbin(tmp_path)
    a = _collect(_aug_chain(lst, binp, 1, dev_norm=True), epochs=1)
    b = _collect(_aug_chain(lst, binp, 4, dev_norm=True), epochs=1)
    assert a == b
    # uint8 wire needs crop/mirror only (an active affine warp lawfully
    # yields raw float32 — still deferred-normalized, just wider)
    it = _aug_chain(lst, binp, 2, dev_norm=True, affine=False)
    batch = next(iter(it))
    assert batch.data.dtype == np.uint8
    assert batch.norm_spec is not None


def test_pipeline_stats_flow(tmp_path):
    """nworker instruments the chain: decode/augment/collate timings,
    pool occupancy and buffer stalls land on one StatSet."""
    lst, binp = _pack_imgbin(tmp_path)
    it = _aug_chain(lst, binp, 2)
    stats = it.pipeline_stats()
    assert stats is not None
    _collect(it, epochs=1)
    line = stats.print('io')
    for key in ('io-decode_ms', 'io-augment_ms', 'io-collate_ms',
                'io-pool.occupancy', 'io-pool.workers'):
        assert key in line, (key, line)
    stats.clear()
    assert stats.print('io') == ''


def test_pooled_decode_error_propagates(tmp_path, monkeypatch):
    """A worker exception (failed JPEG decode) surfaces to the consumer
    instead of wedging the pipeline, and the pool retires cleanly (the
    conftest leak fixture backstops the second half)."""
    from cxxnet_tpu.io.iter_imbin import ImageBinIterator
    lst, binp = _pack_imgbin(tmp_path, n=9)
    it = _aug_chain(lst, binp, 4)
    orig = ImageBinIterator._decode
    calls = []

    def bad(self, blob):
        calls.append(1)
        if len(calls) == 5:
            raise OSError('decode exploded')
        return orig(self, blob)

    monkeypatch.setattr(ImageBinIterator, '_decode', bad)
    with pytest.raises(OSError, match='decode exploded'):
        _collect(it, epochs=1)


# --- scanned step-loop dispatch ------------------------------------------

DROPOUT_MLP = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:ac1] = relu
layer[+1:do1] = dropout
  threshold = 0.3
layer[+1:fc2] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.5
momentum = 0.9
metric[label] = error
eval_train = 0
"""


def _mlp_batches(n=8, bs=32, pad_last=False):
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16).astype(np.float32) * 2
    out = []
    for j in range(n):
        y = rng.randint(0, 4, bs)
        x = centers[y] + 0.3 * rng.randn(bs, 16).astype(np.float32)
        npadd = 5 if (pad_last and j == n - 1) else 0
        out.append(DataBatch(x.reshape(bs, 1, 1, 16),
                             y[:, None].astype(np.float32),
                             num_batch_padd=npadd, pad_synthetic=npadd > 0))
    return out


def _params_equal(a, b):
    for lk, fields in a.params.items():
        for fk in fields:
            pa = np.asarray(a.params[lk][fk])
            pb = np.asarray(b.params[lk][fk])
            assert np.array_equal(pa, pb), \
                f'layer {lk} field {fk}: max diff {np.abs(pa - pb).max()}'


@pytest.mark.parametrize('pad_last', [False, True])
def test_staged_window_bitwise_matches_per_step(pad_last):
    """K=4 scanned dispatches == 8 per-step dispatches, bitwise — with a
    DROPOUT layer (proving the scan derives the exact per-step RNG keys)
    and, in the pad_last leg, a synthetic-pad tail batch whose loss mask
    rides the stack."""
    batches = _mlp_batches(pad_last=pad_last)

    per = NetTrainer(parse_config_string(DROPOUT_MLP))
    per.init_model()
    for b in batches:
        per.update_staged(per.stage_batch(b))

    win = NetTrainer(parse_config_string(DROPOUT_MLP))
    win.init_model()
    fn = win.compile_multi_step(4)
    staged = [win.stage_batch(b) for b in batches]
    for i in range(0, len(staged), 4):
        win.update_staged_window(fn, staged[i:i + 4])

    assert win.epoch_counter == per.epoch_counter == len(batches)
    assert win.sample_counter == per.sample_counter
    _params_equal(win, per)


def test_staged_window_rejects_wrong_arity_and_extra_data():
    t = NetTrainer(parse_config_string(DROPOUT_MLP))
    t.init_model()
    fn = t.compile_multi_step(2)
    staged = [t.stage_batch(b) for b in _mlp_batches(n=3)]
    with pytest.raises(ValueError, match='does not match the step count'):
        t.update_staged_window(fn, staged)
    b = _mlp_batches(n=1)[0]
    b.extra_data = [np.zeros((32, 2), np.float32)]
    with pytest.raises(ValueError, match='extra_data'):
        t.update_staged_window(fn, [t.stage_batch(b)] * 2)


def test_multi_step_losses_feed_divergence_gate():
    """The scan returns the full per-step loss vector and the gate sees
    every step: a NaN injected mid-window must trip nan_action=halt even
    though the window's LAST loss is finite."""
    from cxxnet_tpu.runtime import faults
    conf = DROPOUT_MLP + 'nan_action = halt\n'
    t = NetTrainer(parse_config_string(conf))
    t.init_model()
    fn = t.compile_multi_step(4)
    batches = _mlp_batches(n=4)
    # poison batch 1 of the window: its loss goes NaN, later ones recover
    # is not guaranteed — so instead inject via the fault plan hook,
    # which rewrites the observed loss without touching the weights
    plan = faults.FaultPlan.parse('nan_at_step=2')
    faults.install_plan(plan)
    try:
        staged = [t.stage_batch(b) for b in batches]
        with pytest.raises(faults.DivergenceError) as ei:
            t.update_staged_window(fn, staged)
        assert ei.value.step == 2
    finally:
        faults.install_plan(None)


# --- CLI: steps_per_dispatch end-to-end ----------------------------------

def _write_mnist(tmp_path, n_train=400, n_test=100):
    import gzip
    import struct
    rng = np.random.RandomState(0)

    def dump(n, img_path, lab_path):
        y = rng.randint(0, 4, n).astype(np.uint8)
        x = np.zeros((n, 28, 28), np.uint8)
        for i, c in enumerate(y):
            r0, c0 = (c // 2) * 14, (c % 2) * 14
            x[i, r0:r0 + 14, c0:c0 + 14] = rng.randint(100, 255, (14, 14))
        with gzip.open(str(tmp_path / img_path), 'wb') as f:
            f.write(struct.pack('>iiii', 2051, n, 28, 28))
            f.write(x.tobytes())
        with gzip.open(str(tmp_path / lab_path), 'wb') as f:
            f.write(struct.pack('>ii', 2049, n))
            f.write(y.tobytes())

    dump(n_train, 'train-img.gz', 'train-lab.gz')
    dump(n_test, 'test-img.gz', 'test-lab.gz')


MNIST_CONF = """
data = train
iter = mnist
  path_img = train-img.gz
  path_label = train-lab.gz
  shuffle = 1
  input_flat = 0
iter = end
eval = test
iter = mnist
  path_img = test-img.gz
  path_label = test-lab.gz
  input_flat = 0
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:fc1
  nhidden = 16
  init_sigma = 0.1
layer[2->3] = sigmoid
layer[3->4] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[4->4] = softmax
netconfig = end
input_shape = 1,28,28
batch_size = 100
dev = cpu
eta = 0.1
momentum = 0.9
num_round = 2
metric[label] = error
eval_train = 0
silent = 0
"""


def _run_cli(conf_path, cwd, *overrides, timeout=240):
    env = dict(os.environ)
    env['JAX_PLATFORMS'] = 'cpu'
    env['PYTHONPATH'] = REPO + os.pathsep + env.get('PYTHONPATH', '')
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', conf_path, *overrides],
        cwd=cwd, env=env, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, (r.stdout, r.stderr)
    return r


def test_cli_steps_per_dispatch_bitwise_twin(tmp_path):
    """The CLI acceptance run: steps_per_dispatch=4 training
    bitwise-matches the K=1 per-step loop on the MNIST fixture — model
    files AND the per-round eval lines."""
    _write_mnist(tmp_path)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF)
    r1 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m1')
    r4 = _run_cli('mlp.conf', str(tmp_path), 'model_dir=m4',
                  'steps_per_dispatch=4')
    assert 'falls back' not in r4.stdout
    evals1 = [l for l in r1.stderr.splitlines() if l.startswith('[')]
    evals4 = [l for l in r4.stderr.splitlines() if l.startswith('[')]
    assert evals1 == evals4 and len(evals1) == 2
    for rd in (1, 2):
        a = (tmp_path / 'm1' / f'{rd:04d}.model').read_bytes()
        b = (tmp_path / 'm4' / f'{rd:04d}.model').read_bytes()
        assert a == b, f'round {rd} model diverged under the scanned loop'


def test_cli_scan_fallback_matrix(tmp_path):
    """The fallback matrix is profiling/test_io-only now
    (nnet/execution.py, doc/trainer.md): test_io=1 demotes the scanned
    loop and says so; eval_train=1 with train metrics SCANS (no note)
    and still reports its metrics."""
    _write_mnist(tmp_path, n_train=200)
    conf = tmp_path / 'mlp.conf'
    conf.write_text(MNIST_CONF.replace('num_round = 2', 'num_round = 1'))
    r = _run_cli('mlp.conf', str(tmp_path), 'steps_per_dispatch=4',
                 'test_io=1')
    assert 'falls back to per-step' in r.stdout
    conf.write_text(MNIST_CONF.replace('eval_train = 0', 'eval_train = 1')
                    .replace('num_round = 2', 'num_round = 1'))
    r = _run_cli('mlp.conf', str(tmp_path), 'steps_per_dispatch=4')
    assert 'falls back' not in r.stdout
    assert 'train-error' in r.stderr


def test_cli_pooled_pipeline_and_scan_end_to_end(tmp_path):
    """The full tentpole in one drive: augmented imgbin + nworker pool +
    steps_per_dispatch=4 vs the nworker=1 / K=1 twin — identical models,
    and the round eval lines carry the io- pipeline stats."""
    _pack_imgbin(tmp_path, n=64, size=40)
    conf = tmp_path / 'conv.conf'
    conf.write_text("""
data = train
iter = imgbin
  image_list = train.lst
  image_bin = train.bin
  shuffle = 1
  rand_crop = 1
  rand_mirror = 1
iter = threadbuffer
iter = end
netconfig = start
layer[0->1] = flatten
layer[1->2] = fullc:f1
  nhidden = 4
  init_sigma = 0.1
layer[2->2] = softmax
netconfig = end
input_shape = 3,32,32
batch_size = 16
dev = cpu
eta = 0.01
momentum = 0.9
num_round = 2
metric[label] = error
eval_train = 0
divideby = 256
""")
    ra = _run_cli('conv.conf', str(tmp_path), 'model_dir=ma', 'nworker=1')
    rb = _run_cli('conv.conf', str(tmp_path), 'model_dir=mb', 'nworker=4',
                  'steps_per_dispatch=4')
    assert 'falls back' not in rb.stdout
    assert 'io-pool.occupancy' in ra.stderr
    assert 'io-pool.occupancy' in rb.stderr
    for rd in (1, 2):
        a = (tmp_path / 'ma' / f'{rd:04d}.model').read_bytes()
        b = (tmp_path / 'mb' / f'{rd:04d}.model').read_bytes()
        assert a == b, f'round {rd}: pooled+scanned diverged from serial'
