"""graftcache tiered KV prefix cache suite (serve/kvcache.py +
serve/kvstore.py, doc/serving.md "Tiered KV cache").

The load-bearing claim is tier transparency: demoting an evicted prefix
page to host RAM, spilling it to a crc32-digested disk record, adopting
it from another replica's share dir, or quarantining a poisoned copy
must be BITWISE-invisible to token streams — every promoted stream
equals its cold-prefill serve equals its offline
``transformer.generate`` twin.  Plus the tier mechanics themselves: LRU
demotion ordering, host/disk byte-budget enforcement, refcount safety
(a promoting page is never an eviction victim), the record codec's
key-mismatch rejection (digest collisions never reach a stream), the
``corrupt_kv`` chaos drill, and the ``kv.*`` gauge surface.
"""

import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import jax

from cxxnet_tpu.models import transformer as T
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.serve.decode import DecodeEngine
from cxxnet_tpu.serve.kvcache import TieredKVCache
from cxxnet_tpu.serve.kvstore import (KVStore, decode_record,
                                      encode_record, key_digest)
from cxxnet_tpu.utils.metric import StatSet

pytestmark = pytest.mark.kv_tier

CFG = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                          d_ff=48, num_stages=2, seq_len=32, attn='local')
PARAMS = T.init_params(np.random.RandomState(0), CFG)


def _wait_ok(req, timeout=120):
    assert req.event.wait(timeout), 'request never completed'
    if req.error is not None:
        raise req.error
    return req.result


def _offline(prompt, max_new, temperature=0.0, rng=None):
    return np.asarray(T.generate(PARAMS, prompt, max_new, CFG,
                                 temperature=temperature, rng=rng))[0]


def _assert_twin(got, off):
    got = np.asarray(got)
    assert len(got) >= 1
    np.testing.assert_array_equal(got, off[:len(got)])


def _key(i, nbytes=64):
    """A synthetic PR 12-shaped content key: (model version, pad width,
    logical page, exact padded token span bytes)."""
    return (0, 0, int(i), bytes([i % 251]) * nbytes)


def _rows(seed, shape=(2, 8, 4, 8), dtype=np.float32):
    rng = np.random.RandomState(seed)
    return (rng.randn(*shape).astype(dtype),
            rng.randn(*shape).astype(dtype))


def _engine(**kw):
    kw.setdefault('slots', 2)
    kw.setdefault('pages', 16)
    kw.setdefault('page_size', 8)
    kw.setdefault('max_prompt', 16)
    kw.setdefault('max_new_bound', 16)
    kw.setdefault('prefix_share', 2)
    kw.setdefault('kv_host_mb', 4)
    return DecodeEngine(PARAMS, CFG, **kw)


def _serve(eng, prompt, max_new=6, temp=0.0, rng=None):
    return _wait_ok(eng.submit_direct(prompt, max_new=max_new,
                                      temperature=temp, rng=rng))


# --- record codec (tier 2 on-disk format) ----------------------------------

class TestRecordCodec:
    def test_roundtrip_bitwise(self):
        key = _key(3)
        hk, hv = _rows(1)
        rk, rv = decode_record(encode_record(key, hk, hv), key)
        np.testing.assert_array_equal(hk, rk)
        np.testing.assert_array_equal(hv, rv)
        assert rk.dtype == hk.dtype and rk.shape == hk.shape

    def test_key_mismatch_rejected(self):
        """The sha256 filename is a lookup convenience only: the header
        carries the exact key and a mismatch (digest collision, stale
        version) is a typed rejection, never a silent wrong read."""
        hk, hv = _rows(1)
        blob = encode_record(_key(3), hk, hv)
        for other in [(1, 0, 3, _key(3)[3]),      # other model version
                      (0, 3, 3, _key(3)[3]),      # other pad width
                      (0, 0, 4, _key(3)[3]),      # other logical page
                      _key(4)]:                   # other token span
            with pytest.raises(ValueError, match='key mismatch'):
                decode_record(blob, other)

    def test_truncated_and_bad_magic_rejected(self):
        hk, hv = _rows(2)
        blob = encode_record(_key(1), hk, hv)
        with pytest.raises(ValueError):
            decode_record(blob[:-8], _key(1))
        with pytest.raises(ValueError, match='magic'):
            decode_record(b'JUNK' + blob, _key(1))

    def test_digest_is_content_stable(self):
        """Same key -> same filename on every replica (the cross-replica
        contract); any key component changes it."""
        assert key_digest(_key(5)) == key_digest(_key(5))
        assert key_digest(_key(5)) != key_digest(_key(6))
        assert key_digest((0, 1, 5, _key(5)[3])) != key_digest(_key(5))


# --- tier 1: host-RAM LRU ---------------------------------------------------

class TestHostTier:
    def _cache(self, entries=2, store=None):
        hk, hv = _rows(0)
        per = hk.nbytes + hv.nbytes
        return (TieredKVCache(host_bytes=per * entries, store=store),
                per)

    def test_lru_eviction_order_and_byte_budget(self):
        cache, per = self._cache(entries=2)
        for i in range(3):
            cache.demote(_key(i), *_rows(i))
        # k0 was coldest: evicted (no store -> dropped, counted)
        assert cache.take(_key(0)) is None
        assert cache.host_entries() == 2
        assert cache.host_bytes() <= 2 * per
        assert cache.stats.get('demote_pages') == 3
        assert cache.stats.get('host_evicted') == 1
        hk, hv = cache.take(_key(2))
        np.testing.assert_array_equal(hk, _rows(2)[0])
        np.testing.assert_array_equal(hv, _rows(2)[1])

    def test_redemote_touch_refreshes_lru(self):
        cache, _ = self._cache(entries=2)
        cache.demote(_key(0), *_rows(0))
        cache.demote(_key(1), *_rows(1))
        cache.demote(_key(0), *_rows(0))   # touch: k0 back to MRU
        cache.demote(_key(2), *_rows(2))   # now k1 is the victim
        assert cache.take(_key(1)) is None
        assert cache.take(_key(0)) is not None

    def test_take_put_back_counters(self):
        cache, _ = self._cache(entries=2)
        cache.demote(_key(0), *_rows(0))
        ent = cache.take(_key(0))
        assert ent is not None
        assert cache.stats.get('promote_pages') == 1
        cache.put_back(_key(0), *ent)      # coverage-rule undo: no count
        assert cache.stats.get('promote_pages') == 1
        assert cache.take(_key(0)) is not None

    def test_zero_host_cap_spills_straight_to_store(self, tmp_path):
        stats = StatSet()
        store = KVStore(str(tmp_path / 'r'), 1 << 20, stats=stats)
        try:
            cache = TieredKVCache(host_bytes=0, store=store, stats=stats)
            cache.demote(_key(0), *_rows(0))
            assert cache.flush(10)
            assert stats.get('spills') == 1
            assert cache.host_entries() == 0
            assert cache.prefetch([_key(0)]) == 1   # rises back to tier 1
            assert cache.take(_key(0)) is not None
        finally:
            store.close(10)


# --- tier 2: disk store -----------------------------------------------------

class TestDiskStore:
    def test_spill_load_roundtrip_and_ledger(self, tmp_path):
        st = KVStore(str(tmp_path / 'root'), 1 << 20)
        try:
            key = _key(1)
            hk, hv = _rows(4)
            assert st.spill(key, hk, hv)
            assert st.flush(10)
            assert st.disk_entries() == 1
            assert st.disk_bytes() == os.path.getsize(st.record_path(key))
            # publish discipline: the digest sidecar is durable too
            assert os.path.exists(st.record_path(key) + '.crc32')
            rk, rv = st.load(key)
            np.testing.assert_array_equal(hk, rk)
            np.testing.assert_array_equal(hv, rv)
        finally:
            st.close(10)

    def test_byte_budget_evicts_coldest(self, tmp_path):
        hk, hv = _rows(0)
        size = len(encode_record(_key(0), hk, hv))
        st = KVStore(str(tmp_path / 'root'), int(size * 2.5))
        try:
            for i in range(2):
                st.spill(_key(i), *_rows(i))
            assert st.flush(10)
            # age the first two so mtime-LRU ordering is unambiguous
            for i in range(2):
                old = time.time() - 1000 + i
                os.utime(st.record_path(_key(i)), (old, old))
            st.spill(_key(2), *_rows(2))
            assert st.flush(10)
            assert st.stats.get('disk_evicted') >= 1
            assert st.disk_bytes() <= int(size * 2.5)
            assert st.load(_key(0)) is None          # coldest gone
            assert st.load(_key(2)) is not None      # newest kept
        finally:
            st.close(10)

    def test_corrupt_record_quarantined_reads_as_miss(self, tmp_path):
        st = KVStore(str(tmp_path / 'root'), 1 << 20)
        try:
            key = _key(7)
            st.spill(key, *_rows(7))
            assert st.flush(10)
            path = st.record_path(key)
            with open(path, 'r+b') as f:
                f.truncate(os.path.getsize(path) // 2)
            assert st.load(key) is None
            assert st.stats.get('corrupt_quarantined') == 1
            assert os.path.exists(path + '.quarantine')
            assert not os.path.exists(path)
            assert st.disk_entries() == 0            # ledger follows
        finally:
            st.close(10)

    def test_share_publish_and_adopt(self, tmp_path):
        share = str(tmp_path / 'shared')
        s1 = KVStore(str(tmp_path / 'l1'), 1 << 20, share_dir=share)
        s2 = KVStore(str(tmp_path / 'l2'), 1 << 20, share_dir=share)
        try:
            key = _key(9)
            hk, hv = _rows(9)
            s1.spill(key, hk, hv)
            assert s1.flush(10)
            assert s1.stats.get('published') == 1
            rk, rv = s2.load(key)                    # replica 2 adopts
            np.testing.assert_array_equal(hk, rk)
            np.testing.assert_array_equal(hv, rv)
            assert s2.stats.get('adopts') == 1
            # the adopted copy re-commits locally: the next read is
            # local and the byte budget owns it
            assert os.path.exists(s2.record_path(key))
            assert s2.disk_entries() == 1
            assert s2.load(key) is not None
            assert s2.stats.get('adopts') == 1
        finally:
            s1.close(10)
            s2.close(10)


# --- engine-level: demote -> promote bitwise twins --------------------------

class TestEngineTiers:
    @pytest.mark.parametrize('s0', [16, 13])         # w=0 / w=3
    def test_demote_promote_bitwise_twin(self, s0):
        """Prime a prefix, LRU-evict it down to the host tier, re-serve
        it: the promoted stream equals the cold serve equals offline —
        bitwise — at both pad widths."""
        eng = _engine()
        try:
            rng = np.random.RandomState(7)
            a = rng.randint(0, 64, (1, s0)).astype(np.int32)
            off = _offline(a, 6)
            _assert_twin(_serve(eng, a), off)        # cold + publish
            for i in range(2):                       # evict A down-tier
                f = rng.randint(0, 64, (1, s0)).astype(np.int32)
                _assert_twin(_serve(eng, f), _offline(f, 6))
            assert eng.kv_stats.get('demote_pages') >= 1
            before = eng.stats.get('kv_promoted_pages')
            _assert_twin(_serve(eng, a.copy()), off)  # promoted serve
            assert eng.stats.get('kv_promoted_pages') > before
            assert eng.stats.get('kv_uploads') >= 1
            assert eng.kv_stats.get('hits') >= 1
        finally:
            eng.close(30)

    def test_sampled_promote_twin(self):
        eng = _engine()
        try:
            rng = np.random.RandomState(8)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            key = jax.random.PRNGKey(5)
            off = _offline(a, 6, temperature=0.9, rng=key)
            _assert_twin(_serve(eng, a, temp=0.9, rng=key), off)
            for i in range(2):
                f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                _serve(eng, f)
            got = _serve(eng, a.copy(), temp=0.9, rng=key)
            _assert_twin(got, off)
            assert eng.stats.get('kv_promoted_pages') >= 1
        finally:
            eng.close(30)

    def test_mid_stream_join_promote_twin(self):
        """A promoted request joining a RUNNING decode loop (another
        stream mid-flight) stays bitwise-twin — the upload drains on the
        loop thread strictly before the join integrates."""
        eng = _engine(slots=3)
        try:
            rng = np.random.RandomState(9)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            off = _offline(a, 6)
            _assert_twin(_serve(eng, a), off)
            for i in range(2):
                f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                _serve(eng, f)
            long = rng.randint(0, 64, (1, 16)).astype(np.int32)
            r_long = eng.submit_direct(long, max_new=16)
            time.sleep(0.05)                  # long stream is decoding
            r_a = eng.submit_direct(a.copy(), max_new=6)
            _assert_twin(_wait_ok(r_a), off)
            _assert_twin(_wait_ok(r_long), _offline(long, 16))
            assert eng.stats.get('kv_promoted_pages') >= 1
        finally:
            eng.close(30)

    def test_disk_tier_promote_twin(self, tmp_path):
        """No host tier at all: demotes spill to disk records and the
        promote path rides prefetch (ThreadBuffer) -> verify -> upload;
        streams stay bitwise twins."""
        eng = _engine(kv_host_mb=0, kv_disk_mb=4,
                      kv_dir=str(tmp_path / 'kv'))
        try:
            rng = np.random.RandomState(10)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            off = _offline(a, 6)
            _assert_twin(_serve(eng, a), off)
            for i in range(2):
                f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                _serve(eng, f)
            assert eng._kv.flush(10)          # spills durable
            assert eng.kv_stats.get('spills') >= 1
            _assert_twin(_serve(eng, a.copy()), off)
            assert eng.kv_stats.get('disk_promote_pages') >= 1
            assert eng.stats.get('kv_promoted_pages') >= 1
        finally:
            eng.close(30)

    def test_refcount_promote_never_eviction_victim(self):
        """Concurrent promoted + cold streams under a tight pool: the
        promote splice holds an index ref AND a pending-upload ref, so
        pool-dry reclaim can never free a promoting page — every stream
        twins and no page ends up both free and referenced."""
        eng = _engine(slots=2, pages=10)
        try:
            rng = np.random.RandomState(11)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            off_a = _offline(a, 8)
            _assert_twin(_serve(eng, a, max_new=8), off_a)
            for i in range(2):
                f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                _serve(eng, f)
            prompts = [a.copy()] + [rng.randint(0, 64, (1, 16))
                                    .astype(np.int32) for _ in range(3)]
            outs = [None] * len(prompts)

            def drive(i):
                outs[i] = _wait_ok(eng.submit_direct(prompts[i],
                                                     max_new=8), 120)
            ts = [threading.Thread(target=drive, args=(i,))
                  for i in range(len(prompts))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(120)
            _assert_twin(outs[0], off_a)
            for i in range(1, len(prompts)):
                _assert_twin(outs[i], _offline(prompts[i], 8))
            with eng._cond:
                refs = eng._page_refs.copy()
                free = set(eng._free_pages)
            assert all(refs[p] == 0 for p in free)
        finally:
            eng.close(30)

    def test_kv_kwargs_validation(self):
        with pytest.raises(ValueError, match='prefix_share'):
            DecodeEngine(PARAMS, CFG, prefix_share=0, kv_host_mb=1)
        with pytest.raises(ValueError, match='kv_dir'):
            DecodeEngine(PARAMS, CFG, prefix_share=2, kv_disk_mb=1)
        with pytest.raises(ValueError, match='kv_share_dir'):
            DecodeEngine(PARAMS, CFG, prefix_share=2, kv_host_mb=1,
                         kv_share_dir='/tmp/x')
        with pytest.raises(ValueError, match='>= 0'):
            DecodeEngine(PARAMS, CFG, prefix_share=2, kv_host_mb=-1)


# --- observability ----------------------------------------------------------

class TestGauges:
    def test_kv_gauges_on_hub_and_no_hbm_double_count(self, tmp_path):
        from cxxnet_tpu.obs.hub import TelemetryHub
        from cxxnet_tpu.obs.slo import SLOSpec
        eng = _engine(kv_host_mb=4, kv_disk_mb=4,
                      kv_dir=str(tmp_path / 'kv'))
        try:
            resident0 = eng.resident_bytes()
            rng = np.random.RandomState(12)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            _serve(eng, a)
            for i in range(2):
                _serve(eng, rng.randint(0, 64, (1, 16)).astype(np.int32))
            _serve(eng, a.copy())             # promote -> promote_ms
            host, disk = eng.kv_occupancy()
            assert host > 0
            # tier occupancy is host/disk memory, never HBM: the device
            # ledger the budgeter cross-checks must not move
            assert eng.resident_bytes() == resident0
            hub = TelemetryHub(ring_events=64)
            hub.register_stats('kv', eng.kv_stats,
                               refresh=eng.kv_occupancy)
            text = hub.metrics_text()
            for metric in ('cxxnet_kv_host_bytes',
                           'cxxnet_kv_host_entries',
                           'cxxnet_kv_demote_pages',
                           'cxxnet_kv_hit_rate',
                           'cxxnet_kv_promote_ms_p50',
                           'cxxnet_kv_promote_ms_p99'):
                assert metric in text, metric
            # the satellite contract: kv.* specs parse in the SLO
            # grammar with no extra wiring
            sp = SLOSpec.parse('kv_hit', 'kv.hit_rate>=0.5@60')
            assert sp.key == 'kv.hit_rate' and sp.threshold == 0.5
        finally:
            eng.close(30)


# --- cross-replica shared index --------------------------------------------

class TestCrossReplica:
    def test_two_engines_adopt_via_share_dir(self, tmp_path):
        """Engine 1 prefills, spills and publishes; engine 2 (same
        model, its own local root) adopts the records through the share
        dir and serves the prefix WITHOUT re-prefilling — bitwise twin."""
        share = str(tmp_path / 'shared')
        e1 = _engine(kv_host_mb=0, kv_disk_mb=4,
                     kv_dir=str(tmp_path / 'l1'), kv_share_dir=share)
        e2 = _engine(kv_host_mb=0, kv_disk_mb=4,
                     kv_dir=str(tmp_path / 'l2'), kv_share_dir=share)
        try:
            rng = np.random.RandomState(13)
            a = rng.randint(0, 64, (1, 16)).astype(np.int32)
            off = _offline(a, 6)
            _assert_twin(_serve(e1, a), off)
            for i in range(2):
                f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                _serve(e1, f)
            assert e1._kv.flush(10)
            assert e1.kv_stats.get('published') >= 1
            _assert_twin(_serve(e2, a.copy()), off)
            assert e2.kv_stats.get('adopts') >= 1
            assert e2.stats.get('kv_promoted_pages') >= 1
        finally:
            e1.close(30)
            e2.close(30)

    def test_two_process_cli_adopt(self, tmp_path):
        """The full cross-replica protocol over real process boundaries:
        one CLI replica publishes tier-2 records, a second adopts them —
        and its stream equals the offline twin computed HERE."""
        share = str(tmp_path / 'shared')
        spec = ('vocab=64;d_model=32;heads=4;d_ff=48;stages=2;seq=32;'
                'seed=0;slots=2;pages=16;page_size=8;max_prompt=16;'
                'max_new=8;prefix_share=2;kv_host_mb=0;kv_disk_mb=4;'
                'kv_share_dir=' + share + ';kv_dir=')
        script = (
            'import sys, numpy as np\n'
            'from cxxnet_tpu.wrapper import LMServe\n'
            'spec, mode = sys.argv[1], sys.argv[2]\n'
            'h = LMServe.from_spec(spec)\n'
            'a = (np.arange(16, dtype=np.int32) % 64)[None]\n'
            'toks = h.generate(a, 6)\n'
            'if mode == "publish":\n'
            '    rng = np.random.RandomState(99)\n'
            '    for _ in range(2):\n'
            '        f = rng.randint(0, 64, (1, 16)).astype(np.int32)\n'
            '        h.generate(f, 6)\n'
            '    h.engine._kv.flush(10)\n'
            'print("STREAM " + " ".join(str(int(t)) for t in toks))\n'
            'print("ADOPTS %d PROMOTED %d" % ('
            'h.engine.kv_stats.get("adopts"), '
            'h.engine.stats.get("kv_promoted_pages")))\n'
            'h.close(30)\n')
        env = dict(os.environ, JAX_PLATFORMS='cpu')
        outs = []
        for i, mode in enumerate(('publish', 'adopt')):
            r = subprocess.run(
                [sys.executable, '-c', script,
                 spec + str(tmp_path / f'l{i}'), mode],
                capture_output=True, text=True, timeout=300, env=env,
                cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))))
            assert r.returncode == 0, r.stderr[-2000:]
            outs.append(r.stdout)
        a = (np.arange(16, dtype=np.int32) % 64)[None]
        off = _offline(a, 6)
        for out in outs:
            line = [ln for ln in out.splitlines()
                    if ln.startswith('STREAM')][0]
            _assert_twin(np.array([int(t) for t in line.split()[1:]],
                                  np.int32), off)
        tail = [ln for ln in outs[1].splitlines()
                if ln.startswith('ADOPTS')][0].split()
        assert int(tail[1]) >= 1, f'replica 2 never adopted: {tail}'
        assert int(tail[3]) >= 1, f'replica 2 never promoted: {tail}'


# --- chaos: corrupt_kv ------------------------------------------------------

class TestChaos:
    def test_corrupt_kv_registered_and_grammar_roundtrip(self):
        assert 'corrupt_kv' in faults.FaultPlan.registered_kinds()
        plan = faults.FaultPlan.parse(
            'seed=3;corrupt_kv=2;corrupt_kv@every=5')
        assert 'corrupt_kv=2' in plan.describe()
        assert 'corrupt_kv@every=5' in plan.describe()

    def test_corrupt_kv_truncates_committed_record(self, tmp_path):
        plan = faults.FaultPlan(corrupt_kv=(1,))
        faults.install_plan(plan)
        try:
            st = KVStore(str(tmp_path / 'r'), 1 << 20)
            try:
                key = _key(1)
                st.spill(key, *_rows(2))
                assert st.flush(10)
                assert plan.fired() == ['corrupt_kv=1']
                # digest verify rejects the truncated record: miss,
                # quarantined, never an exception
                assert st.load(key) is None
                assert st.stats.get('corrupt_quarantined') == 1
                assert os.path.exists(st.record_path(key) +
                                      '.quarantine')
                # one plan event poisons ONE record; the next commits
                # clean
                k2 = _key(2)
                st.spill(k2, *_rows(3))
                assert st.flush(10)
                assert st.load(k2) is not None
            finally:
                st.close(10)
        finally:
            faults.clear_plan()

    def test_poisoned_tier2_record_never_nontwin_stream(self, tmp_path):
        """The acceptance drill: a poisoned disk record is quarantined
        on promote and the request falls back to a re-prefill — the
        stream CANNOT diverge from its twin, and nothing crashes."""
        plan = faults.FaultPlan(corrupt_kv=(1,))
        faults.install_plan(plan)
        try:
            eng = _engine(kv_host_mb=0, kv_disk_mb=4,
                          kv_dir=str(tmp_path / 'kv'))
            try:
                rng = np.random.RandomState(14)
                a = rng.randint(0, 64, (1, 16)).astype(np.int32)
                off = _offline(a, 6)
                _assert_twin(_serve(eng, a), off)
                for i in range(2):
                    f = rng.randint(0, 64, (1, 16)).astype(np.int32)
                    _serve(eng, f)
                assert eng._kv.flush(10)
                assert plan.fired() == ['corrupt_kv=1']
                # the first spilled record (A's prefix page) is
                # poisoned: the promote probe must quarantine it and
                # the stream must still twin via re-prefill
                _assert_twin(_serve(eng, a.copy()), off)
                assert eng.kv_stats.get('corrupt_quarantined') >= 1
            finally:
                eng.close(30)
        finally:
            faults.clear_plan()
