"""Differential layer tests — the pairtest harness reborn.

Each layer's JAX forward (and jax.grad backward where the reference
hand-writes one) is checked against an independent NumPy reference
implementation, mirroring the reference's PairTestLayer strategy
(src/layer/pairtest_layer-inl.hpp) with pytest instead of in-graph checks.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.layers import ForwardContext, NodeSpec, create_layer
from cxxnet_tpu.layers.base import get_layer_type


def make_layer(type_str, params=None, name=''):
    layer = create_layer(get_layer_type(type_str), name=name)
    for k, v in (params or {}).items():
        layer.set_param(k, str(v))
    return layer


def run_layer(layer, in_specs, inputs, is_train=False, seed=0):
    out_specs = layer.infer_shapes(in_specs)
    rng = jax.random.PRNGKey(seed)
    params = layer.init_params(rng, in_specs)
    ctx = ForwardContext(is_train=is_train, rng=rng, layer_index=0)
    outs = layer.forward(params, [jnp.asarray(x) for x in inputs], ctx)
    return out_specs, params, [np.asarray(o) for o in outs]


def test_fullc_forward_matches_numpy():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 10).astype(np.float32)
    layer = make_layer('fullc', {'nhidden': 7, 'init_sigma': 0.1})
    specs, params, outs = run_layer(layer, [NodeSpec(1, 1, 10)], [x])
    assert specs[0].x == 7
    w, b = np.asarray(params['wmat']), np.asarray(params['bias'])
    np.testing.assert_allclose(outs[0], x @ w + b, rtol=1e-5)


def test_fullc_backward_matches_manual():
    # reference backward: gW += out_grad^T · in ; gb += sum_rows(out_grad);
    # in_grad = out_grad · W  (fullc_layer-inl.hpp:113-130)
    rng = np.random.RandomState(1)
    x = rng.randn(4, 10).astype(np.float32)
    g = rng.randn(4, 7).astype(np.float32)
    layer = make_layer('fullc', {'nhidden': 7})
    layer.infer_shapes([NodeSpec(1, 1, 10)])
    params = layer.init_params(jax.random.PRNGKey(0), [NodeSpec(1, 1, 10)])
    ctx = ForwardContext(is_train=True, rng=None, layer_index=0)

    def f(p, xin):
        return jnp.sum(layer.forward(p, [xin], ctx)[0] * g)

    grads = jax.grad(f, argnums=(0, 1))(params, jnp.asarray(x))
    w = np.asarray(params['wmat'])
    np.testing.assert_allclose(np.asarray(grads[0]['wmat']), x.T @ g, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grads[0]['bias']), g.sum(0), rtol=1e-4)
    np.testing.assert_allclose(np.asarray(grads[1]), g @ w.T, rtol=1e-4)


@pytest.mark.parametrize('act,fn', [
    ('relu', lambda x: np.maximum(x, 0)),
    ('sigmoid', lambda x: 1 / (1 + np.exp(-x))),
    ('tanh', np.tanh),
    ('softplus', lambda x: np.log1p(np.exp(x))),
])
def test_activations(act, fn):
    x = np.random.RandomState(2).randn(3, 5).astype(np.float32)
    _, _, outs = run_layer(make_layer(act), [NodeSpec(1, 1, 5)], [x])
    np.testing.assert_allclose(outs[0], fn(x), rtol=1e-5, atol=1e-6)


def test_xelu():
    x = np.array([[-2.0, 0.5]], dtype=np.float32)
    _, _, outs = run_layer(make_layer('xelu', {'b': 4}), [NodeSpec(1, 1, 2)], [x])
    np.testing.assert_allclose(outs[0], [[-0.5, 0.5]], rtol=1e-6)


def test_flatten_uses_nchw_order():
    # NHWC input must flatten in reference NCHW element order
    x = np.arange(2 * 3 * 4 * 5, dtype=np.float32).reshape(2, 3, 4, 5)  # b,y,x,c
    _, _, outs = run_layer(make_layer('flatten'), [NodeSpec(5, 3, 4)], [x])
    expect = np.transpose(x, (0, 3, 1, 2)).reshape(2, -1)
    np.testing.assert_array_equal(outs[0], expect)


def test_conv_matches_naive_im2col():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 5, 6, 3).astype(np.float32)        # b,y,x,c
    layer = make_layer('conv', {'nchannel': 4, 'kernel_size': 3,
                                'stride': 2, 'pad': 1})
    specs, params, outs = run_layer(layer, [NodeSpec(3, 5, 6)], [x])
    w = np.asarray(params['wmat'])                       # kh,kw,cin,cout
    b = np.asarray(params['bias'])
    oy, ox = specs[0].y, specs[0].x
    xp = np.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    ref = np.zeros((2, oy, ox, 4), np.float32)
    for i in range(oy):
        for j in range(ox):
            patch = xp[:, i * 2:i * 2 + 3, j * 2:j * 2 + 3, :]
            ref[:, i, j, :] = np.tensordot(patch, w, axes=([1, 2, 3], [0, 1, 2]))
    ref += b
    np.testing.assert_allclose(outs[0], ref, rtol=1e-4, atol=1e-5)


def test_grouped_conv_groups_are_independent():
    rng = np.random.RandomState(4)
    x = rng.randn(1, 4, 4, 4).astype(np.float32)
    layer = make_layer('conv', {'nchannel': 4, 'kernel_size': 1, 'ngroup': 2,
                                'no_bias': 1})
    specs, params, outs = run_layer(layer, [NodeSpec(4, 4, 4)], [x])
    w = np.asarray(params['wmat'])   # (1,1,2,4): first 2 cout from ch 0-1
    ref0 = x[..., :2] @ w[0, 0, :, :2]
    np.testing.assert_allclose(outs[0][..., :2], ref0, rtol=1e-4, atol=1e-5)


def test_max_pooling_ceil_shape_and_values():
    # reference shape: min(in - k + s - 1, in - 1) / s + 1  → 28,k3,s2 → 14
    x = np.random.RandomState(5).randn(1, 28, 28, 2).astype(np.float32)
    layer = make_layer('max_pooling', {'kernel_size': 3, 'stride': 2})
    specs, _, outs = run_layer(layer, [NodeSpec(2, 28, 28)], [x])
    assert (specs[0].y, specs[0].x) == (14, 14)
    # last window is clamped: starts at 26, covers rows 26..27
    ref = x[0, 26:28, 26:28, 0].max()
    np.testing.assert_allclose(outs[0][0, 13, 13, 0], ref, rtol=1e-6)


def test_avg_pooling_divides_by_full_window():
    x = np.ones((1, 6, 6, 1), np.float32)
    layer = make_layer('avg_pooling', {'kernel_size': 3, 'stride': 2})
    specs, _, outs = run_layer(layer, [NodeSpec(1, 6, 6)], [x])
    # ceil formula: min(6-3+1, 5)//2+1 = 3; last window clamps to 2 rows/cols
    # but still divides by the full 9 (pooling_layer-inl.hpp:47-49)
    assert (specs[0].y, specs[0].x) == (3, 3)
    np.testing.assert_allclose(outs[0][0, 2, 2, 0], 4.0 / 9.0, rtol=1e-6)
    np.testing.assert_allclose(outs[0][0, 0, 0, 0], 1.0, rtol=1e-6)


def test_lrn_matches_naive():
    rng = np.random.RandomState(6)
    x = rng.rand(2, 3, 3, 7).astype(np.float32)
    layer = make_layer('lrn', {'local_size': 5, 'alpha': 0.001,
                               'beta': 0.75, 'knorm': 1})
    _, _, outs = run_layer(layer, [NodeSpec(7, 3, 3)], [x])
    ref = np.zeros_like(x)
    for c in range(7):
        lo, hi = max(0, c - 2), min(7, c + 3)
        norm = 1 + 0.001 / 5 * np.sum(x[..., lo:hi] ** 2, axis=-1)
        ref[..., c] = x[..., c] * norm ** -0.75
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5, atol=1e-6)


def test_batch_norm_uses_batch_stats_even_at_eval():
    rng = np.random.RandomState(7)
    x = rng.randn(8, 4, 4, 3).astype(np.float32) * 3 + 1
    layer = make_layer('batch_norm')
    _, params, outs = run_layer(layer, [NodeSpec(3, 4, 4)], [x],
                                is_train=False)
    out = outs[0]
    np.testing.assert_allclose(out.mean(axis=(0, 1, 2)), 0.0, atol=1e-4)
    np.testing.assert_allclose(out.std(axis=(0, 1, 2)), 1.0, atol=1e-3)


def test_dropout_train_scales_and_eval_identity():
    x = np.ones((64, 100), np.float32)
    layer = make_layer('dropout', {'threshold': 0.5})
    _, _, outs_eval = run_layer(layer, [NodeSpec(1, 1, 100)], [x],
                                is_train=False)
    np.testing.assert_array_equal(outs_eval[0], x)
    _, _, outs_train = run_layer(layer, [NodeSpec(1, 1, 100)], [x],
                                 is_train=True)
    vals = np.unique(outs_train[0])
    assert set(np.round(vals, 4)) <= {0.0, 2.0}
    assert abs(outs_train[0].mean() - 1.0) < 0.1


def test_softmax_loss_grad_is_p_minus_y():
    # the defining contract: d(loss)/d(logits) == (softmax(p) - onehot) * scale
    rng = np.random.RandomState(8)
    x = rng.randn(5, 4).astype(np.float32)
    y = np.array([[0.0], [1.0], [2.0], [3.0], [1.0]], np.float32)
    layer = make_layer('softmax', {'batch_size': 5})
    layer.infer_shapes([NodeSpec(1, 1, 4)])
    ctx = ForwardContext(is_train=True, rng=None, layer_index=0)

    grad = jax.grad(
        lambda xin: layer.loss({}, [xin], jnp.asarray(y), ctx))(jnp.asarray(x))
    p = np.exp(x) / np.exp(x).sum(1, keepdims=True)
    onehot = np.eye(4)[y[:, 0].astype(int)]
    np.testing.assert_allclose(np.asarray(grad), (p - onehot) / 5.0,
                               rtol=1e-4, atol=1e-6)


def test_l2_and_multilogistic_grads():
    rng = np.random.RandomState(9)
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.rand(3, 4).astype(np.float32)
    ctx = ForwardContext(is_train=True, rng=None, layer_index=0)
    l2 = make_layer('l2_loss', {'batch_size': 3})
    g = jax.grad(lambda xin: l2.loss({}, [xin], jnp.asarray(y), ctx))(
        jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(g), (x - y) / 3.0, rtol=1e-4)
    ml = make_layer('multi_logistic', {'batch_size': 3})
    g = jax.grad(lambda xin: ml.loss({}, [xin], jnp.asarray(y), ctx))(
        jnp.asarray(x))
    p = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(np.asarray(g), (p - y) / 3.0, rtol=1e-4,
                               atol=1e-6)


def test_concat_and_split():
    a = np.ones((2, 3), np.float32)
    b = 2 * np.ones((2, 4), np.float32)
    layer = make_layer('concat')
    specs, _, outs = run_layer(layer, [NodeSpec(1, 1, 3), NodeSpec(1, 1, 4)],
                               [a, b])
    assert specs[0].x == 7
    np.testing.assert_array_equal(outs[0][:, :3], a)
    ch = make_layer('ch_concat')
    xa = np.ones((2, 4, 4, 3), np.float32)
    xb = np.zeros((2, 4, 4, 2), np.float32)
    specs, _, outs = run_layer(ch, [NodeSpec(3, 4, 4), NodeSpec(2, 4, 4)],
                               [xa, xb])
    assert specs[0].c == 5
    assert outs[0].shape == (2, 4, 4, 5)


def test_prelu():
    x = np.array([[-4.0, 2.0]], np.float32)
    layer = make_layer('prelu', {'init_slope': 0.25})
    _, params, outs = run_layer(layer, [NodeSpec(1, 1, 2)], [x])
    np.testing.assert_allclose(outs[0], [[-1.0, 2.0]], rtol=1e-6)


def test_insanity_eval_uses_midpoint():
    x = np.array([[-6.0, 3.0]], np.float32)
    layer = make_layer('insanity', {'lb': 2, 'ub': 4})
    _, _, outs = run_layer(layer, [NodeSpec(1, 1, 2)], [x], is_train=False)
    np.testing.assert_allclose(outs[0], [[-2.0, 3.0]], rtol=1e-6)


def test_pairtest_agrees_with_itself():
    x = np.random.RandomState(10).randn(2, 6).astype(np.float32)
    layer = make_layer('pairtest-relu-relu')
    _, _, outs = run_layer(layer, [NodeSpec(1, 1, 6)], [x])
    np.testing.assert_allclose(outs[0], np.maximum(x, 0), rtol=1e-6)


def test_maxout():
    x = np.array([[1.0, 5.0, 2.0, -1.0]], np.float32)
    layer = make_layer('maxout', {'ngroup': 2})
    specs, _, outs = run_layer(layer, [NodeSpec(1, 1, 4)], [x])
    assert specs[0].x == 2
    np.testing.assert_allclose(outs[0], [[5.0, 2.0]])


def test_fixconn_fixed_sparse_projection(tmp_path):
    """fixconn loads a 'nrow ncol nnz' + triples text file as a CONSTANT
    (non-learned) projection (fixconn_layer-inl.hpp:42-57)."""
    wf = tmp_path / 'w.txt'
    wf.write_text('3 5 4\n0 0 1.5\n0 4 -2.0\n1 2 0.5\n2 3 1.0\n')
    layer = make_layer('fixconn', {'nhidden': 3, 'fixconn_weight': str(wf)})
    rng = np.random.RandomState(0)
    x = rng.rand(4, 5).astype(np.float32)
    specs, params, outs = run_layer(layer, [NodeSpec(1, 1, 5)], [x])
    assert params == {} or not params, 'fixconn must not learn'
    w = np.zeros((3, 5), np.float32)
    w[0, 0], w[0, 4], w[1, 2], w[2, 3] = 1.5, -2.0, 0.5, 1.0
    np.testing.assert_allclose(outs[0], x @ w.T, rtol=1e-5)
    assert specs[0].flat_size == 3


def test_bias_layer_adds_learned_offset():
    layer = make_layer('bias')
    rng = np.random.RandomState(1)
    x = rng.rand(4, 6).astype(np.float32)
    _, params, outs = run_layer(layer, [NodeSpec(1, 1, 6)], [x])
    bias = np.asarray(list(params.values())[0]).reshape(-1)
    np.testing.assert_allclose(outs[0], x + bias[None, :], rtol=1e-5)


def test_softplus():
    layer = make_layer('softplus')
    x = np.linspace(-4, 4, 12, dtype=np.float32).reshape(3, 4)
    _, _, outs = run_layer(layer, [NodeSpec(1, 1, 4)], [x])
    np.testing.assert_allclose(outs[0], np.log1p(np.exp(x)), rtol=1e-5)


def test_sum_pooling_matches_naive():
    layer = make_layer('sum_pooling', {'kernel_size': 2, 'stride': 2})
    rng = np.random.RandomState(2)
    x = rng.rand(2, 4, 4, 3).astype(np.float32)     # NHWC
    _, _, outs = run_layer(layer, [NodeSpec(3, 4, 4)], [x])
    ref = x.reshape(2, 2, 2, 2, 2, 3).sum(axis=(2, 4))
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)


def test_insanity_pooling_eval_is_max_train_jitters_within_input():
    """insanity_max_pooling == max pooling at eval; training picks values
    that still come from the input (jittered reads, insanity_pooling_layer
    -inl.hpp:112-214)."""
    params = {'kernel_size': 2, 'stride': 2, 'keep': 0.6}
    rng = np.random.RandomState(3)
    x = rng.rand(2, 4, 4, 3).astype(np.float32)
    layer = make_layer('insanity_max_pooling', params)
    _, _, outs = run_layer(layer, [NodeSpec(3, 4, 4)], [x], is_train=False)
    ref = x.reshape(2, 2, 2, 2, 2, 3).max(axis=(2, 4))
    np.testing.assert_allclose(outs[0], ref, rtol=1e-5)
    layer = make_layer('insanity_max_pooling', params)
    _, _, outs_t = run_layer(layer, [NodeSpec(3, 4, 4)], [x], is_train=True)
    assert np.all(np.isin(np.round(outs_t[0], 5), np.round(x, 5))), \
        'train outputs must be actual input values'


def test_pairtest_reports_mismatch(capsys):
    """The differential harness must actually fire: a pairtest of two
    layers that disagree (relu vs sigmoid) reports the relative error
    (pairtest_layer-inl.hpp:75-118 prints mismatches; we keep that
    report-don't-abort contract)."""
    import jax
    x = np.random.RandomState(11).randn(2, 6).astype(np.float32)
    layer = make_layer('pairtest-relu-sigmoid')
    _, _, outs = run_layer(layer, [NodeSpec(1, 1, 6)], [x])
    jax.effects_barrier()
    assert 'MISMATCH' in capsys.readouterr().out
    # master's output is what flows on (relu here)
    np.testing.assert_allclose(outs[0], np.maximum(x, 0), rtol=1e-6)
