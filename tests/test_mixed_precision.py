"""Mixed precision (bfloat16 activations) and uint8 input path."""

import numpy as np

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string
from tests.test_net_mnist import MLP_CONF, synth_batches


def test_mlp_trains_in_bfloat16():
    conf = MLP_CONF + '\ncompute_type = bfloat16\n'
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    batches = synth_batches()
    for round_ in range(6):
        trainer.start_round(round_)
        for b in batches:
            trainer.update(b)
    res = trainer.evaluate(iter(batches[:10]), 'test')
    err = float(res.split(':')[-1])
    assert err < 0.05, f'bf16 MLP failed to learn: {res}'
    # params stay float32 (mixed precision: bf16 activations only)
    assert trainer.params['0']['wmat'].dtype == np.float32


def test_uint8_input_batch():
    conf = """
netconfig=start
layer[0->1] = conv:c1
  nchannel = 4
  kernel_size = 3
layer[1->2] = flatten
layer[2->3] = fullc:f1
  nhidden = 4
layer[3->3] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 8
dev = cpu
metric = error
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    rng = np.random.RandomState(0)
    batch = DataBatch(rng.randint(0, 256, (8, 3, 8, 8), dtype=np.uint8),
                      rng.randint(0, 4, (8, 1)).astype(np.float32))
    trainer.update(batch)          # uint8 ships raw, casts on device
    pred = trainer.predict(batch)
    assert pred.shape == (8,)
