"""Model-zoo shape inference + multi-device sharding tests."""

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.models import (alexnet_conf, inception_bn_conf, lenet_conf,
                               mlp_conf)
from cxxnet_tpu.nnet.net import Net
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.utils.config import parse_config_string


def build_net(conf_text):
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf_text))
    return Net(cfg)


def test_alexnet_shapes():
    net = build_net(alexnet_conf())
    # conv1: (227-11)/4+1 = 55; pool1 ceil: 27; conv2 27; pool2 13;
    # conv3/4/5 13; pool5 6; fc 4096 -> 4096 -> 1000
    specs = net.node_specs
    assert (specs[1].c, specs[1].y, specs[1].x) == (96, 55, 55)
    assert (specs[3].y, specs[3].x) == (27, 27)
    assert (specs[5].c, specs[5].y) == (256, 27)
    assert (specs[7].y, specs[7].x) == (13, 13)
    assert specs[15].y == 6
    assert specs[16].x == 256 * 6 * 6
    assert specs[-1].x == 1000


def test_lenet_shapes():
    net = build_net(lenet_conf())
    assert net.node_specs[1].c == 32         # conv 28->14
    assert net.node_specs[1].y == 14
    assert net.node_specs[2].y == 7          # pool ceil 14->7
    assert net.node_specs[-1].x == 10


def test_inception_bn_builds():
    net = build_net(inception_bn_conf())
    # global pool collapses to 1x1, fc emits classes
    gpool = net.cfg.node_name_map['gpool']
    assert (net.node_specs[gpool].y, net.node_specs[gpool].x) == (1, 1)
    assert net.node_specs[net.cfg.node_name_map['fc']].x == 1000
    # spot-check a concat width: in3a = 64+64+96+32 = 256 channels
    in3a = net.cfg.node_name_map['in3a_out']
    assert net.node_specs[in3a].c == 256
    assert net.node_specs[in3a].y == 28


@pytest.mark.parametrize('n_dev,tp', [(8, 1), (8, 2), (4, 4)])
def test_multidevice_training_step(n_dev, tp):
    """Full train step over a (data, model) mesh on the virtual CPU mesh."""
    conf = mlp_conf(num_class=8, input_dim=32, nhidden=64) + f"""
batch_size = {2 * n_dev}
dev = tpu:0-{n_dev - 1}
tensor_parallel = {tp}
eta = 0.1
metric = error
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    assert trainer._mesh.shape == {'data': n_dev // tp, 'model': tp}
    rng = np.random.RandomState(0)
    bs = 2 * n_dev
    batch = DataBatch(rng.randn(bs, 1, 1, 32).astype(np.float32),
                      rng.randint(0, 8, (bs, 1)).astype(np.float32))
    w_before = np.asarray(trainer.params['0']['wmat'])
    trainer.update(batch)
    assert not np.array_equal(w_before, np.asarray(trainer.params['0']['wmat']))
    # tp: fc1 weight (32, 64) sharded over model axis when tp>1
    if tp > 1:
        sh = trainer.params['0']['wmat'].sharding
        assert 'model' in str(sh.spec) or sh.is_fully_replicated is False


def test_dryrun_multichip_8():
    import __graft_entry__
    __graft_entry__.dryrun_multichip(8)


@pytest.mark.slow
def test_googlenet_multiloss_trains():
    """GoogLeNet v1: 3 softmax heads (2 aux with grad_scale=0.3) sum into
    one training loss — verify gradient flows through every head and the
    shared stem, and that training/eval run end to end."""
    import numpy as np
    from cxxnet_tpu.io.data import DataBatch
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.trainer import NetTrainer
    from cxxnet_tpu.utils.config import parse_config_string

    conf = googlenet_conf(4) + """
batch_size = 8
eta = 0.02
momentum = 0.5
metric = error
random_type = xavier
dev = cpu
input_shape = 3,224,224
"""
    tr = NetTrainer(parse_config_string(conf))
    tr.init_model()
    name_to_idx = {e.name: i for i, e in enumerate(tr.net_cfg.layers)
                   if e.name}
    watch = {k: np.asarray(tr.params[str(name_to_idx[k])]['wmat'])
             for k in ('aux1_fc2', 'aux2_fc2', 'loss3_fc', 'conv1')}

    rng = np.random.RandomState(0)
    y = np.array([0, 1, 2, 3] * 2)
    x = np.zeros((8, 3, 224, 224), np.float32)
    for i, c in enumerate(y):
        x[i, :, (c // 2) * 112:(c // 2 + 1) * 112,
          (c % 2) * 112:(c % 2 + 1) * 112] = 2.0
    batch = DataBatch(x, y.astype(np.float32).reshape(-1, 1))
    for r in range(3):
        tr.start_round(r)
        tr.update(batch)
    for k, before in watch.items():
        after = np.asarray(tr.params[str(name_to_idx[k])]['wmat'])
        assert np.isfinite(after).all(), f'{k} went non-finite'
        assert not np.array_equal(before, after), \
            f'{k} received no gradient — a loss head is disconnected'
    res = tr.evaluate(iter([batch]), 'fit')
    assert 'fit-error:' in res



def _snapshot_params(tr):
    return {k: {f: np.asarray(v) for f, v in d.items()}
            for k, d in tr.params.items()}


def _assert_params_close(a, b, rtol, atol, what=''):
    assert a.keys() == b.keys(), (sorted(a), sorted(b))
    for k in a:
        assert a[k].keys() == b[k].keys(), k
        for f in a[k]:
            np.testing.assert_allclose(
                a[k][f], b[k][f], rtol=rtol, atol=atol,
                err_msg=f'{k}/{f} diverged {what}')
            assert np.isfinite(b[k][f]).all()


def test_tail_batch_mask_on_sharded_mesh():
    """A synthetic-padded tail batch (num_batch_padd, pad_synthetic) must
    produce the same update on an 8-device data-sharded mesh as on one
    device — the loss mask shards with the batch (each of the 8 shards
    holds one row here, so the 3 pad rows span shards 5-7) and the pads
    contribute nothing anywhere."""
    def make(dev_line):
        conf = mlp_conf(num_class=4, input_dim=16, nhidden=32) + f"""
batch_size = 8
{dev_line}
eta = 0.1
momentum = 0.9
metric = error
"""
        tr = NetTrainer(parse_config_string(conf))
        tr.init_model()
        return tr

    rng = np.random.RandomState(5)
    x = rng.randn(8, 1, 1, 16).astype(np.float32)
    y = rng.randint(0, 4, (8, 1)).astype(np.float32)
    x[5:] = 1e6                     # garbage pad rows
    batch = DataBatch(x, y, num_batch_padd=3, pad_synthetic=True)

    results = []
    for dev_line in ('dev = cpu', 'dev = tpu:0-7'):
        tr = make(dev_line)
        tr.update(batch)
        results.append(_snapshot_params(tr))
    _assert_params_close(results[0], results[1], rtol=2e-5, atol=1e-6,
                         what='between 1-dev and 8-dev')


_TP_ORACLE_CONF = """
netconfig=start
layer[+1:cv1] = conv:cv1
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[+1:ac0] = relu
layer[+1:cv2] = conv:cv2
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[+1:fl] = flatten
layer[+1:fc1] = fullc:fc1
  nhidden = 16
layer[+1:ac1] = relu
layer[+1:fc2] = fullc:fc2
  nhidden = 16
layer[+1:ac2] = relu
layer[+1:fc3] = fullc:fc3
  nhidden = 4
layer[+0] = softmax
netconfig=end
input_shape = 2,6,6
batch_size = 16
eta = 0.1
momentum = 0.9
metric = error
random_type = xavier
seed = 3
"""


@pytest.mark.parametrize('tp', [2, 4])
def test_tp_alternating_matches_single_device(tp):
    """Megatron-style alternating col/row TP must be a pure layout choice:
    training on a (data, model) mesh produces the same weights as the
    single-device run (GSPMD inserts the psum/all-gather collectives; the
    math is unchanged).  Exercises conv col->row and fullc col->row->col
    chains, including the conv cv1 fallback (cin=2 not divisible by tp ->
    col even though parity wants whatever comes)."""
    from cxxnet_tpu.parallel.mesh import param_shardings

    def run(conf_suffix):
        tr = NetTrainer(parse_config_string(_TP_ORACLE_CONF + conf_suffix))
        tr.init_model()
        rng = np.random.RandomState(7)
        for _ in range(3):
            x = rng.randn(16, 2, 6, 6).astype(np.float32)
            y = rng.randint(0, 4, (16, 1)).astype(np.float32)
            tr.update(DataBatch(x, y))
        return tr

    ref = run('dev = cpu\n')
    got = run(f'dev = tpu:0-7\ntensor_parallel = {tp}\n')

    # the layout must actually alternate: collect sharded orientations
    specs = [str(got.params[k]['wmat'].sharding.spec)
             for k in sorted(got.params, key=int)
             if 'wmat' in got.params[k]]
    assert any('model' in s for s in specs), f'no TP sharding applied: {specs}'

    for lk, fields in ref.params.items():
        for fk, want in fields.items():
            have = np.asarray(got.params[lk][fk])
            np.testing.assert_allclose(
                have, np.asarray(want), rtol=2e-4, atol=2e-5,
                err_msg=f'tp={tp} diverged at layer {lk} field {fk} '
                        f'(specs={specs})')


def test_tp_pairs_form_through_batch_norm():
    """Per-node shardedness must flow through parameterized channel-wise
    layers (batch_norm): in Inception-BN every conv is followed by BN, so
    if BN broke the chain no col/row pair could ever form and every
    boundary would pay an all-gather instead of one psum."""
    from cxxnet_tpu.layers import base as lbase
    from cxxnet_tpu.models import inception_bn_conf

    tr = NetTrainer(parse_config_string(
        inception_bn_conf()
        + 'batch_size = 8\ndev = tpu:0-7\ntensor_parallel = 2\n'))
    tr.init_model()
    row_convs = 0
    for i, e in enumerate(tr.net_cfg.layers):
        f = tr.params.get(str(i))
        if f and e.type == lbase.kConv:
            s = str(f['wmat'].sharding.spec)
            if s == "PartitionSpec(None, None, 'model', None)":
                row_convs += 1
    assert row_convs >= 5, f'expected row-parallel convs, got {row_convs}'


def test_tp_row_col_alternation_layout():
    """Unit check of the parity walk: fc 16->16->16 chain with tp=2 must
    produce col, row, then col again; row-parallel bias stays replicated."""
    from cxxnet_tpu.parallel.mesh import param_shardings

    tr = NetTrainer(parse_config_string(
        _TP_ORACLE_CONF + 'dev = tpu:0-7\ntensor_parallel = 2\n'))
    tr.init_model()
    name_to_idx = {e.name: i for i, e in enumerate(tr.net_cfg.layers)
                   if e.name}
    spec = lambda name, f: str(  # noqa: E731
        tr.params[str(name_to_idx[name])][f].sharding.spec)
    # cv1: cin=2 not divisible -> col (out=8); cv2: parity now row, cin=8 ok
    assert "'model'" in spec('cv1', 'wmat').split(',')[-1]
    assert "'model'" in spec('cv2', 'wmat').split(',')[-2]
    assert spec('cv2', 'bias') == 'PartitionSpec()'
    # fc chain resumes at col
    assert spec('fc1', 'wmat') == "PartitionSpec(None, 'model')"
    assert spec('fc2', 'wmat') == "PartitionSpec('model',)" or \
        spec('fc2', 'wmat') == "PartitionSpec('model', None)"
    assert spec('fc2', 'bias') == 'PartitionSpec()'
    assert spec('fc3', 'wmat') == "PartitionSpec(None, 'model')"


def test_sibling_1x1_fusion_matches_unfused():
    """Horizontal fusion of sibling 1x1 convs (inception towers) must be
    a pure execution-plan change: same outputs, same gradients, params
    and checkpoints untouched (nnet/net.py:_build_sibling_fusion)."""
    import jax
    import jax.numpy as jnp

    from cxxnet_tpu.layers import ForwardContext
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.net import Net
    from cxxnet_tpu.nnet.net_config import NetConfig

    def build(extra):
        cfg = NetConfig()
        cfg.configure(parse_config_string(
            googlenet_conf() + 'batch_size = 2\n' + extra))
        return Net(cfg)

    fused_net, plain_net = build(''), build('fuse_siblings = 0\n')
    assert fused_net._sibling_groups, 'googlenet must trigger fusion'
    assert not plain_net._sibling_groups
    params = fused_net.init_params(jax.random.PRNGKey(0))
    rng = np.random.RandomState(0)
    batch = jnp.asarray(rng.rand(2, 3, 224, 224).astype(np.float32))
    label = jnp.asarray(rng.randint(0, 1000, (2, 1)).astype(np.float32))

    def loss_of(net):
        def f(p):
            ctx = ForwardContext(is_train=False, rng=None)
            _, loss = net.forward(p, batch, ctx,
                                  labels=net.make_label_info(label))
            return loss
        return f

    lf, gf = jax.value_and_grad(loss_of(fused_net))(params)
    lp, gp = jax.value_and_grad(loss_of(plain_net))(params)
    np.testing.assert_allclose(float(lf), float(lp), rtol=1e-6)
    for k, fields in gf.items():
        for f, v in fields.items():
            np.testing.assert_allclose(
                np.asarray(v), np.asarray(gp[k][f]), rtol=1e-5, atol=1e-6,
                err_msg=f'{k}/{f}')


def test_sibling_fusion_rejects_rewritten_node_and_tp():
    """Fusion must NOT group across an in-place rewrite of the shared
    input node, and must stay off under tensor parallelism (the concat
    axis is the model-sharded axis)."""
    from cxxnet_tpu.models import googlenet_conf
    from cxxnet_tpu.nnet.net import Net
    from cxxnet_tpu.nnet.net_config import NetConfig

    conf = """
netconfig=start
layer[0->1] = conv:c1
  kernel_size = 1
  nchannel = 8
layer[0->0] = dropout
  threshold = 0.5
layer[0->2] = conv:c2
  kernel_size = 1
  nchannel = 8
layer[1,2->3] = ch_concat
layer[3->4] = flatten
layer[4->5] = fullc:fc
  nhidden = 4
layer[5->5] = softmax
netconfig=end
input_shape = 4,6,6
batch_size = 2
"""
    cfg = NetConfig()
    cfg.configure(parse_config_string(conf))
    net = Net(cfg)
    assert not net._sibling_groups, \
        'in-place rewrite of the shared node must veto fusion'

    cfg2 = NetConfig()
    cfg2.configure(parse_config_string(
        googlenet_conf() + 'batch_size = 2\ntensor_parallel = 2\n'))
    assert not Net(cfg2)._sibling_groups, 'tp>1 must disable fusion'


def test_sibling_fusion_on_data_mesh():
    """Fused sibling 1x1 execution must not disturb training on a
    data-sharded mesh: same params after an update as fuse_siblings=0."""
    conf_body = """
netconfig=start
layer[0->1] = conv:trunk
  kernel_size = 3
  pad = 1
  nchannel = 8
layer[1->2] = relu
layer[2->t1] = conv:t1
  kernel_size = 1
  nchannel = 8
layer[2->t2] = conv:t2
  kernel_size = 1
  nchannel = 16
layer[2->t3] = conv:t3
  kernel_size = 1
  nchannel = 4
layer[t1,t2,t3->3] = ch_concat
layer[3->4] = flatten
layer[4->5] = fullc:fc
  nhidden = 4
layer[5->5] = softmax
netconfig=end
input_shape = 3,8,8
batch_size = 16
dev = tpu:0-7
eta = 0.1
momentum = 0.9
metric = error
seed = 11
"""
    rng = np.random.RandomState(2)
    x = rng.randn(16, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 4, (16, 1)).astype(np.float32)

    outs = []
    for extra in ('', 'fuse_siblings = 0\n'):
        tr = NetTrainer(parse_config_string(conf_body + extra))
        tr.init_model()
        if not extra:
            assert tr.net._sibling_groups, 'fusion must engage'
        tr.update(DataBatch(x, y))
        outs.append(_snapshot_params(tr))
    _assert_params_close(outs[0], outs[1], rtol=1e-5, atol=1e-6,
                         what='fused vs unfused')
