"""Native C++ runtime tests: page reader + libjpeg decode vs Python refs."""

import io
import os
import subprocess

import numpy as np
import pytest

from cxxnet_tpu.runtime.native import (NativePageReader, decode_jpeg,
                                       native_available)
from cxxnet_tpu.utils.io_stream import BinaryPage

pytestmark = pytest.mark.skipif(not native_available(),
                                reason='native runtime not built')


def make_bin(tmp_path, pages):
    path = tmp_path / 'x.bin'
    with open(path, 'wb') as f:
        for blobs in pages:
            page = BinaryPage()
            for b in blobs:
                assert page.push(b)
            page.save(f)
    return str(path)


def test_native_page_reader_matches_python(tmp_path):
    pages = [[b'a', b'bb' * 100, b''], [os.urandom(5000)]]
    path = make_bin(tmp_path, pages)
    reader = NativePageReader(path)
    got = list(reader.iter_pages())
    reader.close()
    assert got == pages


def test_native_jpeg_decode_matches_pil(tmp_path):
    from PIL import Image
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 255, (32, 48, 3)).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format='JPEG', quality=95)
    blob = buf.getvalue()
    native = decode_jpeg(blob)
    assert native is not None and native.shape == (32, 48, 3)
    with Image.open(io.BytesIO(blob)) as im:
        pil = np.asarray(im.convert('RGB'))
    # both use libjpeg; allow minor IDCT implementation differences
    assert np.mean(np.abs(native.astype(int) - pil.astype(int))) < 2.0


def test_native_decode_rejects_garbage():
    assert decode_jpeg(b'not a jpeg at all') is None


def test_imgbin_iterator_uses_native_jpeg(tmp_path):
    from PIL import Image
    from cxxnet_tpu.io.data import create_iterator
    rng = np.random.RandomState(1)
    lst = tmp_path / 'a.lst'
    page = BinaryPage()
    with open(lst, 'w') as f:
        for i in range(6):
            arr = rng.randint(0, 255, (20, 20, 3)).astype(np.uint8)
            buf = io.BytesIO()
            Image.fromarray(arr).save(buf, format='JPEG', quality=95)
            assert page.push(buf.getvalue())
            f.write(f'{i}\t{i % 3}\tim{i}.jpg\n')
    with open(tmp_path / 'a.bin', 'wb') as f:
        page.save(f)
    cfg = [('iter', 'imgbin'), ('image_list', str(lst)),
           ('image_bin', str(tmp_path / 'a.bin')),
           ('input_shape', '3,20,20'), ('batch_size', '3'), ('silent', '1')]
    it = create_iterator(cfg)
    it.init()
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data.shape == (3, 3, 20, 20)


def test_native_ordered_page_reader(tmp_path):
    """cxr_open_order reads pages by index with seeks — arbitrary order,
    repeats included (the imgbinx shuffled-epoch access pattern)."""
    from cxxnet_tpu.runtime.native import native_order_available
    if not native_order_available():
        pytest.skip('runtime .so predates cxr_open_order')
    pages = [[b'page0-a', b'page0-b'], [b'page1-a'], [b'page2-a', b'x' * 999]]
    path = make_bin(tmp_path, pages)
    order = [2, 0, 1, 0]
    reader = NativePageReader(path, order=order)
    got = list(reader.iter_pages())
    reader.close()
    assert got == [pages[i] for i in order]


def test_native_ordered_reader_edge_cases(tmp_path):
    from cxxnet_tpu.runtime.native import native_order_available
    if not native_order_available():
        pytest.skip('runtime .so predates cxr_open_order')
    pages = [[b'p0'], [b'p1']]
    path = make_bin(tmp_path, pages)
    # empty order reads NOTHING (sharded worker owning no pages)
    reader = NativePageReader(path, order=[])
    assert list(reader.iter_pages()) == []
    reader.close()
    # an index past EOF is an error, not silent truncation
    reader = NativePageReader(path, order=[0, 7])
    with pytest.raises(RuntimeError, match='truncated'):
        list(reader.iter_pages())
    reader.close()
