"""End-to-end: NetConfig grammar → Net → training on synthetic data.

Covers the minimum end-to-end slice (SURVEY.md §7 stage 4): the MNIST.conf
MLP topology trains on a synthetic separable problem and reaches low error,
plus checkpoint round-trip and the netconfig parser quirks.
"""

import io
import os

import numpy as np
import pytest

from cxxnet_tpu.io.data import DataBatch
from cxxnet_tpu.nnet.net_config import NetConfig
from cxxnet_tpu.nnet.trainer import NetTrainer, parse_devices
from cxxnet_tpu.utils.config import parse_config_string

MLP_CONF = """
netconfig=start
layer[+1:fc1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.1
layer[+1:sg1] = sigmoid:se1
layer[sg1->fc2] = fullc:fc2
  nhidden = 4
  init_sigma = 0.1
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 32
dev = cpu
eta = 0.5
momentum = 0.9
wd  = 0.0
metric[label] = error
"""


def synth_batches(n_batches=40, bs=32, dim=16, k=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, dim).astype(np.float32) * 2
    batches = []
    for _ in range(n_batches):
        y = rng.randint(0, k, size=bs)
        x = centers[y] + 0.3 * rng.randn(bs, dim).astype(np.float32)
        batches.append(DataBatch(x.reshape(bs, 1, 1, dim).astype(np.float32),
                                 y[:, None].astype(np.float32)))
    return batches


def test_netconfig_grammar():
    cfg = NetConfig()
    cfg.configure(parse_config_string(MLP_CONF))
    assert cfg.num_layers == 4
    assert cfg.num_nodes == 4
    assert cfg.node_names == ['in', 'fc1', 'sg1', 'fc2']
    assert cfg.layers[0].nindex_in == [0]
    assert cfg.layers[0].nindex_out == [1]
    assert cfg.layers[1].nindex_in == [1]
    assert cfg.layers[1].nindex_out == [2]
    # layer[sg1->fc2] reuses node name fc1? no — allocates node named fc2
    assert cfg.layers[3].nindex_in == cfg.layers[3].nindex_out  # self-loop
    assert cfg.layer_name_map == {'fc1': 0, 'se1': 1, 'fc2': 2}
    assert cfg.input_shape == (1, 1, 16)


def test_netconfig_binary_roundtrip():
    cfg = NetConfig()
    cfg.configure(parse_config_string(MLP_CONF))
    buf = io.BytesIO()
    cfg.save_net(buf)
    buf.seek(0)
    cfg2 = NetConfig()
    cfg2.load_net(buf)
    assert cfg2.num_layers == cfg.num_layers
    assert cfg2.node_names == cfg.node_names
    assert all(a.struct_eq(b) for a, b in zip(cfg.layers, cfg2.layers))
    assert cfg2.input_shape == cfg.input_shape


def test_parse_devices():
    assert parse_devices('gpu:0-3') == [0, 1, 2, 3]
    assert parse_devices('tpu:0,2,5') == [0, 2, 5]
    assert parse_devices('cpu') == []


def test_mlp_trains_on_synthetic():
    trainer = NetTrainer(parse_config_string(MLP_CONF))
    trainer.init_model()
    batches = synth_batches()
    for round_ in range(6):
        trainer.start_round(round_)
        for b in batches:
            trainer.update(b)
    res = trainer.evaluate(iter(batches[:10]), 'test')
    err = float(res.split(':')[-1])
    assert err < 0.05, f'MLP failed to learn: {res}'


def test_checkpoint_roundtrip_and_continue():
    trainer = NetTrainer(parse_config_string(MLP_CONF))
    trainer.init_model()
    batches = synth_batches(n_batches=10)
    for b in batches:
        trainer.update(b)
    buf = io.BytesIO()
    trainer.save_model(buf)
    res1 = trainer.evaluate(iter(batches), 'test')

    trainer2 = NetTrainer(parse_config_string(MLP_CONF))
    buf.seek(0)
    trainer2.load_model(buf)
    assert trainer2.epoch_counter == trainer.epoch_counter == 10
    res2 = trainer2.evaluate(iter(batches), 'test')
    assert res1.split(':')[-1] == res2.split(':')[-1]


def test_finetune_copies_named_layers():
    trainer = NetTrainer(parse_config_string(MLP_CONF))
    trainer.init_model()
    buf = io.BytesIO()
    trainer.save_model(buf)
    buf.seek(0)
    trainer2 = NetTrainer(parse_config_string(MLP_CONF))
    trainer2.copy_model_from(buf)
    w1 = np.asarray(trainer.params['0']['wmat'])
    w2 = np.asarray(trainer2.params['0']['wmat'])
    np.testing.assert_allclose(w1, w2)
    assert trainer2.epoch_counter == 0


def test_update_period_accumulates():
    conf = MLP_CONF + '\nupdate_period = 2\n'
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    w0 = np.asarray(trainer.params['0']['wmat']).copy()
    batches = synth_batches(n_batches=2)
    trainer.update(batches[0])
    w_after_1 = np.asarray(trainer.params['0']['wmat'])
    np.testing.assert_array_equal(w0, w_after_1)  # no update yet
    assert trainer.epoch_counter == 0
    trainer.update(batches[1])
    assert trainer.epoch_counter == 1
    assert not np.array_equal(w0, np.asarray(trainer.params['0']['wmat']))


def test_shared_layer_reuses_weights():
    conf = """
netconfig=start
layer[+1:h1] = fullc:shared_fc
  nhidden = 16
layer[+1:a1] = sigmoid
layer[a1->h2] = share[shared_fc]
layer[+1] = fullc:out
  nhidden = 16
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 4
dev = cpu
metric = error
"""
    trainer = NetTrainer(parse_config_string(conf))
    trainer.init_model()
    # only 3 layers own params: shared_fc(0), out(3); share layer(2) aliases 0
    assert set(trainer.params.keys()) == {'0', '3'}
    rng = np.random.RandomState(0)
    batch = DataBatch(rng.randn(4, 1, 1, 16).astype(np.float32),
                      np.zeros((4, 1), np.float32))
    trainer.update(batch)  # must run without error


def test_multi_step_scan_matches_sequential():
    """compile_multi_step (the one-dispatch scanned hot loop benchmarks
    time) must produce the same weights as N sequential update_on_device
    steps over the same batch cycle — proving the scan measures the real
    training computation, not a variant of it.  (Per-step RNG folding
    differs between the paths, so the net here has no stochastic layers.)"""
    batches = synth_batches(n_batches=2)
    n_steps = 6

    seq = NetTrainer(parse_config_string(MLP_CONF))
    seq.init_model()
    for t in range(n_steps):
        b = batches[t % 2]
        seq.update_on_device(seq._shard_batch(b.data),
                             seq._shard_batch(b.label, cast=False))

    scan = NetTrainer(parse_config_string(MLP_CONF))
    scan.init_model()
    dstack = scan.shard_batch_stack(
        np.stack([b.data for b in batches]))
    lstack = scan.shard_batch_stack(
        np.stack([b.label for b in batches]), cast=False)
    fn = scan.compile_multi_step(n_steps)
    scan.update_n_on_device(fn, dstack, lstack, n_steps)

    assert scan.epoch_counter == seq.epoch_counter == n_steps
    for lk, fields in seq.params.items():
        for fk, ref in fields.items():
            got = scan.params[lk][fk]
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6,
                err_msg=f'layer {lk} field {fk} diverged')
