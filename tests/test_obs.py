"""graftscope telemetry suite (``-m obs``, doc/observability.md).

The load-bearing claims:

* the hub is ONE registry (idempotent registration, one eval-line
  formatter every subsystem shares),
* spans nest, inherit trace ids on a thread, and a serve request's
  trace id appears on EVERY span of its lifecycle across the batcher
  and engine threads,
* the flight recorder is bounded and a ``TrainingFault`` reaching the
  failure log dumps a postmortem that contains the failing span,
* ``/metrics`` is valid Prometheus text (golden-pinned), ``/statusz``
  is one JSON snapshot, the endpoint thread shuts down clean,
* the CLI serves both live under ``task=online`` with ``obs.port=0``.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.obs import (TelemetryHub, format_report, get_hub,
                            install_hub, record_event, span)
from cxxnet_tpu.obs.endpoints import ObsServer
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.runtime.supervisor import SupervisorConfig, TrainSupervisor
from cxxnet_tpu.serve.batcher import DynamicBatcher
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.utils.metric import StatSet
from tests.test_net_mnist import MLP_CONF, synth_batches

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def hub():
    """A fresh hub installed process-wide for the test (the production
    wiring records through the module-level span()/record_event())."""
    h = TelemetryHub(ring_events=256)
    prev = install_hub(h)
    yield h
    h.disarm()
    install_hub(prev)


def _get(url: str) -> bytes:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read()


# --- hub registry -----------------------------------------------------------

def test_register_stats_idempotent_and_replacing(hub):
    s1, s2 = StatSet(), StatSet()
    assert hub.register_stats('serve', s1) is s1
    assert hub.register_stats('serve', s1) is s1      # re-register: no-op
    assert hub.stat_sets() == {'serve': s1}
    hub.register_stats('serve', s2)                   # restart: replaces
    assert hub.stat_sets() == {'serve': s2}
    hub.register_stats('io', s1)
    assert sorted(hub.stat_sets()) == ['io', 'serve']
    hub.unregister_stats('io')
    assert sorted(hub.stat_sets()) == ['serve']


def test_status_provider_errors_degrade_not_kill(hub):
    hub.register_status('ok', lambda: {'x': 1})
    hub.register_status('broken', lambda: 1 / 0)
    st = hub.status()
    assert st['status']['ok'] == {'x': 1}
    assert 'error' in st['status']['broken']


def test_format_report_is_the_one_formatter(hub):
    """StatSet.print and every report() spell keys through
    format_report — byte-identical output."""
    s = StatSet()
    s.inc('requests', 3)
    s.gauge('queue_peak', 2)
    s.inc('rows[b8]', 16)
    for v in (1.0, 2.0, 3.0, 4.0):
        s.observe('latency_ms', v)
    assert format_report('serve', s) == s.print('serve')
    assert '\tserve-requests:3' in s.print('serve')
    assert '\tserve-latency_ms.p50:2.5' in s.print('serve')


def test_print_and_clear_never_loses_concurrent_updates():
    """The satellite fix: render-and-reset is one atomic drain, so an
    update racing the per-round report lands in this epoch or the next,
    never nowhere (the old print()-then-clear() pair dropped it)."""
    s = StatSet()
    total = 20_000
    done = threading.Event()

    def writer():
        for _ in range(total):
            s.inc('n')
        done.set()

    t = threading.Thread(target=writer)
    t.start()
    seen = 0.0
    while not done.is_set():
        counters, _ = s.drain()
        seen += counters.get('n', 0.0)
    t.join()
    counters, _ = s.drain()
    seen += counters.get('n', 0.0)
    assert seen == total


# --- spans ------------------------------------------------------------------

def test_span_nesting_inherits_trace_id(hub):
    with span('outer', 'test', trace_id='t-req'):
        with span('inner', 'test'):
            pass
    with span('sibling', 'test'):
        pass
    evs = {e['name']: e for e in hub.events()}
    assert evs['inner']['trace_id'] == 't-req'
    assert evs['inner']['attrs']['parent'] == 'outer'
    assert evs['outer']['trace_id'] == 't-req'
    assert evs['sibling']['trace_id'] is None


def test_span_records_error_kind_and_duration(hub):
    with pytest.raises(ValueError):
        with span('boom', 'test'):
            raise ValueError('x')
    ev = hub.events()[-1]
    assert ev['name'] == 'boom'
    assert ev['attrs']['error'] == 'ValueError'
    assert ev['dur_ns'] >= 0


def test_trace_id_propagates_across_threads(hub):
    tid = hub.next_trace_id()

    def worker():
        with span('worker.step', 'test', trace_id=tid):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    with span('main.step', 'test', trace_id=tid):
        pass
    evs = [e for e in hub.events() if e['trace_id'] == tid]
    assert {e['name'] for e in evs} == {'worker.step', 'main.step'}
    assert len({e['thread'] for e in evs}) == 2


def test_ring_is_bounded_newest_win(hub):
    hub.set_ring(64)
    for i in range(500):
        record_event('e', 'test', n=i)
    evs = hub.events()
    assert len(evs) <= 64
    assert evs[-1]['attrs']['n'] == 499        # newest survived


def test_disabled_recorder_records_nothing(hub):
    hub.enabled = False
    with span('off', 'test'):
        record_event('off2', 'test')
    hub.enabled = True
    assert [e for e in hub.events() if e['name'] in ('off', 'off2')] == []


def test_span_decorator_form_respects_enabled_flips(hub):
    """The decorator re-evaluates hub.enabled per CALL — decorating
    while disabled must neither crash nor permanently disable the
    site, and flipping enabled off silences a site decorated while
    on."""
    hub.enabled = False

    @span('decorated', 'test', k=1)
    def work():
        return 42

    assert work() == 42                      # disabled: no record, no crash
    hub.enabled = True
    assert work() == 42
    evs = [e for e in hub.events() if e['name'] == 'decorated']
    assert len(evs) == 1 and evs[0]['attrs']['k'] == 1
    hub.enabled = False
    work()
    assert len([e for e in hub.events() if e['name'] == 'decorated']) == 1
    hub.enabled = True


# --- serve lifecycle trace propagation --------------------------------------

class _StubEngine:
    buckets = (4,)

    def predict_scores(self, data):
        return np.zeros((data.shape[0], 2), np.float32)


def test_request_trace_id_spans_batcher_worker_threads(hub):
    """One request's trace id stitches admit (client thread), queue
    wait + forward + finish (worker thread) into one lifecycle."""
    b = DynamicBatcher(_StubEngine(), max_queue=8, max_wait=0.001,
                       deadline=5.0)
    try:
        req = b.submit_async(np.zeros((1, 3), np.float32))
        b.wait(req)
    finally:
        b.close(timeout=5.0)
    mine = [e for e in hub.events() if e['trace_id'] == req.trace_id]
    names = {e['name'] for e in mine}
    assert {'serve.admit', 'serve.queue', 'serve.finish'} <= names
    assert len({e['thread'] for e in mine}) >= 2


def test_decode_request_lifecycle_spans_in_chrome_trace(hub, tmp_path):
    """Acceptance: a decode request's trace id appears on every span of
    its lifecycle (admit -> queue -> prefill -> emit -> finish) in the
    exported Chrome trace."""
    from cxxnet_tpu.models import transformer as T
    from cxxnet_tpu.serve.decode import DecodeService
    cfg = T.TransformerConfig(vocab_size=64, d_model=32, num_heads=4,
                              d_ff=48, num_stages=2, seq_len=32,
                              attn='local')
    params = T.init_params(np.random.RandomState(0), cfg)
    svc = DecodeService(params, cfg, slots=2, pages=32, page_size=8,
                        max_prompt=16, max_new_bound=8, deadline=60.0)
    try:
        req = svc.submit_async(np.arange(5, dtype=np.int32), max_new=4)
        svc.batcher.wait(req)
    finally:
        svc.close(30.0)
    out = str(tmp_path / 'trace.json')
    hub.export_chrome_trace(out)
    with open(out) as f:
        trace = json.load(f)
    mine = [e for e in trace['traceEvents'] if e.get('ph') == 'X'
            and e['args'].get('trace_id') == req.trace_id]
    names = {e['name'] for e in mine}
    assert {'serve.admit', 'serve.queue', 'decode.prefill',
            'decode.emit', 'decode.finish'} <= names, names
    # shared decode.step spans exist but carry no request trace id
    steps = [e for e in trace['traceEvents'] if e['name'] == 'decode.step']
    assert steps and all('trace_id' not in e['args'] for e in steps)
    # thread names are preserved via metadata events
    assert any(e.get('ph') == 'M' and e['name'] == 'thread_name'
               for e in trace['traceEvents'])


# --- flight recorder dumps --------------------------------------------------

def test_fault_plan_divergence_dumps_flight_record(hub, tmp_path):
    """THE postmortem contract: drive a FaultPlan NaN through a real
    supervised run until the supervisor gives up — the dump appears
    without anyone calling dump(), and it contains the failing
    dispatch span, the stat snapshots, and the failure log."""
    hub.arm_flight_recorder(str(tmp_path / 'flight'))
    hub.register_stats('probe', StatSet())
    batches = synth_batches(n_batches=6)
    plan = faults.FaultPlan(nan_at_step=(3,))
    prev = faults.install_plan(plan)
    tr = NetTrainer(parse_config_string(MLP_CONF))
    tr.init_model()
    log = faults.FailureLog()
    sup = TrainSupervisor(
        tr, str(tmp_path / 'sup'),
        SupervisorConfig(batch_deadline=30.0, max_restarts=0,
                         nan_breaker=1, retry=faults.NO_WAIT_RETRY),
        failure_log=log)
    try:
        with pytest.raises(faults.DivergenceError):
            sup.run(lambda k: iter(batches[k:]))
    finally:
        faults.install_plan(prev)
        sup.close()
    assert plan.fired() == ['nan_at_step=3']
    dumps = sorted(os.listdir(tmp_path / 'flight'))
    assert dumps, 'no flight dump written'
    with open(tmp_path / 'flight' / dumps[0]) as f:
        d = json.load(f)
    assert d['reason'] in ('DivergenceError', 'giving_up')
    span_names = {e['name'] for e in d['events']}
    assert 'train.dispatch' in span_names        # the failing span
    kinds = {r['kind'] for r in d['failure_log']}
    assert 'DivergenceError' in kinds
    assert 'probe' in d['stats']
    # give-up also dumped (both are armed kinds), bounded by keep
    assert len(dumps) <= TelemetryHub.DEFAULT_KEEP


def test_dump_kinds_are_training_faults_only(hub, tmp_path):
    hub.arm_flight_recorder(str(tmp_path / 'flight'))
    log = faults.FailureLog()
    log.record('io_retry', 'transient — not a fault')
    log.record('serve_reload_reject', 'bad ckpt — serving concern')
    assert not os.path.exists(tmp_path / 'flight')
    log.record('PipelineStallError', 'stalled', step=3)
    assert len(os.listdir(tmp_path / 'flight')) == 1


def test_sigusr1_dumps_flight_record(hub, tmp_path):
    import signal
    hub.configure_dump(str(tmp_path / 'flight'))
    if not hub.arm_signal_dump():
        pytest.skip('SIGUSR1 unavailable on this platform')
    record_event('before.signal', 'test')
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        # the handler runs in the main thread between bytecodes
        deadline = time.monotonic() + 5
        while not os.path.exists(tmp_path / 'flight') \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        dumps = os.listdir(tmp_path / 'flight')
        assert len(dumps) == 1 and 'SIGUSR1' in dumps[0]
    finally:
        signal.signal(signal.SIGUSR1, signal.SIG_DFL)


# --- renderers / endpoints --------------------------------------------------

GOLDEN_METRICS = '''\
# TYPE cxxnet_serve_latency_ms_count gauge
cxxnet_serve_latency_ms_count 4
# TYPE cxxnet_serve_latency_ms_mean gauge
cxxnet_serve_latency_ms_mean 2.5
# TYPE cxxnet_serve_latency_ms_p50 gauge
cxxnet_serve_latency_ms_p50 2.5
# TYPE cxxnet_serve_latency_ms_p99 gauge
cxxnet_serve_latency_ms_p99 3.97
# TYPE cxxnet_serve_queue_peak gauge
cxxnet_serve_queue_peak 2
# TYPE cxxnet_serve_requests gauge
cxxnet_serve_requests 3
# TYPE cxxnet_serve_rows gauge
cxxnet_serve_rows{tag="b8"} 16
'''


def test_prometheus_text_golden(hub):
    """The exposition format is an advertised machine surface: pin it
    byte-for-byte (minus the hub's own uptime/ring self-gauges)."""
    s = StatSet()
    s.inc('requests', 3)
    s.gauge('queue_peak', 2)
    s.inc('rows[b8]', 16)
    for v in (1.0, 2.0, 3.0, 4.0):
        s.observe('latency_ms', v)
    hub.register_stats('serve', s)
    text = hub.metrics_text()
    lines = [ln for ln in text.splitlines()
             if 'cxxnet_obs_' not in ln]
    assert '\n'.join(lines) + '\n' == GOLDEN_METRICS
    # the hub self-gauges are present too
    assert 'cxxnet_obs_events_recorded' in text
    assert 'cxxnet_obs_uptime_seconds' in text


def test_endpoints_serve_metrics_statusz_healthz(hub):
    s = StatSet()
    s.inc('tokens', 7)
    hub.register_stats('decode', s,
                       refresh=lambda: s.gauge('free_pages', 31))
    hub.register_status('registry', lambda: {'current': 5,
                                             'transitions': ['SWAPPED']})
    srv = ObsServer(hub, port=0)
    try:
        assert _get(f'{srv.url}/healthz') == b'ok\n'
        text = _get(f'{srv.url}/metrics').decode()
        assert 'cxxnet_decode_tokens 7' in text
        assert 'cxxnet_decode_free_pages 31' in text    # refresh ran
        st = json.loads(_get(f'{srv.url}/statusz'))
        for key in ('uptime_s', 'pid', 'stats', 'status', 'ring_events',
                    'events_recorded', 'flight_dumps'):
            assert key in st, key
        assert st['stats']['decode']['tokens'] == 7
        assert st['status']['registry']['current'] == 5
        with pytest.raises(urllib.error.HTTPError):
            _get(f'{srv.url}/nope')
    finally:
        assert srv.close(timeout=10.0)


def test_endpoint_thread_clean_shutdown(hub):
    srv = ObsServer(hub, port=0)
    name = f'cxxnet-obs-{srv.port}'
    assert any(t.name == name for t in threading.enumerate())
    assert srv.close(timeout=10.0)
    assert srv.close(timeout=1.0)       # idempotent
    assert not any(t.name == name for t in threading.enumerate())
    with pytest.raises(OSError):
        _get(f'{srv.url}/healthz')


# --- wrapper / capi surface -------------------------------------------------

def test_wrapper_and_capi_obs_stats(hub):
    from cxxnet_tpu import capi, wrapper
    s = StatSet()
    s.inc('served', 2)
    hub.register_stats('online', s)
    net = capi.net_create('cpu', '')
    for payload in (wrapper.Net(dev='cpu').obs_stats(),
                    capi.net_obs_stats(net)):
        st = json.loads(payload)
        assert st['stats']['online']['served'] == 2
        assert 'uptime_s' in st


# --- CLI e2e ----------------------------------------------------------------

def test_cli_task_online_obs_port_ephemeral(tmp_path):
    """One live process (task=online, obs.port=0) answers /metrics in
    Prometheus text and /statusz in JSON WHILE training-and-serving,
    with serve/freshness/registry gauges present; the Chrome trace
    exports at exit."""
    from tests.test_io import write_mnist
    write_mnist(str(tmp_path), n=256, rows=8, cols=8, seed=4)
    conf = tmp_path / 'online.conf'
    conf.write_text(f"""
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 0
iter = end
pred = pred.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
dev = cpu
eta = 0.05
momentum = 0.9
metric[label] = error
task = online
num_round = 2
online.save_every = 5
online.reload = 0.02
online.qps = 100
serve.buckets = 8,16
obs.port = 0
obs.trace_export = {tmp_path}/trace.json
""")
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO + os.pathsep + os.environ.get('PYTHONPATH',
                                                             ''))
    out_path = tmp_path / 'stdout.txt'
    with open(out_path, 'w') as out_f:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'cxxnet_tpu.main', str(conf)],
            cwd=str(tmp_path), env=env, stdout=out_f,
            stderr=subprocess.STDOUT, text=True)
        try:
            # the port line prints before init; poll it out of stdout
            port = None
            deadline = time.monotonic() + 120
            while port is None and time.monotonic() < deadline:
                for line in out_path.read_text().splitlines():
                    if line.startswith('obs: telemetry on http://'):
                        port = int(line.split(':')[3].split('/')[0].split()
                                   [0])
                        break
                if port is None:
                    assert proc.poll() is None, out_path.read_text()
                    time.sleep(0.05)
            assert port is not None, out_path.read_text()
            base = f'http://127.0.0.1:{port}'
            # poll /metrics until the serving stack registered (the
            # pipeline starts a beat after the endpoint)
            text = ''
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    break
                try:
                    text = _get(f'{base}/metrics').decode()
                except OSError:
                    time.sleep(0.1)
                    continue
                if 'cxxnet_serve_' in text and 'cxxnet_online_' in text \
                        and 'cxxnet_registry_' in text:
                    break
                time.sleep(0.2)
            assert 'cxxnet_serve_' in text, text[:2000]
            assert 'cxxnet_online_' in text, text[:2000]
            assert 'cxxnet_registry_last_swap_step' in text, text[:2000]
            st = json.loads(_get(f'{base}/statusz'))
            assert st['status']['execution_plan']['k'] >= 1
            assert 'registry' in st['status']
            assert _get(f'{base}/healthz') == b'ok\n'
            rc = proc.wait(timeout=300)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
    assert rc == 0, out_path.read_text()
    # Chrome trace export landed with lifecycle spans inside
    with open(tmp_path / 'trace.json') as f:
        trace = json.load(f)
    names = {e['name'] for e in trace['traceEvents']}
    assert 'train.dispatch' in names
    assert 'serve.finish' in names or 'serve.queue' in names
