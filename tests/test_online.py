"""Train-while-serve suite (doc/online.md): the streaming imgbin source,
the freshness tracker/SLO, registry swap stamps, the OnlinePipeline
hot-swap-under-traffic acceptance run, and the full-loop chaos drill
(writer fault + corrupt serving checkpoint + NaN streak in ONE run,
server never regresses, trainer ends bitwise-equal to a fault-free twin).

CPU-only, deterministic: traffic is in-process, faults are seeded
FaultPlan events, and every stream/pipeline property is asserted against
a static or fault-free twin.
"""

import io as _io
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from cxxnet_tpu.io import iter_imbin
from cxxnet_tpu.io.data import DataBatch, IIterator, create_iterator
from cxxnet_tpu.io.iter_stream import ImageBinStreamIterator, append_records
from cxxnet_tpu.nnet.trainer import NetTrainer
from cxxnet_tpu.online import FreshnessTracker, OnlineConfig, OnlinePipeline
from cxxnet_tpu.runtime import faults
from cxxnet_tpu.utils.config import parse_config_string
from cxxnet_tpu.utils.io_stream import BinaryPage
from tests.test_io import write_mnist

pytestmark = pytest.mark.online

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- streaming imgbin source ----------------------------------------------

@pytest.fixture
def small_pages(monkeypatch):
    """2KB pages so multi-page streams are test-sized; native reader off
    (its page size is the real 64MB)."""
    monkeypatch.setattr(BinaryPage, 'K_PAGE_SIZE', 512)
    monkeypatch.setattr(BinaryPage, 'N_BYTES', 512 * 4)
    from cxxnet_tpu.runtime import native
    monkeypatch.setattr(native, 'native_available', lambda: False)
    monkeypatch.setattr(native, 'native_order_available', lambda: False)


def _png(rng, size=6):
    from PIL import Image
    arr = rng.randint(0, 255, (size, size, 3)).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format='PNG')
    return buf.getvalue()


def _records(n, start=0, seed=0, size=6):
    rng = np.random.RandomState(seed + start)
    return [(i, [i % 4], _png(rng, size)) for i in range(start, start + n)]


def _stream_iter(tmp_path, **params):
    it = ImageBinStreamIterator()
    it.set_param('image_list', str(tmp_path / 's.lst'))
    it.set_param('image_bin', str(tmp_path / 's.bin'))
    it.set_param('silent', '1')
    for k, v in params.items():
        it.set_param(k, str(v))
    it.init()
    return it


def _static_iter(tmp_path):
    it = iter_imbin.ImageBinIterator()
    it.set_param('image_list', str(tmp_path / 's.lst'))
    it.set_param('image_bin', str(tmp_path / 's.bin'))
    it.set_param('silent', '1')
    it.init()
    return it


def _insts(it):
    return [(inst.index, inst.data.tobytes(), inst.label.tobytes())
            for inst in it]


def test_stream_bitwise_twin_while_growing(tmp_path, small_pages):
    """The acceptance property: a stream pass that tails the file WHILE
    a writer appends yields exactly the instance sequence a static
    imgbin pass yields over the final bytes."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    recs = _records(30)
    append_records(binp, lst, recs[:10])

    def writer():
        time.sleep(0.15)
        append_records(binp, lst, recs[10:22])
        time.sleep(0.15)
        append_records(binp, lst, recs[22:])

    t = threading.Thread(target=writer)
    t.start()
    got = _insts(_stream_iter(tmp_path, stream_idle=1.0, stream_poll=0.02))
    t.join()
    want = _insts(_static_iter(tmp_path))
    assert len(got) == 30
    assert got == want


def test_stream_snapshot_pass_replay_stable(tmp_path, small_pages):
    """``stream_idle=0``: one pass over the current bytes; replays yield
    the same prefix (append-only order is stable) and the iterator
    declares itself replay-stable — what supervised recovery re-winds
    on.  A pass started after growth sees the tail appended."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    append_records(binp, lst, _records(12))
    it = _stream_iter(tmp_path)
    assert it.is_replay_stable()
    first = _insts(it)
    assert [i for i, _, _ in first] == list(range(12))
    assert _insts(it) == first                 # replay: same prefix
    append_records(binp, lst, _records(8, start=12))
    grown = _insts(it)
    assert grown[:12] == first                 # prefix unchanged
    assert [i for i, _, _ in grown] == list(range(20))


def test_stream_rejects_shuffle_and_multipart(tmp_path):
    it = ImageBinStreamIterator()
    it.set_param('image_list', str(tmp_path / 's.lst'))
    it.set_param('image_bin', str(tmp_path / 's.bin'))
    it.set_param('shuffle', '1')
    with pytest.raises(ValueError, match='shuffle'):
        it.init()
    it2 = ImageBinStreamIterator()
    it2.set_param('image_conf_prefix', str(tmp_path / 'part%d'))
    it2.set_param('image_conf_ids', '0-1')
    with pytest.raises(ValueError, match='ONE appendable file'):
        it2.init()


def test_stream_incremental_refresh_reads_only_tail(tmp_path, small_pages,
                                                    monkeypatch):
    """Regression for the page-table refactor: catching up after growth
    header-scans ONLY the appended pages (scan_page_table is called with
    start_page = pages already indexed) and never re-yields consumed
    instances."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    append_records(binp, lst, _records(20, size=10))
    calls = []
    real = iter_imbin.scan_page_table

    def spy(path, start_page=0):
        calls.append(start_page)
        return real(path, start_page)

    monkeypatch.setattr(iter_imbin, 'scan_page_table', spy)
    it = _stream_iter(tmp_path)
    first = _insts(it)
    pages0 = len(it._tables[0][0])
    assert pages0 >= 2                       # multi-page under 2KB pages
    assert calls and calls[0] == 0
    append_records(binp, lst, _records(15, start=20, size=10))
    calls.clear()
    second = _insts(it)
    # the grown pass header-scanned ONLY from the already-indexed page on
    assert calls and min(calls) >= pages0
    assert [i for i, _, _ in second] == list(range(35))
    # static-twin equality over the final bytes
    assert second == _insts(_static_iter(tmp_path))


def test_scan_page_table_start_page(tmp_path, small_pages):
    """The factored index scan: start_page returns the page-count tail
    of the full scan (the unit under the stream's incremental refresh)."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    append_records(binp, lst, _records(20, size=10))
    full = iter_imbin.scan_page_table(binp)
    assert len(full) >= 3
    assert iter_imbin.scan_page_table(binp, start_page=1) == full[1:]
    assert iter_imbin.scan_page_table(binp, start_page=len(full)) == []


def test_stream_waits_for_lst_lines(tmp_path, small_pages):
    """A page visible before its .lst lines (a racing writer that broke
    the lines-first contract) is held back until the lines land, not
    mis-paired or fatal."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    recs = _records(6)
    append_records(binp, lst, recs[:3])
    # commit a page with NO lines (bypass the helper's ordering)
    page = BinaryPage()
    for _i, _l, blob in recs[3:]:
        assert page.push(blob)
    with open(binp, 'ab') as f:
        page.save(f)

    def late_lines():
        time.sleep(0.15)
        with open(lst, 'a') as f:
            for i, labels, _b in recs[3:]:
                f.write(f'{i}\t{labels[0]}\tstream\n')

    t = threading.Thread(target=late_lines)
    t.start()
    got = _insts(_stream_iter(tmp_path, stream_poll=0.02, stream_idle=0.5))
    t.join()
    assert [i for i, _, _ in got] == list(range(6))


def test_stream_through_chain_matches_static_imgbin(tmp_path, small_pages):
    """Through the full augment+batch chain (the trainer's view), the
    streaming source is bitwise-identical to static imgbin over the same
    bytes — including per-instance augmentation RNG (epoch-absolute
    index) and the nworker pool."""
    binp, lst = str(tmp_path / 's.bin'), str(tmp_path / 's.lst')
    append_records(binp, lst, _records(37, size=12))

    def chain(source, nworker):
        cfg = [('iter', source),
               ('image_list', lst), ('image_bin', binp),
               ('rand_crop', '1'), ('rand_mirror', '1'),
               ('input_shape', '3,8,8'), ('batch_size', '8'),
               ('round_batch', '1'), ('silent', '1'),
               ('iter', 'threadbuffer'), ('nworker', str(nworker))]
        it = create_iterator(cfg)
        it.init()
        out = [(b.data.tobytes(), b.label.tobytes(),
                b.inst_index.tobytes(), b.num_batch_padd) for b in it]
        close = getattr(it, 'close', None)
        if close:
            close(timeout=5.0)
        return out

    static = chain('imgbin', 1)
    assert chain('imgbin_stream', 1) == static
    assert chain('imgbin_stream', 4) == static


# --- freshness tracker ----------------------------------------------------

def test_freshness_tracker_samples_and_slo():
    log = faults.FailureLog()
    tr = FreshnessTracker(slo_s=0.05, log=log)
    t0 = time.monotonic()
    tr.record_step(10, t0)
    tr.record_swap(10, t0 + 0.01)
    # first serve closes the measurement; later serves don't re-sample
    fresh = tr.note_served(10)
    assert fresh is not None and fresh > 0
    assert tr.note_served(10) is None
    assert tr.stats.quantile('freshness_s', 0.5) == pytest.approx(fresh)
    assert tr.swaps == 1 and tr.unserved_swaps() == 0
    # breach: a sample beyond the SLO trips the typed counter + log
    tr2 = FreshnessTracker(slo_s=0.001, log=log)
    tr2.record_step(20, time.monotonic() - 1.0)
    tr2.record_swap(20)
    assert tr2.note_served(20) > 0.5
    assert tr2.breaches == 1
    assert isinstance(tr2.last_breach, faults.FreshnessSLOError)
    assert log.records('freshness_slo_breach')
    with pytest.raises(faults.FreshnessSLOError):
        tr2.check_strict()


def test_freshness_bootstrap_version_not_a_sample():
    """The boot version was never swapped — serving it measures nothing
    (the SLO is a property of swaps), and non-integer versions are
    ignored."""
    tr = FreshnessTracker()
    tr.record_step(0)
    assert tr.note_served(0) is None
    assert tr.note_served('v1.model') is None
    assert tr.stats.quantile('freshness_s', 0.5) != \
        tr.stats.quantile('freshness_s', 0.5)    # NaN: no samples


# --- registry swap stamps -------------------------------------------------

class _StampEngine:
    buckets = (1,)

    def __init__(self):
        self.version = -1

    def place_params(self, p):
        return p

    def warm_params(self, p):
        pass

    def swap_params(self, p, version=None):
        self.version = version


def test_registry_stamps_swap_step_and_age(tmp_path):
    from cxxnet_tpu.nnet import checkpoint
    from cxxnet_tpu.serve.registry import ModelRegistry
    eng = _StampEngine()
    reg = ModelRegistry(eng, str(tmp_path), current=-1,
                        loader=lambda e, p, retry=None: {})
    assert reg.last_swap_step == -1
    assert reg.last_swap_age_s() != reg.last_swap_age_s()   # NaN: never
    p = str(tmp_path / '0007.model')
    with open(p, 'wb') as f:
        f.write(b'payload')
    checkpoint.write_model_digest(p)
    assert reg.poll_once()
    assert reg.last_swap_step == 7 == eng.version
    age = reg.last_swap_age_s()
    assert 0 <= age < 5.0
    line = reg.report()
    assert '\tregistry-swaps:1' in line
    assert '\tregistry-last_swap_step:7' in line
    assert 'registry-last_swap_age_s:' in line


# --- digest-before-rename publish -----------------------------------------

def test_publish_model_file_digest_before_rename(tmp_path, monkeypatch):
    """The online publish order: the digest sidecar is on disk BEFORE the
    model file is renamed into place (a watcher never sees an
    unverifiable file), and the corrupt_model chaos event fires on the
    STAGED bytes — the published file deterministically fails digest
    verification, with no window in which the good bytes were visible."""
    from cxxnet_tpu.nnet import checkpoint
    seen = {}
    real_replace = os.replace

    def spy(src, dst):
        if str(dst).endswith('.model'):
            seen['sidecar_at_rename'] = os.path.exists(
                checkpoint.model_digest_path(str(dst)))
        return real_replace(src, dst)

    monkeypatch.setattr(checkpoint.os, 'replace', spy)
    p = str(tmp_path / '0001.model')
    checkpoint.publish_model_file(p, lambda f: f.write(b'payload' * 64))
    assert seen['sidecar_at_rename'] is True
    assert checkpoint.verify_model_digest(p) is None
    # corrupt the staged file of the next publish: digest mismatch from
    # the first instant the file exists
    plan = faults.FaultPlan(corrupt_model=(1,))
    prev = faults.install_plan(plan)
    try:
        p2 = str(tmp_path / '0002.model')
        checkpoint.publish_model_file(p2,
                                      lambda f: f.write(b'payload' * 64))
    finally:
        faults.install_plan(prev)
    assert plan.fired() == ['corrupt_model=1']
    assert os.path.exists(p2)
    assert checkpoint.verify_model_digest(p2) is not None


# --- the pipeline ---------------------------------------------------------

MLP_CONF = """
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 4
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,16
batch_size = 8
dev = cpu
eta = 0.05
momentum = 0.9
metric[label] = error
"""


class ListIter(IIterator):
    def __init__(self, batches):
        self.batches = batches

    def __iter__(self):
        return iter(self.batches)


def _make_batches(n, seed=0, bs=8):
    rng = np.random.RandomState(seed)
    centers = np.random.RandomState(99).randn(4, 16).astype(np.float32) * 2
    out = []
    for _ in range(n):
        y = rng.randint(0, 4, bs)
        x = centers[y] + 0.2 * rng.randn(bs, 16).astype(np.float32)
        out.append(DataBatch(x.reshape(bs, 1, 1, 16),
                             y[:, None].astype(np.float32)))
    return out


def _serve_factory():
    return NetTrainer(parse_config_string(MLP_CONF + 'inference_only = 1\n'))


def _request_source(seed=7):
    rng = np.random.RandomState(seed)
    centers = np.random.RandomState(99).randn(4, 16).astype(np.float32) * 2

    def req():
        y = rng.randint(0, 4, 4)
        return (centers[y] + 0.2 * rng.randn(4, 16).astype(np.float32)
                ).reshape(4, 1, 1, 16)
    return req


def _run_pipeline(tmp, batches, rounds=2, fault_plan=None, qps=200.0,
                  save_every=10, log=None, **cfg_kw):
    tr = NetTrainer(parse_config_string(MLP_CONF))
    tr.init_model()
    base = dict(model_dir=os.path.join(tmp, 'm'),
                save_every=save_every, reload_poll=0.02,
                buckets=(4, 8), qps=qps, watchdog_deadline=30.0,
                freshness_slo=30.0, silent=True)
    base.update(cfg_kw)
    cfg = OnlineConfig(**base)
    prev = faults.install_plan(fault_plan)
    pipe = OnlinePipeline(tr, ListIter(batches), _serve_factory, cfg,
                          request_source=_request_source(),
                          failure_log=log)
    try:
        summary = pipe.run(num_rounds=rounds, out=_io.StringIO())
    finally:
        pipe.close(timeout=10.0)
        faults.install_plan(prev)
    return pipe, summary, tr


def test_online_pipeline_acceptance(tmp_path):
    """The ISSUE acceptance run: one pipeline trains, publishes async
    every N steps, hot-swaps the colocated server >= 3 times with ZERO
    dropped requests, and reports freshness p50/p99 on the eval line."""
    batches = _make_batches(40)
    tr = NetTrainer(parse_config_string(MLP_CONF))
    tr.init_model()
    cfg = OnlineConfig(model_dir=str(tmp_path / 'm'), save_every=10,
                       reload_poll=0.02, buckets=(4, 8), qps=200.0,
                       watchdog_deadline=30.0, freshness_slo=30.0,
                       silent=True)
    pipe = OnlinePipeline(tr, ListIter(batches), _serve_factory, cfg,
                          request_source=_request_source())
    out = _io.StringIO()
    try:
        summary = pipe.run(num_rounds=2, out=out)
    finally:
        pipe.close(timeout=10.0)
    assert summary['swaps'] >= 3
    assert summary['dropped'] == 0
    assert summary['served'] > 0
    assert summary['steps'] == 80
    assert summary['freshness_p50_s'] > 0          # measured, not NaN
    assert summary['freshness_p99_s'] >= summary['freshness_p50_s']
    assert summary['slo_breaches'] == 0
    lines = out.getvalue().strip().splitlines()
    assert len(lines) == 2
    for line in lines:
        assert 'online-freshness_s.p50:' in line
        assert 'online-freshness_s.p99:' in line
        assert 'online-swaps:' in line
        assert 'online-dropped:0' in line
    # the serving half: registry stamps ride the serve report
    rep = pipe.serve_report()
    assert 'registry-last_swap_step:' in rep
    # model files are digest-sidecar'd (the registry verified them)
    models = [f for f in os.listdir(tmp_path / 'm')
              if f.endswith('.model')]
    assert len(models) >= 4
    assert all(os.path.exists(str(tmp_path / 'm' / (f + '.crc32')))
               for f in models)


def test_online_freshness_strict_raises_after_run(tmp_path):
    """freshness_strict=1: an impossible SLO raises the typed error at
    the END of the run (training and serving complete first)."""
    batches = _make_batches(30)
    with pytest.raises(faults.FreshnessSLOError):
        _run_pipeline(str(tmp_path), batches, rounds=1,
                      freshness_slo=1e-9, freshness_strict=True)


def test_online_chaos_drill_full_loop(tmp_path):
    """THE chaos drill (ISSUE acceptance): writer fault + corrupt
    serving checkpoint + NaN streak all fire in ONE online run.  The
    served version sequence never regresses and never includes the
    poisoned checkpoint; the trainer recovers and ends BITWISE equal to
    a fault-free twin on the same batches."""
    batches = _make_batches(40, seed=3)
    # commit #2 is the step-10 publish (after the bootstrap); nan streak
    # at steps 13/14 trips the breaker (supervisor nan_breaker=3 default
    # needs 3): use 13,14,15; raise_on_write=2 hits an early write and
    # must be retried transparently
    plan = faults.FaultPlan(
        seed=11, raise_on_write=(2,), corrupt_model=(2,),
        nan_at_step=(13, 14, 15))
    log = faults.FailureLog()
    pipe, summary, chaos_tr = _run_pipeline(
        str(tmp_path / 'chaos'), batches, rounds=2, fault_plan=plan,
        log=log)
    fired = plan.fired()
    assert 'raise_on_write=2' in fired
    assert 'corrupt_model=2' in fired
    assert any(f.startswith('nan_at_step=') for f in fired)
    # the NaN streak was detected and recovered from
    assert log.records('DivergenceError')
    assert log.records('restored')
    assert summary['restarts'] >= 1
    # served versions: strictly increasing, poisoned step 10 never served
    swap_steps = [s for s, _ in sorted(
        pipe.tracker._swap_t.items(), key=lambda kv: kv[1])]
    assert swap_steps == sorted(swap_steps)
    assert 10 not in swap_steps, \
        'the corrupted checkpoint must never be swapped in'
    assert pipe.registry.last_swap_step > 10
    # the registry rejected (not served) the poisoned file
    assert any(s == 'REJECTED' for s in pipe.registry.states())
    # zero dropped requests through all of it
    assert summary['dropped'] == 0
    # bitwise twin: same batches, no faults
    _pipe2, summary2, clean_tr = _run_pipeline(
        str(tmp_path / 'clean'), batches, rounds=2)
    assert summary2['steps'] == summary['steps'] == 80
    for lk, fields in clean_tr.params.items():
        for fk in fields:
            assert np.array_equal(np.asarray(chaos_tr.params[lk][fk]),
                                  np.asarray(clean_tr.params[lk][fk])), \
                f'chaos run diverged from fault-free twin at {lk}/{fk}'


def test_online_save_failure_degrades_freshness_not_training(tmp_path,
                                                             monkeypatch):
    """A serving-checkpoint write that fails past its retries is
    recorded (``async_save_failed``) and SKIPPED: training continues,
    later checkpoints still publish and swap, the server never sees the
    lost step, and nothing raises."""
    from cxxnet_tpu.nnet import checkpoint
    real = checkpoint.publish_model_file

    def flaky(path, write_fn, retry=None):
        if path.endswith('0008.model'):
            raise faults.RetryError('publish_model', 4,
                                    OSError('disk gone'))
        return real(path, write_fn, retry=retry)

    monkeypatch.setattr(checkpoint, 'publish_model_file', flaky)
    log = faults.FailureLog()
    pipe, summary, _tr = _run_pipeline(
        str(tmp_path), _make_batches(24, seed=5), rounds=1, log=log,
        save_every=8)
    assert summary['steps'] == 24
    assert summary['dropped'] == 0
    assert summary['save_failures'] >= 1          # the lost 0008 publish
    assert log.records('async_save_failed')
    swapped = sorted(pipe.tracker._swap_t)
    assert 8 not in swapped                       # never served
    assert any(s > 8 for s in swapped)            # ...but later steps are


# --- wrapper / capi surfaces ----------------------------------------------

def test_wrapper_online_surface(tmp_path):
    from cxxnet_tpu import capi, wrapper
    net = wrapper.Net(dev='cpu', cfg=MLP_CONF)
    net.set_param('seed', 1)
    net.init_model()
    batches = _make_batches(20, seed=9)
    net.online_start(ListIter(batches), str(tmp_path / 'm'), rounds=2,
                     save_every=8, reload=0.02, buckets='4,8',
                     watchdog_deadline=30.0)
    rows = _request_source()()
    # requests flow while training runs in the background
    scores = net.online_scores(rows)
    assert scores.shape == (4, 4)
    pred = net.online_predict(rows)
    assert pred.shape == (4,)
    summary = net.online_wait(timeout=120.0)
    assert summary['steps'] == 40
    assert summary['swaps'] >= 2
    stats = net.online_stats()
    assert 'online-swaps:' in stats and 'registry-last_swap_step:' in stats
    # capi mirrors
    assert 'online-swaps:' in capi.net_online_stats(net)
    import json
    assert json.loads(capi.net_online_wait(net))['steps'] == 40
    net.online_stop(timeout=10.0)
    # idempotent + restartable guard
    net.online_stop()
    with pytest.raises(RuntimeError, match='online_start'):
        net.online_stats()


def test_capi_online_start_parses_cfg(tmp_path):
    from cxxnet_tpu import capi
    net = capi.net_create('cpu', MLP_CONF)
    net.set_param('seed', 2)
    net.init_model()
    batches = _make_batches(10, seed=2)
    capi.net_online_start(
        net, ListIter(batches),
        f'model_dir={tmp_path}/m;rounds=1;save_every=5;reload=0.02;'
        f'buckets=4:8;freshness_slo=30;watchdog_deadline=30')
    rows = np.ascontiguousarray(_request_source()())
    out = capi.net_online_predict(net, memoryview(rows.tobytes()),
                                  rows.shape)
    assert out.shape == (4,)
    capi.net_online_wait(net)
    capi.net_online_stop(net)
    with pytest.raises(ValueError, match='model_dir'):
        capi.net_online_start(net, ListIter(batches), 'rounds=1')


# --- CLI drive ------------------------------------------------------------

def test_cli_task_online_e2e(tmp_path):
    """task=online through the real CLI: trains over mnist, serves the
    pred section's rows at online.qps, hot-swaps >= 3 times with zero
    drops, freshness gauges on every eval line, summary JSON on stdout."""
    write_mnist(str(tmp_path), n=256, rows=8, cols=8, seed=4)
    conf = tmp_path / 'online.conf'
    conf.write_text(f"""
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 0
iter = end
pred = pred.txt
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 32
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
dev = cpu
eta = 0.05
momentum = 0.9
metric[label] = error
task = online
num_round = 2
online.save_every = 5
online.freshness_slo = 60
online.reload = 0.02
online.qps = 100
serve.buckets = 8,16
""")
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO + os.pathsep + os.environ.get('PYTHONPATH',
                                                             ''))
    r = subprocess.run(
        [sys.executable, '-m', 'cxxnet_tpu.main', str(conf)],
        cwd=str(tmp_path), env=env, capture_output=True, text=True,
        timeout=420)
    assert r.returncode == 0, r.stdout + r.stderr
    swaps = [ln for ln in r.stdout.splitlines()
             if ln.startswith('online: hot-swapped step ')]
    assert len(swaps) >= 3, r.stdout
    import json
    summary_line = [ln for ln in r.stdout.splitlines()
                    if ln.startswith('online summary: ')]
    assert summary_line, r.stdout
    summary = json.loads(summary_line[0][len('online summary: '):])
    assert summary['dropped'] == 0
    assert summary['swaps'] >= 3
    assert summary['slo_breaches'] == 0
    eval_lines = [ln for ln in r.stderr.splitlines()
                  if ln.startswith('[') and 'online-freshness_s.p50:' in ln]
    assert len(eval_lines) == 2, r.stderr
    assert 'online-freshness_s.p99:' in eval_lines[-1]
    assert '[online]' in r.stderr and 'registry-swaps:' in r.stderr
    # serving checkpoints landed with digests, by STEP number
    models = sorted(f for f in os.listdir(tmp_path / 'models')
                    if f.endswith('.model'))
    assert len(models) >= 4


def test_cli_task_online_continue_resumes_from_newest_step(tmp_path):
    """continue=1 on task=online: the round-counter scan is gap-tolerant
    (step-named publishes leave holes — 0005, 0010, ...), the newest
    step-named file is adopted, and the publish counter re-arms so the
    resumed run's checkpoints continue STRICTLY past it instead of
    overwriting stale counters."""
    write_mnist(str(tmp_path), n=128, rows=8, cols=8, seed=6)
    conf = tmp_path / 'online.conf'
    conf.write_text(f"""
data = train
iter = mnist
  path_img = {tmp_path}/img.gz
  path_label = {tmp_path}/lbl.gz
  shuffle = 0
iter = end
netconfig=start
layer[+1] = fullc:fc1
  nhidden = 16
  init_sigma = 0.05
layer[+1] = relu
layer[+1] = fullc:fc2
  nhidden = 3
  init_sigma = 0.05
layer[+0] = softmax
netconfig=end
input_shape = 1,1,64
batch_size = 16
dev = cpu
eta = 0.05
metric[label] = error
task = online
num_round = 1
online.save_every = 5
online.reload = 0.02
""")
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO + os.pathsep + os.environ.get('PYTHONPATH',
                                                             ''))

    def run(*overrides):
        r = subprocess.run(
            [sys.executable, '-m', 'cxxnet_tpu.main', str(conf),
             *overrides],
            cwd=str(tmp_path), env=env, capture_output=True, text=True,
            timeout=420)
        assert r.returncode == 0, r.stdout + r.stderr
        return r

    run()
    first = sorted(int(f.split('.')[0]) for f in
                   os.listdir(tmp_path / 'models') if f.endswith('.model'))
    assert len(first) >= 2 and first[-1] >= 5   # step-named, with gaps
    r2 = run('continue=1')
    assert f'Init: continue online run from step {first[-1]}' in r2.stdout
    after = sorted(int(f.split('.')[0]) for f in
                   os.listdir(tmp_path / 'models') if f.endswith('.model'))
    new = [c for c in after if c > first[-1]]
    assert new, 'resumed run must publish past the adopted step'
    # nothing regressed or was overwritten: the old set is a prefix
    assert after[:len(first)] == first
