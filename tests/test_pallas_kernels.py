"""Pallas kernel differential tests (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cxxnet_tpu.ops.pallas_kernels import lrn_pallas, pallas_matmul


def lrn_ref(x, nsize, alpha, beta, knorm):
    """Pure-jnp LRN (the XLA path in layers/norm.py)."""
    c = x.shape[-1]
    half_lo = (nsize - 1) // 2
    sq = x * x
    out = np.zeros_like(x)
    for ch in range(c):
        lo = max(0, ch - half_lo)
        hi = min(c, ch + (nsize - 1 - half_lo) + 1)
        norm = knorm + alpha / nsize * np.sum(sq[..., lo:hi], axis=-1)
        out[..., ch] = x[..., ch] * norm ** -beta
    return out


@pytest.mark.parametrize('nsize', [3, 5, 4])
def test_lrn_pallas_forward(nsize):
    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 5, 96).astype(np.float32)
    out = np.asarray(lrn_pallas(jnp.asarray(x), nsize, 0.001, 0.75, 1.0))
    ref = lrn_ref(x, nsize, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('nsize', [5, 4])
def test_lrn_pallas_grad_matches_autodiff(nsize):
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.rand(2, 2, 3, 32).astype(np.float32) + 0.1)

    def jnp_lrn(x):
        c = x.shape[-1]
        half_lo = (nsize - 1) // 2
        half_hi = nsize - 1 - half_lo
        sq = x * x
        pad = jnp.pad(sq, [(0, 0)] * 3 + [(half_lo + 1, half_hi)])
        cums = jnp.cumsum(pad, axis=-1)
        win = cums[..., nsize:nsize + c] - cums[..., 0:c]
        norm = win * (0.001 / nsize) + 1.0
        return x * jnp.power(norm, -0.75)

    g_ref = jax.grad(lambda x: jnp.sum(jnp_lrn(x) ** 2))(x)
    g_pl = jax.grad(lambda x: jnp.sum(
        lrn_pallas(x, nsize, 0.001, 0.75, 1.0) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_pl), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-5)


def test_lrn_pallas_under_jit():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.rand(4, 2, 2, 16).astype(np.float32))
    f = jax.jit(lambda x: lrn_pallas(x, 5, 0.001, 0.75, 1.0))
    np.testing.assert_allclose(np.asarray(f(x)),
                               lrn_ref(np.asarray(x), 5, 0.001, 0.75, 1.0),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize('m,k,n', [(100, 64, 70), (256, 512, 256)])
def test_pallas_matmul(m, k, n):
    rng = np.random.RandomState(3)
    a = rng.randn(m, k).astype(np.float32)
    b = rng.randn(k, n).astype(np.float32)
    out = np.asarray(pallas_matmul(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(out, a @ b, rtol=1e-4, atol=1e-4)


def test_lrn_layer_uses_pallas_when_enabled(monkeypatch):
    monkeypatch.setenv('CXXNET_PALLAS', '1')
    from cxxnet_tpu.layers import ForwardContext, NodeSpec, create_layer
    from cxxnet_tpu.layers.base import get_layer_type
    rng = np.random.RandomState(4)
    x = rng.rand(2, 3, 3, 8).astype(np.float32)
    layer = create_layer(get_layer_type('lrn'))
    layer.set_param('local_size', '5')
    layer.infer_shapes([NodeSpec(8, 3, 3)])
    ctx = ForwardContext(is_train=False)
    out = layer.forward({}, [jnp.asarray(x)], ctx)[0]
    np.testing.assert_allclose(np.asarray(out),
                               lrn_ref(x, 5, 0.001, 0.75, 1.0),
                               rtol=1e-5, atol=1e-6)


def test_pallas_matmul_grad():
    rng = np.random.RandomState(5)
    a = jnp.asarray(rng.randn(64, 48).astype(np.float32))
    b = jnp.asarray(rng.randn(48, 32).astype(np.float32))
    g = jnp.asarray(rng.randn(64, 32).astype(np.float32))
    da, db = jax.vjp(pallas_matmul, a, b)[1](g)
    np.testing.assert_allclose(np.asarray(da), np.asarray(g @ b.T),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(db), np.asarray(a.T @ g),
                               rtol=1e-4, atol=1e-4)


def test_lrn_pallas_rows_equal_channels():
    # regression: padded row count == channel count must not misroute the
    # band matrix (positional BlockSpec dispatch in _lrn_call)
    from cxxnet_tpu.ops import pallas_kernels as pk
    rng = np.random.RandomState(6)
    c = pk._ROW_TILE
    x = jnp.asarray(rng.rand(pk._ROW_TILE // 4, 2, 2, c).astype(np.float32))
    out = pk.lrn_pallas(x, 5, 0.001, 0.75, 1.0)
    np.testing.assert_allclose(np.asarray(out),
                               lrn_ref(np.asarray(x), 5, 0.001, 0.75, 1.0),
                               rtol=1e-4, atol=1e-5)
